"""Behavior parity against committed real-format fixtures.

Everything else in the suite runs on in-process synthetic data; these
tests pin end-to-end behavior against actual serialized artifacts
(real PNG/JPEG bytes through the native decode op, zip traversal, a
census-schema CSV) with RECORDED accuracy expectations — the analog of
the reference notebooks' known dataset results.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from mmlspark_tpu.data.readers import read_csv, read_images
from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics
from mmlspark_tpu.stages.image import ImageFeaturizer, UnrollImage
from mmlspark_tpu.stages.train_classifier import TrainClassifier

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
IMAGES = os.path.join(FIXTURES, "images")
CENSUS = os.path.join(FIXTURES, "census.csv")


def _labels_from_paths(ds):
    return [os.path.basename(p).split("_")[0] for p in
            (r.path for r in ds["image"])]


def test_read_images_decodes_real_files():
    ds = read_images(IMAGES)
    assert ds.num_rows == 24  # every png/jpg decodes, none dropped
    rows = list(ds["image"])
    for r in rows:
        assert r.data.shape == (32, 32, 3)
        assert r.data.dtype == np.uint8
    # PNG is lossless: the bright half must be bright in BGR bytes too
    top = next(r for r in rows if "top_" in os.path.basename(r.path)
               and r.path.endswith(".png"))
    assert top.data[:16].mean() > top.data[16:].mean() + 60


def test_zip_traversal_reads_archived_images():
    ds = read_images(os.path.join(FIXTURES, "images_extra.zip"))
    assert ds.num_rows == 6
    assert all("zipped/" in r.path for r in ds["image"])


def test_image_classification_from_files_recorded_accuracy():
    """Files -> decode -> unroll -> TrainClassifier: the two visual
    classes are trivially separable; recorded expectation = 100% on the
    training set (24 images, pixel-level signal)."""
    ds = read_images(IMAGES)
    labels = _labels_from_paths(ds)
    ds = ds.with_column("label", labels)
    unrolled = UnrollImage().transform(ds).select("unrolled", "label")
    model = TrainClassifier(
        label_col="label", epochs=30, learning_rate=5e-2
    ).fit(unrolled)
    scored = model.transform(unrolled)
    acc = (np.asarray(scored["scored_labels"]) == np.asarray(labels)).mean()
    assert acc == 1.0, acc


def test_census_csv_recorded_accuracy():
    """CSV slice -> TrainClassifier(LR): recorded expectation from the
    generator's noise level (sigma 0.4 on the margin) is ~0.87-0.93
    held-out; assert the recorded band so silent behavior drift fails."""
    ds = read_csv(CENSUS)
    assert set(ds.columns) == {
        "age", "hours_per_week", "education", "occupation", "income"
    }
    assert ds.num_rows == 400
    train, test = ds.filter(np.arange(400) < 300), ds.filter(
        np.arange(400) >= 300
    )
    model = TrainClassifier(
        label_col="income", epochs=25, learning_rate=5e-2, seed=0
    ).fit(train)
    stats = ComputeModelStatistics().transform(model.transform(test))
    acc = float(stats["accuracy"][0])
    auc = float(stats["AUC"][0])
    assert 0.85 <= acc <= 1.0, acc
    assert auc > 0.93, auc


@pytest.mark.parametrize("ext", ["png", "jpg"])
def test_featurizer_flow_on_files(ext):
    """ImageFeaturizer over real decoded files (notebook-302 shape)."""
    from mmlspark_tpu.stages.dnn_model import TPUModel

    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models import build_model

    ds = read_images(IMAGES)
    keep = [i for i, r in enumerate(ds["image"])
            if r.path.endswith("." + ext)]
    ds = ds.filter(np.isin(np.arange(ds.num_rows), keep))
    g = build_model("resnet20_cifar10", width=8)
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    backbone = TPUModel.from_graph(
        g, v, "resnet20_cifar10", model_config={"width": 8},
        input_col="image",
    )
    out = ImageFeaturizer(
        model=backbone, cut_output_layers=1, scale=1 / 255.0
    ).transform(ds)
    feats = np.asarray(out["features"].tolist())
    assert feats.shape[0] == len(keep) and feats.shape[1] > 1
    assert np.isfinite(feats).all()


def test_titanic_real_fixture_recorded_accuracy():
    """REAL committed table (full 1,309-passenger Titanic manifest,
    OpenML id 40945 extracted from the sklearn wheel): mixed types +
    missing values through CleanMissingData -> TrainClassifier. The
    recorded band is the standard tabular-Titanic result; drift below
    0.75 means real-data handling regressed."""
    from mmlspark_tpu.stages.prep import CleanMissingData

    ds = read_csv(os.path.join(FIXTURES, "titanic.csv"))
    assert ds.num_rows == 1309
    assert ds["age"].dtype.kind == "f"  # real gaps -> NaN
    assert np.isnan(ds["age"]).sum() > 200  # 263 missing ages in the data
    test, train = ds.random_split(327 / 1309, seed=0)
    imputer = CleanMissingData(
        input_cols=["age", "fare"], cleaning_mode="Mean"
    ).fit(train)  # train-only statistics: no test leakage
    train, test = imputer.transform(train), imputer.transform(test)
    model = TrainClassifier(
        label_col="survived", epochs=25, learning_rate=5e-2, seed=0
    ).fit(train)
    stats = ComputeModelStatistics().transform(model.transform(test))
    acc = float(stats["accuracy"][0])
    assert 0.75 <= acc <= 0.9, acc


def test_machine_cpu_real_fixture_recorded_r2():
    """REAL committed regression table (UCI Relative CPU Performance,
    209 machines): vendor categorical + numerics -> TrainRegressor."""
    from mmlspark_tpu.stages.train_regressor import TrainRegressor

    ds = read_csv(os.path.join(FIXTURES, "machine_cpu.csv"))
    assert ds.num_rows == 209
    test, train = ds.random_split(52 / 209, seed=0)
    model = TrainRegressor(
        label_col="performance", model="random_forest", num_trees=30,
        seed=0,
    ).fit(train)
    stats = ComputeModelStatistics().transform(model.transform(test))
    r2 = float(stats["R^2"][0])
    assert r2 > 0.55, r2
