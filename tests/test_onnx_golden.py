"""Golden real-model ONNX import tests.

The other ONNX tests build protos byte-by-byte (self-referential by
design); this file is the external ground truth the reference relies on:
it loads a REAL serialized network produced by another framework's
exporter, the way CNTKModel loads real CNTK graphs
(SerializableFunction.scala:19-38), and cuts it by layer name the way
ImageFeaturizer does (ImageFeaturizer.scala:122).

torch (CPU) is in the environment; torchvision is not, so the standard
ResNet-18 topology is defined here (identical layer plan: 7x7/2 stem,
maxpool, 4 stages of 2 BasicBlocks at 64/128/256/512, global avgpool,
fc). Random-init weights — the assertion is numerical parity of the
imported graph against torch's own forward, not ImageNet accuracy.

The torch legacy exporter only needs the `onnx` package for an
onnxscript-function post-pass that is a no-op for plain models; with the
package absent we stub that single hook (the serialized bytes are
produced by torch's C++ exporter either way).
"""

from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
nn = torch.nn

import jax.numpy as jnp  # noqa: E402

from mmlspark_tpu.models.onnx_import import load_onnx  # noqa: E402


def _export_onnx(model, args, path, opset=13, fold=False):
    """torch.onnx.export via the TorchScript exporter, tolerating an
    absent `onnx` package (its only use is the onnxscript no-op pass).
    ``fold=False`` keeps BatchNormalization nodes instead of letting the
    exporter fuse them into conv weights, so the imported BN math gets
    real-exporter coverage."""
    kw = dict(
        dynamo=False, opset_version=opset, do_constant_folding=fold,
        input_names=["input"], output_names=["output"],
    )
    try:
        torch.onnx.export(model, args, str(path), **kw)
        return
    except Exception as e:  # noqa: BLE001 — retry only the known gap
        if "onnx is not installed" not in str(e):
            raise
    try:
        from torch.onnx._internal.torchscript_exporter import (
            onnx_proto_utils,
        )
    except ImportError:
        pytest.skip("torch exporter needs the onnx package on this version")
    orig = onnx_proto_utils._add_onnxscript_fn
    onnx_proto_utils._add_onnxscript_fn = lambda b, _ops: b
    try:
        torch.onnx.export(model, args, str(path), **kw)
    finally:
        onnx_proto_utils._add_onnxscript_fn = orig


class _BasicBlock(nn.Module):
    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU(inplace=True)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout),
            )

    def forward(self, x):
        idt = x if self.down is None else self.down(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + idt)


class _ResNet18(nn.Module):
    """Standard ResNet-18 layer plan (He et al.; torchvision-equivalent)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        blocks, cin = [], 64
        for cout, stride in [(64, 1), (64, 1), (128, 2), (128, 1),
                             (256, 2), (256, 1), (512, 2), (512, 1)]:
            blocks.append(_BasicBlock(cin, cout, stride))
            cin = cout
        self.layers = nn.Sequential(*blocks)
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layers(x)
        x = self.avgpool(x).flatten(1)
        return self.fc(x)


@pytest.fixture(scope="module")
def rn18(tmp_path_factory):
    """Exported ResNet-18 + its torch reference outputs, built once."""
    torch.manual_seed(0)
    model = _ResNet18().eval()
    # BN with random init has running_var=1, mean=0 — perturb so the
    # imported BatchNormalization math is actually exercised
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, nn.BatchNorm2d):
                m.running_mean.normal_(0, 0.05)
                m.running_var.uniform_(0.7, 1.3)
                m.weight.normal_(1.0, 0.1)
                m.bias.normal_(0, 0.1)
    x = torch.randn(2, 3, 224, 224)
    with torch.no_grad():
        y = model(x)
    path = tmp_path_factory.mktemp("onnx_golden") / "rn18.onnx"
    _export_onnx(model, (x,), path)
    graph = load_onnx(str(path))
    return model, graph, x, y


def test_resnet18_import_matches_torch(rn18):
    _model, graph, x, y_ref = rn18
    variables = graph.init()
    y = np.asarray(graph.apply(variables, jnp.asarray(x.numpy())))
    assert y.shape == (2, 1000)
    np.testing.assert_allclose(y, y_ref.numpy(), atol=1e-4, rtol=1e-4)


def test_resnet18_real_graph_structure(rn18):
    """The exported graph carries torch's real node names; the op
    inventory is the real-world CNN set, not what our exporter emits."""
    _model, graph, _x, _y = rn18
    ops = {n.op for n in graph.nodes}
    assert {"Conv", "BatchNormalization", "Relu", "MaxPool",
            "GlobalAveragePool", "Flatten", "Gemm", "Add"} <= ops
    # torch's scoped names survive the wire round-trip
    assert any("/fc/Gemm" in n for n in graph.layer_names)


def test_resnet18_cut_matches_torch_hook(rn18):
    """cut() at a real mid-graph node == torch's activation at the same
    module, captured with a forward hook — the ImageFeaturizer headless-
    net contract (ImageFeaturizer.scala:122) on a real exported file."""
    model, graph, x, _y = rn18
    # last block's final relu: torch names it /layers/layers.7/relu_1/Relu
    target = [n for n in graph.layer_names if n.endswith("relu_1/Relu")][-1]
    headless = graph.cut(target)
    assert headless.layer_names[-1] == target

    captured = {}
    block = model.layers[7]
    hook = block.register_forward_hook(
        lambda _m, _i, out: captured.__setitem__("act", out.detach())
    )
    with torch.no_grad():
        model(x)
    hook.remove()

    feat = np.asarray(headless.apply(graph.init(), jnp.asarray(x.numpy())))
    assert feat.shape == tuple(captured["act"].shape)
    np.testing.assert_allclose(
        feat, captured["act"].numpy(), atol=1e-4, rtol=1e-4
    )


def test_resnet18_tpumodel_stage_roundtrip(rn18, tmp_path):
    """The imported real model drives the TPUModel inference stage —
    the full CNTKModel-analog path (CNTKModel.scala:215-262) on a real
    exported file, including output-node surgery by name."""
    from mmlspark_tpu.data.dataset import Dataset
    from mmlspark_tpu.stages.dnn_model import TPUModel

    _model, graph, x, y_ref = rn18
    stage = TPUModel.from_graph(
        graph, graph.init(), "rn18", input_col="image",
        output_col="scores", batch_size=2,
    )
    out = stage.transform(Dataset({"image": x.numpy()}))
    scores = np.stack(list(out.column("scores")))
    np.testing.assert_allclose(scores, y_ref.numpy(), atol=1e-3, rtol=1e-3)


class _MiniEncoder(nn.Module):
    """A transformer encoder layer: exercises MatMul/Softmax/fused
    LayerNormalization (opset 17) from a real exporter."""

    def __init__(self, d=32, heads=4):
        super().__init__()
        self.layer = nn.TransformerEncoderLayer(
            d_model=d, nhead=heads, dim_feedforward=64,
            batch_first=True, dropout=0.0,
        )

    def forward(self, x):
        return self.layer(x)


def test_transformer_encoder_import_matches_torch(tmp_path):
    torch.manual_seed(1)
    model = _MiniEncoder().eval()
    x = torch.randn(2, 7, 32)
    with torch.no_grad():
        y = model(x)
    path = tmp_path / "encoder.onnx"
    _export_onnx(model, (x,), path, opset=17)
    graph = load_onnx(str(path))
    got = np.asarray(graph.apply(graph.init(), jnp.asarray(x.numpy())))
    np.testing.assert_allclose(got, y.numpy(), atol=1e-4, rtol=1e-4)


class _MobileBlock(nn.Module):
    """MobileNet-style stem: standard conv + depthwise (groups=C) conv +
    pointwise conv + ReLU6 — exercises grouped Conv and Clip from a real
    exporter."""

    def __init__(self):
        super().__init__()
        self.net = nn.Sequential(
            nn.Conv2d(3, 16, 3, 2, 1, bias=False), nn.BatchNorm2d(16),
            nn.ReLU(),
            nn.Conv2d(16, 16, 3, 1, 1, groups=16, bias=False),
            nn.BatchNorm2d(16), nn.ReLU(),
            nn.Conv2d(16, 32, 1, bias=False), nn.BatchNorm2d(32),
            nn.ReLU6(),
            nn.AdaptiveAvgPool2d(1), nn.Flatten(), nn.Linear(32, 10),
        )

    def forward(self, x):
        return self.net(x)


def test_depthwise_conv_import_matches_torch(tmp_path):
    torch.manual_seed(2)
    model = _MobileBlock().eval()
    x = torch.randn(2, 3, 32, 32)
    with torch.no_grad():
        y = model(x)
    path = tmp_path / "mobile.onnx"
    _export_onnx(model, (x,), path)
    graph = load_onnx(str(path))
    ops = {n.op for n in graph.nodes}
    assert "Clip" in ops  # ReLU6
    got = np.asarray(graph.apply(graph.init(), jnp.asarray(x.numpy())))
    np.testing.assert_allclose(got, y.numpy(), atol=1e-5, rtol=1e-5)


class _BiLSTMTagger(nn.Module):
    """Notebook-304-shaped net from a REAL exporter: embedding ->
    bidirectional LSTM -> per-token linear head. torch exports this as
    Gather + ONNX LSTM(direction=bidirectional) + Transpose/Reshape +
    Gemm — the opaque-serialized-BiLSTM family CNTKModel served."""

    def __init__(self, vocab=23, embed=12, hidden=8, tags=5):
        super().__init__()
        self.emb = nn.Embedding(vocab, embed)
        self.lstm = nn.LSTM(embed, hidden, bidirectional=True)
        self.head = nn.Linear(2 * hidden, tags)

    def forward(self, ids):  # ids: (T, B) int64
        h, _ = self.lstm(self.emb(ids))
        return self.head(h)  # (T, B, tags)


def test_bilstm_import_matches_torch(tmp_path):
    torch.manual_seed(3)
    model = _BiLSTMTagger().eval()
    ids = torch.randint(0, 23, (9, 2))
    with torch.no_grad():
        y = model(ids)
    path = tmp_path / "bilstm.onnx"
    _export_onnx(model, (ids,), path)
    graph = load_onnx(str(path))
    got = np.asarray(
        graph.apply(graph.init(), jnp.asarray(ids.numpy().astype(np.int32)))
    )
    np.testing.assert_allclose(got, y.numpy(), atol=1e-4, rtol=1e-4)
