"""Meta-test: every fault hook point is actually drilled somewhere.

The injector's ``SITES`` tuple is the contract between the runtime's
hook points and the chaos suites — a site that no test ever names is a
hook nothing would notice breaking (the hook call could be deleted and
the suite would stay green). This test greps the test tree itself so
adding a site to ``SITES`` without a drill fails CI immediately, and so
does deleting the drill that covered an existing site.

Same spirit for ``KINDS``: every kind the injector can draw must appear
in at least one drill spec, or the kind's raise/corrupt path is dead
code as far as the suite is concerned.
"""

from __future__ import annotations

import pathlib

import pytest

from mmlspark_tpu.core.faults import KINDS, SITES

TESTS_DIR = pathlib.Path(__file__).resolve().parent
SELF = pathlib.Path(__file__).name


def _test_sources() -> dict[str, str]:
    out: dict[str, str] = {}
    for path in sorted(TESTS_DIR.glob("test_*.py")):
        if path.name == SELF:
            continue
        out[path.name] = path.read_text(encoding="utf-8")
    return out


@pytest.mark.parametrize("site", SITES)
def test_every_site_is_drilled(site: str) -> None:
    """Each hook point in SITES is named by at least one other test
    (a Fault(...) schedule, a parse_fault_spec string, or a hook-call
    assertion) — deleting a site's only drill breaks this, not just
    silently shrinking coverage."""
    hits = [name for name, src in _test_sources().items() if site in src]
    assert hits, (
        f"fault site {site!r} is not exercised by any test under "
        f"tests/ — add a drill before relying on the hook"
    )


@pytest.mark.parametrize("kind", KINDS)
def test_every_kind_is_drilled(kind: str) -> None:
    """Each injectable kind appears in at least one drill spec."""
    hits = [name for name, src in _test_sources().items() if kind in src]
    assert hits, (
        f"fault kind {kind!r} is not exercised by any test under "
        f"tests/ — add a drill before relying on the kind"
    )


def test_sites_and_kinds_are_stable_contracts() -> None:
    """The tuples this meta-test iterates must keep the entries the
    runtime wires (a rename here must be a deliberate, grep-visible
    change across the chaos suites)."""
    assert set(SITES) >= {
        "serve.prefill", "serve.decode", "serve.snapshot",
        "serve.handoff", "train.step", "train.checkpoint",
        "train.restore",
    }
    assert set(KINDS) >= {"transient", "oom", "stall", "kill",
                          "poison", "corrupt"}
