"""Unit tests for bench.py's emission envelope (no backend needed).

The envelope is the part the driver depends on when everything else goes
wrong (BENCH_r01-r03 all failed differently), so its rules are pinned
directly: headline-value provenance, failure classification, smoke-mode
labeling, and scratch persistence.
"""

import importlib
import json
import os
import sys


def _bench(monkeypatch, tmp_path, **env):
    monkeypatch.setenv("MMLTPU_BENCH_SCRATCH", str(tmp_path / "scratch.json"))
    monkeypatch.delenv("MMLTPU_BENCH_CPU_SMOKE", raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    import bench

    return importlib.reload(bench)


def test_headline_null_unless_tpu_provenance(monkeypatch, tmp_path):
    bench = _bench(monkeypatch, tmp_path)
    cpu = bench._final_line(
        {"images_per_sec_per_chip": 700.0,
         "group_backends": {"inference": "cpu"}},
        attempt=1,
    )
    assert cpu["value"] is None
    assert cpu["images_per_sec_per_chip"] == 700.0  # stays in the body

    tpu = bench._final_line(
        {"images_per_sec_per_chip": 427020.0,
         "group_backends": {"inference": "tpu"}},
        attempt=1,
    )
    assert tpu["value"] == 427020.0
    assert "images_per_sec_per_chip" not in tpu or tpu["value"] is not None


def test_smoke_mode_scale_labels(monkeypatch, tmp_path):
    bench = _bench(monkeypatch, tmp_path, MMLTPU_BENCH_CPU_SMOKE="1")
    smoke = bench._final_line(
        {"images_per_sec_per_chip": 700.0,
         "group_backends": {"inference": "cpu"}},
        attempt=3, error="backend probe failed: probe hung >60s",
    )
    assert smoke["scale"] == "cpu_smoke"
    assert smoke["value"] is None
    assert smoke["error_class"] == "backend_unreachable"

    partial = bench._final_line(
        {"images_per_sec_per_chip": 427020.0,
         "group_backends": {"inference": "tpu", "train": "cpu"}},
        attempt=3, error="TPU unreachable",
    )
    assert partial["scale"] == "partial_tpu_then_cpu_smoke"
    assert partial["value"] == 427020.0


def test_error_classifier(monkeypatch, tmp_path):
    bench = _bench(monkeypatch, tmp_path)
    for err, cls in [
        ("backend init hung for 900s (watchdog)", "backend_unreachable"),
        ("backend probe failed: spawn error", "backend_unreachable"),
        ("RPC UNAVAILABLE: relay", "backend_unreachable"),
        ("TPU unreachable", "backend_unreachable"),
        ("TypeError: bad shape", "bench_failure"),
    ]:
        line = bench._final_line({}, attempt=3, error=err)
        assert line["error_class"] == cls, (err, line["error_class"])


def test_probe_key_dropped_on_success_kept_on_failure(monkeypatch, tmp_path):
    bench = _bench(monkeypatch, tmp_path)
    ok = bench._final_line({"probe": "1 tpu TPU v5 lite"}, attempt=1)
    assert "probe" not in ok
    bad = bench._final_line(
        {"probe": "probe hung >60s"}, attempt=2, error="x failed"
    )
    assert bad["probe"] == "probe hung >60s"


def test_scratch_merge_roundtrip_and_missing_groups(monkeypatch, tmp_path):
    bench = _bench(monkeypatch, tmp_path)
    merged = bench._scratch_merge({"images_per_sec_per_chip": 1.0, "mfu": 0.1})
    assert bench._group_done(merged, "inference")
    assert not bench._group_done(merged, "flash")
    line = bench._final_line(bench._scratch_load(), attempt=1)
    assert set(line["missing_metrics"]) == {
        "stage", "resnet50", "train", "trees", "flash", "flash_long",
        "int8_serving", "feed_synth", "decode", "serve", "serve_paged",
        "serve_int8", "serve_sharded", "serve_faults", "serve_supervisor",
        "serve_disagg", "serve_multimodel", "serve_chunked",
        "train_resilience", "integrity",
    }
    # merge is a real file round-trip: a fresh load sees the update
    with open(os.environ["MMLTPU_BENCH_SCRATCH"], encoding="utf-8") as f:
        assert json.load(f)["mfu"] == 0.1


def test_chained_op_seconds_contract(monkeypatch, tmp_path):
    """The dispatch-cancelling timing harness (shared with
    tools/flash_tpu_evidence.py) returns positive per-iteration seconds
    plus a fallback flag, and traces the step per chain — not per
    iteration (the chained iterations live inside one lax.scan)."""
    bench = _bench(monkeypatch, tmp_path)
    import jax
    import jax.numpy as jnp

    q = jnp.ones((1, 8, 1, 4), jnp.float32)
    k = v = q
    calls = []

    def step(qq, k, v):
        calls.append(1)
        return qq * 2.0

    secs, fell_back = bench._chained_op_seconds(
        jax, jnp, step, q, k, v, n1=2, n2=4, trials=1
    )
    assert secs > 0 and isinstance(fell_back, bool)
    # per chain (2 chains), never per iteration (n1 + n2 = 6); exact
    # trace counts are JAX-internal, so only the upper bound is pinned
    assert len(calls) < 6


def test_final_stdout_line_is_compact_json(monkeypatch, tmp_path, capsys):
    """The PRINTED terminal line must parse as JSON and stay under the
    compact budget even when the full payload is enormous (the driver's
    bounded tail capture truncates long lines to null) — with the full
    payload written next to bench.py as BENCH_FULL.json."""
    bench = _bench(monkeypatch, tmp_path)
    monkeypatch.setenv(
        "MMLTPU_BENCH_FULL_PATH", str(tmp_path / "BENCH_FULL.json")
    )
    # a deliberately bloated payload: per-group dumps far past the limit
    results = {
        "images_per_sec_per_chip": 427020.0,
        "group_backends": {"inference": "tpu"},
        "group_seconds": {g: 12.3456789 for g in bench._GROUPS},
        "decode": {
            "kv_vs_recompute_speedup": 3.1,
            "decode_blocks": {"speedup_t8_vs_t1": 2.4},
            "blob": ["x" * 64] * 64,
        },
        "serve": {"tokens_per_sec": 512.5, "blob": ["y" * 64] * 64},
    }
    line = bench._final_line(results, attempt=1)
    assert len(json.dumps(line).encode()) > bench._COMPACT_LIMIT_BYTES
    assert bench._emit(line) is True
    out = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(out)  # valid JSON ...
    assert len(out.encode()) < 1500  # ... under the tail-capture budget
    assert parsed["value"] == 427020.0
    assert parsed["full"] == "BENCH_FULL.json"
    assert "group_seconds" in parsed
    # headline figures surface speedups/throughput without the blobs
    assert any("speedup" in k for k in parsed.get("headlines", {}))
    # the full payload survives intact on disk
    with open(tmp_path / "BENCH_FULL.json", encoding="utf-8") as f:
        full = json.load(f)
    assert full["decode"]["blob"][0] == "x" * 64
    # exactly-once: a second emit is a no-op
    assert bench._emit(line) is False


def test_compact_line_sheds_until_under_budget(monkeypatch, tmp_path):
    """Progressive shedding: even a pathological error string cannot
    push the compact line past the budget."""
    bench = _bench(monkeypatch, tmp_path)
    line = bench._final_line(
        {"group_seconds": {f"g{i}": 1.0 for i in range(40)}},
        attempt=3, error="E" * 5000,
    )
    compact = bench._compact_line(line)
    assert len(json.dumps(compact).encode()) <= bench._COMPACT_LIMIT_BYTES
    assert compact["error"].startswith("E")
    assert compact["error_class"] == "bench_failure"


def test_vs_baseline_is_own_committed_record(monkeypatch, tmp_path):
    """The reference publishes no numbers, so vs_baseline is the ratio
    against the repo's newest committed BENCH_LOCAL_r*.json headline —
    picked NUMERICALLY (r10 > r4), labeled by source, computed only for
    a TPU-provenance headline, and never able to break emission."""
    import json as _json

    bench = _bench(monkeypatch, tmp_path)
    # controlled record dir: point the module at tmp_path
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    (tmp_path / "BENCH_LOCAL_r4.json").write_text(
        _json.dumps({"value": 1.0e6}))
    (tmp_path / "BENCH_LOCAL_r10.json").write_text(
        _json.dumps({"value": 2.0e6}))
    line = bench._final_line(
        {"images_per_sec_per_chip": 3.0e6,
         "group_backends": {"inference": "tpu"}},
        attempt=1,
    )
    assert line["vs_baseline"] == 1.5  # vs r10 (numeric sort), not r4
    assert "BENCH_LOCAL_r10" in line["vs_baseline_source"]
    # CPU provenance nulls the headline -> no baseline ratio either
    cpu_line = bench._final_line(
        {"images_per_sec_per_chip": 700.0,
         "group_backends": {"inference": "cpu"}},
        attempt=1,
    )
    assert cpu_line["value"] is None
    assert cpu_line["vs_baseline"] is None
    # a malformed record must not break emission
    (tmp_path / "BENCH_LOCAL_r11.json").write_text('{"value": "junk"}')
    ok = bench._final_line(
        {"images_per_sec_per_chip": 3.0e6,
         "group_backends": {"inference": "tpu"}},
        attempt=1,
    )
    assert ok["value"] == 3.0e6  # emission survived
    null_line = bench._final_line({}, attempt=1)
    assert null_line["vs_baseline"] is None
    assert "vs_baseline_source" not in null_line
