"""TPUModel inference stage tests (reference behavior: CNTKModelSuite +
fuzzing serialization invariants for the DNN stage)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models import build_model
from mmlspark_tpu.stages.dnn_model import TPUModel


@pytest.fixture(scope="module")
def mlp_model():
    g = build_model("mlp", num_outputs=3, hidden=(8,))
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))
    return TPUModel.from_graph(
        g, v, "mlp",
        model_config={"num_outputs": 3, "hidden": (8,)},
        input_col="features", output_col="scores", batch_size=4,
    )


def _feature_ds(n=10, d=4, seed=0):
    rng = np.random.default_rng(seed)
    return Dataset({"features": rng.normal(size=(n, d)).astype(np.float64),
                    "idx": np.arange(n)})


def test_batched_inference_matches_direct(mlp_model):
    ds = _feature_ds(n=10)
    out = mlp_model.transform(ds)
    assert out["scores"].shape == (10, 3)
    # row count not divisible by batch_size=4 -> padding trimmed correctly
    direct = mlp_model.graph().apply(
        mlp_model.weights, jnp.asarray(ds["features"], jnp.float32)
    )
    np.testing.assert_allclose(out["scores"], np.asarray(direct), rtol=2e-2,
                               atol=1e-2)
    # input dataset columns preserved
    assert list(out["idx"]) == list(range(10))


def test_batch_invariance(mlp_model):
    """Same rows, different batch sizes -> same scores (the reference's
    minibatch semantics: batching is an execution detail)."""
    ds = _feature_ds(n=7)
    a = mlp_model.copy().set(batch_size=2).transform(ds)["scores"]
    b = mlp_model.copy().set(batch_size=16).transform(ds)["scores"]
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=1e-2)


def test_bf16_feed_matches_f32(mlp_model):
    """feed_dtype='bfloat16' halves host->HBM bytes; scores stay within
    bf16 input-quantization tolerance of the f32 feed, padding included
    (n=10 not divisible by batch 4)."""
    ds = _feature_ds(n=10)
    f32 = mlp_model.transform(ds)["scores"]
    bf16 = mlp_model.copy().set(feed_dtype="bfloat16").transform(ds)["scores"]
    assert bf16.shape == f32.shape
    np.testing.assert_allclose(bf16, f32, rtol=3e-2, atol=3e-2)


def test_bf16_feed_leaves_token_inputs_alone():
    """Integer (token) columns must not be cast to bfloat16. The model is
    an embedding lookup (transformer), so a wrongly-cast float index
    batch raises inside jnp.take — the guard is regression-detectable,
    not just shape-checked."""
    cfg = {"vocab_size": 16, "d_model": 8, "heads": 2, "depth": 1,
           "max_len": 3}
    g = build_model("transformer_lm", **cfg)
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 3), jnp.int32))
    stage = TPUModel.from_graph(
        g, v, "transformer_lm", model_config=cfg,
        input_col="tokens", batch_size=4, feed_dtype="bfloat16",
        data_parallel=False,
    )
    ds = Dataset({"tokens": np.arange(18).reshape(6, 3) % 16})  # int input
    out = stage.transform(ds)
    assert out["scores"].shape == (6, 3, 16)


def test_output_node_cut(mlp_model):
    ds = _feature_ds(n=5)
    headless = mlp_model.copy().set(output_node="hidden1")
    out = headless.transform(ds)
    assert out["scores"].shape == (5, 8)  # hidden activations as features


def test_object_vector_column_coerced(mlp_model):
    ds = Dataset({"features": [np.zeros(4), np.ones(4), np.full(4, 2.0)]})
    out = mlp_model.transform(ds)
    assert out["scores"].shape == (3, 3)


def test_missing_weights_friendly_error():
    stage = TPUModel(model_name="mlp", input_col="features")
    with pytest.raises(FriendlyError):
        stage.transform(_feature_ds())


def test_ragged_input_friendly_error(mlp_model):
    ds = Dataset({"features": [np.zeros(3), np.zeros(4)]})
    with pytest.raises(FriendlyError):
        mlp_model.transform(ds)


def test_round_trip_identical_scores(tmp_path, mlp_model):
    ds = _feature_ds(n=6)
    mlp_model.save(str(tmp_path / "m"))
    loaded = PipelineStage.load(str(tmp_path / "m"))
    np.testing.assert_allclose(
        loaded.transform(ds)["scores"], mlp_model.transform(ds)["scores"]
    )


def test_set_model_location(tmp_path, mlp_model):
    mlp_model.save(str(tmp_path / "loc"))
    fresh = TPUModel(input_col="features", output_col="scores",
                     model_name="mlp").set_model_location(str(tmp_path / "loc"))
    out = fresh.transform(_feature_ds(n=3))
    assert out["scores"].shape == (3, 3)


def test_resnet_inference_sharded_over_mesh():
    """CIFAR-shaped end-to-end inference across the 8-device CPU mesh."""
    g = build_model("resnet20_cifar10", width=8)
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    stage = TPUModel.from_graph(
        g, v, "resnet20_cifar10", model_config={"width": 8},
        input_col="image", output_col="scores", batch_size=16,
    )
    rng = np.random.default_rng(0)
    ds = Dataset({"image": rng.normal(size=(10, 32, 32, 3)).astype(np.float32)})
    out = stage.transform(ds)
    assert out["scores"].shape == (10, 10)
    preds = np.argmax(out["scores"], axis=1)
    assert preds.shape == (10,)
