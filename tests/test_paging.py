"""Paged KV-cache subsystem (ISSUE 9 tentpole).

The contract under test (docs/SERVING.md "Paged KV cache"):
``PagedCachePool`` virtualizes the dense slot pool's worst-case slabs
behind fixed-shape page stores and per-slot page tables, and NOTHING
the serving engine guarantees moves: greedy token streams stay
bit-identical to the dense pool (which is itself pinned byte-identical
to ``generate()``), the compile-count pins hold, page pressure walks
the PR 7 degradation ladder instead of crashing, and every terminal
status — completed, expired, quarantined — returns its pages. The
prefix cache prefills a shared prompt header ONCE, maps it refcounted
into later slots, and copy-on-extends the moment a write frontier
enters a shared page. Runs on the 8 virtual CPU devices
``tests/conftest.py`` forces.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import Fault, FaultInjector, ResourceExhausted
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.parallel.mesh import make_mesh
from mmlspark_tpu.serve import ServeEngine
from mmlspark_tpu.serve.paging import (
    MIN_PAGE_SIZE,
    PagedCachePool,
    default_page_size,
)
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4

TERMINAL = {"completed", "expired", "failed", "stalled"}


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new, eos_id=None):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new,
                   eos_id=eos_id)
    return np.asarray(out)[0]


def _pool(m, v, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("cache_len", 32)
    return PagedCachePool(m, v, **kw)


def _entry_pages(pool) -> int:
    """Distinct pages the prefix-cache entries keep pinned — what a
    drained pool legitimately holds back from the free list."""
    pages: set[int] = set()
    for e in pool.snapshot()["prefix_entries"]:
        pages.update(e["pages"])
    return len(pages)


def _fake_prefill(pool, length, seed=0):
    """A synthetic batch-1 linear cache — deterministic values so page
    scatter/gather round-trips are content-checkable without a model."""
    rng = np.random.default_rng(seed)
    cache = {}
    for name, (pk, _pv, _pt) in pool.buffers.items():
        hk, d = pk.shape[1], pk.shape[3]
        k = rng.normal(size=(1, length, hk, d)).astype(np.float32)
        v = rng.normal(size=(1, length, hk, d)).astype(np.float32)
        cache[name] = (jnp.asarray(k, jnp.bfloat16),
                       jnp.asarray(v, jnp.bfloat16))
    return cache


# -- page geometry ---------------------------------------------------------


def test_default_page_size():
    assert default_page_size(64) == 8
    assert default_page_size(32) == 8
    assert default_page_size(40) == 8
    assert default_page_size(8) == 8
    assert default_page_size(48) == 8
    for cl in (16, 24, 48, 96, 80):
        ps = default_page_size(cl)
        assert ps >= MIN_PAGE_SIZE and cl % ps == 0
        assert ps % MIN_PAGE_SIZE == 0  # the kernel's sublane contract
    # no multiple of 8 divides these: refuse at BUILD time — the old
    # behavior returned e.g. 10 for 20 and every paged decode dispatch
    # then died on the kernel's sublane check
    for cl in (20, 36, 100):
        with pytest.raises(FriendlyError, match="multiple"):
            default_page_size(cl)


def test_pool_and_engine_flag_validation(lm):
    m, v, _ = lm
    with pytest.raises(FriendlyError, match="page_size"):
        _pool(m, v, page_size=4)
    with pytest.raises(FriendlyError, match="multiple"):
        _pool(m, v, page_size=12)  # not sublane-tileable by the kernel
    with pytest.raises(FriendlyError, match="divide"):
        _pool(m, v, page_size=24)  # 24 does not divide 32
    with pytest.raises(FriendlyError, match="multiple"):
        _pool(m, v, cache_len=20)  # no valid default page size
    with pytest.raises(FriendlyError, match="trash page"):
        _pool(m, v, num_pages=1)
    # paging knobs without paged=True must refuse loudly, not silently
    # serve dense
    with pytest.raises(FriendlyError, match="paged=True"):
        ServeEngine(m, v, slots=2, cache_len=32, page_size=8)
    with pytest.raises(FriendlyError, match="paged=True"):
        ServeEngine(m, v, slots=2, cache_len=32, prefix_cache=True)


# -- host allocator invariants ---------------------------------------------


def test_alloc_refcount_free_and_double_free(lm):
    m, v, _ = lm
    pool = _pool(m, v)  # page_size 8, default worst-case budget
    assert pool.pages_free == pool.pages_allocatable
    slot = pool.lease()
    pool.write_prefill(slot, _fake_prefill(pool, 12), 12)
    snap = pool.snapshot()
    assert snap["npages"][slot] == 2  # ceil(12 / 8)
    mapped = snap["page_table"][slot][:2]
    assert all(snap["refcounts"][p] == 1 for p in mapped)
    assert pool.pages_free == pool.pages_allocatable - 2
    pool.free(slot)
    assert pool.pages_free == pool.pages_allocatable
    assert sum(pool.snapshot()["refcounts"]) == 0
    with pytest.raises(FriendlyError, match="not leased"):
        pool.free(slot)  # double free
    with pytest.raises(FriendlyError, match="underflow"):
        pool._decref(mapped[0])  # page already back on the free list


def test_freed_rows_point_at_the_trash_page(lm):
    m, v, _ = lm
    pool = _pool(m, v)
    slot = pool.lease()
    pool.write_prefill(slot, _fake_prefill(pool, 9), 9)
    assert any(p != 0 for p in pool.snapshot()["page_table"][slot])
    pool.free(slot)
    # every entry of the freed row absorbs dead-row writes harmlessly
    assert all(p == pool._trash_page(0)
               for p in pool.snapshot()["page_table"][slot])


def test_page_scatter_gather_roundtrip(lm):
    """write_prefill's paged scatter and gather_prefix's linearization
    are exact inverses — the resume path feeds the prefill program the
    same bytes the original prefill produced."""
    m, v, _ = lm
    pool = _pool(m, v, prefix_cache=True)
    cache = _fake_prefill(pool, 14, seed=3)
    seq = np.arange(14, dtype=np.int32) % 8
    slot = pool.lease()
    pool.write_prefill(slot, cache, 14)
    pool.prefix_insert(slot, seq)
    hit = pool.prefix_lookup(seq, bucket_fn=lambda n: n)
    assert hit is not None
    entry, keep = hit
    assert keep == 13  # full prefix minus the one remainder token
    lin = pool.gather_prefix(entry, keep)
    for name, (ck, cv) in cache.items():
        gk, gv = lin[name]
        np.testing.assert_array_equal(
            np.asarray(gk[0, :keep], np.float32),
            np.asarray(ck[0, :keep], np.float32), err_msg=name)
        np.testing.assert_array_equal(
            np.asarray(gv[0, :keep], np.float32),
            np.asarray(cv[0, :keep], np.float32), err_msg=name)


def test_pool_exhaustion_raises_resource_exhausted(lm):
    m, v, _ = lm
    pool = _pool(m, v, num_pages=4)  # 1 trash + 3 allocatable
    slot = pool.lease()
    with pytest.raises(ResourceExhausted, match="exhausted"):
        pool.write_prefill(slot, _fake_prefill(pool, 32), 32)  # 4 pages
    # pages mapped before the failure stay accounted to the slot, so
    # freeing it leaks nothing
    assert pool.pages_free == 0
    pool.free(slot)
    assert pool.pages_free == pool.pages_allocatable


def test_map_prefix_stale_entry_refuses_resurrection(lm):
    """The resume-retry hazard: attempt 1 maps a prefix entry, the
    remainder write's page pressure evicts that very entry, and the
    retry re-enters map_prefix. The stale re-map must map NOTHING and
    return False (the engine then falls back to a full prefill) — the
    old path released the slot's references, dropped the pages onto the
    free list, and re-mapped them anyway, leaving a page mapped and
    allocatable at once."""
    m, v, _ = lm
    pool = _pool(m, v, prefix_cache=True)
    seq = np.arange(14, dtype=np.int32) % 8
    s0 = pool.lease()
    pool.write_prefill(s0, _fake_prefill(pool, 14), 14)
    pool.prefix_insert(s0, seq)
    pool.free(s0)
    entry, keep = pool.prefix_lookup(seq, bucket_fn=lambda n: n)
    s1 = pool.lease()
    assert pool.map_prefix(s1, entry, keep) is True  # attempt 1
    # mid-attempt eviction, exactly as _evict_prefix_entries does it:
    # the entry leaves the cache and drops its page references (the
    # pages survive on slot 1's references alone)
    assert pool._prefix.pop(seq.tobytes()) is entry
    for page in entry.pages:
        pool._decref(page)
    assert pool.map_prefix(s1, entry, keep) is False  # stale retry
    # invariant: no page is simultaneously mapped and on a free list
    snap = pool.snapshot()
    free = {p for f in pool._free_pages for p in f}
    for s in range(pool.num_slots):
        mapped = set(snap["page_table"][s][:snap["npages"][s]])
        assert not (free & mapped)
    # slot 1 kept its attempt-1 mappings; retirement returns every
    # page without a refcount underflow
    pool.free(s1)
    assert pool.pages_free == pool.pages_allocatable
    assert sum(pool.snapshot()["refcounts"]) == 0


# -- shard locality under a mesh -------------------------------------------


def test_prefix_eviction_is_shard_local(lm):
    """Pressure on one data shard evicts only that shard's prefix
    entries: evicting another shard's entry frees nothing on the
    pressured shard, so the old global-LRU sweep wiped unrelated
    shards' cached prefixes and still exhausted."""
    m, v, _ = lm
    pool = PagedCachePool(m, v, slots=4, cache_len=32,
                          mesh=make_mesh({"data": 2}), num_pages=6,
                          prefix_cache=True)
    # per shard: 1 trash + 2 allocatable pages
    s0, s1, s2 = pool.lease(), pool.lease(), pool.lease()
    a = np.arange(16, dtype=np.int32) % 8
    b = (a + 1) % 8
    pool.write_prefill(s0, _fake_prefill(pool, 16, seed=1), 16)  # shard 0
    pool.prefix_insert(s0, a)
    pool.write_prefill(s2, _fake_prefill(pool, 16, seed=2), 16)  # shard 1
    pool.prefix_insert(s2, b)
    for s in (s0, s1, s2):
        pool.free(s)
    assert pool.pages_free == 0  # both shards fully pinned by entries
    p0 = pool._alloc_page(0)  # pressure on shard 0
    assert pool.prefix_evictions == 1
    assert pool._shard_of_page(p0) == 0
    p1 = pool._alloc_page(0)  # the evicted entry's second page
    # nothing local left to evict: raise rather than wipe shard 1
    with pytest.raises(ResourceExhausted, match="exhausted"):
        pool._alloc_page(0)
    snap = pool.snapshot()
    assert [e["prompt"] for e in snap["prefix_entries"]] == [b.tolist()]
    pool._decref(p0)
    pool._decref(p1)
    assert pool.pages_free == pool.pages_allocatable - _entry_pages(pool)


def test_prefix_cross_shard_hit_copies_pages_local(lm):
    """A hit from a slot on another data shard localizes the entry's
    pages by copy instead of mapping them remotely — the per-page
    placement contract (every page a slot maps lives on the slot's
    shard) holds, the bytes match, and the entry's own pages are
    untouched."""
    m, v, _ = lm
    pool = PagedCachePool(m, v, slots=4, cache_len=32,
                          mesh=make_mesh({"data": 2}),
                          prefix_cache=True)
    seq = np.arange(12, dtype=np.int32) % 8
    s0 = pool.lease()  # slot 0 -> shard 0
    pool.write_prefill(s0, _fake_prefill(pool, 12, seed=5), 12)
    pool.prefix_insert(s0, seq)
    s1, s2 = pool.lease(), pool.lease()  # slot 2 -> shard 1
    hit = pool.prefix_lookup(seq, bucket_fn=lambda n: n, slot=s2)
    assert hit is not None
    entry, keep = hit
    assert pool.map_prefix(s2, entry, keep) is True
    n = -(-keep // pool.page_size)
    snap = pool.snapshot()
    mapped = snap["page_table"][s2][:n]
    lo = pool._pages_per_shard
    assert all(lo <= pg < 2 * lo for pg in mapped), mapped
    assert pool.prefix_shard_copies == n
    for name, (pk, pv, _pt) in pool.buffers.items():
        for i, pg in enumerate(mapped):
            src = entry.pages[i]
            np.testing.assert_array_equal(
                np.asarray(pk[pg], np.float32),
                np.asarray(pk[src], np.float32), err_msg=name)
            np.testing.assert_array_equal(
                np.asarray(pv[pg], np.float32),
                np.asarray(pv[src], np.float32), err_msg=name)
    # localized copies are private (refcount 1), the entry's pages
    # keep only their original references
    assert all(snap["refcounts"][pg] == 1 for pg in mapped)
    for s in (s2, s1, s0):
        pool.free(s)
    assert pool.pages_free == pool.pages_allocatable - _entry_pages(pool)


# -- engine parity: paged == dense == generate() ---------------------------


@pytest.mark.slow  # ci.sh's paged gate runs the full file unfiltered
def test_paged_parity_ragged_prompts_and_joins(lm):
    """The dense-pool oracle: the SAME raggedy mid-run-join soak the
    dense engine pins against ``generate()``, through the paged pool —
    token streams byte-identical, compile pins intact, and the drained
    pool page-leak-free."""
    m, v, ids = lm
    lengths = [4, 1, 12, 7, 8, 3, 10, 2, 5, 9]
    prompts = [np.asarray(ids[0, :n]) for n in lengths]
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=16,
                         paged=True)
    assert engine.pool.page_size == 8
    rids, results = [], {}
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        for i, p in enumerate(prompts):
            rids.append(engine.submit(p, max_new_tokens=4))
            if i % 2:
                results.update({r.id: r for r in engine.step()})
        results.update(engine.run())
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 4),
            err_msg=f"request={rid}")
    assert engine.decode_compile_count <= engine.num_decode_blocks
    assert engine.prefill_compile_count <= engine.num_prefill_buckets
    # every retired request returned its pages
    assert engine.pool.pages_free == engine.pool.pages_allocatable
    d = engine.metrics.to_dict()
    assert d["page_size"] == 8 and d["pages_total"] > 0
    assert d["page_utilization"] == 0.0  # drained


@pytest.mark.slow  # ci.sh's paged gate runs the full file unfiltered
def test_mid_block_eos_paged(lm):
    """A request dying mid-block releases its pages and matches
    ``generate()`` with the same eos_id byte for byte."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :3])
    free_run = _ref(m, v, prompt, 12)
    eos = int(free_run[len(prompt) + 2])
    want = _ref(m, v, prompt, 12, eos_id=eos)
    stop = len(prompt) + int(np.argmax(want[len(prompt):] == eos))
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=4,
                         decode_block=8, paged=True)
    rid = engine.submit(prompt, max_new_tokens=12, eos_id=eos)
    results = engine.run()
    np.testing.assert_array_equal(
        np.asarray(results[rid].tokens), want[:stop + 1])
    assert engine.pool.pages_free == engine.pool.pages_allocatable


# -- prefix cache + copy-on-extend -----------------------------------------


@pytest.mark.slow  # ci.sh's paged gate runs the full file unfiltered
def test_prefix_cache_hit_and_copy_on_extend(lm):
    """Two prompts sharing a 10-token prefix: the second prefills only
    the remainder off the cached pages, copy-on-extends the shared
    partial page when its own writes land, and still matches
    ``generate()`` byte for byte — as does a later exact re-ask of the
    first prompt, proving the cached entry survived the divergence
    untouched."""
    m, v, ids = lm
    a = np.asarray(ids[0, :12])
    b = np.concatenate([a[:10], (a[10:12] + 1) % 8]).astype(np.int32)
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=4,
                         decode_block=4, paged=True, prefix_cache=True)
    ra = engine.submit(a, max_new_tokens=6)
    results = engine.run()
    assert engine.pool.prefix_hits == 0  # first ask is the miss
    rb = engine.submit(b, max_new_tokens=6)
    results.update(engine.run())
    ra2 = engine.submit(a, max_new_tokens=6)
    results.update(engine.run())
    np.testing.assert_array_equal(
        np.asarray(results[ra].tokens), _ref(m, v, a, 6))
    np.testing.assert_array_equal(
        np.asarray(results[rb].tokens), _ref(m, v, b, 6))
    np.testing.assert_array_equal(
        np.asarray(results[ra2].tokens), _ref(m, v, a, 6))
    stats = engine.pool.paging_stats()
    assert stats["prefix_cache_hits_total"] == 2
    assert stats["cow_copies_total"] >= 1  # b's writes entered page 1
    assert stats["prefix_tokens_saved_total"] >= 10
    assert stats["prefix_cache_entries"] >= 1
    # the resume program compiled at most once per remainder bucket
    assert engine.resume_compile_count <= engine.num_prefill_buckets


@pytest.mark.slow  # ci.sh's paged gate runs the full file unfiltered
def test_prefix_shared_header_prefills_once(lm):
    """A batch of prompts sharing one header: prefill work lands once
    per UNIQUE prefix — every later admit is a hit (> 0 hit rate) and
    every stream still matches ``generate()``."""
    m, v, ids = lm
    header = np.asarray(ids[0, :9])
    tails = [np.asarray(ids[0, 9:9 + n]) for n in (1, 2, 3, 1)]
    prompts = [np.concatenate([header, (t + i) % 8]).astype(np.int32)
               for i, t in enumerate(tails)]
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=4, paged=True, prefix_cache=True)
    rids = [engine.submit(p, max_new_tokens=4) for p in prompts]
    results = engine.run()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 4),
            err_msg=f"request={rid}")
    assert engine.pool.prefix_hits >= len(prompts) - 1
    assert engine.metrics.to_dict()["prefix_cache_hits_total"] >= 3


# -- page pressure: the PR 7 degradation ladder ----------------------------


@pytest.mark.slow  # ci.sh's paged gate runs the full file unfiltered
def test_page_pressure_degrades_and_still_completes(lm):
    """A page budget too small for the offered concurrency: allocator
    exhaustion surfaces as RESOURCE_EXHAUSTED inside the engine's fault
    envelope and walks the existing ladder (shrink blocks, preempt,
    tighten admission) — every request still completes with
    ``generate()``-exact tokens, and the drained pool leaks nothing."""
    m, v, ids = lm
    prompts = [np.asarray(ids[0, :n]) for n in (8, 7, 6, 5)]
    # each request spans ceil((8 + 8) / 8) = 2 pages; 3 allocatable
    # pages cannot hold two tenants at once
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=4, paged=True, num_pages=4,
                         retry_backoff_s=0.0)
    rids = [engine.submit(p, max_new_tokens=8) for p in prompts]
    results = engine.run()
    for rid, p in zip(rids, prompts):
        assert results[rid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 8),
            err_msg=f"request={rid}")
    d = engine.metrics.to_dict()
    assert d["preemptions_total"] + d["degraded_mode"] >= 1
    assert engine.pool.pages_free == engine.pool.pages_allocatable


@pytest.mark.slow  # ci.sh's paged gate runs the full file unfiltered
def test_quarantine_returns_pages(lm):
    """Leak-on-quarantine guard: a poisoned request retires as 'failed'
    and its pages go back on the free list like any other retirement."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.prefill", "poison", request=0)])
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=4,
                         paged=True, faults=inj, retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (6, 4, 9)]
    rids = [engine.submit(p, max_new_tokens=4) for p in prompts]
    results = engine.run()
    assert results[rids[0]].status == "failed"
    assert engine.metrics.quarantined_total == 1
    for rid, p in zip(rids[1:], prompts[1:]):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 4))
    assert engine.pool.pages_free == engine.pool.pages_allocatable
    assert sum(engine.pool.snapshot()["refcounts"]) == 0


# -- snapshot / restore ----------------------------------------------------


@pytest.mark.slow  # ci.sh's paged gate runs the full file unfiltered
def test_snapshot_restore_roundtrip_paged(lm):
    """Mid-run checkpoint of a paged + prefix-cache engine: the paging
    plane rides in the snapshot and is internally consistent (refcount
    totals equal mapped-page references), and a restored engine
    finishes every request bit-identically to ``generate()``."""
    m, v, ids = lm
    prompts = [np.asarray(ids[0, :n]) for n in (9, 4, 11)]
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=2, paged=True, prefix_cache=True)
    rids = [engine.submit(p, max_new_tokens=6) for p in prompts]
    for _ in range(2):
        engine.step()
    snap = engine.snapshot()
    pg = snap["paging"]
    assert pg["page_size"] == 8
    refs = sum(pg["npages"]) + sum(
        len(e["pages"]) for e in pg["prefix_entries"])
    assert sum(pg["refcounts"]) == refs
    import json

    json.dumps(snap)  # the checkpoint must stay JSON-able
    rebuilt = ServeEngine.restore(snap, m, v, slots=2, decode_block=2,
                                  paged=True, prefix_cache=True)
    results = rebuilt.run()
    by_id = {r: res for r, res in results.items()}
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            np.asarray(by_id[rid].tokens), _ref(m, v, p, 6),
            err_msg=f"request={rid}")
    # drained up to the pages the prefix entries deliberately pin
    assert (rebuilt.pool.pages_free
            == rebuilt.pool.pages_allocatable - _entry_pages(rebuilt.pool))


# -- 2x2 mesh soak ---------------------------------------------------------


@pytest.mark.slow  # ci.sh's paged gate runs the full file unfiltered
def test_mesh_soak_paged_matches_dense_2x2(lm):
    """The sharded oracle: dense and paged engines on the SAME 2x2
    (data, model) mesh, same raggedy shared-prefix traffic with mid-run
    joins — token streams identical request for request, compile pins
    intact on the paged engine, prefix hits landing, and the
    workload-sized page budget strictly undercutting the dense pool's
    per-device bytes."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    header = row[:9]
    prompts = [row[:4], np.concatenate([header, row[9:10]]), row[:2],
               np.concatenate([header, (row[9:11] + 1) % 8]), row[:6]]
    prompts = [np.asarray(p, np.int32) for p in prompts]
    budgets = [6, 5, 4, 6, 5]

    def drive(**kw):
        engine = ServeEngine(m, v, slots=4, cache_len=32, max_queue=8,
                             decode_block=4, mesh="data=2,model=2", **kw)
        results, rids = {}, []
        with serve_compile_guard(engine, min_decode=1, min_prefill=1):
            for p, n in zip(prompts[:3], budgets[:3]):
                rids.append(engine.submit(p, max_new_tokens=n))
            for _ in range(2):
                results.update({r.id: r for r in engine.step()})
            for p, n in zip(prompts[3:], budgets[3:]):  # mid-run joins
                rids.append(engine.submit(p, max_new_tokens=n))
            while engine.busy:
                results.update({r.id: r for r in engine.step()})
        return engine, rids, results

    dense_eng, dense_rids, dense_res = drive()
    # budget sized to the workload (each request spans <= 2 pages of 8
    # across prompt+budget <= 16 positions), NOT the dense worst case
    paged_eng, paged_rids, paged_res = drive(
        paged=True, num_pages=14, prefix_cache=True)
    for dr, pr in zip(dense_rids, paged_rids):
        np.testing.assert_array_equal(
            np.asarray(paged_res[pr].tokens),
            np.asarray(dense_res[dr].tokens),
            err_msg=f"request={pr}")
    assert paged_eng.decode_compile_count <= paged_eng.num_decode_blocks
    assert paged_eng.prefill_compile_count <= paged_eng.num_prefill_buckets
    assert paged_eng.pool.prefix_hits >= 1
    assert (paged_eng.pool.device_bytes_per_device()
            < dense_eng.pool.device_bytes_per_device())
    # drained up to the pages the prefix entries deliberately pin
    assert (paged_eng.pool.pages_free
            == paged_eng.pool.pages_allocatable
            - _entry_pages(paged_eng.pool))
