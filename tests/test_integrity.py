"""End-to-end integrity plane (ISSUE 18 tentpole).

The contract under test (docs/OBSERVABILITY.md "Integrity",
docs/TRAINING.md "Integrity audits"): a seeded ``corrupt`` fault —
one deterministic bit-flip — injected at each wired site is DETECTED
within one audit interval, with zero false positives on clean runs:

* ``train.step`` — the in-graph param/opt-state checksum folded into
  the compiled step catches the divergent replica at the next audit
  boundary; the replica is quarantined (re-replicated from a majority
  device) and the deterministic replay adjudicates the verdict.
* ``train.checkpoint`` — the manifest's payload sha256 rejects a
  bit-flipped payload BEFORE orbax reads it (typed error naming both
  hashes); the previous committed checkpoint restores bit-identically
  (drilled in tests/test_train_resilience.py).
* ``serve.handoff`` — checksummed KV hand-off payloads are verified on
  adopt; a mismatch falls back to full local prefill, bit-identically.
* ``serve.snapshot`` — ``ServeEngine.restore()`` rejects a corrupted
  snapshot (typed error); failover falls back to a fresh engine and
  the streams stay bit-identical to ``generate()``.

The checksum primitives themselves are pinned first: the in-graph
device fold equals the host twin, and every single-bit flip changes
it. Serve compile pins and the one-host-sync-per-block contract hold
with integrity enabled.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from mmlspark_tpu.core import integrity
from mmlspark_tpu.core.faults import Fault, FaultInjector, parse_fault_spec
from mmlspark_tpu.core.integrity import (
    CheckpointCorruption,
    IntegrityError,
    SnapshotCorruption,
)
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.serve import DisaggFleet, ReplicaSet, ServeEngine
from mmlspark_tpu.testing.compile_guard import serve_compile_guard
from mmlspark_tpu.train.demo import run_train_demo

PERIOD = 4


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new)
    return np.asarray(out)[0]


def _assert_parity(m, v, results, gids, prompts, max_new):
    assert len(results) == len(gids)
    for gid, p in zip(gids, prompts):
        res = results[gid]
        assert res.status == "completed", f"gid={gid}: {res.status}"
        np.testing.assert_array_equal(
            np.asarray(res.tokens), _ref(m, v, p, max_new),
            err_msg=f"gid={gid}",
        )


def _demo_tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": rng.normal(size=(7, 5)).astype(np.float32),
        "b": rng.normal(size=(5,)).astype(np.float32),
        "emb": {"table": rng.normal(size=(11, 3)).astype(np.float32),
                "ids": np.arange(6, dtype=np.int32)},
    }


# -- checksum primitives ---------------------------------------------------


def test_device_fold_matches_host_twin():
    """The in-graph fold (jitted, uint32 carry) and the host-side
    numpy twin agree on arbitrary pytrees — the audit compares them
    directly, so this equality IS the zero-false-positive property."""
    tree = _demo_tree()
    dev = int(jax.jit(integrity.tree_checksum)(tree))
    assert dev == integrity.tree_checksum_host(tree)
    assert 0 <= dev < 2 ** 32


def test_every_single_bit_flip_changes_the_fold():
    """The fold's per-position multipliers are odd (invertible mod
    2^32): any single-bit flip in any leaf changes the checksum."""
    tree = _demo_tree()
    base = integrity.tree_checksum_host(tree)
    for seed in range(24):
        flipped = dict(tree)
        flipped["w"] = integrity.flip_bit_array(tree["w"], seed)
        assert integrity.tree_checksum_host(flipped) != base, seed
        assert not np.array_equal(flipped["w"], tree["w"])


def test_fold_order_sensitivity():
    """Identical bytes in swapped leaf positions fold differently —
    a transposed restore cannot alias a clean checksum."""
    a = {"x": np.ones((4,), np.float32), "y": np.zeros((4,), np.float32)}
    b = {"x": np.zeros((4,), np.float32), "y": np.ones((4,), np.float32)}
    assert integrity.tree_checksum_host(a) != integrity.tree_checksum_host(b)


def test_payload_checksum_verify_and_corrupt_cycle():
    """Hand-off payloads: stamp -> verify passes; seeded bit-flip ->
    verify names both digests; a stampless (pre-integrity) payload is
    accepted unverified for back-compat."""
    rng = np.random.default_rng(3)
    payload = {
        "prompt": np.arange(5, dtype=np.int32),
        "prefix": np.arange(5, 9, dtype=np.int32),
        "length": 9,
        "first_token": 3,
        "kv": {"k": rng.normal(size=(2, 4, 8)).astype(np.float32)},
    }
    payload["checksum"] = integrity.payload_checksum(payload)
    ok, expected, actual = integrity.verify_payload(payload)
    assert ok and expected == actual

    for seed in (0, 1, 17):
        bad = integrity.corrupt_payload(payload, seed)
        ok, expected, actual = integrity.verify_payload(bad)
        assert not ok
        assert expected == payload["checksum"] and actual != expected

    unstamped = {k: v for k, v in payload.items() if k != "checksum"}
    assert integrity.verify_payload(unstamped)[0]


def test_json_checksum_detects_snapshot_bit_flips():
    snap = {"version": 3, "tick": 41, "slots": [1, 0, 7],
            "nested": {"tokens": [5, 6, 7], "done": False}}
    snap["checksum"] = integrity.json_checksum(snap)
    assert integrity.json_checksum(snap) == snap["checksum"]
    for seed in (0, 5, 23):
        bad = integrity.flip_bit_json(snap, seed)
        assert integrity.json_checksum(bad) != bad["checksum"], seed


def test_typed_errors_name_both_hashes():
    e = CheckpointCorruption(7, expected="aa" * 32, actual="bb" * 32)
    assert isinstance(e, IntegrityError)
    assert e.step == 7
    assert "aa" * 32 in str(e) and "bb" * 32 in str(e)
    s = SnapshotCorruption(expected="cafe", actual="beef")
    assert isinstance(s, IntegrityError)
    assert "cafe" in str(s) and "beef" in str(s)


# -- corrupt fault kind (satellite: faults.py) -----------------------------


def test_corrupt_spec_round_trips_and_is_seeded():
    inj = parse_fault_spec("seed=3,train.step:corrupt=0.2")
    fires = {t: inj.corrupt_spec("train.step", tick=t) for t in range(6)}
    seeds = {t: s for t, s in fires.items() if s is not None}
    assert seeds, "the seeded rate stream must fire within 6 ticks"
    assert all(isinstance(s, int) for s in seeds.values())
    # the stream is deterministic: a fresh injector from the same spec
    # fires at the same ticks with the same seeds
    inj2 = parse_fault_spec("seed=3,train.step:corrupt=0.2")
    assert fires == {t: inj2.corrupt_spec("train.step", tick=t)
                     for t in range(6)}


def test_scheduled_corrupt_carries_its_value_as_seed():
    inj = FaultInjector([Fault("train.step", "corrupt", tick=2,
                               value=99)])
    assert inj.corrupt_spec("train.step", tick=0) is None
    assert inj.corrupt_spec("train.step", tick=2) == 99


# -- train.step: in-graph audit + quarantine + replay ----------------------


def test_train_step_corrupt_detected_within_one_audit_interval():
    """The headline train drill: seeded bit-flips on one replica's
    params are caught at the next audit boundary, the replica is
    quarantined and re-replicated from a majority device, and every
    suspicion gets a replay verdict."""
    out = run_train_demo(epochs=2, n_samples=96, batch_size=32,
                         seed=0, audit_every=2,
                         faults="seed=3,train.step:corrupt=0.2")
    assert out["faults_injected"].get("corrupt", 0) >= 1
    assert out["train.integrity.audits"] == 3  # 6 steps / audit_every=2
    assert out["train.integrity.sdc_suspected"] >= 1
    verdicts = out["replay_verdicts"]
    assert len(verdicts) == out["train.integrity.sdc_suspected"]
    for v in verdicts:
        assert v["verdict"] in ("transient_sdc",
                                "software_nondeterminism")
    adjudicated = (out["train.integrity.replay_transient_sdc"]
                   + out["train.integrity.replay_software_nondeterminism"])
    assert adjudicated == out["train.integrity.sdc_suspected"]
    # a step-level drill must not spill into the checkpoint surface
    assert out["train.integrity.checksum_failures"] == 0


def test_train_clean_soak_zero_false_positives():
    """50 audited steps with NO faults: every audit passes — the
    device fold and the host twin never disagree on a clean run."""
    out = run_train_demo(epochs=5, n_samples=80, batch_size=8,
                         seed=1, audit_every=4, checkpoint_every=0)
    assert out["steps_total"] == 50
    assert out["train.integrity.audits"] == 12  # floor(50 / 4)
    assert out["train.integrity.sdc_suspected"] == 0
    assert out["train.integrity.replay_transient_sdc"] == 0
    assert out["train.integrity.replay_software_nondeterminism"] == 0
    assert out["replay_verdicts"] == []


def test_train_audits_off_by_default():
    out = run_train_demo(epochs=2, n_samples=96, batch_size=32, seed=0)
    assert out["audit_every"] == 0
    assert out["train.integrity.audits"] == 0
    assert out["train.integrity.sdc_suspected"] == 0


# -- serve.handoff: checksummed hand-offs ----------------------------------


@pytest.mark.slow  # ci.sh's integrity gate runs the full file unfiltered
def test_handoff_corrupt_falls_back_bit_identically(lm):
    """A corrupted hand-off payload is rejected on adopt (digest
    mismatch), the decode replica re-prefills locally, and every
    stream stays bit-identical to ``generate()`` — under the compile
    pins."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.handoff", "corrupt", tick=0)])
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        slots=2, cache_len=32, max_queue=8,
                        decode_block=4, faults=inj,
                        retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    with serve_compile_guard(fleet.engine(0), min_prefill=1), \
            serve_compile_guard(fleet.engine(1), min_decode=1):
        gids = [fleet.submit(p, 6) for p in prompts]
        results = fleet.run()
    _assert_parity(m, v, results, gids, prompts, 6)
    md = fleet.metrics_dict()
    assert md["integrity_handoff_checksum_failures_total"] >= 1
    assert md["handoff_fallbacks_total"] >= 1
    assert md["integrity_snapshot_checksum_failures_total"] == 0


@pytest.mark.slow  # ci.sh's integrity gate runs the full file unfiltered
def test_handoff_clean_run_verifies_without_failures(lm):
    """Every adopted payload is verified; a clean run records zero
    checksum failures and zero fallbacks (no false positives)."""
    m, v, ids = lm
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        slots=2, cache_len=32, max_queue=8,
                        decode_block=4, retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7)]
    gids = [fleet.submit(p, 6) for p in prompts]
    results = fleet.run()
    _assert_parity(m, v, results, gids, prompts, 6)
    md = fleet.metrics_dict()
    assert md["handoffs_total"] == len(prompts)
    assert md["integrity_handoff_checksum_failures_total"] == 0
    assert md["handoff_fallbacks_total"] == 0


# -- serve.snapshot: verified restore --------------------------------------


def test_engine_restore_rejects_corrupted_snapshot(lm):
    """``ServeEngine.restore()`` verifies the snapshot digest before
    rebuilding anything: a bit-flipped snapshot raises the typed
    error; the clean snapshot round-trips; a stampless legacy
    snapshot is still accepted."""
    m, v, ids = lm
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=4)
    engine.submit(np.asarray(ids[0, :5]), max_new_tokens=4)
    engine.run()
    snap = engine.snapshot()
    assert snap["checksum"] == integrity.json_checksum(snap)

    for seed in (0, 1, 2):
        bad = integrity.flip_bit_json(snap, seed)
        with pytest.raises(SnapshotCorruption) as exc:
            ServeEngine.restore(bad, m, v)
        assert bad["checksum"] in str(exc.value)

    ServeEngine.restore(snap, m, v)  # clean round-trip still works
    legacy = {k: s for k, s in snap.items() if k != "checksum"}
    ServeEngine.restore(legacy, m, v)


@pytest.mark.slow  # ci.sh's integrity gate runs the full file unfiltered
def test_snapshot_corrupt_failover_falls_back_to_fresh_engine(lm):
    """A corrupted snapshot + a same-tick kill: the failover path
    rejects the snapshot, rebuilds a FRESH engine, re-admits the
    in-flight prompts, and the streams stay bit-identical."""
    m, v, ids = lm
    inj = FaultInjector([
        Fault("serve.snapshot", "corrupt", tick=1, replica=1),
        Fault("serve.decode", "kill", tick=1, replica=1),
    ])
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=8, decode_block=2,
                    snapshot_every_ticks=1, faults=inj,
                    retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    gids = [rs.submit(p, 10) for p in prompts]
    results = rs.run()
    md = rs.metrics_dict()
    assert md["integrity_snapshot_checksum_failures_total"] == 1
    assert rs.replica_failovers_total >= 1
    _assert_parity(m, v, results, gids, prompts, 10)


@pytest.mark.slow  # ci.sh's integrity gate runs the full file unfiltered
def test_clean_chaos_soak_zero_integrity_false_positives(lm):
    """Seeded NON-corrupt chaos (kills with snapshots on): every
    failover restores from a verified snapshot with ZERO checksum
    failures — the stamps never false-positive on clean payloads."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.decode", "kill", tick=1,
                               replica=0)])
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=8, decode_block=2,
                    snapshot_every_ticks=1, faults=inj,
                    retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    gids = [rs.submit(p, 8) for p in prompts]
    results = rs.run()
    md = rs.metrics_dict()
    assert rs.replica_failovers_total >= 1
    assert md["integrity_snapshot_checksum_failures_total"] == 0
    _assert_parity(m, v, results, gids, prompts, 8)


# -- contracts with integrity enabled --------------------------------------


def test_decode_sync_contract_holds_after_verified_restore(lm, monkeypatch):
    """The one-host-sync-per-block contract survives the integrity
    plane: after a checksum-VERIFIED snapshot restore, a request
    decoding 16 tokens through T=8 blocks still pays at most one
    fetch per block, bit-identical to ``generate()``."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :4])
    src = ServeEngine(m, v, slots=1, cache_len=32, decode_block=8)
    snap = src.snapshot()
    ok = integrity.json_checksum(
        {k: s for k, s in snap.items() if k != "checksum"})
    assert snap["checksum"] == ok
    engine = ServeEngine.restore(snap, m, v, slots=1, cache_len=32, decode_block=8)
    rid = engine.submit(prompt, max_new_tokens=17)

    syncs = {"n": 0}
    real_device_get = jax.device_get
    real_asarray = np.asarray

    def counting_device_get(x, *a, **kw):
        syncs["n"] += 1
        return real_device_get(x, *a, **kw)

    def counting_asarray(x, *a, **kw):
        if isinstance(x, jax.Array):
            syncs["n"] += 1
        return real_asarray(x, *a, **kw)

    monkeypatch.setattr(jax, "device_get", counting_device_get)
    monkeypatch.setattr(np, "asarray", counting_asarray)
    res = engine.run()[rid]
    monkeypatch.undo()

    np.testing.assert_array_equal(
        np.asarray(res.tokens), _ref(m, v, prompt, 17)
    )
    assert syncs["n"] <= 2, f"host syncs: {syncs['n']} (> 1 per block)"
