"""Registry-wide fuzz tests.

The reference's distinctive QA idea (core/test/fuzzing/.../FuzzingTest.scala:
25-211): load EVERY registered stage and assert framework-wide invariants
with explicit exemption lists — every stage has an experiment (fit/transform)
test object (:25-64), every stage serializes (:66-110), uids are sane
(:155), params are well-formed (:165-211).
"""

from __future__ import annotations

import numpy as np
import pytest

import mmlspark_tpu
from mmlspark_tpu.core.stage import Estimator, Model, PipelineStage
from tests.fuzzing_objects import (
    DERIVED_MODEL_CLASSES,
    EXEMPTIONS,
    build_test_objects,
)


def framework_stage_classes() -> dict[str, type]:
    """Registered stages that belong to the framework (test-local classes
    registered by other test modules are out of scope)."""
    return {
        name: cls
        for name, cls in mmlspark_tpu.all_stages().items()
        if cls.__module__.startswith("mmlspark_tpu")
    }


@pytest.fixture(scope="module")
def objects():
    return build_test_objects()


def test_every_stage_has_experiment(objects):
    """FuzzingTest.scala:25-64: no stage ships without a fuzz test object."""
    covered = (
        set(objects) | set(DERIVED_MODEL_CLASSES) | set(EXEMPTIONS)
    )
    missing = sorted(set(framework_stage_classes()) - covered)
    assert not missing, (
        f"stages with no fuzz test object (add to fuzzing_objects.py or "
        f"exempt with a reason): {missing}"
    )


def test_no_stale_providers(objects):
    unknown = sorted(
        (set(objects) | set(DERIVED_MODEL_CLASSES)) -
        set(framework_stage_classes())
    )
    assert not unknown, f"providers for unregistered stages: {unknown}"


def test_experiment_fuzzing(objects):
    """Every stage fits/transforms on its test object without error and
    yields a Dataset."""
    from mmlspark_tpu.data.dataset import Dataset

    failures = []
    for name, objs in objects.items():
        for obj in objs:
            try:
                stage = obj.stage
                if isinstance(stage, Estimator):
                    model = stage.fit(obj.fit_ds)
                    assert isinstance(model, Model), f"{name}.fit -> {model}"
                    out = model.transform(obj.score_ds)
                else:
                    out = stage.transform(obj.score_ds)
                assert isinstance(out, Dataset)
            except Exception as e:  # noqa: BLE001 - collecting all failures
                failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, "experiment fuzzing failures:\n" + "\n".join(failures)


def test_serialization_fuzzing(objects, tmp_path):
    """FuzzingTest.scala:66-110 + RoundTripTestBase: save -> load ->
    transform must equal the original's transform."""
    failures = []
    for name, objs in objects.items():
        obj = objs[0]
        try:
            stage = obj.stage
            if isinstance(stage, Estimator):
                stage = stage.fit(obj.fit_ds)
            path = str(tmp_path / name)
            stage.save(path)
            loaded = PipelineStage.load(path)
            a = stage.transform(obj.score_ds)
            b = loaded.transform(obj.score_ds)
            assert a.columns == b.columns, f"{name}: column mismatch"
            for c in a.columns:
                col_a, col_b = a[c], b[c]
                if col_a.dtype == object:
                    if len(col_a) and isinstance(
                        col_a[0], (bytes, str, type(None))
                    ):
                        assert list(col_a) == list(col_b), f"{name}.{c}"
                else:
                    np.testing.assert_allclose(
                        np.asarray(col_a, np.float64),
                        np.asarray(col_b, np.float64),
                        rtol=1e-5,
                        atol=1e-6,
                        err_msg=f"{name}.{c}",
                    )
        except Exception as e:  # noqa: BLE001
            failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, "serialization fuzzing failures:\n" + "\n".join(
        failures
    )


def test_uids_sane():
    """FuzzingTest.scala:155: uid prefix matches the class name, no exotic
    characters."""
    import re

    for name, cls in framework_stage_classes().items():
        try:
            stage = cls()
        except Exception:
            continue  # stages with required ctor params covered elsewhere
        assert stage.uid.startswith(name), stage.uid
        assert re.fullmatch(r"[A-Za-z0-9_]+", stage.uid), stage.uid


def test_params_well_formed():
    """FuzzingTest.scala:165-211: every param has a doc string, a sane name,
    and a default that passes its own validation."""
    failures = []
    for name, cls in framework_stage_classes().items():
        for pname, p in cls.params().items():
            if not p.doc:
                failures.append(f"{name}.{pname}: empty doc")
            if not pname.islower() and not pname.isidentifier():
                failures.append(f"{name}.{pname}: bad name")
            try:
                p.validate(p.get_default())
            except Exception as e:  # noqa: BLE001
                failures.append(f"{name}.{pname}: default fails validation: {e}")
    assert not failures, "\n".join(failures)


def test_transformers_do_not_mutate_input(objects):
    """Datasets are immutable values; a stage must never modify its input
    in place (the Spark DataFrame contract the framework mirrors)."""
    from tests.fuzzing_objects import build_test_objects  # fresh copies

    for name, objs in build_test_objects().items():
        obj = objs[0]
        stage = obj.stage
        ds = obj.score_ds
        before = {c: np.copy(ds[c]) if ds[c].dtype != object else list(ds[c])
                  for c in ds.columns}
        try:
            if isinstance(stage, Estimator):
                stage.fit(obj.fit_ds).transform(ds)
            else:
                stage.transform(ds)
        except Exception:
            continue
        for c, old in before.items():
            cur = ds[c]
            if cur.dtype == object:
                assert list(cur) == list(old) or all(
                    a is b for a, b in zip(cur, old)
                ), f"{name} mutated column {c}"
            else:
                np.testing.assert_array_equal(
                    cur, old, err_msg=f"{name} mutated column {c}"
                )


def test_decode_api_fuzzing():
    """Decode-surface fuzz (reference FuzzingTest philosophy applied to
    the r5 generation API): random transformer_lm configs and random
    generate()/beam_search() arguments must either work or raise the
    framework's typed errors — never a bare TypeError/IndexError/
    ZeroDivisionError from deep inside a trace."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.core.exceptions import FriendlyError, ParamError
    from mmlspark_tpu.models import beam_search, build_model, generate

    rng = np.random.default_rng(0)
    built = 0
    for _ in range(25):
        cfg = dict(
            vocab_size=int(rng.choice([4, 8, 16])),
            d_model=int(rng.choice([8, 16])),
            # weighted toward valid combos so the fuzz exercises real
            # decodes, while still sampling every rejection class
            heads=int(rng.choice([1, 2, 2, 2, 3])),
            depth=int(rng.choice([1, 2])),
            max_len=int(rng.choice([4, 12])),
            causal=bool(rng.choice([True, True, True, False])),
            window=[None, None, 1, 3, 0][rng.integers(0, 5)],
            kv_heads=[None, None, 1, 2, 3][rng.integers(0, 5)],
            pos_embedding=str(rng.choice(["learned", "rope"])),
        )
        try:
            m = build_model("transformer_lm", attn_impl="dense", **cfg)
        except (FriendlyError, ParamError):
            continue  # invalid combo rejected with a typed error: pass
        built += 1
        v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
        prompt = jnp.asarray(
            rng.integers(0, cfg["vocab_size"], size=(2, 3)), jnp.int32
        )
        n = int(rng.choice([0, 1, 5]))
        kwargs = dict(
            temperature=float(rng.choice([0.0, 0.7, -1.0])),
            top_k=[None, 1, 99][rng.integers(0, 3)],
            top_p=[None, 0.5, 2.0][rng.integers(0, 3)],
            eos_id=[None, 1][rng.integers(0, 2)],
            rng=jax.random.PRNGKey(1),
        )
        try:
            out = generate(m, v, prompt, n, **kwargs)
            assert out.shape == (2, 3 + n)
        except (FriendlyError, ParamError):
            pass
        try:
            bout = beam_search(
                m, v, prompt, max(n, 1),
                beams=int(rng.choice([0, 1, 2, 99])),
                length_penalty=float(rng.choice([0.0, 0.6, -1.0])),
            )
            assert bout.shape[0] == 2
        except (FriendlyError, ParamError):
            pass
    assert built >= 5  # the fuzz must actually exercise valid configs
