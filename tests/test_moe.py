"""Mixture-of-experts / expert parallelism tests (virtual 8-device CPU
mesh, see conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import ParamError
from mmlspark_tpu.parallel import EXPERT_RULES, make_mesh
from mmlspark_tpu.parallel.expert import (
    moe_dispatch,
    moe_ffn,
    router_probs,
    validate_experts,
)


def test_dispatch_routes_each_token_once():
    rng = jax.random.PRNGKey(0)
    probs = jax.nn.softmax(jax.random.normal(rng, (16, 4)), axis=-1)
    dispatch, combine, aux = moe_dispatch(probs, capacity=16)
    d = np.asarray(dispatch)
    # with ample capacity every token lands in exactly one (expert, slot)
    assert np.all(d.sum(axis=(1, 2)) == 1.0)
    # combine weights equal the chosen expert's router prob
    chosen = np.asarray(probs).max(axis=1)
    np.testing.assert_allclose(
        np.asarray(combine).sum(axis=(1, 2)), chosen, rtol=1e-6
    )
    assert np.isfinite(float(aux))


def test_dispatch_capacity_drops_overflow():
    # all tokens prefer expert 0; capacity 2 keeps exactly 2
    probs = jnp.tile(jnp.array([[0.9, 0.1]]), (8, 1))
    dispatch, _, _ = moe_dispatch(probs, capacity=2)
    kept = np.asarray(dispatch).sum()
    assert kept == 2.0


def test_moe_ffn_matches_per_token_expert_dense():
    # with ample capacity, each token's MoE output equals its argmax
    # expert's dense FFN scaled by that expert's router probability
    rng = np.random.default_rng(0)
    b, t, d, f, e = 2, 4, 8, 16, 3
    x = jnp.asarray(rng.normal(size=(b, t, d)), jnp.float32)
    gate = jnp.asarray(rng.normal(size=(d, e)), jnp.float32)
    w_in = jnp.asarray(rng.normal(size=(e, d, f)) * 0.1, jnp.float32)
    b_in = jnp.asarray(rng.normal(size=(e, f)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.normal(size=(e, f, d)) * 0.1, jnp.float32)
    b_out = jnp.asarray(rng.normal(size=(e, d)) * 0.1, jnp.float32)
    out, aux = moe_ffn(x, gate, w_in, b_in, w_out, b_out,
                       capacity_factor=float(e))  # capacity = n tokens
    probs = np.asarray(router_probs(x.reshape(-1, d), gate))
    chosen = probs.argmax(-1)
    flat = np.asarray(x).reshape(-1, d)
    def dense_expert(tok, c):
        h = np.asarray(jax.nn.gelu(tok @ np.asarray(w_in[c])
                                   + np.asarray(b_in[c])))
        return h @ np.asarray(w_out[c]) + np.asarray(b_out[c])

    want = np.stack(
        [probs[i, c] * dense_expert(flat[i], c)
         for i, c in enumerate(chosen)]
    ).reshape(b, t, d)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-3, atol=1e-4)
    assert np.isfinite(float(aux))


def test_dispatch_mask_excludes_padding():
    rng = jax.random.PRNGKey(1)
    probs = jax.nn.softmax(jax.random.normal(rng, (8, 2)), axis=-1)
    mask = jnp.array([1, 1, 1, 1, 0, 0, 0, 0], jnp.float32)
    dispatch, combine, aux = moe_dispatch(probs, capacity=8, mask=mask)
    d = np.asarray(dispatch)
    # padding tokens route nowhere and consume no capacity
    assert np.all(d[4:].sum(axis=(1, 2)) == 0.0)
    assert np.all(d[:4].sum(axis=(1, 2)) == 1.0)
    # aux equals the unmasked aux computed on real tokens only
    _, _, aux_real = moe_dispatch(probs[:4], capacity=8)
    np.testing.assert_allclose(float(aux), float(aux_real), rtol=1e-6)


def test_moe_ffn_mask_zeroes_padding_rows():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 2, 6)), jnp.float32)
    gate = jnp.asarray(rng.normal(size=(6, 2)), jnp.float32)
    w_in = jnp.asarray(rng.normal(size=(2, 6, 8)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.normal(size=(2, 8, 6)) * 0.1, jnp.float32)
    zeros_in, zeros_out = jnp.zeros((2, 8)), jnp.zeros((2, 6))
    mask = jnp.array([1, 1, 0, 0], jnp.float32)
    out, _ = moe_ffn(x, gate, w_in, zeros_in, w_out, zeros_out,
                     capacity_factor=2.0, mask=mask)
    assert np.all(np.asarray(out)[2:] == 0.0)  # padding rows untouched
    assert np.any(np.asarray(out)[:2] != 0.0)


def test_router_probs_normalized():
    x = jnp.ones((3, 5, 4))
    gate = jnp.eye(4, 6)
    p = router_probs(x, gate)
    np.testing.assert_allclose(np.asarray(p.sum(-1)),
                               np.ones((3, 5)), rtol=1e-6)


def test_validate_experts():
    with pytest.raises(ParamError):
        validate_experts(1)
    mesh = make_mesh({"expert": 4})
    with pytest.raises(ParamError):
        validate_experts(6, mesh)
    validate_experts(8, mesh)  # ok


def test_moe_lm_forward_and_grad():
    from mmlspark_tpu.models import build_model

    graph = build_model(
        "transformer_lm_moe", vocab_size=32, d_model=16, heads=2, depth=1,
        n_experts=4, max_len=8,
    )
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, size=(4, 8)), jnp.int32
    )
    variables = graph.init(jax.random.PRNGKey(0), ids[:1])
    # init must not persist per-call sown losses
    assert all("losses" not in v for v in variables.values())
    out = graph.apply(variables, ids)
    assert out.shape == (4, 8, 32)
    out2, updated = graph.apply(variables, ids, train=True)
    assert "losses" in updated["block0"]
    aux = jax.tree_util.tree_leaves(updated["block0"]["losses"])
    assert len(aux) == 1 and np.isfinite(float(aux[0]))


def test_trainer_moe_expert_parallel():
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

    mesh_axes = {"data": 2, "expert": 4}
    mesh = make_mesh(mesh_axes)
    graph = build_model(
        "transformer_lm_moe", vocab_size=32, d_model=16, heads=2, depth=1,
        n_experts=4, max_len=8, mesh=mesh,
    )
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 32, size=(16, 8)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    trainer = SPMDTrainer(
        graph,
        TrainConfig(
            epochs=2, batch_size=8, learning_rate=1e-2,
            mesh_axes=mesh_axes, param_rules=EXPERT_RULES,
            log_every=1, shuffle=False,
        ),
    )
    variables = trainer.train(ids, labels)
    losses = [h["loss"] for h in trainer.history if "loss" in h]
    assert len(losses) >= 2 and all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    out = graph.apply(variables, jnp.asarray(ids[:2]))
    assert out.shape == (2, 8, 32)


def test_trainer_moe_checkpoint_resume(tmp_path):
    # regression: sown losses must not leak into the carried rest tree,
    # or restore against the init-derived target fails
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

    graph = build_model(
        "transformer_lm_moe", vocab_size=16, d_model=8, heads=2, depth=1,
        n_experts=2, max_len=4,
    )
    rng = np.random.default_rng(2)
    ids = rng.integers(0, 16, size=(8, 4)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    cfg = dict(
        batch_size=4, learning_rate=1e-2, log_every=1, shuffle=False,
        mesh_axes={"data": 2},  # keep batch at 4 -> 2 steps per epoch
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=1,
    )
    SPMDTrainer(graph, TrainConfig(epochs=1, **cfg)).train(ids, labels)
    resumed = SPMDTrainer(graph, TrainConfig(epochs=2, **cfg))
    variables = resumed.train(ids, labels)
    steps = [h["step"] for h in resumed.history if "loss" in h]
    assert steps and min(steps) >= 2  # resumed past epoch 1
    out = graph.apply(variables, jnp.asarray(ids[:2]))
    assert out.shape == (2, 4, 16)


def test_moe_ffn_prime_token_count_keeps_group_size():
    """Non-smooth token counts must pad to the group multiple, not
    degenerate to 1-token groups (the old divisor-of-n scheme made
    capacity vacuous for prime B*T)."""
    rng = np.random.default_rng(3)
    b, t, d, f, e = 1, 13, 8, 16, 3  # 13 tokens: prime
    x = jnp.asarray(rng.normal(size=(b, t, d)), jnp.float32)
    gate = jnp.asarray(rng.normal(size=(d, e)), jnp.float32)
    w_in = jnp.asarray(rng.normal(size=(e, d, f)) * 0.1, jnp.float32)
    b_in = jnp.asarray(rng.normal(size=(e, f)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.normal(size=(e, f, d)) * 0.1, jnp.float32)
    b_out = jnp.asarray(rng.normal(size=(e, d)) * 0.1, jnp.float32)
    out, aux = moe_ffn(x, gate, w_in, b_in, w_out, b_out,
                       capacity_factor=float(e), group_size=8)
    assert out.shape == (b, t, d)
    assert np.isfinite(float(aux))
    # ample capacity: must match the per-token dense computation exactly,
    # including the final (padded) partial group
    probs = np.asarray(router_probs(x.reshape(-1, d), gate))
    chosen = probs.argmax(-1)
    flat = np.asarray(x).reshape(-1, d)

    def dense_expert(tok, c):
        h = np.asarray(jax.nn.gelu(tok @ np.asarray(w_in[c])
                                   + np.asarray(b_in[c])))
        return h @ np.asarray(w_out[c]) + np.asarray(b_out[c])

    want = np.stack(
        [probs[i, c] * dense_expert(flat[i], c)
         for i, c in enumerate(chosen)]
    ).reshape(b, t, d)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-3, atol=1e-4)


def test_moe_attention_features_uniform_with_dense_lm():
    """The MoE family carries the same attention feature set: a
    window + MQA + RoPE MoE LM builds, runs forward+grad, and records
    the config in extra (no learned position table under rope)."""
    from mmlspark_tpu.models import build_model

    m = build_model("transformer_lm_moe", vocab_size=32, d_model=16,
                    heads=4, depth=1, n_experts=2, max_len=16,
                    window=6, kv_heads=1, pos_embedding="rope")
    assert m.extra["window"] == 6 and m.extra["kv_heads"] == 1
    x = jnp.asarray(np.arange(16)[None] % 32, jnp.int32)
    v = m.init(jax.random.PRNGKey(0), x)
    assert "pos" not in v["embed"]["params"]
    loss = jax.jit(lambda p: jnp.mean(
        m.apply(p, x).astype(jnp.float32) ** 2))
    assert float(loss(v)) > 0
    g = jax.jit(jax.grad(loss))(v)
    assert jax.tree_util.tree_reduce(
        lambda a, b: a + float(jnp.sum(jnp.abs(b))), g, 0.0) > 0

    with pytest.raises(ParamError, match="kv_heads"):
        build_model("transformer_lm_moe", vocab_size=32, d_model=16,
                    heads=4, depth=1, n_experts=2, max_len=16, kv_heads=3)


def test_moe_ffn_dropless_matches_capacity_path():
    """The decode-step dropless router must equal the capacity path
    wherever the latter drops nothing (ample capacity) — the numerical
    contract that makes kv-cache MoE generation exact."""
    from mmlspark_tpu.parallel.expert import moe_ffn_dropless

    rng = np.random.default_rng(1)
    b, t, d, f, e = 2, 4, 8, 16, 3
    x = jnp.asarray(rng.normal(size=(b, t, d)), jnp.float32)
    gate = jnp.asarray(rng.normal(size=(d, e)), jnp.float32)
    w_in = jnp.asarray(rng.normal(size=(e, d, f)) * 0.1, jnp.float32)
    b_in = jnp.asarray(rng.normal(size=(e, f)) * 0.1, jnp.float32)
    w_out = jnp.asarray(rng.normal(size=(e, f, d)) * 0.1, jnp.float32)
    b_out = jnp.asarray(rng.normal(size=(e, d)) * 0.1, jnp.float32)
    cap_out, _ = moe_ffn(x, gate, w_in, b_in, w_out, b_out,
                         capacity_factor=float(e))
    drop_out = moe_ffn_dropless(x, gate, w_in, b_in, w_out, b_out)
    np.testing.assert_allclose(np.asarray(drop_out), np.asarray(cap_out),
                               rtol=1e-4, atol=1e-5)


def test_moe_generate_kv_cache_matches_unpadded_oracle():
    """MoE generation (round 5): the kv-cache path routes the prefill
    through the capacity path over the UNPADDED prompt and decode steps
    droplessly. With capacity >= tokens (nothing ever dropped), greedy
    tokens must equal the growing-unpadded-buffer oracle — a plain
    scoring forward per step, the semantics a user scores with."""
    from mmlspark_tpu.core.exceptions import FriendlyError
    from mmlspark_tpu.models import build_model, generate
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    m = build_model(
        "transformer_lm_moe", vocab_size=8, d_model=32, heads=2, depth=2,
        max_len=32, n_experts=2, capacity_factor=2.0,  # capacity = tokens
    )
    v, ids = overfit_periodic_lm(m, steps=40)
    prompt = ids[:, :6]
    out = np.asarray(generate(m, v, prompt, max_new_tokens=8))
    buf = np.asarray(prompt)
    for _ in range(8):
        lg = np.asarray(m.apply(v, jnp.asarray(buf)))
        nxt = lg[:, -1].argmax(-1).astype(np.int32)
        buf = np.concatenate([buf, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, buf)
    # the pad-filled recompute path stays rejected
    with pytest.raises(FriendlyError, match="kv_cache"):
        generate(m, v, prompt, max_new_tokens=2, kv_cache=False)


def test_moe_one_token_prompt_prefill_uses_capacity_routing():
    """Regression (r5 review): a (B, 1) PROMPT is a prefill, not a
    decode step — its logits must equal the plain scoring forward even
    under a capacity so tight that the dropless decode router would
    disagree (all rows route to one expert; capacity keeps only one)."""
    from mmlspark_tpu.models import build_model, generate

    m = build_model(
        "transformer_lm_moe", vocab_size=8, d_model=16, heads=2, depth=1,
        max_len=8, n_experts=2, capacity_factor=0.5,
    )
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
    prompt = jnp.full((4, 1), 3, jnp.int32)  # identical rows: one expert
    out = np.asarray(generate(m, v, prompt, max_new_tokens=1))
    want = np.asarray(m.apply(v, prompt))[:, -1].argmax(-1)
    np.testing.assert_array_equal(out[:, 1], want)
