"""Notebook tier artifacts (reference notebooks/samples + its headless
runner, tools/notebook/tester/NotebookTestSuite.py).

The committed .ipynb files are GENERATED from examples/e*.py by
tools/make_notebooks.py; this test regenerates them into a temp dir and
compares cell sources so the committed artifacts cannot drift from the
scripts. Execution of the notebooks is covered by
``python tools/notebook_tester.py`` (600 s/notebook, PROC_SHARD
sharding) — run out-of-suite like the reference's notebook tier.
"""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SAMPLES = os.path.join(REPO, "notebooks", "samples")


def _cells(path):
    nb = json.load(open(path, encoding="utf-8"))
    return [
        ("".join(c["source"]), c["cell_type"]) for c in nb["cells"]
    ]


def test_committed_notebooks_match_scripts(tmp_path):
    pytest.importorskip("nbformat")
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import make_notebooks

    written = make_notebooks.main(str(tmp_path))
    # the ten reference sample notebooks + TPU-native additions (e306+)
    assert len(written) == len(make_notebooks.TITLES)
    assert len(written) >= 10
    for name in written:
        committed = os.path.join(SAMPLES, name)
        assert os.path.exists(committed), f"missing committed {name}"
        assert _cells(committed) == _cells(str(tmp_path / name)), (
            f"{name} drifted — regenerate with tools/make_notebooks.py"
        )


def test_notebook_tester_discover_shards():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import notebook_tester

    all_names = notebook_tester.discover([])
    assert len(all_names) >= 10  # ten reference notebooks + additions
    os.environ["PROC_SHARD"] = "0/3"
    try:
        shard0 = notebook_tester.discover([])
    finally:
        del os.environ["PROC_SHARD"]
    assert shard0 == all_names[::3]
    only = notebook_tester.discover(["301"])
    assert len(only) == 1 and only[0].startswith("301")
