"""Serving resilience (ISSUE 7 tentpole).

The contract under test (docs/SERVING.md "Failure semantics"): the
deterministic fault-injection harness (``core/faults.py``) drives the
engine's hook points, and the engine answers with — capped-backoff
retry that is INVISIBLE to results (transient faults absorbed, token
streams still byte-identical to ``generate()``); per-request QUARANTINE
(a poisoned or undispatachable request retires as ``"failed"``, slot
freed and device live mask dead, everyone else unharmed); graceful
DEGRADATION under RESOURCE_EXHAUSTED (down the existing power-of-two
block ladder + admission caps + preemption-with-resume, recovery probe
re-escalates, compile pins hold because no new program ever compiles);
and ``snapshot()``/``restore()`` crash recovery whose post-restore
tokens are bit-identical (the kill-mid-run crash drill). The seeded
chaos soak closes the loop: random fault schedules through full runs,
single-device and 2x2 mesh, every request reaching a definite terminal
status.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.integrity import SnapshotCorruption
from mmlspark_tpu.core.faults import (
    Fault,
    FaultInjector,
    EngineKilled,
    ResourceExhausted,
    TransientFault,
    is_resource_exhausted,
    is_transient,
    parse_fault_spec,
)
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.serve import ServeEngine
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4

TERMINAL = {"completed", "expired", "failed", "stalled"}


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new)
    return np.asarray(out)[0]


# -- injector unit tests (pure host, no engine) ----------------------------


def test_fault_schedule_deterministic():
    inj = FaultInjector([Fault("serve.decode", "transient", times=2)])
    with pytest.raises(TransientFault):
        inj.fire("serve.decode", tick=0)
    with pytest.raises(TransientFault):
        inj.fire("serve.decode", tick=1)
    inj.fire("serve.decode", tick=2)   # entry spent: silent
    inj.fire("serve.prefill", tick=0)  # wrong site: never fires
    assert inj.counts == {"transient": 2}
    assert inj.injected_total == 2


def test_fault_schedule_pinning():
    inj = FaultInjector([Fault("serve.prefill", "oom", tick=3, request=7)])
    inj.fire("serve.prefill", tick=3, request=5)  # wrong request
    inj.fire("serve.prefill", tick=2, request=7)  # wrong tick
    inj.fire("serve.prefill", tick=3)             # no request context
    with pytest.raises(ResourceExhausted, match="RESOURCE_EXHAUSTED"):
        inj.fire("serve.prefill", tick=3, request=7)
    assert inj.injected_total == 1


def test_seeded_rates_replay():
    def run(seed):
        inj = FaultInjector(seed=seed, rates={"transient": 0.3})
        fired = []
        for t in range(60):
            try:
                inj.fire("serve.decode", tick=t)
                fired.append(0)
            except TransientFault:
                fired.append(1)
        return fired

    assert run(7) == run(7)   # same seed, same fault replay
    assert run(7) != run(8)   # different seed, different schedule
    assert 0 < sum(run(7)) < 60


def test_injector_and_fault_validation():
    with pytest.raises(FriendlyError, match="seed"):
        FaultInjector(rates={"transient": 0.5})
    with pytest.raises(FriendlyError, match="rate"):
        FaultInjector(seed=0, rates={"transient": 1.5})
    with pytest.raises(FriendlyError, match="kind"):
        FaultInjector(seed=0, rates={"nope": 0.1})
    with pytest.raises(FriendlyError, match="site"):
        Fault("bad.site", "transient")
    with pytest.raises(FriendlyError, match="kind"):
        Fault("serve.decode", "nope")


def test_parse_fault_spec():
    inj = parse_fault_spec("seed=7, transient=0.05,oom=0.02,stall_s=0.002")
    assert inj.rates == {"transient": 0.05, "oom": 0.02}
    assert inj.stall_s == 0.002
    with pytest.raises(FriendlyError, match="fault spec"):
        parse_fault_spec("transient")
    with pytest.raises(FriendlyError, match="key"):
        parse_fault_spec("bogus=1")
    with pytest.raises(FriendlyError, match="value"):
        parse_fault_spec("transient=lots")


def test_classifiers_cover_injected_and_real_spellings():
    assert is_transient(TransientFault("x"))
    assert not is_transient(ResourceExhausted("x"))
    assert not is_transient(EngineKilled("x"))
    assert is_resource_exhausted(ResourceExhausted("x"))
    # the REAL runtime's status spellings match by name + message
    assert is_resource_exhausted(RuntimeError("RESOURCE_EXHAUSTED: pool"))

    class XlaRuntimeError(RuntimeError):
        pass

    assert is_transient(XlaRuntimeError("UNAVAILABLE: link down"))
    assert is_transient(XlaRuntimeError("DEADLINE_EXCEEDED: slow"))
    assert not is_transient(XlaRuntimeError("INTERNAL: compiler bug"))
    # status text in a non-runtime error type is NOT retryable
    assert not is_transient(RuntimeError("UNAVAILABLE"))


# -- transient retry: invisible to results ---------------------------------


def test_transient_faults_retry_transparently(lm):
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:4], row[:5], row[:6]]
    inj = FaultInjector([
        Fault("serve.decode", "transient", times=2),
        Fault("serve.prefill", "transient", times=1),
        Fault("serve.device_get", "transient", times=1),
    ])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=4,
                         faults=inj, retry_backoff_s=0.0)
    rids = [engine.submit(p, max_new_tokens=6) for p in prompts]
    results = engine.run()
    for rid, p in zip(rids, prompts):
        assert results[rid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 6)
        )
    assert engine.metrics.retries_total == 4
    assert engine.metrics.faults_injected_total == 4
    assert engine.metrics.failed == 0
    assert engine.metrics.quarantined_total == 0


def test_stall_fault_slows_but_never_fails(lm):
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.decode", "stall", times=2)],
                        stall_s=0.001)
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=2,
                         faults=inj)
    prompt = np.asarray(ids[0, :4])
    rid = engine.submit(prompt, max_new_tokens=6)
    res = engine.run()[rid]
    assert res.status == "completed"
    np.testing.assert_array_equal(
        np.asarray(res.tokens), _ref(m, v, prompt, 6)
    )
    assert inj.counts.get("stall") == 2
    assert engine.metrics.retries_total == 0  # a stall is not an error


# -- quarantine: blast radius is one request -------------------------------


def test_prefill_fault_beyond_retries_quarantines_one_request(lm):
    m, v, ids = lm
    row = np.asarray(ids[0])
    # request id 1's prefill fails EVERY attempt; ids 0/2 are untouched
    inj = FaultInjector([
        Fault("serve.prefill", "transient", request=1, times=10),
    ])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=4,
                         faults=inj, retry_limit=2, retry_backoff_s=0.0)
    rids = [engine.submit(row[:n], max_new_tokens=5) for n in (4, 5, 6)]
    results = engine.run()
    assert results[rids[1]].status == "failed"
    assert results[rids[1]].generated == 0
    for rid, n in ((rids[0], 4), (rids[2], 6)):
        assert results[rid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, row[:n], 5)
        )
    assert engine.metrics.quarantined_total == 1
    assert engine.metrics.failed == 1
    # the quarantined request's slot was freed and re-leased (3 requests
    # flowed through 2 slots); pool accounting is clean afterwards
    assert engine.pool.leased_count == 0 and not engine.busy


def test_prefill_poison_quarantines_before_results(lm):
    m, v, ids = lm
    row = np.asarray(ids[0])
    inj = FaultInjector([Fault("serve.prefill", "poison", request=0)])
    engine = ServeEngine(m, v, slots=2, cache_len=32, faults=inj)
    rid_bad = engine.submit(row[:4], max_new_tokens=5)
    rid_ok = engine.submit(row[:5], max_new_tokens=5)
    results = engine.run()
    assert results[rid_bad].status == "failed"
    assert results[rid_bad].generated == 0  # the poison never landed
    assert results[rid_ok].status == "completed"
    np.testing.assert_array_equal(
        np.asarray(results[rid_ok].tokens), _ref(m, v, row[:5], 5)
    )
    assert engine.metrics.quarantined_total == 1


def test_decode_poison_quarantines_only_that_row(lm):
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:4], row[:5], row[:6]]
    inj = FaultInjector([
        Fault("serve.device_get", "poison", tick=1, times=1),
    ])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=2,
                         faults=inj)
    rids = [engine.submit(p, max_new_tokens=8) for p in prompts]
    results = engine.run()
    statuses = [results[r].status for r in rids]
    assert statuses.count("failed") == 1
    assert engine.metrics.quarantined_total == 1
    for rid, p in zip(rids, prompts):
        res = results[rid]
        if res.status == "failed":
            # the corrupted block never reached the result: every token
            # it DID get is a real pre-fault token
            assert all(0 <= int(t) < 8 for t in res.tokens)
            assert res.generated < 8
        else:
            assert res.status == "completed"
            np.testing.assert_array_equal(
                np.asarray(res.tokens), _ref(m, v, p, 8)
            )
    # the quarantined slot is re-leasable: fresh traffic completes
    rid2 = engine.submit(row[:4], max_new_tokens=4)
    res2 = engine.run()[rid2]
    assert res2.status == "completed"
    np.testing.assert_array_equal(
        np.asarray(res2.tokens), _ref(m, v, row[:4], 4)
    )


# -- graceful degradation under memory pressure ----------------------------


def test_oom_steps_down_ladder_and_recovers(lm):
    m, v, ids = lm
    row = np.asarray(ids[0])
    inj = FaultInjector([Fault("serve.decode", "oom", times=2)])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=8,
                         faults=inj, retry_limit=3, retry_backoff_s=0.0,
                         degrade_recover_ticks=2)
    rids = [engine.submit(row[:4], max_new_tokens=20),
            engine.submit(row[:5], max_new_tokens=20)]
    with serve_compile_guard(engine, min_decode=1):
        results = engine.run()
    for rid, n in zip(rids, (4, 5)):
        assert results[rid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, row[:n], 20)
        )
    # two OOMs walked the cap 8 -> 4 -> 2: the degraded dispatch ran a
    # SMALLER ladder size (already compiled — that is the whole point),
    # and the recovery probe re-escalated to full service by the end
    assert "2" in engine.metrics.decode_blocks
    assert inj.counts.get("oom") == 2
    assert not engine.degraded
    assert engine.metrics.to_dict()["degraded_mode"] == 0
    assert engine.metrics.faults_by_kind.get("oom") == 2


def test_oom_at_ladder_floor_preempts_and_resumes(lm):
    m, v, ids = lm
    row = np.asarray(ids[0])
    inj = FaultInjector([Fault("serve.decode", "oom", times=2)])
    # decode_block=1: the ladder has nowhere to step down, so pressure
    # must preempt the youngest active request instead
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=1,
                         faults=inj, retry_limit=3, retry_backoff_s=0.0,
                         degrade_recover_ticks=2)
    rid_a = engine.submit(row[:4], max_new_tokens=6)
    rid_b = engine.submit(row[:5], max_new_tokens=6)
    results = engine.run()
    assert engine.metrics.preemptions_total >= 1
    # the preempted request RESUMED (prompt + emitted prefix re-prefill)
    # and still matches an uninterrupted generate() byte for byte
    for rid, n in ((rid_a, 4), (rid_b, 5)):
        assert results[rid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, row[:n], 6)
        )
    assert not engine.degraded  # admission cap re-escalated


# -- crash drill: kill mid-run, restore, bit-identical ---------------------


def test_crash_drill_restore_is_bit_identical(lm):
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:4], row[:5], row[:6], row[:3]]
    inj = FaultInjector([Fault("serve.decode", "kill", tick=2)])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=2,
                         faults=inj)
    rids = [engine.submit(p, max_new_tokens=8) for p in prompts]
    results = {}
    snap = engine.snapshot()
    with pytest.raises(EngineKilled):
        while engine.busy:
            snap = engine.snapshot()  # checkpoint BEFORE each tick
            for res in engine.step():
                results[res.id] = res
    json.dumps(snap)  # the checkpoint is a plain JSON-able dict
    assert snap["active"] or snap["queued"]  # it died mid-flight

    rebuilt = ServeEngine.restore(snap, m, v, slots=2, decode_block=2)
    assert rebuilt.tick == snap["tick"]
    results.update(rebuilt.run())
    assert set(results) == set(rids)
    for rid, p in zip(rids, prompts):
        assert results[rid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 8),
            err_msg=f"request {rid} diverged across the crash",
        )
    # new requests on the restored engine get FRESH ids
    assert rebuilt.submit(row[:4], max_new_tokens=2) == max(rids) + 1


def test_restore_guards(lm):
    m, v, _ = lm
    engine = ServeEngine(m, v, slots=2, cache_len=32)
    snap = engine.snapshot()
    # a tampered-but-stamped snapshot trips the checksum guard before
    # the version/model guards ever run
    with pytest.raises(SnapshotCorruption, match="checksum"):
        ServeEngine.restore({**snap, "version": 99}, m, v)
    unstamped = {k: val for k, val in snap.items() if k != "checksum"}
    with pytest.raises(FriendlyError, match="version"):
        ServeEngine.restore({**unstamped, "version": 99}, m, v)
    with pytest.raises(FriendlyError, match="model"):
        ServeEngine.restore({**unstamped, "model": "other_lm"}, m, v)
    # idle snapshot restores to an idle engine
    rebuilt = ServeEngine.restore(snap, m, v, slots=2)
    assert not rebuilt.busy and rebuilt.tick == engine.tick


# -- seeded chaos soak -----------------------------------------------------


def _chaos_soak(m, v, ids, seed, mesh=None):
    row = np.asarray(ids[0])
    rng = np.random.default_rng(seed)
    lengths = rng.integers(2, 9, size=8)
    budgets = rng.integers(3, 11, size=8)
    prompts = [row[:int(n)] for n in lengths]
    inj = FaultInjector(
        seed=seed,
        rates={"transient": 0.08, "oom": 0.04, "stall": 0.02,
               "poison": 0.04},
        stall_s=0.0005,
    )
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=16,
                         decode_block=4, mesh=mesh, faults=inj,
                         retry_limit=2, retry_backoff_s=0.0,
                         degrade_recover_ticks=3)
    results, rids = {}, []
    # request-scoped faults must NEVER escape run(): the whole soak runs
    # under the compile-count pins (degradation only moves DOWN the
    # existing ladder, so no new programs may appear)
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            rids.append(engine.submit(p, max_new_tokens=int(n)))
            if i % 2:
                results.update({r.id: r for r in engine.step()})
        results.update(engine.run())

    assert set(results) == set(rids)
    n_completed = 0
    for rid, p, n in zip(rids, prompts, budgets):
        res = results[rid]
        assert res.status in TERMINAL, (rid, res.status)
        if res.status == "completed":
            n_completed += 1
            # unfaulted (and resumed) requests stay token-identical
            np.testing.assert_array_equal(
                np.asarray(res.tokens), _ref(m, v, p, int(n)),
                err_msg=f"seed={seed} mesh={mesh} request={rid}",
            )
    assert n_completed >= 1  # the engine kept serving under fire
    assert engine.metrics.faults_injected_total == inj.injected_total
    assert engine.pool.leased_count == 0 and not engine.busy
    # consistency of the terminal accounting
    md = engine.metrics.to_dict()
    assert (md["completed"] + md["expired"] + md["failed"]
            + md["stalled"]) == len(rids)
    return engine


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chaos_soak_single_device(lm, seed):
    m, v, ids = lm
    _chaos_soak(m, v, ids, seed)


@pytest.mark.parametrize("seed", [3, pytest.param(4, marks=pytest.mark.slow)])
def test_chaos_soak_sharded(lm, seed):
    m, v, ids = lm
    _chaos_soak(m, v, ids, seed, mesh={"data": 2, "model": 2})


# -- zero-overhead contract -------------------------------------------------


def test_disabled_injection_compiles_same_program_set(lm):
    """With ``faults=None`` the hot path must compile exactly the same
    program set as the pre-resilience engine: one decode program per
    ladder size actually run, one prefill program per bucket hit —
    nothing extra from the hook points."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=4)
    assert engine._faults is None  # default: injection disabled
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        rids = [engine.submit(row[:n], max_new_tokens=6)
                for n in (4, 6)]
        results = engine.run()
    for rid, n in zip(rids, (4, 6)):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, row[:n], 6)
        )
    assert engine.metrics.retries_total == 0
    assert engine.metrics.faults_injected_total == 0
    assert engine.metrics.to_dict()["degraded_mode"] == 0
