"""SPMD trainer tests on the 8-device CPU mesh: loss decreases, gradient
sync across shards is correct, checkpoint/resume works (reference analog:
ValidateCntkTrain.scala e2e tiny-epoch training)."""

import jax
import pytest
import numpy as np

from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models import build_model
from mmlspark_tpu.stages.dnn_learner import DNNLearner
from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig, masked_loss


def _two_blob_data(n=256, d=8, seed=0):
    rng = np.random.default_rng(seed)
    half = n // 2
    x = np.concatenate(
        [rng.normal(-1.5, 1.0, (half, d)), rng.normal(1.5, 1.0, (half, d))]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(half), np.ones(half)]).astype(np.int32)
    perm = rng.permutation(n)
    return x[perm], y[perm]


def test_loss_decreases_and_learns():
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(16,))
    trainer = SPMDTrainer(
        g, TrainConfig(epochs=5, batch_size=64, learning_rate=1e-2,
                       log_every=1)
    )
    variables = trainer.train(x, y)
    losses = [h["loss"] for h in trainer.history]
    assert losses[-1] < losses[0] * 0.5
    logits = np.asarray(g.apply(variables, x))
    acc = float((np.argmax(logits, 1) == y).mean())
    assert acc > 0.95


def test_batch_sharded_over_mesh_matches_single_device():
    """Gradient sync: training over the 8-way data axis must match the math
    of unsharded training (same seed, same batches => same params)."""
    x, y = _two_blob_data(n=128)
    cfg = dict(epochs=2, batch_size=32, learning_rate=5e-3, shuffle=False,
               log_every=1)
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    v8 = SPMDTrainer(g, TrainConfig(**cfg)).train(x, y)
    v1 = SPMDTrainer(
        g, TrainConfig(**cfg, mesh_axes={"data": 1})
    ).train(x, y)
    for a, b in zip(jax.tree_util.tree_leaves(v8), jax.tree_util.tree_leaves(v1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-4)


def test_mask_weighted_loss_ignores_padding():
    import jax.numpy as jnp

    logits = jnp.array([[2.0, 0.0], [0.0, 2.0], [9.0, -9.0]])
    labels = jnp.array([0, 1, 1])  # third row wrong but masked out
    full = masked_loss("softmax_xent", logits, labels,
                       jnp.array([True, True, True]))
    masked = masked_loss("softmax_xent", logits, labels,
                         jnp.array([True, True, False]))
    assert float(masked) < float(full)


def test_checkpoint_resume(tmp_path):
    x, y = _two_blob_data(n=64)
    g = build_model("mlp", num_outputs=2, hidden=(8,))

    def cfg(epochs):
        return TrainConfig(
            epochs=epochs, batch_size=32, learning_rate=1e-2,
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1,
            shuffle=False, log_every=1,
        )

    t1 = SPMDTrainer(g, cfg(epochs=1))
    t1.train(x, y)
    # resume run: picks up from the saved step, continues to epoch 2
    t2 = SPMDTrainer(g, cfg(epochs=2))
    t2.train(x, y)
    assert t2.history[0]["step"] > 0  # did not restart from step 0


def test_dnn_learner_stage_end_to_end():
    x, y = _two_blob_data(n=128)
    ds = Dataset({"features": x, "label": y})
    learner = DNNLearner(
        model_name="mlp",
        model_config={"hidden": (16,)},
        epochs=4,
        batch_size=32,
        learning_rate=1e-2,
    )
    model = learner.fit(ds)
    out = model.transform(ds)
    preds = np.argmax(out["scores"], axis=1)
    assert (preds == y).mean() > 0.9
    assert model.train_history  # history carried on the model


def test_dnn_learner_drops_nan_labels():
    x, y = _two_blob_data(n=64)
    yf = y.astype(np.float64)
    yf[:8] = np.nan
    ds = Dataset({"features": x, "label": yf})
    model = DNNLearner(model_name="mlp", epochs=1, batch_size=32).fit(ds)
    assert model.weights is not None


def test_regression_mse_loss():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    y = x @ w
    ds = Dataset({"features": x, "label": y})
    model = DNNLearner(
        model_name="linear", loss="mse", epochs=60, batch_size=64,
        learning_rate=0.1, optimizer="momentum",
    ).fit(ds)
    out = model.transform(ds)
    pred = out["scores"][:, 0]
    resid = np.mean((pred - y) ** 2) / np.var(y)
    assert resid < 0.05


def test_mid_epoch_resume_continues_data_position(tmp_path):
    """Kill mid-epoch; resume must continue at the next batch, not replay
    the epoch (step arithmetic drives the LR schedule and history)."""
    x, y = _two_blob_data(n=96)  # 3 steps/epoch at batch 32
    g = build_model("mlp", num_outputs=2, hidden=(8,))

    def cfg(epochs):
        return TrainConfig(epochs=epochs, batch_size=32, learning_rate=1e-2,
                           checkpoint_dir=str(tmp_path / "ck"),
                           checkpoint_every=1, shuffle=False, log_every=1)

    # full 2-epoch run for ground truth step count
    t_full = SPMDTrainer(g, cfg(2))
    t_full.train(x, y)
    total_steps_full = t_full.history[-1]["step"]
    # now simulate crash after 1 epoch + resume to 2 epochs
    import shutil
    shutil.rmtree(tmp_path / "ck")
    SPMDTrainer(g, cfg(1)).train(x, y)
    t_resumed = SPMDTrainer(g, cfg(2))
    t_resumed.train(x, y)
    assert t_resumed.history[-1]["step"] == total_steps_full
    assert t_resumed.history[0]["step"] == 3  # continued, no replay


def test_steps_per_dispatch_exactness():
    """Chaining K steps in one lax.scan dispatch is an execution strategy,
    not a semantic change: final params must match the 1-step path,
    including an epoch tail that doesn't fill a chunk (10 steps, K=4)."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(80, 6)).astype(np.float32)  # 10 batches of 8
    y = (x[:, 0] > 0).astype(np.int32)
    graph = build_model("mlp", num_outputs=2, hidden=(8,))

    def run(k):
        tr = SPMDTrainer(
            graph,
            TrainConfig(epochs=2, batch_size=8, learning_rate=1e-2,
                        steps_per_dispatch=k, seed=3),
        )
        return tr.train(x, y)

    v1, v4 = run(1), run(4)
    flat1 = jax.tree_util.tree_leaves(v1)
    flat4 = jax.tree_util.tree_leaves(v4)
    for a, b in zip(flat1, flat4):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_remat_is_semantics_preserving():
    """jax.checkpoint trades FLOPs for memory; final params must match the
    non-remat run exactly."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = (x[:, 1] > 0).astype(np.int32)
    graph = build_model("mlp", num_outputs=2, hidden=(8,))

    def run(remat):
        tr = SPMDTrainer(
            graph,
            TrainConfig(epochs=2, batch_size=16, learning_rate=1e-2,
                        remat=remat, seed=5),
        )
        return tr.train(x, y)

    for a, b in zip(
        jax.tree_util.tree_leaves(run(False)),
        jax.tree_util.tree_leaves(run(True)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_grad_accum_matches_full_batch_sgd():
    """grad_accum=K averages micro-batch gradients before ONE optimizer
    update, so SGD training must reproduce the no-accumulation params up
    to compute precision. The model family computes in bf16, so the
    micro vs full forward differs at bf16 epsilon (2^-8 relative) per
    step — tolerances are bf16-scale, not f32-exact."""
    import jax.numpy as jnp

    from mmlspark_tpu.models import build_model

    rng = np.random.default_rng(3)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)

    def run(accum):
        graph = build_model("mlp", num_outputs=2, hidden=(16,))
        tr = SPMDTrainer(
            graph,
            TrainConfig(epochs=2, batch_size=16, learning_rate=0.1,
                        optimizer="sgd", grad_accum=accum, shuffle=False,
                        log_every=100),
        )
        v = tr.train(x, y)
        return jax.tree_util.tree_leaves(v), [
            h["loss"] for h in tr.history if "loss" in h
        ]

    p1, l1 = run(1)
    p2, l2 = run(2)
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-2)
    np.testing.assert_allclose(l1, l2, atol=2e-3, rtol=2e-2)


def test_grad_accum_exact_on_padded_tail():
    """The tail batch (4 real rows + 12 padding at n=20, batch=16) must
    produce the SAME update under accumulation: micro losses accumulate
    as weighted sums normalized once, so padding concentrated in some
    micro-batches cannot shrink the step."""
    rng = np.random.default_rng(7)
    x = rng.normal(size=(20, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)

    def run(accum):
        graph = build_model("mlp", num_outputs=2, hidden=(16,))
        tr = SPMDTrainer(
            graph,
            TrainConfig(epochs=1, batch_size=16, learning_rate=0.1,
                        optimizer="sgd", grad_accum=accum, shuffle=False,
                        log_every=100),
        )
        v = tr.train(x, y)
        return jax.tree_util.tree_leaves(v)

    for a, b in zip(run(1), run(2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-2)


def test_atomic_store_opt_state_roundtrip(tmp_path):
    """The checkpoint store must round-trip a real optimizer state
    EXACTLY: every leaf bit-identical, every dtype preserved (adam's
    int32 step count included), and the JSON meta sidecar intact."""
    import optax

    from mmlspark_tpu.train.resilience import AtomicCheckpointStore

    params = {
        "w": np.arange(6, dtype=np.float32).reshape(2, 3) / 7.0,
        "b": np.linspace(-1, 1, 3).astype(np.float16),
    }
    tx = optax.adam(1e-3)
    opt = tx.init(params)
    grads = jax.tree_util.tree_map(np.ones_like, params)
    _, opt = tx.update(grads, opt, params)  # non-trivial mu/nu/count
    state = {"params": params, "opt_state": jax.device_get(opt)}

    store = AtomicCheckpointStore(str(tmp_path / "ck"))
    store.save(4, state, meta={"note": "roundtrip"})
    target = jax.tree_util.tree_map(np.zeros_like, state)
    restored, meta, step = store.restore(target)
    assert step == 4
    assert meta == {"note": "roundtrip"}
    for a, b in zip(
        jax.tree_util.tree_leaves(state),
        jax.tree_util.tree_leaves(restored),
    ):
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)


def test_split_merge_variables_exact_reconstruction():
    """_split_variables must strip ONLY the sown per-call losses;
    _merge_variables must reassemble everything else exactly."""
    from mmlspark_tpu.train.trainer import (
        _merge_variables,
        _split_variables,
    )

    rng = np.random.default_rng(0)
    variables = {
        "block0": {
            "params": {"w": rng.normal(size=(2, 2)).astype(np.float32)},
            "batch_stats": {"mean": np.zeros(2, np.float32)},
            "losses": {"aux": np.float32(0.5)},
        },
        "head": {"params": {"b": np.ones(3, np.float32)}},
    }
    params, rest = _split_variables(variables)
    assert set(params) == {"block0", "head"}
    assert "losses" not in rest["block0"]
    assert "params" not in rest["block0"]
    merged = _merge_variables(params, rest)
    expected = {
        "block0": {
            "params": variables["block0"]["params"],
            "batch_stats": variables["block0"]["batch_stats"],
        },
        "head": {"params": variables["head"]["params"]},
    }
    assert jax.tree_util.tree_structure(merged) == \
        jax.tree_util.tree_structure(expected)
    for a, b in zip(
        jax.tree_util.tree_leaves(merged),
        jax.tree_util.tree_leaves(expected),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grad_accum_divisibility_guard():
    from mmlspark_tpu.core.exceptions import FriendlyError
    from mmlspark_tpu.models import build_model

    graph = build_model("mlp", num_outputs=2, hidden=(8,))
    x = np.zeros((12, 4), np.float32)
    y = np.zeros((12,), np.int32)
    tr = SPMDTrainer(
        graph,
        TrainConfig(epochs=1, batch_size=12, grad_accum=5, shuffle=False),
    )
    with pytest.raises(FriendlyError, match="grad_accum"):
        tr.train(x, y)
