"""Param system tests (reference behavior: core/contracts/Params.scala,
exercised by VerifyMMLParams-style suites)."""

import pytest

from mmlspark_tpu.core.exceptions import ParamError
from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param, positive
from mmlspark_tpu.core.stage import Transformer


class _Toy(Transformer, HasInputCol, HasOutputCol):
    n = Param("a positive int", 3, ptype=int, validator=positive)
    mode = Param("string-enum domain", "mean", domain=("mean", "median", "custom"))

    def _transform(self, ds):
        return ds


def test_defaults_and_set():
    t = _Toy()
    assert t.n == 3
    assert t.input_col == "input"
    t.set(n=5, input_col="x")
    assert t.n == 5 and t.input_col == "x"
    assert t.is_set("n") and not t.is_set("mode")


def test_chainable_set_returns_self():
    t = _Toy()
    assert t.set(n=7) is t


def test_type_check():
    with pytest.raises(ParamError):
        _Toy().set(n="seven")
    # float->int accepted only for numeric widening on declared numeric params
    t = _Toy().set(n=4)
    assert isinstance(t.n, int)


def test_bool_not_int():
    with pytest.raises(ParamError):
        _Toy().set(n=True)


def test_domain_enforced():
    t = _Toy()
    t.set(mode="median")
    with pytest.raises(ParamError):
        t.set(mode="bogus")


def test_validator():
    with pytest.raises(ParamError):
        _Toy().set(n=0)


def test_unknown_param_rejected():
    with pytest.raises(ParamError):
        _Toy().set(nope=1)


def test_params_table_includes_mixins():
    names = set(_Toy.params())
    assert {"n", "mode", "input_col", "output_col"} <= names


def test_copy_preserves_explicit_values_only():
    t = _Toy().set(n=9)
    c = t.copy()
    assert c.n == 9 and not c.is_set("mode")
    assert c.uid != t.uid
    c2 = t.copy(n=11)
    assert c2.n == 11 and t.n == 9


def test_explain_params_mentions_domain():
    text = _Toy().explain_params()
    assert "median" in text and "positive int" in text


def test_uids_unique_and_prefixed():
    a, b = _Toy(), _Toy()
    assert a.uid != b.uid
    assert a.uid.startswith("_Toy")


# -- app config namespace (core/config.py, MMLConfig analog) ----------------


def test_config_defaults_and_env_override(monkeypatch):
    from mmlspark_tpu.core import config

    config.reset()
    try:
        assert config.get("native_cc") == "c++"
        assert config.get("native_build") is True
        monkeypatch.setenv("MMLSPARK_TPU_NATIVE_BUILD", "false")
        monkeypatch.setenv("MMLSPARK_TPU_NATIVE_CC", "g++-12")
        config.reset()
        assert config.get("native_build") is False
        assert config.get("native_cc") == "g++-12"
    finally:
        config.reset()


def test_config_file_layer_and_unknown_keys(tmp_path, monkeypatch):
    import json

    from mmlspark_tpu.core import config
    from mmlspark_tpu.core.exceptions import FriendlyError

    path = tmp_path / "conf.json"
    path.write_text(json.dumps({"log_level": "DEBUG"}))
    monkeypatch.setenv("MMLSPARK_TPU_CONFIG", str(path))
    config.reset()
    try:
        assert config.get("log_level") == "DEBUG"
        info = config.explain()
        assert info["log_level"]["value"] == "DEBUG"
        assert "doc" in info["cache_dir"]
        with pytest.raises(FriendlyError):
            config.get("nope")
        path.write_text(json.dumps({"not_a_key": 1}))
        config.reset()
        with pytest.raises(FriendlyError, match="unknown config key"):
            config.get("log_level")
    finally:
        monkeypatch.delenv("MMLSPARK_TPU_CONFIG")
        config.reset()
