"""Doc generation + profiling hooks (reference analogs: codegen DocGen
.rst emission; Timer stage tracing upgraded with jax.profiler)."""

import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)


def test_docgen_emits_rst_for_every_stage_module(tmp_path):
    import docgen

    paths = docgen.generate(str(tmp_path))
    names = {os.path.basename(p) for p in paths}
    assert "index.rst" in names and "models.rst" in names
    # the major stage modules each get a page
    for expected in ("train_classifier.rst", "prep.rst", "image.rst",
                     "dnn_model.rst"):
        assert expected in names, names
    # spot-check content: TrainClassifier page documents its params
    text = (tmp_path / "train_classifier.rst").read_text()
    assert "TrainClassifier" in text
    assert "label_col" in text and "learner" in text.lower()
    # models page lists registered architectures
    mtext = (tmp_path / "models.rst").read_text()
    assert "resnet20_cifar10" in mtext and "transformer_lm" in mtext
    # index references every page
    itext = (tmp_path / "index.rst").read_text()
    assert "train_classifier" in itext


def test_docgen_param_table_shape(tmp_path):
    import docgen

    from mmlspark_tpu.stages.train_classifier import TrainClassifier

    rows = docgen._param_table(TrainClassifier)
    assert any("label_col" in r for r in rows)
    assert any("=" * 5 in r for r in rows)  # rst table rules


def test_trace_profile_writes_trace(tmp_path):
    import jax.numpy as jnp

    from mmlspark_tpu.utils.profiling import annotate, trace_profile

    out = str(tmp_path / "trace")
    with trace_profile(out):
        with annotate("matmul"):
            (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    found = [
        f for root, _, files in os.walk(out) for f in files
        if f.endswith((".pb", ".json.gz", ".trace.json.gz"))
    ]
    assert found, f"no trace artifacts under {out}"


def test_timer_profile_dir(tmp_path):
    from mmlspark_tpu.data.dataset import Dataset
    from mmlspark_tpu.stages.prep import SelectColumns, Timer

    ds = Dataset({"a": np.arange(4.0), "b": np.arange(4.0)})
    out_dir = str(tmp_path / "timer-trace")
    timer = Timer(stage=SelectColumns(cols=["a"]), profile_dir=out_dir)
    out = timer.transform(ds)
    assert out.columns == ["a"]
    assert timer.records and timer.records[0]["seconds"] >= 0
    assert os.path.isdir(out_dir) and os.listdir(out_dir)


def test_docgen_html_rendering(tmp_path):
    """The static HTML assembly (sphinx stand-in): tables become real
    <table> markup and toctree entries become links."""
    import tools.docgen as docgen

    rst_dir = str(tmp_path / "api")
    html_dir = str(tmp_path / "html")
    docgen.generate(rst_dir)
    written = docgen.render_html(rst_dir, html_dir)
    assert len(written) > 10
    with open(os.path.join(html_dir, "dnn_learner.html")) as f:
        page = f.read()
    assert "<table><tr><th>param</th>" in page
    assert "batch_size" in page and "<h2>DNNLearner</h2>" in page
    with open(os.path.join(html_dir, "index.html")) as f:
        index = f.read()
    assert "<a href='dnn_learner.html'>" in index
