"""Chunked prefill + pipelined async host runtime (ISSUE 20 tentpole).

The contract under test (docs/PERFORMANCE.md "Chunked prefill & async
host loop"): with ``prefill_chunk=N`` a long prompt's fill becomes
bounded N-token chunk dispatches interleaved with decode ticks under
ONE program family per chunk bucket (``prefill_compile_count <=
num_chunk_buckets``); with ``async_host=True`` decode block N+1
dispatches behind block N's in-flight execution and N's tokens are
fetched only after N+1 is enqueued — still at most one host sync per
block. In BOTH modes (and combined, and on a 2x2 mesh, and across
paged/int8/prefix-cache pools, and through a kill-mid-chunk crash
drill) token streams stay bit-identical to the synchronous monolithic
engine and to the ``generate()`` oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import EngineKilled, Fault, FaultInjector
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.serve import ServeEngine
from mmlspark_tpu.serve.metrics import ServeMetrics
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new, eos_id=None):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new,
                   eos_id=eos_id)
    return np.asarray(out)[0]


# -- config validation -----------------------------------------------------


def test_chunk_validation(lm):
    m, v, _ = lm
    for bad in (12, 6, 3, 9):
        with pytest.raises(FriendlyError, match="power of two"):
            ServeEngine(m, v, slots=1, cache_len=32, prefill_chunk=bad)
    with pytest.raises(FriendlyError, match="exceeds cache_len"):
        ServeEngine(m, v, slots=1, cache_len=32, prefill_chunk=64)
    moe = build_model(
        "transformer_lm_moe", vocab_size=8, d_model=16, heads=2,
        depth=1, n_experts=2, max_len=16,
    )
    mv = moe.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))
    with pytest.raises(FriendlyError, match="MoE"):
        ServeEngine(moe, mv, slots=1, cache_len=16, prefill_chunk=8)


def test_chunk_bucket_ladder(lm):
    m, v, _ = lm
    e = ServeEngine(m, v, slots=1, cache_len=32, prefill_chunk=16)
    # ladder {8, 16}: two chunk buckets, and the prefill pin redirects
    assert e.num_chunk_buckets == 2
    assert e.num_prefill_buckets == 2
    assert e.chunk_bucket(1) == 8
    assert e.chunk_bucket(8) == 8
    assert e.chunk_bucket(9) == 16
    assert e.chunk_bucket(16) == 16
    e8 = ServeEngine(m, v, slots=1, cache_len=32, prefill_chunk=8)
    assert e8.num_chunk_buckets == 1
    # no chunking: the monolithic bucket count is untouched
    mono = ServeEngine(m, v, slots=1, cache_len=32)
    assert mono.num_prefill_buckets > 0
    assert mono.num_chunk_buckets == 0


# -- parity: chunked fills vs generate() / monolithic ----------------------


@pytest.mark.slow  # ci.sh's chunked gate runs the full file unfiltered
def test_chunked_parity_ragged_prompts_and_mid_fill_joins(lm):
    """Chunk=8 over prompts from 1 to 12 tokens (multi-chunk fills for
    the long ones), heterogeneous budgets, and mid-run joins landing
    while other slots are mid-fill AND mid-decode — every stream equals
    generate()'s, under the compile guard with the TIGHTENED pin."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:12], row[:1], row[:9], row[:4], row[:11], row[:6]]
    budgets = [6, 9, 4, 8, 5, 7]

    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=4, prefill_chunk=8)
    results, rids = {}, []
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        for p, n in zip(prompts[:3], budgets[:3]):
            rids.append(engine.submit(p, max_new_tokens=n))
        for _ in range(3):
            results.update({r.id: r for r in engine.step()})
        # joins land while slot 0's 12-token fill may still be open
        for p, n in zip(prompts[3:], budgets[3:]):
            rids.append(engine.submit(p, max_new_tokens=n))
        while engine.busy:
            results.update({r.id: r for r in engine.step()})

    for rid, p, n in zip(rids, prompts, budgets):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, n),
            err_msg=f"chunked fill diverged: request={rid}",
        )
    # the tentpole pin: one program per chunk bucket, ceiling included
    assert engine.prefill_compile_count <= engine.num_chunk_buckets == 1
    assert engine.metrics.chunked_prefills_total >= len(prompts) + 1


def test_chunked_parity_mid_fill_eos_and_tiny_budget(lm):
    """A fill whose FIRST token is the EOS retires at fill completion
    without ever activating; budget=1 retires the same way — both
    match generate()'s trim."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :9])  # 2 chunks at chunk=8
    free = _ref(m, v, prompt, 4)
    eos = int(free[len(prompt)])  # the first generated token

    engine = ServeEngine(m, v, slots=2, cache_len=32, prefill_chunk=8)
    r_eos = engine.submit(prompt, max_new_tokens=4, eos_id=eos)
    r_one = engine.submit(prompt, max_new_tokens=1)
    res = engine.run()
    np.testing.assert_array_equal(
        np.asarray(res[r_eos].tokens), free[:len(prompt) + 1]
    )
    assert res[r_eos].generated == 1
    np.testing.assert_array_equal(
        np.asarray(res[r_one].tokens), free[:len(prompt) + 1]
    )


@pytest.mark.slow  # ci.sh's chunked gate runs the full file unfiltered
def test_chunked_parity_paged_prefix_and_int8(lm):
    """Chunked fills land bit-identically through the paged pool with
    the prefix cache on (a resubmitted prompt seeds its carry from the
    shared prefix) and with int8 KV — the one write_prefill at fill
    completion quantizes ONCE from the bf16 carry, exactly like the
    monolithic path."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:12], row[:12], row[:9], row[:5]]  # [1] re-uses [0]

    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         prefill_chunk=8, paged=True, page_size=8,
                         prefix_cache=True, kv_dtype="int8")
    rids = [engine.submit(p, max_new_tokens=5) for p in prompts]
    res = engine.run()
    oracle = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         paged=True, page_size=8, prefix_cache=True,
                         kv_dtype="int8")
    orids = [oracle.submit(p, max_new_tokens=5) for p in prompts]
    ores = oracle.run()
    for rid, oid, p in zip(rids, orids, prompts):
        np.testing.assert_array_equal(
            np.asarray(res[rid].tokens), np.asarray(ores[oid].tokens),
            err_msg=f"chunked+paged+int8 diverged from monolithic: {p}",
        )
    # dense int8: chunked fills are start=0 whole-range writes (no
    # prefix cache on dense pools), still bit-identical
    dense = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                        prefill_chunk=8, kv_dtype="int8")
    drids = [dense.submit(p, max_new_tokens=5) for p in prompts]
    dres = dense.run()
    for rid, did in zip(rids, drids):
        np.testing.assert_array_equal(
            np.asarray(res[rid].tokens), np.asarray(dres[did].tokens)
        )


# -- parity: async host loop -----------------------------------------------


def test_async_parity_and_at_most_one_sync_per_block(lm, monkeypatch):
    """The async loop's relaxed sync contract: one request decoding 16
    tokens through T=8 blocks pays at most 2 synced fetches (one per
    block — the pipelined fetch lands a tick late but never adds a
    sync), and the stream equals generate()'s."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :4])
    engine = ServeEngine(m, v, slots=1, cache_len=32, decode_block=8,
                         async_host=True)
    rid = engine.submit(prompt, max_new_tokens=17)

    syncs = {"n": 0}
    real_device_get = jax.device_get
    real_asarray = np.asarray

    def counting_device_get(x, *a, **kw):
        syncs["n"] += 1
        return real_device_get(x, *a, **kw)

    def counting_asarray(x, *a, **kw):
        if isinstance(x, jax.Array):
            syncs["n"] += 1
        return real_asarray(x, *a, **kw)

    monkeypatch.setattr(jax, "device_get", counting_device_get)
    monkeypatch.setattr(np, "asarray", counting_asarray)
    res = engine.run()[rid]
    monkeypatch.undo()

    np.testing.assert_array_equal(
        np.asarray(res.tokens), _ref(m, v, prompt, 17)
    )
    assert syncs["n"] <= 2, f"host syncs: {syncs['n']} (> 1 per block)"
    d = engine.metrics.to_dict()
    assert d["async_host"] == 1
    assert d["host_idle_fraction"] is not None


@pytest.mark.slow  # ci.sh's chunked gate runs the full file unfiltered
def test_async_parity_ragged_with_joins_and_overlap(lm):
    """Multi-slot async run with mid-run joins (new fills start while a
    speculative block is in flight — the identity fence and deferred
    frees keep re-leases safe): streams equal generate()'s and the
    engine really pipelined (overlapped dispatches recorded)."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:4], row[:1], row[:9], row[:6], row[:2]]
    budgets = [10, 7, 3, 12, 5]

    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=4, async_host=True,
                         prefill_chunk=8)
    results, rids = {}, []
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        for p, n in zip(prompts[:3], budgets[:3]):
            rids.append(engine.submit(p, max_new_tokens=n))
        for _ in range(2):
            results.update({r.id: r for r in engine.step()})
        for p, n in zip(prompts[3:], budgets[3:]):
            rids.append(engine.submit(p, max_new_tokens=n))
        while engine.busy:
            results.update({r.id: r for r in engine.step()})

    for rid, p, n in zip(rids, prompts, budgets):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, n),
            err_msg=f"async stream diverged: request={rid}",
        )
    assert engine.metrics.overlapped_dispatches_total > 0
    assert engine.decode_compile_count <= engine.num_decode_blocks
    assert engine.prefill_compile_count <= engine.num_chunk_buckets


@pytest.mark.slow  # ci.sh's chunked gate runs the full file unfiltered
def test_chunked_async_parity_2x2_mesh(lm):
    """Chunked fills + the pipelined loop on a data=2,model=2 mesh:
    streams stay bit-identical to single-device generate() and both
    compile pins hold (per-tick inputs still commit to the pinned
    NamedShardings)."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:12], row[:3], row[:9], row[:6]]

    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=4, prefill_chunk=8,
                         async_host=True, mesh={"data": 2, "model": 2})
    results, rids = {}, []
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        rids = [engine.submit(p, max_new_tokens=6) for p in prompts]
        while engine.busy:
            results.update({r.id: r for r in engine.step()})
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 6),
            err_msg=f"mesh chunked+async diverged: request={rid}",
        )
    assert engine.prefill_compile_count <= engine.num_chunk_buckets
    assert engine.decode_compile_count <= engine.num_decode_blocks


# -- crash drill: kill mid-chunk, restore, bit-identical -------------------


@pytest.mark.slow  # ci.sh's chunked gate runs the full file unfiltered
def test_kill_mid_chunk_restore_is_bit_identical(lm):
    """A kill landing at the prefill site while a multi-chunk fill is
    open (chunked + async engine): the park closes the deferred-free
    window, the snapshot carries the mid-fill request as a queued
    entry, and the restored engine finishes every stream bit-identical
    to the uncrashed oracle."""
    import json

    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:12], row[:9], row[:4], row[:11]]
    # tick 0 dispatches each fill's first chunk (both prompts > chunk);
    # tick 1's first prefill firing is slot 0's FINAL chunk while slot
    # 1's fill is still open — the kill lands mid-multi-chunk-fill
    inj = FaultInjector([Fault("serve.prefill", "kill", tick=1)])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=2,
                         prefill_chunk=8, async_host=True, faults=inj)
    rids = [engine.submit(p, max_new_tokens=8) for p in prompts]
    results = {}
    snap = engine.snapshot()
    with pytest.raises(EngineKilled):
        while engine.busy:
            snap = engine.snapshot()
            for res in engine.step():
                results[res.id] = res
    json.dumps(snap)
    assert snap["active"] or snap["queued"]

    rebuilt = ServeEngine.restore(snap, m, v, slots=2, decode_block=2,
                                  prefill_chunk=8, async_host=True)
    results.update(rebuilt.run())
    assert set(results) == set(rids)
    for rid, p in zip(rids, prompts):
        assert results[rid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 8),
            err_msg=f"request {rid} diverged across the mid-chunk kill",
        )


# -- disaggregated fleet: prefill replicas chunk their backlogs ------------


@pytest.mark.slow  # ci.sh's chunked gate runs the full file unfiltered
def test_disagg_chunked_handoff(lm):
    """A prefill-role replica with chunking on advances its fill
    backlog chunk by chunk and fires the KV hand-off at FILL COMPLETION
    — the decode replica adopts without compiling a prefill program,
    and every stream equals generate()'s."""
    from mmlspark_tpu.serve.fleet import DisaggFleet

    m, v, ids = lm
    prompts = [np.asarray(ids[0, :n]) for n in (12, 4, 9, 6)]
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        slots=2, cache_len=32, max_queue=8,
                        decode_block=4, prefill_chunk=8,
                        retry_backoff_s=0.0)
    gids = [fleet.submit(p, 6) for p in prompts]
    results = fleet.run()
    for gid, p in zip(gids, prompts):
        np.testing.assert_array_equal(
            np.asarray(results[gid].tokens), _ref(m, v, p, 6),
            err_msg=f"disagg chunked hand-off diverged: {p}",
        )
    assert fleet.engine(1).prefill_compile_count == 0
    assert fleet.engine(0).metrics.chunked_prefills_total >= len(prompts)
    assert fleet.engine(0).metrics.handoffs_out_total == len(prompts)


# -- pool plumbing: deferred frees + ranged dense writes -------------------


def test_deferred_free_window_and_dense_start_validation(lm):
    from mmlspark_tpu.serve.cache_pool import SlotCachePool

    m, v, _ = lm
    pool = SlotCachePool(m, v, slots=2, cache_len=32)
    s0 = pool.lease()
    s1 = pool.lease()
    pool.defer_frees(1)
    pool.free(s0)
    # inside the window: the lease is NOT reusable yet...
    with pytest.raises(FriendlyError):
        pool.lease()
    # ...and a second free of the same slot is still a double free
    with pytest.raises(FriendlyError, match="double free"):
        pool.free(s0)
    pool.defer_frees(2)
    pool.free(s1)
    pool.flush_frees(1)  # releases gen<=1 only
    assert pool.lease() == s0
    with pytest.raises(FriendlyError):
        pool.lease()
    pool.flush_frees(None)  # close the window: everything releases
    assert pool.lease() == s1

    # ranged writes: int8 dense pools quantize per-head over the FULL
    # row, so a partial write would re-scale earlier positions
    pool8 = SlotCachePool(m, v, slots=1, cache_len=32, kv_dtype="int8")
    slot = pool8.lease()
    from mmlspark_tpu.models.generate import init_cache

    cache = init_cache(m, v, 1, 32)
    with pytest.raises(FriendlyError, match="start=0"):
        pool8.write_prefill(slot, cache, 8, start=4)


# -- honest attribution + schema under pipelining --------------------------


def test_perf_queued_attribution():
    from mmlspark_tpu.core.perf import PerfAnalytics, ProgramCost

    p = PerfAnalytics(n_devices=1)
    p.register_program(
        "decode[T=4]",
        ProgramCost(flops=1e9, bytes_accessed=1e6, source="test"),
    )
    # 10ms interval, 6ms of it queued behind the previous block
    p.record_dispatch("decode[T=4]", 0.010, tokens=4, queued_s=0.006)
    p.record_dispatch("decode[T=4]", 0.004, tokens=4)
    fam = p.summary()["families"]["decode[T=4]"]
    assert fam["device_s"] == pytest.approx(0.008)
    assert fam["queued_s"] == pytest.approx(0.006)
    # MFU divides by EXECUTING time only — pipelining can't halve it
    assert fam["mfu"] == pytest.approx(2e9 / 0.008 / p.peak.flops_per_s)
    # queued_s clamps into [0, seconds]
    p.record_dispatch("decode[T=4]", 0.002, queued_s=5.0)
    assert p.summary()["families"]["decode[T=4]"]["device_s"] == \
        pytest.approx(0.008)


def test_metrics_new_keys_and_host_idle():
    a = ServeMetrics("m", slots=2)
    d = a.to_dict()
    # inert defaults on a monolithic-synchronous engine
    assert d["prefill_chunk"] == 0
    assert d["chunked_prefills_total"] == 0
    assert d["async_host"] == 0
    assert d["overlapped_dispatches_total"] == 0
    assert d["host_idle_fraction"] is None

    b = ServeMetrics("m", slots=2, prefill_chunk=16, async_host=True)
    b.record_prefill_chunk()
    b.record_prefill_chunk()
    b.record_overlapped_dispatch()
    b.record_host_sync(0.002)
    b.sample_tick(0, 1, 0.010, tokens_emitted=1)
    d = b.to_dict()
    assert d["prefill_chunk"] == 16
    assert d["chunked_prefills_total"] == 2
    assert d["async_host"] == 1
    assert d["overlapped_dispatches_total"] == 1
    assert d["host_idle_fraction"] == pytest.approx(0.2)
    assert d["host_sync_wait_s"] == pytest.approx(0.002)
