"""Multi-model serving (mmlspark_tpu.serve.multimodel).

The contract under test (docs/SERVING.md "Multi-model serving"): one
engine hosts several named deployments — stateful LM-decode engines
next to stateless power-of-two-bucketed batch deployments (ONNX-imported
graphs included) — behind one ``submit(model=...)/step()/run()`` facade,
and every request's output is BIT-IDENTICAL to a dedicated single-model
run: the LM emits the same tokens as a lone ``ServeEngine``, a batch
deployment emits the same rows as a direct ``graph.apply`` on the same
examples. Compile pins hold per deployment (the LM's decode/prefill
pins unchanged, batch dispatch bounded by ``num_batch_buckets``),
round-robin scheduling under a device budget never starves a model,
per-model SLOs shed independently, and the ``serve.batch`` fault site
carries the same retry/quarantine/degrade envelope as the LM sites.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import parse_fault_spec
from mmlspark_tpu.core.perf import SloTargets
from mmlspark_tpu.models import build_model
from mmlspark_tpu.serve import ServeEngine
from mmlspark_tpu.serve.multimodel import (
    BatchDeployment,
    MultiModelEngine,
    engine_from_spec,
    parse_models_spec,
)
from mmlspark_tpu.serve.supervisor import ReplicaSet
from mmlspark_tpu.testing.compile_guard import (
    compile_guard,
    serve_compile_guard,
)


def _tiny_lm(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


def _lm_vars(m, seed=0):
    return m.init(jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))


def _mlp(num_outputs=3, hidden=(16,)):
    m = build_model("mlp", num_outputs=num_outputs, hidden=hidden)
    v = m.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.float32))
    return m, v


def _examples(n, dim=8, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=(dim,)).astype(np.float32) for _ in range(n)]


def _prompts(n, vocab=8, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, vocab, size=int(rng.integers(4, 12)))
        for _ in range(n)
    ]


# -- batch deployment ------------------------------------------------------


def test_batch_deployment_rejects_causal_graph():
    m = _tiny_lm()
    with pytest.raises(FriendlyError, match="causal"):
        BatchDeployment(m, _lm_vars(m))


def test_batch_bucket_ladder():
    m, v = _mlp()
    dep = BatchDeployment(m, v, max_batch=8)
    assert [dep.batch_bucket(k) for k in (1, 2, 3, 4, 5, 8)] == \
        [1, 2, 4, 4, 8, 8]
    assert dep.num_batch_buckets == 4  # {1, 2, 4, 8}
    # non-power-of-two max_batch floors down the ladder
    assert BatchDeployment(m, v, max_batch=6).max_batch == 4


def test_batch_deployment_bit_parity_and_compile_pin():
    """A full bucket-sized submission group comes back BIT-EQUAL to a
    direct ``graph.apply`` on the stacked batch (padding is identity at
    bucket size), and however sizes vary the dispatch never compiles
    more than one program per ladder bucket."""
    m, v = _mlp()
    dep = BatchDeployment(m, v, max_batch=4)
    xs = _examples(4)
    direct = np.asarray(m.apply(v, jnp.asarray(np.stack(xs))))

    with compile_guard(lambda: dep.batch_compile_count,
                       max_programs=dep.num_batch_buckets,
                       label="batch dispatch"):
        ids = [dep.submit(x) for x in xs]
        results = {r.id: r for r in dep.step()}
        assert sorted(results) == ids
        for i, rid in enumerate(ids):
            r = results[rid]
            assert r.status == "completed"
            np.testing.assert_array_equal(np.asarray(r.output), direct[i])

        # ragged arrivals land on existing buckets, not new programs
        for k in (1, 3, 2, 4):
            for x in _examples(k, seed=k):
                dep.submit(x)
            got = dep.step()
            assert len(got) == k
            assert all(r.status == "completed" for r in got)
    assert dep.batch_compile_count <= dep.num_batch_buckets


def test_batch_padding_rows_do_not_leak():
    """A partial batch (k < bucket) returns exactly k results and each
    equals the unpadded direct apply row — the zero padding rows are
    sliced off, never surfaced."""
    m, v = _mlp()
    dep = BatchDeployment(m, v, max_batch=8)
    xs = _examples(3, seed=7)
    direct = np.asarray(m.apply(v, jnp.asarray(np.stack(xs))))
    ids = [dep.submit(x) for x in xs]
    results = {r.id: r for r in dep.step()}
    assert sorted(results) == ids
    for i, rid in enumerate(ids):
        np.testing.assert_array_equal(
            np.asarray(results[rid].output), direct[i]
        )


def test_batch_admission_control():
    m, v = _mlp()
    dep = BatchDeployment(m, v, max_batch=4, max_queue=2)
    dep.submit(np.zeros(8, np.float32))
    # shape/dtype lock: the first submit fixes the example geometry
    with pytest.raises(FriendlyError, match="does not match"):
        dep.submit(np.zeros(9, np.float32))
    with pytest.raises(FriendlyError, match="does not match"):
        dep.submit(np.zeros(8, np.float64))
    dep.submit(np.zeros(8, np.float32))
    with pytest.raises(FriendlyError, match="queue is full"):
        dep.submit(np.zeros(8, np.float32))
    assert dep.metrics.rejected == 1


# -- the multi-model engine ------------------------------------------------


def test_multimodel_concurrent_bit_identical(tmp_path):
    """The acceptance bar: one engine serves an LM plus two stateless
    models (one ONNX-imported) concurrently, and EVERY output is
    bit-identical to a dedicated single-model run — the LM under its
    unchanged compile pins, each batch deployment within its bucket
    pin."""
    from mmlspark_tpu.models.onnx_export import save_onnx

    lm = _tiny_lm()
    lmv = _lm_vars(lm)
    clf, clfv = _mlp()
    onnx_path = str(tmp_path / "clf.onnx")
    save_onnx(clf, clfv, (1, 8), onnx_path)
    og = build_model("onnx", path=onnx_path)
    ogv = og.init()

    prompts = _prompts(6)
    xs = _examples(4, seed=3)
    oxs = _examples(4, seed=4)

    # dedicated single-model references
    ref_eng = ServeEngine(lm, lmv, slots=2, cache_len=32, max_queue=8)
    ref_ids = [ref_eng.submit(p, 5) for p in prompts[:2]]
    ref_res = ref_eng.run()
    ref_tokens = {i: ref_res[i].tokens for i in ref_ids}
    clf_direct = np.asarray(clf.apply(clfv, jnp.asarray(np.stack(xs))))
    ox_direct = np.asarray(og.apply(ogv, jnp.asarray(np.stack(oxs))))

    eng = MultiModelEngine(device_budget=2)
    lm_dep = eng.add_lm("lm", lm, lmv, slots=2, cache_len=32, max_queue=8)
    clf_dep = eng.add_batch("clf", clf, clfv, max_batch=4)
    ox_dep = eng.add_onnx("ox", onnx_path, max_batch=4)
    assert eng.models == ["lm", "clf", "ox"]

    with serve_compile_guard(lm_dep):
        gids = {}
        for i, p in enumerate(prompts[:2]):
            gids[("lm", i)] = eng.submit(p, model="lm", max_new_tokens=5)
        for i, x in enumerate(xs):
            gids[("clf", i)] = eng.submit(x, model="clf")
        for i, x in enumerate(oxs):
            gids[("ox", i)] = eng.submit(x, model="ox")
        res = eng.run()

    assert len(res) == len(gids)
    for i, rid in enumerate(ref_ids):
        got = res[gids[("lm", i)]]
        assert got.status == "completed"
        np.testing.assert_array_equal(got.tokens, ref_tokens[rid])
    for i in range(4):
        np.testing.assert_array_equal(
            np.asarray(res[gids[("clf", i)]].output), clf_direct[i]
        )
        np.testing.assert_array_equal(
            np.asarray(res[gids[("ox", i)]].output), ox_direct[i]
        )
    assert clf_dep.batch_compile_count <= clf_dep.num_batch_buckets
    assert ox_dep.batch_compile_count <= ox_dep.num_batch_buckets

    # routing bookkeeping + per-model namespaces in the shared registry
    assert eng.model_of(gids[("lm", 0)]) == "lm"
    assert eng.model_of(gids[("ox", 3)]) == "ox"
    md = eng.metrics_dict()
    assert md["multimodel"] and md["deployments"] == 3
    assert md["submitted"] == 10 and md["completed"] == 10
    assert md["per_model"]["lm"]["kind"] == "lm"
    assert md["per_model"]["clf"]["kind"] == "batch"
    reg = md["registry"]
    for name in ("lm", "clf", "ox"):
        assert reg[f"model{name}.serve.completed"] > 0
    prom = eng.to_prometheus()
    assert "modellm_serve_completed_total" in prom
    assert "modelox_serve_completed_total" in prom
    # one collision-free exposition: no duplicate family lines
    samples = [
        ln.split()[0] for ln in prom.splitlines()
        if ln and not ln.startswith("#")
    ]
    assert len(samples) == len(set(samples))


def test_onnx_roundtrip_deployment_bit_equal(tmp_path):
    """Satellite: export -> import -> serve. The ONNX-imported graph's
    deployment output is bit-equal to calling the imported graph's
    ``apply`` directly on the same (bucket-sized) batch, and close to
    the original flax graph it round-tripped from."""
    from mmlspark_tpu.models.onnx_export import save_onnx

    m, v = _mlp(num_outputs=4, hidden=(16, 16))
    path = str(tmp_path / "roundtrip.onnx")
    save_onnx(m, v, (1, 8), path)
    og = build_model("onnx", path=path)
    ogv = og.init()

    xs = _examples(4, seed=11)
    stacked = jnp.asarray(np.stack(xs))
    direct = np.asarray(og.apply(ogv, stacked))

    dep = BatchDeployment(og, ogv, max_batch=4)
    ids = [dep.submit(x) for x in xs]
    results = {r.id: r for r in dep.step()}
    for i, rid in enumerate(ids):
        np.testing.assert_array_equal(
            np.asarray(results[rid].output), direct[i]
        )
    # the round trip itself only drifts by compute-dtype differences
    flax_out = np.asarray(m.apply(v, stacked))
    np.testing.assert_allclose(direct, flax_out, atol=5e-2)


def test_submit_routing_errors():
    lm = _tiny_lm()
    clf, clfv = _mlp()
    eng = MultiModelEngine()
    eng.add_lm("lm", lm, _lm_vars(lm), slots=2, cache_len=32)
    eng.add_batch("classifier", clf, clfv, max_batch=4)

    # several deployments: model= is required
    with pytest.raises(FriendlyError, match="pass model="):
        eng.submit(np.zeros(8, np.float32))
    # unknown names suggest the nearest deployment
    with pytest.raises(FriendlyError, match="did you mean 'classifier'"):
        eng.submit(np.zeros(8, np.float32), model="clasifier")
    # LM-only kwargs are rejected on batch deployments and vice versa
    with pytest.raises(FriendlyError, match="max_new_tokens"):
        eng.submit(np.zeros(4, np.int32), model="lm")
    with pytest.raises(FriendlyError, match="stateless batch"):
        eng.submit(np.zeros(8, np.float32), model="classifier",
                   max_new_tokens=4)
    with pytest.raises(FriendlyError, match="unknown request id"):
        eng.model_of(123)


def test_duplicate_and_invalid_deployment_names():
    clf, clfv = _mlp()
    eng = MultiModelEngine()
    eng.add_batch("clf", clf, clfv)
    with pytest.raises(FriendlyError, match="already exists"):
        eng.add_batch("clf", clf, clfv)
    with pytest.raises(FriendlyError, match="invalid"):
        eng.add_batch("a.b", clf, clfv)
    with pytest.raises(FriendlyError, match="managed by MultiModelEngine"):
        eng.add_batch("other", clf, clfv, registry=object())


def test_fairness_under_saturating_lm_stream():
    """Satellite: with device_budget=1 and a saturating LM stream, the
    round-robin cursor still admits the classifier within ceil(D/B)=2
    ticks — no deployment starves behind a hot neighbour."""
    lm = _tiny_lm()
    clf, clfv = _mlp()
    eng = MultiModelEngine(device_budget=1)
    eng.add_lm("lm", lm, _lm_vars(lm), slots=2, cache_len=32,
               max_queue=32, decode_block=4)
    eng.add_batch("clf", clf, clfv, max_batch=4)

    # saturate the LM first: plenty of queued decode work every tick
    for p in _prompts(8):
        eng.submit(p, model="lm", max_new_tokens=8)
    for _ in range(3):
        eng.step()
    # now a classifier burst arrives mid-stream
    clf_gids = {eng.submit(x, model="clf") for x in _examples(4)}
    ticks_to_serve = None
    for t in range(1, 5):
        got = {r.id for r in eng.step()}
        if clf_gids & got:
            ticks_to_serve = t
            break
    assert ticks_to_serve is not None and ticks_to_serve <= 2, (
        f"classifier starved for {ticks_to_serve} ticks under "
        "a saturating LM stream"
    )
    eng.run()  # drain


def test_per_model_shed_independence():
    """Satellite: each deployment carries its OWN SloMonitor — one
    model burning its SLO sheds only its own admissions; the neighbour
    keeps completing with zero shed ticks."""
    clf_a, v_a = _mlp()
    clf_b, v_b = _mlp(num_outputs=2)
    eng = MultiModelEngine()
    # an unmeetable TTFT target: any real dispatch latency burns it
    dep_a = eng.add_batch(
        "burns", clf_a, v_a, max_batch=2,
        slo=SloTargets(ttft_p99_ms=1e-9, min_samples=1),
    )
    dep_b = eng.add_batch("fine", clf_b, v_b, max_batch=2)

    # enough traffic for the window to fill, then keep submitting
    for round_ in range(4):
        for x in _examples(2, seed=round_):
            eng.submit(x, model="burns")
            eng.submit(x, model="fine")
        for _ in range(4):
            eng.step()

    assert dep_a.metrics.slo_shed_ticks_total > 0
    assert dep_b.metrics.slo_shed_ticks_total == 0
    assert dep_b.metrics.completed == 8
    reg = eng.registry.to_dict()
    assert reg["modelburns.serve.slo_shed_ticks"] > 0
    assert reg["modelfine.serve.slo_shed_ticks"] == 0


# -- serve.batch fault envelope --------------------------------------------


def test_serve_batch_transient_faults_absorbed():
    """Transient dispatch faults on the serve.batch site retry and every
    example still completes — same envelope as the LM decode sites."""
    m, v = _mlp()
    inj = parse_fault_spec("seed=3,serve.batch:transient=0.4")
    dep = BatchDeployment(m, v, max_batch=4, faults=inj, retry_limit=8)
    ids = [dep.submit(x) for x in _examples(8)]
    results = {}
    for _ in range(50):
        for r in dep.step():
            results[r.id] = r
        if not dep.busy:
            break
    assert sorted(results) == ids
    assert all(r.status == "completed" for r in results.values())
    assert dep.metrics.retries_total >= 1
    assert dep.metrics.faults_injected_total >= 1


def test_serve_batch_retry_exhaustion_quarantines_batch():
    """Retry exhaustion fails the WHOLE in-flight batch as terminal
    'failed' results — the deployment keeps serving instead of dying."""
    m, v = _mlp()
    inj = parse_fault_spec("seed=1,serve.batch:transient=1.0")
    dep = BatchDeployment(m, v, max_batch=4, faults=inj, retry_limit=1)
    ids = [dep.submit(x) for x in _examples(3)]
    results = {r.id: r for r in dep.step()}
    assert sorted(results) == ids
    assert all(r.status == "failed" for r in results.values())
    assert all(r.output is None for r in results.values())
    assert all(r.generated == 0 for r in results.values())
    assert dep.metrics.quarantined_total == 3
    assert dep.metrics.failed == 3
    # still serving: the next batch quarantines too instead of raising
    dep.submit(_examples(1)[0])
    assert all(r.status == "failed" for r in dep.step())


class _OnceOOM:
    """Minimal injector stand-in: one RESOURCE_EXHAUSTED on the first
    fire, silent after — deterministic OOM drill without rate math."""

    listener = None

    def __init__(self):
        self.fired = False

    def fire(self, site, *, tick, request=None, replica=None):
        if not self.fired:
            self.fired = True
            raise RuntimeError("RESOURCE_EXHAUSTED: injected oom drill")


def test_serve_batch_oom_degrades_and_recovers():
    """RESOURCE_EXHAUSTED halves the batch admission cap down the
    EXISTING bucket ladder (no new program), requeues the batch intact,
    and clean dispatches re-escalate the cap back to max_batch."""
    m, v = _mlp()
    dep = BatchDeployment(m, v, max_batch=4, faults=_OnceOOM(),
                          degrade_recover_ticks=2)
    ids = [dep.submit(x) for x in _examples(4)]
    assert dep.step() == []  # the OOM tick: requeued, nothing retired
    assert dep.degraded and dep.queue_depth == 4
    before = dep.batch_compile_count
    results = {}
    for _ in range(10):
        for r in dep.step():
            results[r.id] = r
        if not dep.busy and not dep.degraded:
            break
    assert sorted(results) == ids
    assert all(r.status == "completed" for r in results.values())
    assert not dep.degraded  # cap re-escalated after clean dispatches
    # degradation rode existing ladder buckets: no new programs beyond
    # the ladder's own ceiling
    assert dep.batch_compile_count <= dep.num_batch_buckets
    assert dep.batch_compile_count >= before


def test_engine_kill_is_terminal():
    from mmlspark_tpu.core.faults import EngineKilled

    class _Kill:
        listener = None

        def fire(self, site, *, tick, request=None, replica=None):
            raise EngineKilled("injected kill")

    m, v = _mlp()
    dep = BatchDeployment(m, v, max_batch=2, faults=_Kill())
    dep.submit(_examples(1)[0])
    with pytest.raises(EngineKilled):
        dep.step()
    with pytest.raises(FriendlyError, match="killed"):
        dep.step()


# -- spec grammar ----------------------------------------------------------


def test_parse_models_spec_grammar():
    entries = parse_models_spec(
        "lm=transformer_lm:slots=4:cache_len=64:"
        "slo=ttft_p99_ms=50+error_rate=0.5;"
        "clf=mlp:max_batch=8:hidden=16x16:input_shape=8;"
        "ox=onnx:path=/tmp/m.onnx"
    )
    by_name = {e.name: e for e in entries}
    assert list(by_name) == ["lm", "clf", "ox"]
    assert by_name["lm"].deploy_kwargs == {
        "slots": 4, "cache_len": 64,
        "slo": "ttft_p99_ms=50,error_rate=0.5",  # '+' spells ','
    }
    assert by_name["clf"].deploy_kwargs == {"max_batch": 8}
    assert by_name["clf"].build_config == {
        "hidden": (16, 16), "input_shape": 8,
    }
    assert by_name["ox"].build_config == {"path": "/tmp/m.onnx"}

    with pytest.raises(FriendlyError, match="expected 'name=arch'"):
        parse_models_spec("justaname")
    with pytest.raises(FriendlyError, match="duplicate deployment name"):
        parse_models_spec("a=mlp;a=linear")
    with pytest.raises(FriendlyError, match="key=value"):
        parse_models_spec("a=mlp:oops")
    with pytest.raises(FriendlyError, match="spec is empty"):
        parse_models_spec(" ; ")


def test_engine_from_spec_kind_detection_and_wrong_keys():
    eng = engine_from_spec(
        "lm=transformer_lm:slots=2:cache_len=32:vocab_size=8:"
        "d_model=32:heads=2:depth=1:max_len=32;"
        "clf=mlp:max_batch=4:num_outputs=3:hidden=16x16:input_shape=8",
        seed=0,
    )
    assert isinstance(eng.deployment("lm"), ServeEngine)
    assert isinstance(eng.deployment("clf"), BatchDeployment)

    # deployment keys of the wrong kind name the offending entry
    with pytest.raises(FriendlyError, match="'clf' .* do not apply"):
        engine_from_spec(
            "clf=mlp:slots=4:hidden=16x16:input_shape=8", seed=0
        )
    with pytest.raises(FriendlyError, match="'lm' .* do not apply"):
        engine_from_spec(
            "lm=transformer_lm:max_batch=4:vocab_size=8:d_model=32:"
            "heads=2:depth=1:max_len=32", seed=0
        )
    # archs without a recorded input_shape need the spec key
    with pytest.raises(FriendlyError, match="input_shape"):
        engine_from_spec("clf=mlp:hidden=16x16", seed=0)


def test_registry_unknown_model_suggests_and_names_onnx():
    """Satellite: a typo'd build_model name suggests the nearest
    registered architecture and points at the ONNX escape hatch for
    foreign graphs."""
    with pytest.raises(FriendlyError, match="did you mean 'mlp'"):
        build_model("mpl")
    with pytest.raises(FriendlyError, match="onnx"):
        build_model("definitely_not_a_model")


# -- demo + CLI surface ----------------------------------------------------


def test_run_demo_multimodel(tmp_path):
    from mmlspark_tpu.serve.demo import run_demo

    tel = str(tmp_path / "tel")
    out = run_demo(
        models=(
            "lm=transformer_lm:slots=2:cache_len=32:vocab_size=8:"
            "d_model=32:heads=2:depth=1:max_len=32;"
            "clf=mlp:max_batch=4:num_outputs=3:hidden=16x16:"
            "input_shape=8"
        ),
        n_requests=3, max_new_tokens=4, arrivals_per_tick=2, seed=0,
        device_budget=2, telemetry_dir=tel,
    )
    assert out["multimodel"] and out["deployments"] == 2
    assert out["submitted"] == 6 and out["completed"] == 6
    assert set(out["per_model"]) == {"lm", "clf"}
    assert out["per_model"]["lm"]["decode_compile_count"] >= 1
    assert out["per_model"]["clf"]["batch_compile_count"] >= 1
    for fname in ("events.jsonl", "metrics.json", "trace.json",
                  "metrics.prom"):
        assert os.path.exists(os.path.join(tel, fname))
    with open(os.path.join(tel, "events.jsonl")) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    routed = [e for e in events if e.get("name") == "routed"]
    assert {e["attrs"]["model"] for e in routed} == {"lm", "clf"}
    with open(os.path.join(tel, "metrics.prom")) as f:
        prom = f.read()
    # the telemetry dir carries the MERGED TelemetryHub exposition:
    # per-model prefixes become {model=...} labels on shared families
    assert 'serve_ttft_ms_count{model="lm"}' in prom
    assert 'serve_ttft_ms_count{model="clf"}' in prom


# -- replica routing with a model dimension --------------------------------


def test_replica_set_model_routing():
    """The supervisor's routing key grows a model dimension: replicas
    partition over the models round-robin, submit requires model= and
    routes within that model's replicas only."""
    lm_a = _tiny_lm(depth=1)
    lm_b = _tiny_lm(depth=2)
    va, vb = _lm_vars(lm_a), _lm_vars(lm_b, seed=1)
    rs = ReplicaSet(
        lm_a, va, replicas=2, slots=2, cache_len=32,
        models={"small": (lm_a, va), "big": (lm_b, vb)},
    )
    assert rs.models == ["small", "big"]
    assert rs.replica_model(0) == "small"
    assert rs.replica_model(1) == "big"

    with pytest.raises(FriendlyError, match="model="):
        rs.submit(np.zeros(4, np.int32), 4)
    with pytest.raises(FriendlyError, match="unknown model"):
        rs.submit(np.zeros(4, np.int32), 4, model="medium")

    # bit-parity per model against dedicated engines
    prompts = _prompts(4)
    ref_small = ServeEngine(lm_a, va, slots=2, cache_len=32)
    ref_big = ServeEngine(lm_b, vb, slots=2, cache_len=32)
    ids_s = [ref_small.submit(p, 4) for p in prompts[:2]]
    ids_b = [ref_big.submit(p, 4) for p in prompts[2:]]
    res_s, res_b = ref_small.run(), ref_big.run()
    toks_s = [res_s[i].tokens for i in ids_s]
    toks_b = [res_b[i].tokens for i in ids_b]

    gs = [rs.submit(p, 4, model="small") for p in prompts[:2]]
    gb = [rs.submit(p, 4, model="big") for p in prompts[2:]]
    res = rs.run()
    for g, toks in zip(gs + gb, toks_s + toks_b):
        np.testing.assert_array_equal(res[g].tokens, toks)

    md = rs.metrics_dict()
    assert md["per_replica"]["replica0"]["model"] == "small"
    assert md["per_replica"]["replica1"]["model"] == "big"

    # the model kwarg is rejected on single-model sets
    rs_single = ReplicaSet(lm_a, va, replicas=1, slots=2, cache_len=32)
    with pytest.raises(FriendlyError, match="multi-model"):
        rs_single.submit(np.zeros(4, np.int32), 4, model="small")

    with pytest.raises(FriendlyError, match="at least one model"):
        ReplicaSet(lm_a, va, replicas=2, models={})
    with pytest.raises(FriendlyError, match="replicas"):
        ReplicaSet(lm_a, va, replicas=1,
                   models={"a": (lm_a, va), "b": (lm_b, vb)})
