"""Disaggregated prefill/decode fleet (ISSUE 13 tentpole).

The contract under test (docs/SERVING.md "Disaggregated fleet"): a
``DisaggFleet`` of dedicated prefill and decode replicas behind the
``submit()/step()/run()`` facade serves every stream BIT-IDENTICALLY
to a homogeneous ``ReplicaSet`` at equal device count (and to
``generate()``, the shared oracle) — across ragged prompts, mid-run
joins, single device AND a 2x2 mesh, with per-engine compile pins
intact and decode replicas compiling ZERO prefill programs on the
hand-off path. The cross-replica hand-off plane survives injected
``serve.handoff`` faults (retry, then full-prefill fallback), replica
kills, and drains; the fleet-wide prefix index turns a repeat prompt
into a decode-only request on ANY replica with refcount conservation
(``refcount_audit``: refcount total == mapped references on every
pool, fleet index refs == open indexed requests); and the autoscaler
grows a role under bursty load and drains back to baseline with zero
lost or duplicated requests.

Satellites ride here too: ``ServeMetrics`` percentile helpers return
0.0 (never NaN/None) on empty histograms; an unknown fault site names
ALL six hook points; hedged duplicate prefills of the same prompt
never double-insert or refcount-leak the shared prefix entry.
"""

from __future__ import annotations

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import (
    SITES,
    Fault,
    FaultInjector,
    parse_fault_spec,
)
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.serve import (
    AutoscalePolicy,
    DisaggFleet,
    ReplicaSet,
    ServeEngine,
    parse_autoscale_spec,
)
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new)
    return np.asarray(out)[0]


def _assert_parity(m, v, results, gids, prompts, max_new):
    assert len(results) == len(gids)
    for gid, p in zip(gids, prompts):
        res = results[gid]
        assert res.status == "completed", f"gid={gid}: {res.status}"
        np.testing.assert_array_equal(
            np.asarray(res.tokens), _ref(m, v, p, max_new),
            err_msg=f"gid={gid}",
        )


def _assert_engine_pins(engine):
    assert engine.decode_compile_count <= engine.num_decode_blocks
    assert engine.prefill_compile_count <= engine.num_prefill_buckets


def _assert_pool_audits(fleet):
    """The allocator conservation law on EVERY live paged pool, plus
    the fleet index's own refs == open-indexed audit."""
    for rep in fleet._reps:
        pool = rep.engine.pool
        if hasattr(pool, "refcount_audit"):
            total, mapped = pool.refcount_audit()
            assert total == mapped, (
                f"replica {rep.idx} ({rep.role}): refcount_total="
                f"{total} != mapped_references={mapped}"
            )
    stats = fleet.prefix_index_stats()
    assert stats["refs_total"] == stats["open_indexed"], stats


# -- bit-identity vs the homogeneous ReplicaSet ----------------------------


def _parity_drill(m, v, ids, mesh=None, **extra):
    """The acceptance drill: a 1-prefill + 1-decode fleet vs a
    2-replica homogeneous ReplicaSet at EQUAL device count, ragged
    prompts with mid-run joins, every stream compared token-for-token
    (and against the ``generate()`` oracle). Decode replicas must ride
    the hand-off path — zero prefill compiles."""
    kw = dict(slots=2, cache_len=32, max_queue=8, decode_block=4,
              mesh=mesh, retry_backoff_s=0.0, **extra)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7, 6, 8)]

    rs = ReplicaSet(m, v, replicas=2, **kw)
    rs_gids = [rs.submit(p, 6) for p in prompts[:4]]
    for _ in range(2):
        rs.step()
    rs_gids += [rs.submit(p, 6) for p in prompts[4:]]  # mid-run join
    rs_res = rs.run()

    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        **kw)
    guards = [
        serve_compile_guard(fleet.engine(0), min_prefill=1),
        serve_compile_guard(fleet.engine(1), min_decode=1),
    ]
    with guards[0], guards[1]:
        gids = [fleet.submit(p, 6) for p in prompts[:4]]
        for _ in range(2):
            fleet.step()
        gids += [fleet.submit(p, 6) for p in prompts[4:]]
        results = fleet.run()

    _assert_parity(m, v, results, gids, prompts, 6)
    for rg, fg, p in zip(rs_gids, gids, prompts):
        np.testing.assert_array_equal(
            np.asarray(rs_res[rg].tokens),
            np.asarray(results[fg].tokens),
            err_msg=f"fleet diverged from homogeneous set: {p}",
        )
    # true disaggregation: the decode replica never compiled a prefill
    # program (every request arrived as a KV hand-off) and the prefill
    # replica never compiled a decode block
    assert fleet.engine(1).prefill_compile_count == 0
    assert fleet.engine(0).decode_compile_count == 0
    assert fleet.handoffs_total == len(prompts)
    md = fleet.metrics_dict()
    assert md["per_role"]["prefill"]["handoffs_out_total"] == len(prompts)
    assert md["per_role"]["decode"]["handoffs_adopted_total"] == \
        len(prompts)
    for i in range(2):
        _assert_engine_pins(fleet.engine(i))
    _assert_pool_audits(fleet)


def test_disagg_bit_identical_single_device(lm):
    m, v, ids = lm
    _parity_drill(m, v, ids, mesh=None)


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_disagg_bit_identical_2x2_mesh(lm):
    m, v, ids = lm
    _parity_drill(m, v, ids, mesh={"data": 2, "model": 2})


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_disagg_bit_identical_paged_prefix_mesh(lm):
    """The full stack: paged pools + prefix caches on a 2x2 mesh, the
    hand-off payload landing through ``write_prefill``'s paged path."""
    m, v, ids = lm
    _parity_drill(m, v, ids, mesh={"data": 2, "model": 2},
                  paged=True, prefix_cache=True)


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_disagg_bit_identical_int8_kv(lm):
    """int8 KV pools re-quantize the handed-off bf16 linear cache
    deterministically — same bits as the homogeneous int8 run."""
    m, v, ids = lm
    kw = dict(slots=2, cache_len=32, max_queue=8, decode_block=4,
              kv_dtype="int8", retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    rs = ReplicaSet(m, v, replicas=2, **kw)
    rs_gids = [rs.submit(p, 6) for p in prompts]
    rs_res = rs.run()
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        **kw)
    gids = [fleet.submit(p, 6) for p in prompts]
    results = fleet.run()
    for rg, fg in zip(rs_gids, gids):
        np.testing.assert_array_equal(
            np.asarray(rs_res[rg].tokens),
            np.asarray(results[fg].tokens),
        )


# -- fleet-wide prefix index -----------------------------------------------


def test_fleet_prefix_index_cross_replica_hit(lm):
    """One replica's completed prefill is EVERY replica's cache hit:
    a repeat prompt skips prefill fleet-wide (the prefill replica sees
    no new work), lands decode-only on any decode replica, and every
    pool's refcount audit stays conserved."""
    m, v, ids = lm
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=2,
                        slots=2, cache_len=32, max_queue=8,
                        decode_block=4, paged=True, prefix_cache=True,
                        retry_backoff_s=0.0)
    p = np.asarray(ids[0, :6])
    g0 = fleet.submit(p, 8)
    r0 = fleet.run()
    assert fleet.fleet_prefix_hits_total == 0
    prefills_before = fleet.engine(0).metrics.submitted

    g1 = fleet.submit(p, 8)
    g2 = fleet.submit(p, 8)
    # mid-flight: both hits hold a reference on the index entry
    stats = fleet.prefix_index_stats()
    assert stats["refs_total"] == stats["open_indexed"] == 2
    res = fleet.run()
    assert fleet.fleet_prefix_hits_total == 2
    assert fleet.fleet_prefill_tokens_saved_total == 2 * len(p)
    # the prefill replica never saw the repeats
    assert fleet.engine(0).metrics.submitted == prefills_before
    oracle = _ref(m, v, p, 8)
    for gid, results in ((g0, r0), (g1, res), (g2, res)):
        np.testing.assert_array_equal(
            np.asarray(results[gid].tokens), oracle, err_msg=f"{gid}")
    _assert_pool_audits(fleet)
    md = fleet.metrics_dict()
    assert md["fleet_prefix_hits_total"] == 2
    assert md["fleet_prefix_entries"] >= 1


def test_fleet_index_lru_eviction_pins_referenced_entries(lm):
    m, v, ids = lm
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        prefix_index_capacity=2, slots=2, cache_len=32,
                        max_queue=8, retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (4, 5, 6, 7)]
    # wave 1 fills the index to capacity and commits (refs drop to 0)
    for p in prompts[:2]:
        fleet.submit(p, 4)
    fleet.run()
    assert fleet.prefix_index_stats()["entries"] == 2
    # wave 2's inserts evict the now-unreferenced wave-1 entries; a
    # single-burst wave would instead PIN every entry (refs > 0) and
    # the index would deliberately overshoot rather than drop a
    # referenced payload
    for p in prompts[2:]:
        fleet.submit(p, 4)
    fleet.run()
    stats = fleet.prefix_index_stats()
    assert stats["entries"] <= 2
    assert stats["evictions_total"] >= 2
    assert stats["refs_total"] == 0


# -- hand-off fault site ---------------------------------------------------


def test_handoff_transient_fault_retries_bit_identically(lm):
    """A transient ``serve.handoff`` fault is absorbed by the adopt
    retry loop — the payload lands on a later attempt, no fallback."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.handoff", "transient", times=2)])
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        slots=2, cache_len=32, max_queue=8,
                        decode_block=4, faults=inj,
                        retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    gids = [fleet.submit(p, 6) for p in prompts]
    results = fleet.run()
    _assert_parity(m, v, results, gids, prompts, 6)
    md = fleet.metrics_dict()
    assert md["handoff_fallbacks_total"] == 0


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_handoff_lost_payload_falls_back_to_full_prefill(lm):
    """A hand-off that cannot land (persistent fault) falls back to a
    full local prefill on the decode replica — the stream still
    completes bit-identically, and the fallback is counted."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.handoff", "transient",
                               times=1000)])
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        slots=2, cache_len=32, max_queue=8,
                        decode_block=4, faults=inj,
                        retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9)]
    gids = [fleet.submit(p, 6) for p in prompts]
    results = fleet.run()
    _assert_parity(m, v, results, gids, prompts, 6)
    md = fleet.metrics_dict()
    assert md["handoff_fallbacks_total"] == len(prompts)
    # the fallback ran real prefills on the decode replica
    assert fleet.engine(1).prefill_compile_count > 0


# -- failover / drain ------------------------------------------------------


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_decode_replica_kill_failover_bit_identical(lm):
    """Killing a decode replica mid-decode-block restores it from its
    periodic snapshot; handed-off streams resume through the
    emitted-prefix / local-re-prefill path bit-identically."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.decode", "kill", tick=3,
                               replica=1)])
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=2,
                        slots=4, cache_len=32, max_queue=8,
                        decode_block=2, snapshot_every_ticks=2,
                        faults=inj, retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7, 6, 8)]
    budgets = [12, 3, 12, 3, 12, 12]
    gids = [fleet.submit(p, b) for p, b in zip(prompts, budgets)]
    results = fleet.run()
    assert fleet.replica_failovers_total == 1
    assert len(results) == len(gids)
    for gid, p, b in zip(gids, prompts, budgets):
        assert results[gid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[gid].tokens), _ref(m, v, p, b),
            err_msg=f"gid={gid}",
        )
    assert fleet.replica_state(1) in ("healthy", "degraded")
    assert fleet.replica_role(1) == "decode"  # role survives failover
    _assert_pool_audits(fleet)


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_prefill_replica_kill_failover_bit_identical(lm):
    """Killing the PREFILL replica loses its undelivered payloads; the
    fleet re-routes every affected request from its ledger through the
    restored engine and the streams stay bit-identical."""
    m, v, ids = lm
    # tick 0: a prefill-role engine retires each request at admission
    # (the slot frees on hand-off), so its whole backlog prefills in
    # the first tick — later ticks never dispatch a prefill
    inj = FaultInjector([Fault("serve.prefill", "kill", tick=0,
                               replica=0)])
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        slots=2, cache_len=32, max_queue=8,
                        decode_block=2, snapshot_every_ticks=2,
                        faults=inj, retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    gids = [fleet.submit(p, 8) for p in prompts]
    results = fleet.run()
    assert fleet.replica_failovers_total == 1
    _assert_parity(m, v, results, gids, prompts, 8)
    assert fleet.replica_role(0) == "prefill"


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_drain_decode_replica_migrates_bit_identically(lm):
    """Zero-loss drain of a decode replica mid-run: pending streams
    migrate to the surviving decode replica with their emitted
    prefixes; the drained replica leaves the prefix-index locality
    sets."""
    m, v, ids = lm
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=2,
                        slots=4, cache_len=32, max_queue=8,
                        decode_block=2, snapshot_every_ticks=2,
                        retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7)]
    gids = [fleet.submit(p, 12) for p in prompts]
    for _ in range(3):
        fleet.step()
    fleet.drain(1)
    assert fleet.replica_state(1) in ("draining", "drained")
    g_late = fleet.submit(prompts[0], 12)
    results = fleet.run()
    assert fleet.replica_state(1) == "drained"
    assert fleet.drains_total == 1
    _assert_parity(m, v, results, gids + [g_late],
                   prompts + [prompts[0]], 12)
    for entry in fleet._index.values():
        assert 1 not in entry.home
    with pytest.raises(FriendlyError, match="already"):
        fleet.drain(1)
    _assert_pool_audits(fleet)


# -- autoscaling -----------------------------------------------------------


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_autoscaler_scales_up_under_burst_and_drains_back(lm):
    """Bursty arrivals push per-replica load over ``queue_high``: the
    fleet spawns replicas from the parked budget; once traffic stops,
    idle replicas drain back to baseline. Every request completes
    exactly once — nothing lost, nothing duplicated."""
    m, v, ids = lm
    fleet = DisaggFleet(
        m, v, prefill_replicas=1, decode_replicas=1,
        autoscale=AutoscalePolicy(
            max_prefill=2, max_decode=3, queue_high=1.0,
            slo_burn_ticks=0, idle_ticks=2, cooldown_ticks=0,
        ),
        slots=1, cache_len=32, max_queue=16, decode_block=4,
        retry_backoff_s=0.0,
    )
    assert fleet._parked == {"prefill": 1, "decode": 2}
    prompts = [np.asarray(ids[0, 2:2 + 4 + (i % 3)]) for i in range(8)]
    gids = [fleet.submit(p, 8) for p in prompts]
    results = fleet.run()
    assert fleet.scale_ups_total >= 1
    assert len(results) == len(set(gids)) == len(gids)
    for gid, p in zip(gids, prompts):
        assert results[gid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[gid].tokens), _ref(m, v, p, 8))
    # idle fleet shrinks back to the baseline floor
    for _ in range(12):
        fleet.step()
    assert fleet.scale_downs_total >= 1
    assert fleet.prefill_replicas == 1
    assert fleet.decode_replicas == 1
    md = fleet.metrics_dict()
    assert md["parked_prefill"] == 1
    assert md["parked_decode"] == 2
    _assert_pool_audits(fleet)


def test_autoscale_spec_parsing_and_validation(lm):
    pol = parse_autoscale_spec("max_decode=4,queue_high=1.5,idle_ticks=3")
    assert pol.max_decode == 4
    assert pol.queue_high == 1.5
    assert pol.idle_ticks == 3
    assert pol.min_decode == 1  # defaults survive partial specs
    with pytest.raises(FriendlyError, match="unknown autoscale key"):
        parse_autoscale_spec("bogus=3")
    with pytest.raises(FriendlyError, match="max_decode"):
        AutoscalePolicy(min_decode=3, max_decode=2)
    m, v, _ids = lm
    with pytest.raises(FriendlyError, match="autoscale floor"):
        DisaggFleet(m, v, decode_replicas=1,
                    autoscale=AutoscalePolicy(min_decode=2))


# -- construction / validation ---------------------------------------------


def test_fleet_ctor_validation(lm):
    m, v, _ids = lm
    with pytest.raises(FriendlyError, match="at least one replica"):
        DisaggFleet(m, v, prefill_replicas=0)
    with pytest.raises(FriendlyError, match="managed by DisaggFleet"):
        DisaggFleet(m, v, role="decode")
    with pytest.raises(FriendlyError, match="managed by DisaggFleet"):
        DisaggFleet(m, v, replica=0)
    with pytest.raises(FriendlyError, match="role must be"):
        ServeEngine(m, v, role="hybrid")


# -- fleet snapshot / restore ----------------------------------------------


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_fleet_snapshot_restore_resumes_bit_identically(lm):
    """The fleet checkpoint round-trip: open streams restore onto a
    FRESH fleet with their emitted prefixes and finish bit-identically
    under their original global ids."""
    m, v, ids = lm
    kw = dict(slots=2, cache_len=32, max_queue=8, decode_block=2,
              retry_backoff_s=0.0)
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        **kw)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    gids = [fleet.submit(p, 12) for p in prompts]
    for _ in range(4):
        fleet.step()
    snap = fleet.snapshot()
    assert snap["version"] == 1
    restored = DisaggFleet.restore(snap, m, v, **kw)
    results = restored.run()
    _assert_parity(m, v, results, gids, prompts, 12)
    with pytest.raises(FriendlyError, match="snapshot version"):
        DisaggFleet.restore({"version": 99}, m, v, **kw)


# -- satellite: percentile helpers are 0.0 on empty ------------------------


def test_percentile_helpers_zero_on_empty_histograms(lm):
    """Regression: a cold engine (or role with no finished work yet —
    routine in a disagg fleet) reports 0.0 percentiles, never
    NaN/None, so dashboards and route ordering stay arithmetic-safe."""
    m, v, _ids = lm
    eng = ServeEngine(m, v, slots=2, cache_len=32)
    assert eng.metrics.ttft_p99_ms() == 0.0
    assert eng.metrics.per_token_p99_ms() == 0.0
    assert eng.metrics.tick_p99_ms() == 0.0
    fleet = DisaggFleet(m, v)
    assert fleet.ttft_p99_ms() == 0.0
    assert fleet.metrics_dict()["ttft_ms_p99"] == 0.0


# -- satellite: unknown fault site names every hook point ------------------


def test_unknown_fault_site_error_lists_all_sites():
    # seven serve.* sites plus the trainer's four train.* sites
    assert "serve.handoff" in SITES and "train.step" in SITES
    assert "serve.batch" in SITES
    assert len(SITES) == 11
    with pytest.raises(FriendlyError) as ei:
        parse_fault_spec("bogus.site:transient=0.5")
    for site in SITES:
        assert site in str(ei.value)
    with pytest.raises(FriendlyError) as ei:
        Fault("bogus.site", "transient")
    for site in SITES:
        assert site in str(ei.value)


# -- satellite: hedged double-prefill of a shared prefix -------------------


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _hedge_prefix_drill(m, v, ids, mesh=None):
    """Two hedged copies prefill the SAME prompt on different
    replicas; first-committed-wins cancels the loser mid-flight. The
    shared prefix entry must exist at most once per pool and every
    pool's refcounts must stay conserved — a hedge must never
    double-insert or leak."""
    clk = _FakeClock()
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=8, decode_block=2, hedge_ms=50.0,
                    clock=clk, mesh=mesh, paged=True,
                    prefix_cache=True, snapshot_every_ticks=None,
                    retry_backoff_s=0.0)
    p = np.asarray(ids[0, :6])
    gid = rs.submit(p, 12)
    rs.step()
    clk.t = 0.2  # past the hedge deadline: duplicate onto replica 1
    results = rs.run()
    assert rs.hedges_total == 1
    np.testing.assert_array_equal(
        np.asarray(results[gid].tokens), _ref(m, v, p, 12))
    for i in range(2):
        pool = rs.engine(i).pool
        total, mapped = pool.refcount_audit()
        assert total == mapped, f"replica {i}: {total} != {mapped}"
        # the prompt's prefix entry exists AT MOST once per pool
        assert pool.paging_stats()["prefix_cache_entries"] <= 1
    # resubmitting the same prompt hits a prefix cache, not a re-insert
    g2 = rs.submit(p, 12)
    res2 = rs.run()
    np.testing.assert_array_equal(
        np.asarray(res2[g2].tokens), _ref(m, v, p, 12))
    for i in range(2):
        total, mapped = rs.engine(i).pool.refcount_audit()
        assert total == mapped


def test_hedged_shared_prefix_no_double_insert_single_device(lm):
    m, v, ids = lm
    _hedge_prefix_drill(m, v, ids, mesh=None)


@pytest.mark.slow  # ci.sh's disagg gate runs the full file unfiltered
def test_hedged_shared_prefix_no_double_insert_2x2_mesh(lm):
    m, v, ids = lm
    _hedge_prefix_drill(m, v, ids, mesh={"data": 2, "model": 2})


# -- metrics schema --------------------------------------------------------


def test_fleet_metrics_dict_schema(lm):
    m, v, ids = lm
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        slots=2, cache_len=32, max_queue=8,
                        retry_backoff_s=0.0)
    fleet.submit(np.asarray(ids[0, :5]), 4)
    fleet.run()
    md = fleet.metrics_dict()
    for key in ("disagg", "prefill_replicas", "decode_replicas",
                "fleet_ticks", "submitted", "completed", "failed",
                "expired", "stalled", "tokens_generated",
                "tokens_per_sec", "wall_s", "ttft_ms_p99",
                "handoffs_total", "handoff_fallbacks_total",
                "fleet_prefix_hits_total", "fleet_prefix_entries",
                "fleet_prefill_tokens_saved_total",
                "replica_failovers_total", "drains_total",
                "scale_ups_total", "scale_downs_total",
                "parked_prefill", "parked_decode", "per_role",
                "per_replica"):
        assert key in md, key
    for role in ("prefill", "decode"):
        for key in ("replicas", "submitted", "tokens_generated",
                    "queue_depth", "handoffs_out_total",
                    "handoffs_adopted_total",
                    "handoff_fallbacks_total"):
            assert key in md["per_role"][role], (role, key)
    for rep_key, rep in md["per_replica"].items():
        assert rep["role"] in ("prefill", "decode"), rep_key
        for key in ("state", "failovers", "submitted", "completed",
                    "tokens_generated", "handoffs_out_total",
                    "handoffs_adopted_total", "queue_depth",
                    "decode_compile_count", "prefill_compile_count"):
            assert key in rep, (rep_key, key)
    assert md["submitted"] == 1
    assert md["completed"] == 1
