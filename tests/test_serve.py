"""Continuous-batching serving engine (mmlspark_tpu.serve).

The contract under test (docs/SERVING.md): a slot-based KV-cache pool
with exact lease/free accounting, an engine whose staggered multi-tenant
decode emits BYTE-IDENTICAL tokens to single-request ``generate()``
while compiling the fused decode step exactly once, deterministic
tick-based deadlines, and typed admission-control errors.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.metrics_contracts import MetricData
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.serve import ServeEngine, SlotCachePool
from mmlspark_tpu.testing.compile_guard import (
    compile_guard,
    serve_compile_guard,
)

PERIOD = 4


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


# -- slot pool -------------------------------------------------------------


def test_slot_pool_lease_free_accounting():
    m = _tiny()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    pool = SlotCachePool(m, v, slots=3, cache_len=16)
    assert pool.free_count == 3 and pool.leased_count == 0
    assert pool.utilization == 0.0

    a, b, c = pool.lease(), pool.lease(), pool.lease()
    assert sorted((a, b, c)) == [0, 1, 2]
    assert pool.free_count == 0 and pool.utilization == 1.0
    with pytest.raises(FriendlyError, match="no free KV-cache slots"):
        pool.lease()

    pool.free(b)
    assert pool.free_count == 1 and pool.leased_count == 2
    with pytest.raises(FriendlyError, match="not leased"):
        pool.free(b)  # double free
    assert pool.lease() == b  # the freed slot is reusable

    # buffer geometry: one (K, V) pair per cache-accepting block, slot-major
    for ck, cv in pool.buffers.values():
        assert ck.shape[:2] == (3, 16) and ck.dtype == jnp.bfloat16
        assert cv.shape == ck.shape


def test_slot_pool_guards():
    m = _tiny()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(FriendlyError, match="slots"):
        SlotCachePool(m, v, slots=0, cache_len=16)
    with pytest.raises(FriendlyError, match="cache_len"):
        SlotCachePool(m, v, slots=2, cache_len=1)


# -- token parity (the acceptance test) ------------------------------------


@pytest.mark.parametrize("config", [
    {},                                        # learned positions
    {"pos_embedding": "rope", "kv_heads": 1},  # RoPE + MQA
])
def test_staggered_arrivals_match_generate(config):
    """Three requests with different prompt lengths, submitted on
    different ticks, sharing 2 slots: every request's token stream must
    be byte-identical to a single-request ``generate()`` call, and the
    fused decode step must have compiled exactly once — requests joining
    and leaving mid-flight never retrace it."""
    m = _tiny(**config)
    v, ids = _train_lm(m)
    prompts = [np.asarray(ids[0, :n]) for n in (4, 6, 7)]
    want = {
        i: np.asarray(generate(m, v, p[None], max_new_tokens=8))[0]
        for i, p in enumerate(prompts)
    }

    engine = ServeEngine(m, v, slots=2, cache_len=32)
    results = {}
    rid_to_idx = {}
    with compile_guard(lambda: engine.decode_compile_count,
                       max_programs=engine.num_decode_blocks,
                       min_programs=1, label="decode"):
        for i, p in enumerate(prompts):  # staggered: one submit per tick
            rid_to_idx[engine.submit(p, max_new_tokens=8)] = i
            for res in engine.step():
                results[res.id] = res
        while engine.busy:
            for res in engine.step():
                results[res.id] = res

    assert len(results) == 3
    for rid, res in results.items():
        assert res.status == "completed"
        np.testing.assert_array_equal(
            np.asarray(res.tokens), want[rid_to_idx[rid]]
        )


def test_more_requests_than_slots_still_match():
    """Queue pressure: 4 requests through 1 slot — pure sequential
    reuse of the same slot buffers (stale K/V from the previous tenant
    must be invisible)."""
    m = _tiny()
    v, ids = _train_lm(m)
    prompts = [np.asarray(ids[0, :n]) for n in (4, 5, 6, 8)]
    engine = ServeEngine(m, v, slots=1, cache_len=32, max_queue=4)
    rids = [engine.submit(p, max_new_tokens=6) for p in prompts]
    results = engine.run()
    for rid, p in zip(rids, prompts):
        want = np.asarray(generate(m, v, p[None], max_new_tokens=6))[0]
        np.testing.assert_array_equal(np.asarray(results[rid].tokens), want)
    # distinct XLA programs, one per ladder block size actually run —
    # never one per token or per scan iteration
    assert 1 <= engine.decode_compile_count <= engine.num_decode_blocks


def test_eos_retires_early():
    m = _tiny()
    v, ids = _train_lm(m)
    prompt = np.asarray(ids[0, :4])
    ref = np.asarray(generate(m, v, prompt[None], max_new_tokens=8))[0]
    eos = int(ref[5])  # the 2nd generated token, by construction
    engine = ServeEngine(m, v, slots=2, cache_len=32)
    rid = engine.submit(prompt, max_new_tokens=8, eos_id=eos)
    res = engine.run()[rid]
    assert res.status == "completed"
    assert res.generated == 2 and int(res.tokens[-1]) == eos


# -- deadlines and admission control ---------------------------------------


def test_deadline_expiry_in_queue():
    """With 1 slot busy on a long request, a queued request whose
    deadline passes expires WITHOUT ever being admitted (no prefill, no
    tokens) — deterministic in ticks."""
    m = _tiny()
    v, ids = _train_lm(m, steps=5)
    engine = ServeEngine(m, v, slots=1, cache_len=32, max_queue=2)
    rid_a = engine.submit(np.asarray(ids[0, :4]), max_new_tokens=10)
    rid_b = engine.submit(np.asarray(ids[0, :5]), max_new_tokens=4,
                          deadline_ticks=2)
    results = engine.run()
    assert results[rid_a].status == "completed"
    assert results[rid_a].generated == 10
    assert results[rid_b].status == "expired"
    assert results[rid_b].generated == 0
    assert engine.metrics.expired == 1 and engine.metrics.completed == 1


def test_run_max_ticks_attaches_partial_results():
    """``run(max_ticks=N)`` overrunning must not DISCARD the finished
    work: the raised FriendlyError carries ``err.results`` with every
    completed request plus the pending ones retired as ``"stalled"``,
    and the engine is left drained (not busy, pool empty)."""
    m = _tiny()
    v, ids = _train_lm(m, steps=5)
    engine = ServeEngine(m, v, slots=1, cache_len=32, max_queue=4,
                         decode_block=1)
    rid_short = engine.submit(np.asarray(ids[0, :4]), max_new_tokens=2)
    rid_long = engine.submit(np.asarray(ids[0, :5]), max_new_tokens=20)
    with pytest.raises(FriendlyError, match="stalled") as ei:
        engine.run(max_ticks=4)
    results = ei.value.results
    assert results[rid_short].status == "completed"
    assert results[rid_short].generated == 2
    assert results[rid_long].status == "stalled"
    # partial progress travels with the stalled result
    assert 0 < results[rid_long].generated < 20
    assert engine.metrics.stalled == 1 and engine.metrics.completed == 1
    assert not engine.busy and engine.pool.leased_count == 0
    # the drained engine is still serviceable
    rid2 = engine.submit(np.asarray(ids[0, :4]), max_new_tokens=2)
    assert engine.run()[rid2].status == "completed"


def test_expire_active_slot_forces_device_state_dead():
    """Expiring an ACTIVE request must kill its device-side row — live
    mask False, position 0 — immediately, so the fused decode spends no
    flash-decode KV traffic on a corpse and the slot is re-leasable."""
    m = _tiny()
    v, ids = _train_lm(m, steps=5)
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=4,
                         decode_block=1)
    prompt_b = np.asarray(ids[0, :5])
    ref_b = generate(m, v, prompt_b[None], 10)[0]
    rid_a = engine.submit(np.asarray(ids[0, :4]), max_new_tokens=12,
                          deadline_ticks=2)
    rid_b = engine.submit(prompt_b, max_new_tokens=10)
    results = {r.id: r for r in engine.step()}  # tick 0: both admitted
    slot_a = next(s for s, st in engine._sched.active.items()
                  if st.req.id == rid_a)
    while rid_a not in results:
        results.update({r.id: r for r in engine.step()})
    assert results[rid_a].status == "expired"
    # the expired row is dead ON DEVICE, mid-run, with B still active
    assert not bool(np.asarray(jax.device_get(engine.pool.live))[slot_a])
    assert int(np.asarray(jax.device_get(
        engine.pool.positions))[slot_a]) == 0
    assert any(st.req.id == rid_b
               for st in engine._sched.active.values())
    # the freed slot re-leases cleanly while B keeps decoding
    rid_c = engine.submit(np.asarray(ids[0, :6]), max_new_tokens=4)
    results.update(engine.run())
    assert results[rid_b].status == "completed"
    np.testing.assert_array_equal(np.asarray(results[rid_b].tokens),
                                  np.asarray(ref_b))
    assert results[rid_c].status == "completed"


def test_expired_slot_releases_same_tick():
    """The slot freed by an active-request expiry is safe to re-lease
    in the SAME tick: the replacement prefills into it immediately and
    its stream matches ``generate()`` (no stale KV bleed-through)."""
    m = _tiny()
    v, ids = _train_lm(m, steps=5)
    engine = ServeEngine(m, v, slots=1, cache_len=32, max_queue=4,
                         decode_block=1)
    prompt_b = np.asarray(ids[0, :5])
    ref_b = generate(m, v, prompt_b[None], 6)[0]
    rid_a = engine.submit(np.asarray(ids[0, :4]), max_new_tokens=12,
                          deadline_ticks=2)
    rid_b = engine.submit(prompt_b, max_new_tokens=6)  # waits for the slot
    results = engine.run()
    assert results[rid_a].status == "expired"
    assert results[rid_b].status == "completed"
    # B entered the slot on the very tick A expired out of it
    assert results[rid_b].first_token_tick == results[rid_a].finish_tick
    np.testing.assert_array_equal(np.asarray(results[rid_b].tokens),
                                  np.asarray(ref_b))


def test_queue_full_raises_typed_error():
    m = _tiny()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = ServeEngine(m, v, slots=1, cache_len=32, max_queue=2)
    engine.submit(np.ones(4, np.int32), max_new_tokens=2)
    engine.submit(np.ones(4, np.int32), max_new_tokens=2)
    with pytest.raises(FriendlyError, match="queue is full"):
        engine.submit(np.ones(4, np.int32), max_new_tokens=2)
    assert engine.metrics.rejected == 1
    assert engine.metrics.submitted == 2


def test_submit_validation():
    m = _tiny()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    engine = ServeEngine(m, v, slots=1, cache_len=16)
    with pytest.raises(FriendlyError, match="1-D"):
        engine.submit(np.ones((2, 4), np.int32), max_new_tokens=2)
    with pytest.raises(FriendlyError, match="max_new_tokens"):
        engine.submit(np.ones(4, np.int32), max_new_tokens=0)
    with pytest.raises(FriendlyError, match="cache_len"):
        engine.submit(np.ones(10, np.int32), max_new_tokens=10)
    with pytest.raises(FriendlyError, match="deadline_ticks"):
        engine.submit(np.ones(4, np.int32), max_new_tokens=2,
                      deadline_ticks=0)
    with pytest.raises(FriendlyError, match="non-empty"):
        engine.submit(np.zeros(0, np.int32), max_new_tokens=2)
    with pytest.raises(FriendlyError, match="max_new_tokens"):
        engine.submit(np.ones(4, np.int32), max_new_tokens=-3)
    # a prompt >= cache_len gets the POINTED admission error (it could
    # never fit a single generated token, whatever the budget)
    with pytest.raises(FriendlyError, match="truncate the prompt"):
        engine.submit(np.ones(16, np.int32), max_new_tokens=1)
    # out-of-vocab prompt tokens are rejected at submit, not at decode
    with pytest.raises(FriendlyError, match=r"in \[0, 8\)"):
        engine.submit(np.full(4, 99, np.int32), max_new_tokens=2)
    with pytest.raises(FriendlyError, match=r"in \[0, 8\)"):
        engine.submit(np.asarray([1, -2, 3], np.int32), max_new_tokens=2)
    # nothing above leaked into the accounting
    assert engine.metrics.submitted == 0 and not engine.busy


def test_engine_build_guards():
    m = _tiny()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    # learned position table bounds cache_len
    with pytest.raises(FriendlyError, match="position table"):
        ServeEngine(m, v, cache_len=64)
    # sliding-window models roll their cache; the linear slot pool
    # refuses rather than silently mis-serving long requests
    mw = _tiny(window=6)
    vw = mw.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(FriendlyError, match="window"):
        ServeEngine(mw, vw, cache_len=32)
    ServeEngine(mw, vw, cache_len=6)  # cache_len <= window is fine


# -- metrics ---------------------------------------------------------------


def test_metrics_dict_and_snapshot():
    m = _tiny()
    v, ids = _train_lm(m, steps=5)
    engine = ServeEngine(m, v, slots=2, cache_len=32)
    engine.submit(np.asarray(ids[0, :4]), max_new_tokens=3)
    engine.submit(np.asarray(ids[0, :6]), max_new_tokens=3)
    engine.run()

    d = engine.metrics.to_dict()
    for key in ("queue_depth_mean", "queue_depth_max", "ttft_ticks_mean",
                "ttft_ms_mean", "per_token_ms", "slot_utilization_mean",
                "slot_utilization_peak", "tokens_per_sec"):
        assert d[key] is not None, key
    assert d["completed"] == 2 and d["tokens_generated"] == 6
    assert 0.0 < d["slot_utilization_peak"] <= 1.0
    json.dumps(d)  # the CLI's one-line contract: JSON-able as-is

    records = engine.metrics.snapshot()
    assert records and all(isinstance(r, MetricData) for r in records)
    assert all(r.group in ("serve", "table") for r in records)
    names = {r.name for r in records}
    assert "serve.completed" in names and "serve.per_token_ms" in names
    # non-scalar metrics must NOT be dropped: prefill_buckets reaches the
    # metrics plane as a create_table record
    tables = [r for r in records if r.group == "table"]
    assert any(r.name == "serve.prefill_buckets" for r in tables)


# -- compile-count invariants (bucketed prefill + fused decode) -------------


def test_mixed_length_soak_pins_compile_counts():
    """Soak with mixed-length joiners: every distinct prompt length in
    [1, 12] flows through 2 slots. The fused decode step must compile
    exactly once and bucketed prefill at most once per power-of-two
    bucket — NOT once per distinct length — while every request still
    matches single-request ``generate()`` byte for byte."""
    m = _tiny()
    v, ids = _train_lm(m)
    lengths = [4, 1, 12, 7, 8, 3, 10, 2, 5, 9]  # raggedy on purpose
    prompts = [np.asarray(ids[0, :n]) for n in lengths]
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=16)
    assert engine.num_prefill_buckets == 3  # 8, 16, 32
    rids = []
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        results = {}
        for i, p in enumerate(prompts):  # two joiners per tick
            rids.append(engine.submit(p, max_new_tokens=4))
            if i % 2:
                results.update({r.id: r for r in engine.step()})
        results.update(engine.run())
    for rid, p in zip(rids, prompts):
        want = np.asarray(generate(m, v, p[None], max_new_tokens=4))[0]
        np.testing.assert_array_equal(np.asarray(results[rid].tokens), want)
    # the 10 distinct lengths landed in at most 2 buckets (8 and 16):
    # far fewer programs than the per-length prefill would have traced
    assert engine.prefill_compile_count <= 2
    buckets = engine.metrics.prefill_buckets
    assert set(buckets) <= {"8", "16"}
    assert sum(buckets.values()) == len(prompts)
    # length-aware decode touched strictly less KV than a dense read
    d = engine.metrics.to_dict()
    assert 0.0 < d["decode_flop_utilization"] < 1.0
    assert d["decode_live_kv_tokens"] < d["decode_dense_kv_tokens"]


def test_compile_guard_raises_on_violation():
    calls = {"n": 0}

    def count():
        return calls["n"]

    with pytest.raises(AssertionError, match="at most"):
        with compile_guard(count, max_programs=0, label="demo"):
            calls["n"] += 1
    with pytest.raises(AssertionError, match="at least"):
        with compile_guard(count, max_programs=3, min_programs=1,
                           label="demo"):
            pass
    with pytest.raises(ValueError, match="max_programs"):
        with compile_guard(count, max_programs=0, min_programs=1):
            pass


# -- soak / CLI (slow tier) ------------------------------------------------


@pytest.mark.slow
def test_demo_soak():
    from mmlspark_tpu.serve.demo import run_demo

    out = run_demo(slots=3, n_requests=10, max_new_tokens=6,
                   arrivals_per_tick=2, cache_len=48, seed=1)
    assert out["completed"] == 10 and out["expired"] == 0
    assert 1 <= out["decode_compiles"] <= out["decode_block"].bit_length()
    assert out["tokens_generated"] == 60


@pytest.mark.slow
def test_cli_serve_demo_emits_one_json_line():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    res = subprocess.run(
        [sys.executable, "-m", "mmlspark_tpu", "--cpu-mesh", "4", "serve",
         "--demo", "--slots", "2", "--requests", "4",
         "--max-new-tokens", "4"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd="/root/repo",
    )
    assert res.returncode == 0, res.stderr
    lines = [ln for ln in res.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1  # ONE parseable JSON line, mirroring bench
    metrics = json.loads(lines[0])
    for key in ("queue_depth_mean", "ttft_ms_mean", "per_token_ms",
                "slot_utilization_mean", "tokens_per_sec"):
        assert key in metrics, key
    assert metrics["completed"] == 4
    assert 1 <= metrics["decode_compiles"] <= (
        metrics["decode_block"].bit_length()
    )
