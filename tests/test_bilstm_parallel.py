"""BiLSTM multi-chip legs (BASELINE config #5, reference notebook 304).

The reference runs its BiLSTM through CNTKModel data-parallel only
(SURVEY.md §5: no sequence parallelism exists there). Parity leg: DP
training on the mesh. Upgrade leg: sequence-dim sharding via the chunked
recurrence chain (parallel/sequence_rnn.py) — exact against the dense
flax path, and differentiable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.models import build_model
from mmlspark_tpu.parallel import bilstm_seq_parallel_apply, make_mesh


@pytest.fixture(scope="module")
def tagger():
    graph = build_model(
        "bilstm_tagger", vocab_size=31, embed_dim=8, hidden=6, num_tags=5
    )
    variables = graph.init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
    )
    return graph, variables


def _ids(rng, b, t, vocab=31):
    return rng.integers(0, vocab, size=(b, t)).astype(np.int32)


def test_seq_parallel_matches_dense(tagger):
    graph, variables = tagger
    rng = np.random.default_rng(0)
    ids = _ids(rng, 3, 16)
    mesh = make_mesh({"seq": 8})
    dense = np.asarray(graph.apply(variables, jnp.asarray(ids)))
    par = np.asarray(
        bilstm_seq_parallel_apply(graph, variables, ids, mesh)
    )
    np.testing.assert_allclose(par, dense, atol=1e-5, rtol=1e-5)


def test_seq_parallel_data_seq_mesh(tagger):
    """2D data x seq mesh: batch and time sharded simultaneously."""
    graph, variables = tagger
    rng = np.random.default_rng(1)
    ids = _ids(rng, 4, 12)
    mesh = make_mesh({"data": 2, "seq": 4})
    dense = np.asarray(graph.apply(variables, jnp.asarray(ids)))
    par = np.asarray(
        bilstm_seq_parallel_apply(graph, variables, ids, mesh)
    )
    np.testing.assert_allclose(par, dense, atol=1e-5, rtol=1e-5)


def test_seq_parallel_rejects_indivisible(tagger):
    graph, variables = tagger
    ids = _ids(np.random.default_rng(2), 2, 9)
    mesh = make_mesh({"seq": 8})
    with pytest.raises(ValueError, match="not divisible"):
        bilstm_seq_parallel_apply(graph, variables, ids, mesh)


def test_seq_parallel_grads_match_dense(tagger):
    """ppermute transposes cleanly: the seq-sharded forward trains.
    Gradients w.r.t. every variable match the dense path."""
    graph, variables = tagger
    rng = np.random.default_rng(3)
    ids = _ids(rng, 2, 8)
    tags = rng.integers(0, 5, size=(2, 8)).astype(np.int32)
    mesh = make_mesh({"seq": 4})

    def loss_dense(v):
        logits = graph.apply(v, jnp.asarray(ids))
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.take_along_axis(lp, jnp.asarray(tags)[..., None], -1)
        )

    def loss_par(v):
        logits = bilstm_seq_parallel_apply(graph, v, ids, mesh)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.take_along_axis(lp, jnp.asarray(tags)[..., None], -1)
        )

    from jax.flatten_util import ravel_pytree

    gd = jax.grad(loss_dense)(variables)
    gp = jax.grad(loss_par)(variables)
    flat_d, _ = ravel_pytree(gd)
    flat_p, _ = ravel_pytree(gp)
    # tolerance: the bf16 head matmul backward accumulates in a
    # different order under shard_map; LSTM grads are f32
    np.testing.assert_allclose(
        np.asarray(flat_p), np.asarray(flat_d), atol=2e-3, rtol=2e-2
    )


def test_bilstm_mixed_axis_training_step(tagger):
    """BASELINE config #5's training claim end-to-end: ONE jitted SGD
    step with batch sharded over 'data' AND time sharded over 'seq'
    simultaneously. The backward traverses the chunked recurrence chain
    (ppermute transpose); loss must decrease over a few steps and the
    trained weights must still agree with the dense forward."""
    from mmlspark_tpu.parallel import bilstm_seq_parallel_train_step

    graph, variables = tagger
    rng = np.random.default_rng(5)
    ids = _ids(rng, 4, 12)
    tags = (ids % 5).astype(np.int32)
    mesh = make_mesh({"data": 2, "seq": 4})

    losses = []
    v = variables
    for _ in range(4):
        loss, v = bilstm_seq_parallel_train_step(
            graph, v, ids, tags, mesh, learning_rate=5e-2
        )
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses

    par = np.asarray(bilstm_seq_parallel_apply(graph, v, ids, mesh))
    dense = np.asarray(graph.apply(v, jnp.asarray(ids)))
    np.testing.assert_allclose(par, dense, atol=1e-5, rtol=1e-5)


def test_bilstm_dp_training_on_mesh():
    """Reference-parity leg: data-parallel BiLSTM training over the mesh
    (the multi-chip shape notebook 304's eval implies), loss decreasing."""
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

    graph = build_model(
        "bilstm_tagger", vocab_size=31, embed_dim=8, hidden=6, num_tags=5
    )
    rng = np.random.default_rng(4)
    n = jax.device_count()
    ids = _ids(rng, 8 * n, 8)
    # learnable rule: tag = token parity — loss must drop fast
    tags = (ids % 5).astype(np.int32)
    trainer = SPMDTrainer(
        graph,
        TrainConfig(
            epochs=6, batch_size=4 * n, learning_rate=5e-2,
            mesh_axes={"data": n}, log_every=1, shuffle=False,
        ),
    )
    trainer.train(ids, tags)
    losses = [h["loss"] for h in trainer.history]
    assert losses[-1] < losses[0] * 0.8, losses
