"""Model graph + registry tests: named nodes, cut-at-node, train mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.models import build_model, registered_models
from mmlspark_tpu.models.graph import FINAL_NODE


def test_registry_lists_families():
    names = registered_models()
    for expected in ("resnet20_cifar10", "resnet50", "mlp", "linear",
                     "bilstm_tagger"):
        assert expected in names
    with pytest.raises(FriendlyError):
        build_model("nope")


def test_resnet20_shapes_and_nodes():
    g = build_model("resnet20_cifar10")
    assert g.layer_names == ["stem", "stage1", "stage2", "stage3", "pool", "z"]
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    out = g.apply(v, jnp.zeros((2, 32, 32, 3)))
    assert out.shape == (2, 10) and out.dtype == jnp.float32
    feats = g.apply(v, jnp.zeros((2, 32, 32, 3)), output_node="pool")
    assert feats.shape == (2, 64)
    by_index = g.apply(v, jnp.zeros((2, 32, 32, 3)), output_node=4)
    np.testing.assert_allclose(np.asarray(by_index), np.asarray(feats))


def test_cut_produces_prefix_graph():
    g = build_model("resnet20_cifar10")
    head = g.cut("pool")
    assert head.layer_names == ["stem", "stage1", "stage2", "stage3", "pool"]
    with pytest.raises(FriendlyError):
        g.cut("not_a_node")


def test_train_mode_updates_batch_stats():
    g = build_model("resnet20_cifar10", width=8)
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    out, updated = g.apply(v, x, train=True)
    assert out.shape == (4, 10)
    before = jax.tree_util.tree_leaves(
        {k: s.get("batch_stats") for k, s in v.items() if "batch_stats" in s}
    )
    after = jax.tree_util.tree_leaves(
        {k: s.get("batch_stats") for k, s in updated.items() if "batch_stats" in s}
    )
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(before, after)
    )


def test_bilstm_token_logits():
    g = build_model("bilstm_tagger", vocab_size=30, embed_dim=8, hidden=8,
                    num_tags=4)
    ids = jnp.array([[1, 2, 3], [4, 5, 6]], dtype=jnp.int32)
    v = g.init(jax.random.PRNGKey(0), ids)
    out = g.apply(v, ids)
    assert out.shape == (2, 3, 4)
    # backward direction sees the future: changing last token changes first
    # token's logits
    ids2 = ids.at[0, 2].set(7)
    out2 = g.apply(v, ids2)
    assert not np.allclose(np.asarray(out[0, 0]), np.asarray(out2[0, 0]))


def test_final_node_convention():
    for name in ("mlp", "linear", "resnet20_cifar10", "bilstm_tagger"):
        g = build_model(name)
        assert g.layer_names[-1] == FINAL_NODE
