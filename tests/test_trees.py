"""Histogram tree learner tests.

Mirrors the reference's learner-coverage idea in
train-classifier/src/test/scala/VerifyTrainClassifier.scala (every
supported learner trained + scored on generated data) for the tree family.
"""

from __future__ import annotations

import numpy as np
import pytest

from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.classical import NaiveBayes, OneVsRest
from mmlspark_tpu.stages.trees import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    GBTClassifier,
    GBTRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
    bin_features,
    quantile_edges,
)


def xor_ds(n=400, seed=0, noise=0.0):
    """Linearly inseparable interaction with ASYMMETRIC thresholds.

    Perfectly balanced XOR has exactly zero marginal gain for every
    feature at every depth (conditioning on other features keeps the
    symmetry), so greedy split choice there is pure tie-breaking noise —
    the asymmetric cut points give the greedy search a real gradient
    while keeping the problem unsolvable for linear models.
    """
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = ((x[:, 0] > 0.45) ^ (x[:, 1] > -0.35)).astype(np.int32)
    if noise:
        flip = rng.random(n) < noise
        y = np.where(flip, 1 - y, y)
    return Dataset({"features": x, "label": y})


def reg_ds(n=500, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (2.0 * x[:, 0] + np.sin(3.0 * x[:, 1])).astype(np.float32)
    return Dataset({"features": x, "label": y})


def r2(pred, y):
    return 1.0 - ((pred - y) ** 2).sum() / ((y - y.mean()) ** 2).sum()


# -- binning ---------------------------------------------------------------


def test_quantile_edges_constant_column_never_splits():
    x = np.stack([np.ones(50), np.arange(50.0)], axis=1)
    edges = quantile_edges(x, 8)
    assert np.all(np.isinf(edges[0]))
    bins = bin_features(x, edges)
    assert np.all(bins[:, 0] == 0)
    assert bins[:, 1].max() > 0


def test_bin_features_monotone():
    x = np.linspace(-3, 3, 100).reshape(-1, 1)
    edges = quantile_edges(x, 16)
    bins = bin_features(x, edges)[:, 0]
    assert np.all(np.diff(bins) >= 0)
    assert bins.max() <= 15


def test_binning_parity_with_per_column_reference():
    """The vectorized one-sort quantile_edges / vmapped bin_features must
    reproduce the straightforward per-column np.quantile/searchsorted
    semantics they replaced (incl. nan/inf columns, constant columns,
    few-valued columns, and empty columns)."""
    rng = np.random.default_rng(3)
    n, d, max_bins = 500, 23, 16
    x = rng.normal(size=(n, d))
    x[:, 0] = 1.0  # constant
    x[:, 1] = rng.integers(0, 3, n)  # few-valued -> duplicate quantiles
    x[rng.random((n, d)) < 0.05] = np.nan  # scattered missing
    x[rng.random((n, d)) < 0.02] = np.inf
    x[:, 2] = np.nan  # entirely empty column

    qs = np.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    ref_edges = np.full((d, max_bins - 1), np.inf)
    for j in range(d):
        col = x[:, j][np.isfinite(x[:, j])]
        if col.size == 0:
            continue
        e = np.unique(np.quantile(col, qs))
        e = e[e < col.max()]
        ref_edges[j, : e.size] = e
    edges = quantile_edges(x, max_bins)
    np.testing.assert_allclose(edges, ref_edges, rtol=1e-12, atol=0)

    bins = bin_features(x, edges)
    ref_bins = np.empty((n, d), dtype=np.int32)
    xf32 = x.astype(np.float32)  # binning compares in f32
    for j in range(d):
        # reference = searchsorted against the FINITE edges: +inf padding
        # separates nothing, so codes past the last finite edge are one
        # routing-equivalent class (inf/nan land there too)
        fin = edges[j][np.isfinite(edges[j])].astype(np.float32)
        ref_bins[:, j] = np.searchsorted(fin, xf32[:, j], side="right")
    np.testing.assert_array_equal(bins, ref_bins)


# -- classification --------------------------------------------------------


def test_decision_tree_solves_xor():
    """XOR is the canonical linearly-inseparable problem: LR fails, a
    depth-2+ tree nails it."""
    ds = xor_ds()
    model = DecisionTreeClassifier(label_col="label", max_depth=4).fit(ds)
    scores = np.asarray(model.transform(ds)["scores"])
    acc = (scores.argmax(1) == np.asarray(ds["label"])).mean()
    assert acc > 0.95


def test_tree_scores_are_log_probs():
    ds = xor_ds()
    model = DecisionTreeClassifier(label_col="label", max_depth=4).fit(ds)
    scores = np.asarray(model.transform(ds)["scores"])
    probs = np.exp(scores)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)


def test_max_depth_1_is_a_stump():
    """A depth-1 tree cuts one feature once — it cannot express the
    interaction, so it must trail the deep tree by a wide margin."""
    ds = xor_ds()
    y = np.asarray(ds["label"])
    stump = DecisionTreeClassifier(label_col="label", max_depth=1).fit(ds)
    deep = DecisionTreeClassifier(label_col="label", max_depth=4).fit(ds)
    acc_stump = (
        np.asarray(stump.transform(ds)["scores"]).argmax(1) == y
    ).mean()
    acc_deep = (
        np.asarray(deep.transform(ds)["scores"]).argmax(1) == y
    ).mean()
    assert acc_stump < acc_deep - 0.15
    # and the stump really is depth 1: exactly one real split
    assert int((np.asarray(stump.threshs) < 32).sum()) == 1


def test_min_instances_per_node_coarsens_tree():
    ds = xor_ds(noise=0.1)
    fine = DecisionTreeClassifier(label_col="label", max_depth=6).fit(ds)
    coarse = DecisionTreeClassifier(
        label_col="label", max_depth=6, min_instances_per_node=100
    ).fit(ds)
    # sentinel threshold == max_bins means "no split"; the constrained tree
    # must refuse strictly more splits
    n_splits_fine = int((np.asarray(fine.threshs) < 32).sum())
    n_splits_coarse = int((np.asarray(coarse.threshs) < 32).sum())
    assert n_splits_coarse < n_splits_fine


def test_random_forest_beats_single_tree_on_noise():
    train = xor_ds(seed=0, noise=0.25)
    test = xor_ds(seed=9)
    y = np.asarray(test["label"])
    tree = DecisionTreeClassifier(label_col="label", max_depth=6).fit(train)
    forest = RandomForestClassifier(
        label_col="label", max_depth=6, num_trees=25, feature_subset="all"
    ).fit(train)
    acc_tree = (
        np.asarray(tree.transform(test)["scores"]).argmax(1) == y
    ).mean()
    acc_forest = (
        np.asarray(forest.transform(test)["scores"]).argmax(1) == y
    ).mean()
    assert acc_forest >= acc_tree - 0.02  # forest at least matches


def test_random_forest_deterministic_by_seed():
    ds = xor_ds()
    a = RandomForestClassifier(label_col="label", num_trees=5, seed=3).fit(ds)
    b = RandomForestClassifier(label_col="label", num_trees=5, seed=3).fit(ds)
    np.testing.assert_array_equal(np.asarray(a.feats), np.asarray(b.feats))
    np.testing.assert_array_equal(
        np.asarray(a.values), np.asarray(b.values)
    )


def test_gbt_classifier_binary_and_multiclass():
    ds = xor_ds()
    model = GBTClassifier(label_col="label", max_iter=10, max_depth=3).fit(ds)
    scores = np.asarray(model.transform(ds)["scores"])
    assert scores.shape[1] == 2
    acc = (scores.argmax(1) == np.asarray(ds["label"])).mean()
    assert acc > 0.95

    rng = np.random.default_rng(4)
    n = 450
    x = rng.normal(size=(n, 5)).astype(np.float32)
    y3 = (x[:, 0] > 0.4).astype(np.int32) + (x[:, 0] > -0.4).astype(np.int32)
    ds3 = Dataset({"features": x, "label": y3})
    m3 = GBTClassifier(label_col="label", max_iter=8, max_depth=3).fit(ds3)
    s3 = np.asarray(m3.transform(ds3)["scores"])
    assert s3.shape[1] == 3
    assert (s3.argmax(1) == y3).mean() > 0.9


def test_prime_row_count():
    """Non-smooth sizes must not break device-side histogram shapes."""
    ds = xor_ds(n=397)  # prime
    model = GBTClassifier(label_col="label", max_iter=3, max_depth=3).fit(ds)
    scores = np.asarray(model.transform(ds)["scores"])
    assert scores.shape == (397, 2)


# -- regression ------------------------------------------------------------


def test_regression_tree_recovers_step_function():
    x = np.linspace(-2, 2, 300).reshape(-1, 1).astype(np.float32)
    y = np.where(x[:, 0] > 0.3, 5.0, -1.0).astype(np.float32)
    ds = Dataset({"features": x, "label": y})
    model = DecisionTreeRegressor(label_col="label", max_depth=3).fit(ds)
    pred = np.asarray(model.transform(ds)["scores"])
    # not 1.0: the quantile bin straddling the step cannot be separated
    # (histogram-tree resolution limit), costing a few mixed rows
    assert r2(pred, y) > 0.95


def test_regression_leaf_is_label_mean():
    """Depth-0-equivalent check: single split region means match leaves."""
    x = np.array([[0.0]] * 10 + [[1.0]] * 10, np.float32)
    y = np.array([2.0] * 10 + [6.0] * 10, np.float32)
    ds = Dataset({"features": x, "label": y})
    model = DecisionTreeRegressor(
        label_col="label", max_depth=1, lambda_=0.0
    ).fit(ds)
    pred = np.asarray(model.transform(ds)["scores"])
    np.testing.assert_allclose(pred[:10], 2.0, atol=1e-4)
    np.testing.assert_allclose(pred[10:], 6.0, atol=1e-4)


def test_gbt_regressor_beats_single_tree():
    train, test = reg_ds(seed=1), reg_ds(seed=2)
    y = np.asarray(test["label"])
    tree = DecisionTreeRegressor(label_col="label", max_depth=3).fit(train)
    gbt = GBTRegressor(label_col="label", max_iter=25, max_depth=3).fit(train)
    r2_tree = r2(np.asarray(tree.transform(test)["scores"]), y)
    r2_gbt = r2(np.asarray(gbt.transform(test)["scores"]), y)
    assert r2_gbt > r2_tree


def test_random_forest_regressor_runs():
    ds = reg_ds()
    model = RandomForestRegressor(
        label_col="label", num_trees=8, max_depth=4, feature_subset="all"
    ).fit(ds)
    pred = np.asarray(model.transform(ds)["scores"])
    assert r2(pred, np.asarray(ds["label"])) > 0.5


# -- persistence -----------------------------------------------------------


@pytest.mark.parametrize(
    "est",
    [
        DecisionTreeClassifier(label_col="label", max_depth=3),
        GBTClassifier(label_col="label", max_iter=3, max_depth=2),
        GBTRegressor(label_col="label", max_iter=3, max_depth=2),
    ],
    ids=["tree", "gbt_cls", "gbt_reg"],
)
def test_save_load_roundtrip(tmp_path, est):
    ds = xor_ds(n=120)
    model = est.fit(ds)
    before = np.asarray(model.transform(ds)["scores"])
    model.save(str(tmp_path / "m"))
    loaded = PipelineStage.load(str(tmp_path / "m"))
    after = np.asarray(loaded.transform(ds)["scores"])
    np.testing.assert_allclose(before, after, rtol=1e-6)


# -- classical -------------------------------------------------------------


def test_naive_bayes_posterior_and_rejects_negative():
    rng = np.random.default_rng(0)
    n = 300
    x = rng.poisson(1.0, size=(n, 6)).astype(np.float32)
    y = rng.integers(0, 2, n).astype(np.int32)
    x[y == 1, 0] += 4
    ds = Dataset({"features": x, "label": y})
    model = NaiveBayes(label_col="label").fit(ds)
    scores = np.asarray(model.transform(ds)["scores"])
    assert (scores.argmax(1) == y).mean() > 0.9

    bad = Dataset({"features": -x, "label": y})
    with pytest.raises(Exception, match="non-negative"):
        NaiveBayes(label_col="label").fit(bad)


def test_one_vs_rest_multiclass():
    rng = np.random.default_rng(2)
    n = 300
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (x[:, 0] > 0.5).astype(np.int32) + (x[:, 0] > -0.5).astype(np.int32)
    ds = Dataset({"features": x, "label": y})
    ovr = OneVsRest(
        learner=DecisionTreeClassifier(label_col="ignored", max_depth=3),
        label_col="label",
    ).fit(ds)
    scores = np.asarray(ovr.transform(ds)["scores"])
    assert scores.shape == (n, 3)
    assert (scores.argmax(1) == y).mean() > 0.9


# -- TrainClassifier / TrainRegressor dispatch -----------------------------


def census_like(n=300, seed=7):
    rng = np.random.default_rng(seed)
    age = rng.uniform(18, 80, n)
    hours = rng.uniform(10, 60, n)
    edu = rng.choice(["hs", "college", "phd"], n)
    score = (age - 40) / 20 + (hours - 35) / 15 + (edu == "phd") * 1.5
    label = np.where(score + rng.normal(0, 0.4, n) > 0, ">50K", "<=50K")
    return Dataset({
        "age": age,
        "hours": hours,
        "education": list(edu),
        "income": list(label),
    })


@pytest.mark.parametrize(
    "learner", ["decision_tree", "random_forest", "gbt", "naive_bayes"]
)
def test_train_classifier_dispatch(learner):
    from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics
    from mmlspark_tpu.stages.train_classifier import TrainClassifier

    train, test = census_like(seed=7), census_like(n=150, seed=8)
    model = TrainClassifier(label_col="income", model=learner).fit(train)
    stats = ComputeModelStatistics().transform(model.transform(test))
    acc = float(stats["accuracy"][0])
    # dispatch sanity, not a leaderboard: axis-aligned trees approximate
    # the diagonal boundary coarsely at n=300
    floor = 0.6 if learner == "naive_bayes" else 0.7
    assert acc > floor, f"{learner}: accuracy {acc}"


@pytest.mark.parametrize("learner", ["decision_tree", "random_forest", "gbt"])
def test_train_regressor_dispatch(learner):
    from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics
    from mmlspark_tpu.stages.train_regressor import TrainRegressor

    rng = np.random.default_rng(1)
    n = 300
    # several correlated informative columns so Spark's onethird
    # feature-subset default (random forest) still sees signal per tree
    xn = rng.normal(size=n)
    x2 = xn + rng.normal(0, 0.3, n)
    x3 = xn + rng.normal(0, 0.3, n)
    cat = rng.choice(["a", "b", "c"], n)
    y = xn * 2 + (cat == "b") * 3 + rng.normal(0, 0.1, n)
    ds = Dataset({
        "xn": xn, "x2": x2, "x3": x3, "cat": list(cat), "delay": y
    })
    model = TrainRegressor(label_col="delay", model=learner).fit(ds)
    stats = ComputeModelStatistics().transform(model.transform(ds))
    assert float(stats["R^2"][0]) > 0.5, learner


def test_one_vs_rest_string_and_missing_labels():
    """Generic-combinator contract: string labels index to levels, missing
    labels drop (code-review finding: bare astype crashed on strings)."""
    rng = np.random.default_rng(5)
    x = rng.normal(size=(90, 4)).astype(np.float32)
    y = np.where(x[:, 0] > 0.3, "hi", "lo").astype(object)
    y[0] = None
    ds = Dataset({"features": x, "lab": y})
    ovr = OneVsRest(
        learner=DecisionTreeClassifier(label_col="ignored", max_depth=3),
        label_col="lab",
    ).fit(ds)
    assert ovr.levels == ["hi", "lo"]
    scores = np.asarray(ovr.transform(ds)["scores"])
    assert scores.shape == (90, 2)
    pred = np.asarray(ovr.levels, object)[scores.argmax(1)]
    assert (pred[1:] == y[1:]).mean() > 0.9


def test_negative_labels_rejected():
    """{-1,+1} encoding must error, not silently wrap class -1 onto k-1."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(40, 3)).astype(np.float32)
    y = np.where(x[:, 0] > 0, 1, -1).astype(np.int32)
    ds = Dataset({"features": x, "label": y})
    with pytest.raises(Exception, match=r"\[0, k\)"):
        DecisionTreeClassifier(label_col="label").fit(ds)


def test_feature_importances(tmp_path):
    """Split-gain importances: the informative features dominate, the
    vector is normalized, and it persists through save/load."""
    ds = xor_ds(n=500)
    model = GBTClassifier(label_col="label", max_iter=5, max_depth=3).fit(ds)
    imp = np.asarray(model.feature_importances)
    assert imp.shape == (6,)
    np.testing.assert_allclose(imp.sum(), 1.0, rtol=1e-6)
    assert imp[0] + imp[1] > 0.8  # x0/x1 carry the signal
    model.save(str(tmp_path / "m"))
    loaded = PipelineStage.load(str(tmp_path / "m"))
    np.testing.assert_allclose(
        np.asarray(loaded.feature_importances), imp
    )

    reg = DecisionTreeRegressor(label_col="label", max_depth=4).fit(
        reg_ds()
    )
    rimp = np.asarray(reg.feature_importances)
    assert rimp[0] + rimp[1] > 0.9
