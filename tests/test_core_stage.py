"""Stage / Pipeline / serialization round-trip tests (reference:
RoundTripTestBase, core/test/base/.../TestBase.scala:179-255)."""

import numpy as np
import pytest

from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.serialize import load_dataset, save_dataset
from mmlspark_tpu.core.stage import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
)
from mmlspark_tpu.core.schema import ColumnMeta, CategoricalMeta
from mmlspark_tpu.data.dataset import Dataset


class AddConstant(Transformer):
    input_col = Param("input column", "numbers", ptype=str)
    output_col = Param("output column", "plus", ptype=str)
    amount = Param("amount to add", 1.0, ptype=float)

    def _transform(self, ds):
        return ds.with_column(self.output_col, ds[self.input_col] + self.amount)


class MeanCenter(Estimator):
    input_col = Param("input column", "numbers", ptype=str)
    output_col = Param("output column", "centered", ptype=str)

    def _fit(self, ds):
        return MeanCenterModel(
            input_col=self.input_col,
            output_col=self.output_col,
            mean=float(np.mean(ds[self.input_col])),
        )


class MeanCenterModel(Model):
    input_col = Param("input column", "numbers", ptype=str)
    output_col = Param("output column", "centered", ptype=str)
    mean = Param("learned mean", 0.0, ptype=float)

    def _transform(self, ds):
        return ds.with_column(self.output_col, ds[self.input_col] - self.mean)


def test_transformer(basic_dataset):
    out = AddConstant(amount=2.0).transform(basic_dataset)
    assert list(out["plus"]) == [2, 3, 4, 5]


def test_estimator_fit_transform(basic_dataset):
    model = MeanCenter().fit(basic_dataset)
    out = model.transform(basic_dataset)
    assert abs(float(np.mean(out["centered"]))) < 1e-12


def test_pipeline(basic_dataset):
    pipe = Pipeline([AddConstant(amount=10.0), MeanCenter(input_col="plus")])
    model = pipe.fit(basic_dataset)
    assert isinstance(model, PipelineModel)
    out = model.transform(basic_dataset)
    assert "plus" in out and "centered" in out


def test_registry_contains_stages():
    reg = PipelineStage.registry()
    for name in ("AddConstant", "MeanCenter", "MeanCenterModel", "Pipeline"):
        assert name in reg
    # abstract intermediates stay out
    assert "Transformer" not in reg and "Estimator" not in reg


def test_stage_round_trip(tmp_path, basic_dataset):
    stage = AddConstant(amount=3.5)
    stage.save(str(tmp_path / "s"))
    loaded = PipelineStage.load(str(tmp_path / "s"))
    assert type(loaded) is AddConstant
    assert loaded.amount == 3.5
    np.testing.assert_array_equal(
        loaded.transform(basic_dataset)["plus"],
        stage.transform(basic_dataset)["plus"],
    )


def test_fitted_pipeline_round_trip(tmp_path, basic_dataset):
    model = Pipeline([AddConstant(amount=1.0), MeanCenter(input_col="plus")]).fit(
        basic_dataset
    )
    model.save(str(tmp_path / "pm"))
    loaded = PipelineStage.load(str(tmp_path / "pm"))
    a = model.transform(basic_dataset)
    b = loaded.transform(basic_dataset)
    np.testing.assert_allclose(
        np.asarray(a["centered"], float), np.asarray(b["centered"], float)
    )


def test_array_param_round_trip(tmp_path):
    class Weighted(Transformer):
        weights = Param("weight matrix")

        def _transform(self, ds):
            return ds

    w = np.arange(12.0).reshape(3, 4)
    stage = Weighted().set(weights={"layer": {"kernel": w, "bias": np.zeros(4)}})
    stage.save(str(tmp_path / "w"))
    loaded = PipelineStage.load(str(tmp_path / "w"))
    np.testing.assert_array_equal(loaded.weights["layer"]["kernel"], w)


def test_dataset_round_trip(tmp_path, basic_dataset):
    ds = basic_dataset.with_meta(
        "words",
        ColumnMeta(categorical=CategoricalMeta(("a", "b"), has_null=True)),
    ).with_partitions(3)
    save_dataset(ds, str(tmp_path / "d"))
    back = load_dataset(str(tmp_path / "d"))
    assert back.num_rows == 4
    assert list(back["words"]) == list(ds["words"])
    assert back.meta_of("words").categorical.has_null
    assert back.num_partitions == 3
    np.testing.assert_array_equal(back["doubles"], ds["doubles"])


def test_dataset_round_trip_meta_arrays_and_reserved_names(tmp_path):
    ds = Dataset({"file": np.arange(3), "x": np.ones(3)}).with_meta(
        "x", ColumnMeta(extra={"centers": np.zeros(3)})
    )
    save_dataset(ds, str(tmp_path / "d2"))
    back = load_dataset(str(tmp_path / "d2"))
    np.testing.assert_array_equal(back["file"], np.arange(3))
    np.testing.assert_array_equal(back.meta_of("x").extra["centers"], np.zeros(3))


def test_int_param_rejects_fractional_float():
    from mmlspark_tpu.core.exceptions import ParamError
    from mmlspark_tpu.core.params import Param

    class P(Transformer):
        n = Param("count", 1, ptype=int)

        def _transform(self, ds):
            return ds

    with pytest.raises(ParamError):
        P().set(n=2.7)
    assert P().set(n=2.0).n == 2


def test_pipeline_stages_append_not_discarded(basic_dataset):
    p = Pipeline()
    p.stages.append(AddConstant(amount=4.0))
    out = p.fit(basic_dataset).transform(basic_dataset)
    assert list(out["plus"]) == [4, 5, 6, 7]


def test_numpy_scalar_param_accepted():
    stage = AddConstant().set(amount=np.float64(2.5))
    assert stage.amount == 2.5 and isinstance(stage.amount, float)

    class Counted(Transformer):
        n = Param("count", 0, ptype=int)

        def _transform(self, ds):
            return ds

    assert Counted().set(n=np.int64(5)).n == 5


def test_pipeline_skips_transform_after_last_estimator(basic_dataset):
    calls = []

    class Spy(Transformer):
        def _transform(self, ds):
            calls.append("t")
            return ds

    class SpyEst(Estimator):
        def _fit(self, ds):
            return Spy()

    Pipeline([SpyEst(), Spy()]).fit(basic_dataset)
    # neither the fitted model of the last estimator nor the trailing
    # transformer should have run during fit
    assert calls == []


def test_is_tpu_recognizes_relay_platform(monkeypatch):
    """The axon relay registers platform 'axon' while proxying a real
    chip; is_tpu() must key on device_kind, not just the platform name —
    a platform-name-only check would silently run interpreter-mode
    kernels and smoke-scale benches ON the TPU."""
    from mmlspark_tpu.core import env

    class _Dev:
        def __init__(self, kind):
            self.device_kind = kind

    monkeypatch.setattr(env, "backend", lambda: "axon")
    import jax

    monkeypatch.setattr(jax, "devices", lambda: [_Dev("TPU v5 lite")])
    assert env.is_tpu()
    monkeypatch.setattr(jax, "devices", lambda: [_Dev("v6e")])
    assert env.is_tpu()
    monkeypatch.setattr(jax, "devices", lambda: [_Dev("cpu")])
    assert not env.is_tpu()
