"""Test fixture configuration.

Unit tests run on a virtual 8-device CPU mesh — the JAX analog of the
reference's shared ``local[*]`` SparkSession per suite
(core/test/base/src/main/scala/SparkSessionFactory.scala:40-51): multi-worker
parallelism exercised in one process, no real pod needed.

The interpreter may import jax at startup (site customization registering a
real TPU backend), so env vars alone are not enough: we set XLA_FLAGS before
the first backend initialization and force the platform through jax.config.
"""

import os

import re

flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    "tests require the virtual 8-device CPU mesh; backend was initialized "
    f"too early (got {jax.devices()})"
)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def basic_dataset():
    """Tiny mixed-type dataset (reference TestBase.makeBasicDF,
    core/test/base/src/main/scala/TestBase.scala:138-152)."""
    from mmlspark_tpu.data.dataset import Dataset

    return Dataset(
        {
            "numbers": np.array([0, 1, 2, 3], dtype=np.int64),
            "doubles": np.array([0.0, 1.5, 3.0, 4.5]),
            "words": ["guitars", "drums", "bass", "keys"],
            "flags": np.array([True, False, True, False]),
        }
    )
