"""Two-process jax.distributed smoke test (multi-host bring-up).

The reference launches multi-node training by writing an MPI hostfile and
shelling out to ``mpiexec`` (CommandBuilders.scala:95-116
``MultiNodeParallelLauncher``). The TPU-native equivalent is
``jax.distributed.initialize`` + GSPMD collectives over the global device
view. This test actually EXECUTES that path: two OS processes on
localhost, one CPU device each, form a 2-process cluster through
``mmlspark_tpu.parallel.mesh.initialize_distributed`` and run a psum over
the global mesh — multi-host is exercised code, not a claim.

Runs in subprocesses so the parent's jax backend state is untouched.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

_WORKER = r"""
import os, sys
# one CPU device per process; the axon relay shim must not register
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.parallel.mesh import initialize_distributed

coord = sys.argv[1]
pid = int(sys.argv[2])
initialize_distributed(
    coordinator_address=coord, num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import multihost_utils

mesh = Mesh(np.array(jax.devices()).reshape(2), ("data",))

# one global array sharded over the two processes; psum over the mesh
local = jnp.full((1, 4), float(pid + 1))
glob = multihost_utils.host_local_array_to_global_array(
    np.asarray(local), mesh, P("data")
)

@jax.jit
def total(x):
    return jnp.sum(x)  # GSPMD inserts the cross-host all-reduce

out = float(total(glob))
assert out == (1.0 + 2.0) * 4, out
print(f"proc {pid} ok: global sum {out}", flush=True)
"""


def test_two_process_psum(tmp_path):
    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)

    env = dict(os.environ)
    # the relay registration hook would touch the (possibly absent) TPU
    # tunnel inside each worker; multi-host CPU must not depend on it
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # the worker script lives in tmp_path, so python puts tmp_path (not our
    # cwd) on sys.path — the repo root must be importable even when the
    # package isn't pip-installed in this interpreter
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    existing = env.get("PYTHONPATH")
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + existing if existing else repo_root
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), coord, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd="/root/repo",
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} ok: global sum 12.0" in out, out
