"""Pipeline parallelism: schedule correctness, gradients, trainer
integration (virtual 8-device CPU mesh, see conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.parallel import make_mesh
from mmlspark_tpu.parallel.pipeline import (
    PIPELINE_STAGE_RULES,
    pipeline_apply,
)


def _linear_stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _stacked_linear(rng, n_stages, d):
    k1, k2 = jax.random.split(rng)
    return {
        "w": jax.random.normal(k1, (n_stages, d, d)) * 0.3,
        "b": jax.random.normal(k2, (n_stages, d)) * 0.1,
    }


def _sequential(params, x, n_stages):
    for i in range(n_stages):
        x = _linear_stage(jax.tree_util.tree_map(lambda a: a[i], params), x)
    return x


def test_matches_sequential():
    n, d, m, b = 4, 8, 8, 6
    mesh = make_mesh({"pipe": n})
    params = _stacked_linear(jax.random.PRNGKey(0), n, d)
    mb = jax.random.normal(jax.random.PRNGKey(1), (m, b, d))
    got = pipeline_apply(_linear_stage, params, mb, mesh)
    want = jax.vmap(lambda x: _sequential(params, x, n))(mb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_matches_sequential_under_jit_dp():
    # dp × pp mesh: batch dim sharded over data at the same time
    mesh = make_mesh({"data": 2, "pipe": 4})
    n, d = 4, 8
    params = _stacked_linear(jax.random.PRNGKey(2), n, d)
    mb = jax.random.normal(jax.random.PRNGKey(3), (4, 4, d))

    @jax.jit
    def run(p, x):
        return pipeline_apply(_linear_stage, p, x, mesh)

    got = run(params, mb)
    want = jax.vmap(lambda x: _sequential(params, x, n))(mb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_sequential():
    n, d = 2, 6
    mesh = make_mesh({"pipe": n})
    params = _stacked_linear(jax.random.PRNGKey(4), n, d)
    mb = jax.random.normal(jax.random.PRNGKey(5), (2, 3, d))

    def loss_pipe(p):
        return pipeline_apply(_linear_stage, p, mb, mesh).sum()

    def loss_seq(p):
        return jax.vmap(lambda x: _sequential(p, x, n))(mb).sum()

    g1 = jax.grad(loss_pipe)(params)
    g2 = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_shape_validation():
    mesh = make_mesh({"pipe": 4})
    params = _stacked_linear(jax.random.PRNGKey(0), 3, 4)  # wrong stages
    mb = jnp.zeros((4, 2, 4))
    with pytest.raises(FriendlyError):
        pipeline_apply(_linear_stage, params, mb, mesh)
    params = _stacked_linear(jax.random.PRNGKey(0), 4, 4)
    with pytest.raises(FriendlyError):
        pipeline_apply(_linear_stage, params, jnp.zeros((3, 2, 4)), mesh)
    with pytest.raises(FriendlyError):
        pipeline_apply(_linear_stage, params, mb, make_mesh({"data": 4}))


def test_pipelined_lm_forward_matches_stage_loop():
    from mmlspark_tpu.models import build_model

    mesh = make_mesh({"pipe": 4})
    graph = build_model(
        "transformer_lm_pipelined", vocab_size=32, d_model=16, heads=2,
        depth=4, max_len=8, mesh=mesh,
    )
    ids = np.random.default_rng(0).integers(0, 32, size=(8, 8))
    ids = jnp.asarray(ids, jnp.int32)
    variables = graph.init(jax.random.PRNGKey(0), ids[:1])
    out = graph.apply(variables, ids)
    assert out.shape == (8, 8, 32)

    # reference: run the same stages sequentially (batch of 1 triggers the
    # non-pipelined fallback path inside apply)
    outs = [graph.apply(variables, ids[i : i + 1]) for i in range(8)]
    want = jnp.concatenate(outs, axis=0)
    # bfloat16 compute: batched vs batch-1 runs fuse differently
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=5e-2, atol=2e-2)


def test_pipelined_lm_output_node():
    from mmlspark_tpu.models import build_model

    mesh = make_mesh({"pipe": 2})
    graph = build_model(
        "transformer_lm_pipelined", vocab_size=16, d_model=8, heads=2,
        depth=2, max_len=4, mesh=mesh,
    )
    ids = jnp.zeros((2, 4), jnp.int32)
    variables = graph.init(jax.random.PRNGKey(0), ids[:1])
    trunk = graph.apply(variables, ids, output_node="stages")
    assert trunk.shape == (2, 4, 8)  # d_model features, not logits
    emb = graph.apply(variables, ids, output_node="embed")
    assert emb.shape == (2, 4, 8)
    with pytest.raises(FriendlyError):
        graph.apply(variables, ids, output_node="stage")  # typo must raise


def test_pipelined_builder_validation():
    from mmlspark_tpu.core.exceptions import ParamError
    from mmlspark_tpu.models import build_model

    mesh = make_mesh({"pipe": 2})
    with pytest.raises(ParamError):
        build_model(
            "transformer_lm_pipelined", vocab_size=16, d_model=8, heads=2,
            depth=2, max_len=4, mesh=mesh, n_microbatches=3,
        )


def test_trainer_pipelined_lm():
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

    mesh_axes = {"data": 2, "pipe": 2}
    mesh = make_mesh(mesh_axes)
    graph = build_model(
        "transformer_lm_pipelined", vocab_size=32, d_model=16, heads=2,
        depth=2, max_len=8, mesh=mesh,
    )
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 32, size=(16, 8)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    trainer = SPMDTrainer(
        graph,
        TrainConfig(
            epochs=2, batch_size=8, learning_rate=1e-2,
            mesh_axes=mesh_axes, param_rules=PIPELINE_STAGE_RULES,
            log_every=1, shuffle=False,
        ),
    )
    variables = trainer.train(ids, labels)
    losses = [h["loss"] for h in trainer.history if "loss" in h]
    assert len(losses) >= 2 and all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    out = graph.apply(variables, jnp.asarray(ids[:4]))
    assert out.shape == (4, 8, 32)
