"""ONNX export round-trips (the SerializableFunction write-path analog)."""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.models import build_model
from mmlspark_tpu.models.onnx_export import export_onnx, save_onnx
from mmlspark_tpu.models.onnx_import import load_onnx


def test_mlp_round_trip(rng):
    g = build_model("mlp", num_outputs=3, hidden=(8, 6))
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))
    x = rng.normal(size=(5, 4)).astype(np.float32)
    want = np.asarray(g.apply(v, jnp.asarray(x)))
    g2 = load_onnx(export_onnx(g, v, (5, 4)))
    got = np.asarray(g2.apply(g2.init(), jnp.asarray(x)))
    # flax computes hidden layers in bfloat16; the ONNX path is float32,
    # so agreement is to bf16 resolution
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_linear_round_trip(rng):
    g = build_model("linear", num_outputs=2)
    v = g.init(jax.random.PRNGKey(1), jnp.zeros((1, 6)))
    x = rng.normal(size=(4, 6)).astype(np.float32)
    want = np.asarray(g.apply(v, jnp.asarray(x)))
    g2 = load_onnx(export_onnx(g, v, (4, 6)))
    got = np.asarray(g2.apply(g2.init(), jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_bilstm_tagger_round_trip(rng):
    g = build_model(
        "bilstm_tagger", vocab_size=30, embed_dim=6, hidden=5, num_tags=4
    )
    v = g.init(jax.random.PRNGKey(1), jnp.zeros((1, 7), jnp.int32))
    ids = rng.integers(0, 30, (3, 7)).astype(np.int32)
    want = np.asarray(g.apply(v, jnp.asarray(ids)))
    g2 = load_onnx(export_onnx(g, v, (3, 7)))
    got = np.asarray(g2.apply(g2.init(), jnp.asarray(ids)))
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
    # per-token argmax tags agree exactly
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))


def test_exported_graph_compiles_under_jit(rng):
    """Reshape targets bake static dims, so the imported graph must trace
    cleanly (shape constants resolve from initializers, not tracers)."""
    g = build_model(
        "bilstm_tagger", vocab_size=12, embed_dim=4, hidden=3, num_tags=2
    )
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 5), jnp.int32))
    g2 = load_onnx(export_onnx(g, v, (2, 5)))
    fwd = jax.jit(lambda vv, x: g2.apply(vv, x))
    ids = rng.integers(0, 12, (2, 5)).astype(np.int32)
    out = np.asarray(fwd(g2.init(), jnp.asarray(ids)))
    assert out.shape == (2, 5, 2)


def test_save_onnx_writes_file(tmp_path, rng):
    g = build_model("linear", num_outputs=2)
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 3)))
    path = str(tmp_path / "m.onnx")
    save_onnx(g, v, (2, 3), path)
    with open(path, "rb") as f:
        g2 = load_onnx(f.read())
    assert g2.layer_names == ["z"]


def test_unsupported_family_errors():
    g = build_model("resnet20_cifar10", width=8)
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    with pytest.raises(FriendlyError, match="no ONNX exporter"):
        export_onnx(g, v, (1, 32, 32, 3))


def test_transformer_lm_round_trip(rng):
    """Causal transformer -> primitive-op ONNX (decomposed LayerNorm,
    attention, tanh-gelu) -> import; logits agree to bf16 resolution and
    the block-output named-node cut works like on the flax graph."""
    B, T = 2, 10
    g = build_model(
        "transformer_lm", vocab_size=32, d_model=16, heads=4, depth=2,
        max_len=T, attn_impl="dense",
    )
    v = g.init(jax.random.PRNGKey(1), jnp.zeros((1, T), jnp.int32))
    ids = rng.integers(0, 32, size=(B, T)).astype(np.int32)
    want = np.asarray(g.apply(v, jnp.asarray(ids)))

    g2 = load_onnx(export_onnx(g, v, (B, T)))
    got = np.asarray(g2.apply(g2.init(), jnp.asarray(ids)))
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
    assert (got.argmax(-1) == want.argmax(-1)).mean() > 0.95

    # named-node cut at a block output = flax-side layer_names contract
    hidden = np.asarray(
        g2.apply(g2.init(), jnp.asarray(ids), output_node="block0")
    )
    assert hidden.shape == (B, T, 16)
    flax_hidden = np.asarray(
        g.apply(v, jnp.asarray(ids), output_node="block0")
    )
    np.testing.assert_allclose(hidden, flax_hidden, rtol=5e-2, atol=5e-2)


def test_transformer_lm_non_causal_round_trip(rng):
    """Encoder (bidirectional) export drops the causal mask."""
    B, T = 2, 6
    g = build_model(
        "transformer_lm", vocab_size=16, d_model=8, heads=2, depth=1,
        max_len=T, causal=False, attn_impl="dense",
    )
    v = g.init(jax.random.PRNGKey(2), jnp.zeros((1, T), jnp.int32))
    ids = rng.integers(0, 16, size=(B, T)).astype(np.int32)
    want = np.asarray(g.apply(v, jnp.asarray(ids)))
    g2 = load_onnx(export_onnx(g, v, (B, T)))
    got = np.asarray(g2.apply(g2.init(), jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)


def test_transformer_lm_rope_round_trip(rng):
    """RoPE export (r5): position enters as the in-graph rotate-half of
    q/k against cos/sin constants — no position table in the payload —
    and the round trip must agree with the flax model like the
    learned-pos path does."""
    B, T = 2, 10
    g = build_model(
        "transformer_lm", vocab_size=32, d_model=16, heads=4, depth=2,
        max_len=T, attn_impl="dense", pos_embedding="rope",
    )
    v = g.init(jax.random.PRNGKey(3), jnp.zeros((1, T), jnp.int32))
    ids = rng.integers(0, 32, size=(B, T)).astype(np.int32)
    want = np.asarray(g.apply(v, jnp.asarray(ids)))
    g2 = load_onnx(export_onnx(g, v, (B, T)))
    got = np.asarray(g2.apply(g2.init(), jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
    assert (got.argmax(-1) == want.argmax(-1)).mean() > 0.95


def test_transformer_lm_window_round_trip(rng):
    """Sliding-window export (r5): the in-graph additive mask must ALSO
    kill out-of-window keys — silently exporting a full-causal graph
    for a window model would diverge past the window. Covered for both
    position modes, with T well past the window."""
    B, T, W = 2, 12, 4
    for pos_mode in ("learned", "rope"):
        g = build_model(
            "transformer_lm", vocab_size=32, d_model=16, heads=4,
            depth=1, max_len=T, attn_impl="dense", window=W,
            pos_embedding=pos_mode,
        )
        v = g.init(jax.random.PRNGKey(5), jnp.zeros((1, T), jnp.int32))
        ids = rng.integers(0, 32, size=(B, T)).astype(np.int32)
        want = np.asarray(g.apply(v, jnp.asarray(ids)))
        g2 = load_onnx(export_onnx(g, v, (B, T)))
        got = np.asarray(g2.apply(g2.init(), jnp.asarray(ids)))
        np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2,
                                   err_msg=pos_mode)
        # allclose above is the mask-correctness gate (a dropped window
        # mask diverges logits wholesale past the window); the argmax
        # rate only guards gross divergence — random-init near-ties
        # flip a token or two between the bf16 flax model and the f32
        # export
        assert (got.argmax(-1) == want.argmax(-1)).mean() > 0.85, pos_mode


def test_transformer_lm_gqa_round_trip(rng):
    """GQA export (r5): narrow K/V slices expand in-graph (Reshape →
    Expand → Reshape = jnp.repeat's kv-head-per-group layout); combined
    with RoPE to cover the full serving configuration."""
    B, T = 2, 8
    g = build_model(
        "transformer_lm", vocab_size=32, d_model=16, heads=4, depth=2,
        max_len=T, attn_impl="dense", kv_heads=2, pos_embedding="rope",
    )
    v = g.init(jax.random.PRNGKey(6), jnp.zeros((1, T), jnp.int32))
    ids = rng.integers(0, 32, size=(B, T)).astype(np.int32)
    want = np.asarray(g.apply(v, jnp.asarray(ids)))
    g2 = load_onnx(export_onnx(g, v, (B, T)))
    got = np.asarray(g2.apply(g2.init(), jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
    assert (got.argmax(-1) == want.argmax(-1)).mean() > 0.85
