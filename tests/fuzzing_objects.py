"""Per-stage test-object providers for the registry-wide fuzz tests.

Mirror of the reference's ``FuzzObject`` providers (core/test/fuzzing/...
Fuzzing.scala:15-27): every stage class contributes at least one
(stage, dataset) pair; FuzzingTest then asserts framework-wide invariants
over ALL registered stages with explicit exemption lists.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.schema import ImageRow
from mmlspark_tpu.core.stage import Pipeline, PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models import build_model
from mmlspark_tpu.testing.datagen import DatasetOptions, generate_dataset


@dataclass
class FuzzObject:
    stage: PipelineStage
    fit_ds: Dataset
    #: dataset for transform after fit (defaults to fit_ds)
    transform_ds: Dataset | None = None

    @property
    def score_ds(self) -> Dataset:
        return self.transform_ds if self.transform_ds is not None else self.fit_ds


def _mixed_ds(seed=0):
    return generate_dataset(
        DatasetOptions(num_rows=24, missing_ratio=0.0), seed=seed
    )


def _numeric_ds(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(32, 4))
    y = (x[:, 0] > 0).astype(np.int32)
    return Dataset({"features": x.astype(np.float32), "label": y})


def _text_ds(seed=0):
    rng = np.random.default_rng(seed)
    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    docs = [" ".join(rng.choice(words, 6)) for _ in range(16)]
    return Dataset({"text": docs})


def _counts_ds(seed=0):
    """Non-negative count-like features (NaiveBayes requirement)."""
    rng = np.random.default_rng(seed)
    x = rng.poisson(2.0, size=(32, 5)).astype(np.float32)
    y = (x[:, 0] > 1).astype(np.int32)
    return Dataset({"features": x, "label": y})


def _image_ds(n=3):
    rng = np.random.default_rng(0)
    rows = [
        ImageRow(f"img{i}", rng.integers(0, 256, (8, 8, 3), dtype=np.uint8))
        for i in range(n)
    ]
    return Dataset({"image": rows})


def _tiny_tpu_model():
    from mmlspark_tpu.stages.dnn_model import TPUModel

    g = build_model("mlp", num_outputs=2, hidden=(4,))
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))
    return TPUModel.from_graph(
        g, v, "mlp", model_config={"num_outputs": 2, "hidden": (4,)},
        input_col="features", batch_size=8,
    )


def _tiny_resnet_model():
    from mmlspark_tpu.stages.dnn_model import TPUModel

    g = build_model("resnet20_cifar10", width=8)
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    return TPUModel.from_graph(
        g, v, "resnet20_cifar10", model_config={"width": 8},
        input_col="image", batch_size=8,
    )


def build_test_objects() -> dict[str, list[FuzzObject]]:
    """stage class name -> test objects. Fitted-model classes are covered
    through their estimator's fit (listed in DERIVED below)."""
    from mmlspark_tpu.stages.dnn_learner import DNNLearner
    from mmlspark_tpu.stages.ensemble import EnsembleByKey
    from mmlspark_tpu.stages.eval_metrics import (
        ComputeModelStatistics,
        ComputePerInstanceStatistics,
    )
    from mmlspark_tpu.stages.featurize import AssembleFeatures, Featurize
    from mmlspark_tpu.stages.find_best import FindBestModel
    from mmlspark_tpu.stages.image import (
        ImageFeaturizer,
        ImageSetAugmenter,
        ImageTransformer,
        UnrollImage,
    )
    from mmlspark_tpu.stages.prep import (
        Cacher,
        CheckpointData,
        ClassBalancer,
        CleanMissingData,
        DataConversion,
        DropColumns,
        MultiColumnAdapter,
        PartitionSample,
        Repartition,
        SelectColumns,
        SummarizeData,
        Timer,
    )
    from mmlspark_tpu.stages.classical import NaiveBayes, OneVsRest
    from mmlspark_tpu.stages.text import TextFeaturizer
    from mmlspark_tpu.stages.train_classifier import TrainClassifier
    from mmlspark_tpu.stages.train_regressor import TrainRegressor
    from mmlspark_tpu.stages.trees import (
        DecisionTreeClassifier,
        DecisionTreeRegressor,
        GBTClassifier,
        GBTRegressor,
        RandomForestClassifier,
        RandomForestRegressor,
    )
    from mmlspark_tpu.stages.value_indexer import IndexToValue, ValueIndexer
    from mmlspark_tpu.stages.word2vec import Word2Vec

    mixed = _mixed_ds()
    numeric = _numeric_ds()
    import tempfile

    ckdir = tempfile.mkdtemp(prefix="fuzz_ck_")

    classifier_ds = mixed
    trained_classifier = TrainClassifier(label_col="label", epochs=1).fit(
        classifier_ds
    )
    scored = trained_classifier.transform(classifier_ds)

    objects: dict[str, list[FuzzObject]] = {
        "Pipeline": [
            FuzzObject(
                Pipeline([SelectColumns(cols=["num_0", "label"])]), mixed
            )
        ],
        "TPUModel": [FuzzObject(_tiny_tpu_model(), numeric)],
        "Word2Vec": [
            FuzzObject(
                Word2Vec(input_col="text", vector_size=4, window=2,
                         min_count=1, epochs=1),
                _text_ds(),
            )
        ],
        "DecisionTreeClassifier": [
            FuzzObject(
                DecisionTreeClassifier(label_col="label", max_depth=3),
                numeric,
            )
        ],
        "RandomForestClassifier": [
            FuzzObject(
                RandomForestClassifier(
                    label_col="label", max_depth=3, num_trees=3
                ),
                numeric,
            )
        ],
        "GBTClassifier": [
            FuzzObject(
                GBTClassifier(label_col="label", max_depth=2, max_iter=2),
                numeric,
            )
        ],
        "DecisionTreeRegressor": [
            FuzzObject(
                DecisionTreeRegressor(label_col="label", max_depth=3),
                numeric,
            )
        ],
        "RandomForestRegressor": [
            FuzzObject(
                RandomForestRegressor(
                    label_col="label", max_depth=3, num_trees=3
                ),
                numeric,
            )
        ],
        "GBTRegressor": [
            FuzzObject(
                GBTRegressor(label_col="label", max_depth=2, max_iter=2),
                numeric,
            )
        ],
        "NaiveBayes": [
            FuzzObject(NaiveBayes(label_col="label"), _counts_ds())
        ],
        "OneVsRest": [
            FuzzObject(
                OneVsRest(
                    learner=DecisionTreeClassifier(
                        label_col="label", max_depth=2
                    ),
                    label_col="label",
                ),
                numeric,
            )
        ],
        "DNNLearner": [
            FuzzObject(
                DNNLearner(model_name="mlp", model_config={"hidden": (4,)},
                           epochs=1, batch_size=16),
                numeric,
            )
        ],
        "ValueIndexer": [
            FuzzObject(ValueIndexer(input_col="str_0", output_col="i"), mixed)
        ],
        "IndexToValue": [
            FuzzObject(
                IndexToValue(input_col="i", output_col="orig"),
                ValueIndexer(input_col="str_0", output_col="i")
                .fit(mixed)
                .transform(mixed),
            )
        ],
        "AssembleFeatures": [
            FuzzObject(
                AssembleFeatures(
                    columns_to_featurize=["num_0", "num_1", "str_0"],
                    number_of_features=128,
                ),
                mixed,
            )
        ],
        "Featurize": [
            FuzzObject(
                Featurize(
                    feature_columns={"features": ["num_0", "str_0"]},
                    number_of_features=128,
                ),
                mixed,
            )
        ],
        "TextFeaturizer": [
            FuzzObject(
                TextFeaturizer(input_col="str_0", output_col="tf",
                               num_features=64),
                mixed,
            )
        ],
        "TrainClassifier": [
            FuzzObject(TrainClassifier(label_col="label", epochs=1), mixed)
        ],
        "TrainRegressor": [
            FuzzObject(
                TrainRegressor(label_col="num_0", epochs=1), mixed
            )
        ],
        "ComputeModelStatistics": [FuzzObject(ComputeModelStatistics(), scored)],
        "ComputePerInstanceStatistics": [
            FuzzObject(ComputePerInstanceStatistics(), scored)
        ],
        "FindBestModel": [
            FuzzObject(
                FindBestModel(models=[trained_classifier]), classifier_ds
            )
        ],
        "ImageTransformer": [
            FuzzObject(ImageTransformer().resize(6, 6), _image_ds())
        ],
        "UnrollImage": [FuzzObject(UnrollImage(), _image_ds())],
        "ImageFeaturizer": [
            FuzzObject(
                ImageFeaturizer(model=_tiny_resnet_model(),
                                cut_output_layers=1),
                _image_ds(),
            )
        ],
        "ImageSetAugmenter": [FuzzObject(ImageSetAugmenter(), _image_ds())],
        "Cacher": [FuzzObject(Cacher(), mixed)],
        "CheckpointData": [
            FuzzObject(
                CheckpointData(checkpoint_dir=f"{ckdir}/cp",
                               remove_checkpoint=False),
                mixed,
            )
        ],
        "DropColumns": [FuzzObject(DropColumns(cols=["bool_0"]), mixed)],
        "SelectColumns": [FuzzObject(SelectColumns(cols=["num_0"]), mixed)],
        "Repartition": [FuzzObject(Repartition(n=2), mixed)],
        "ClassBalancer": [
            FuzzObject(ClassBalancer(input_col="label"), mixed)
        ],
        "Timer": [
            FuzzObject(Timer(stage=SelectColumns(cols=["num_0"])), mixed)
        ],
        "CleanMissingData": [
            FuzzObject(
                CleanMissingData(input_cols=["num_0"]),
                generate_dataset(
                    DatasetOptions(num_rows=16, missing_ratio=0.3), seed=3
                ),
            )
        ],
        "DataConversion": [
            FuzzObject(
                DataConversion(cols=["num_0"], convert_to="float"), mixed
            )
        ],
        "PartitionSample": [
            FuzzObject(PartitionSample(mode="Head", count=5), mixed)
        ],
        "SummarizeData": [FuzzObject(SummarizeData(), mixed)],
        "MultiColumnAdapter": [
            FuzzObject(
                MultiColumnAdapter(
                    base_stage=ValueIndexer(),
                    input_cols=["str_0"],
                    output_cols=["str_0_i"],
                ),
                mixed,
            )
        ],
        "EnsembleByKey": [
            FuzzObject(
                EnsembleByKey(keys=["str_0"], cols=["num_0"]), mixed
            )
        ],
    }
    return objects


#: fitted-model classes exercised via their estimator's fit in fuzzing
DERIVED_MODEL_CLASSES = {
    "PipelineModel": "Pipeline",
    "ValueIndexerModel": "ValueIndexer",
    "AssembleFeaturesModel": "AssembleFeatures",
    "FeaturizeModel": "Featurize",
    "TextFeaturizerModel": "TextFeaturizer",
    "TrainedClassifierModel": "TrainClassifier",
    "TrainedRegressorModel": "TrainRegressor",
    "ClassBalancerModel": "ClassBalancer",
    "CleanMissingDataModel": "CleanMissingData",
    "BestModel": "FindBestModel",
    "TreeClassifierModel": "DecisionTreeClassifier",
    "GBTClassifierModel": "GBTClassifier",
    "TreeRegressorModel": "DecisionTreeRegressor",
    "GBTRegressorModel": "GBTRegressor",
    "NaiveBayesModel": "NaiveBayes",
    "Word2VecModel": "Word2Vec",
    "OneVsRestModel": "OneVsRest",
}

#: stages that cannot be generically fuzzed, with the reason
EXEMPTIONS: dict[str, str] = {}
