"""Dataset tests — the DataFrame-replacement semantics everything rests on."""

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import SchemaError
from mmlspark_tpu.core.schema import (
    ColumnMeta,
    LABEL_KIND,
    SCORED_LABELS_KIND,
    CategoricalMeta,
    find_label_column,
    find_scored_labels_column,
    fresh_column_name,
    tag_column,
    CLASSIFICATION,
    get_score_value_kind,
)
from mmlspark_tpu.data.dataset import Dataset


def test_basic_shape(basic_dataset):
    assert basic_dataset.num_rows == 4
    assert set(basic_dataset.columns) == {"numbers", "doubles", "words", "flags"}
    assert basic_dataset["numbers"].dtype == np.int64
    assert basic_dataset["words"].dtype == object


def test_length_mismatch_rejected():
    with pytest.raises(SchemaError):
        Dataset({"a": [1, 2], "b": [1, 2, 3]})


def test_select_drop_rename(basic_dataset):
    sel = basic_dataset.select("numbers", "words")
    assert sel.columns == ["numbers", "words"]
    dropped = basic_dataset.drop("flags")
    assert "flags" not in dropped
    ren = basic_dataset.rename({"numbers": "ints"})
    assert "ints" in ren and "numbers" not in ren
    # original untouched (immutability)
    assert "numbers" in basic_dataset


def test_with_column_and_meta(basic_dataset):
    meta = ColumnMeta(categorical=CategoricalMeta(("a", "b")))
    ds = basic_dataset.with_column("cat", ["a", "b", "a", "b"], meta)
    assert ds.meta_of("cat").categorical.num_levels == 2
    with pytest.raises(SchemaError):
        basic_dataset.with_column("bad", [1, 2])


def test_filter_take_gather(basic_dataset):
    f = basic_dataset.filter(basic_dataset["numbers"] > 1)
    assert f.num_rows == 2 and list(f["words"]) == ["bass", "keys"]
    assert basic_dataset.take(2).num_rows == 2
    g = basic_dataset.gather(np.array([3, 0]))
    assert list(g["words"]) == ["keys", "guitars"]


def test_sample_deterministic(basic_dataset):
    a = basic_dataset.sample(fraction=0.5, seed=7)
    b = basic_dataset.sample(fraction=0.5, seed=7)
    assert list(a["numbers"]) == list(b["numbers"])
    assert a.num_rows == 2


def test_concat_and_vector_columns():
    d1 = Dataset({"v": np.ones((2, 3)), "s": ["x", "y"]})
    d2 = Dataset({"v": np.zeros((1, 3)), "s": ["z"]})
    cat = Dataset.concat([d1, d2])
    assert cat.num_rows == 3 and cat["v"].shape == (3, 3)
    with pytest.raises(SchemaError):
        Dataset.concat([d1, d1.rename({"v": "w"})])


def test_ragged_object_column():
    ds = Dataset({"seq": [np.arange(2), np.arange(5), np.arange(1)]})
    assert ds["seq"].dtype == object
    assert len(ds["seq"][1]) == 5


def test_pandas_round_trip(basic_dataset):
    df = basic_dataset.to_pandas()
    back = Dataset.from_pandas(df)
    assert back.num_rows == 4
    assert list(back["words"]) == list(basic_dataset["words"])


def test_map_column(basic_dataset):
    ds = basic_dataset.map_column("words", str.upper, output="loud")
    assert list(ds["loud"]) == ["GUITARS", "DRUMS", "BASS", "KEYS"]


def test_score_column_protocol(basic_dataset):
    ds = basic_dataset.with_meta(
        "numbers", tag_column(None, LABEL_KIND, "m1", CLASSIFICATION)
    ).with_meta("flags", tag_column(None, SCORED_LABELS_KIND, "m1", CLASSIFICATION))
    assert find_label_column(ds) == "numbers"
    assert find_scored_labels_column(ds, "m1") == "flags"
    assert get_score_value_kind(ds, "m1") == CLASSIFICATION
    assert find_label_column(ds, "other") is None


def test_fresh_column_name(basic_dataset):
    assert fresh_column_name(basic_dataset, "new") == "new"
    assert fresh_column_name(basic_dataset, "numbers") == "numbers_1"


def test_partitions(basic_dataset):
    ds = basic_dataset.with_partitions(4)
    assert ds.num_partitions == 4
    assert basic_dataset.num_partitions == 1


def test_rename_collision_rejected(basic_dataset):
    with pytest.raises(SchemaError):
        basic_dataset.rename({"numbers": "doubles"})


def test_with_column_replacement_resets_meta(basic_dataset):
    tagged = basic_dataset.with_meta(
        "numbers", ColumnMeta(categorical=CategoricalMeta(("a", "b")))
    )
    replaced = tagged.with_column("numbers", np.zeros(4))
    assert replaced.meta_of("numbers").is_empty()
    kept = tagged.with_column(
        "numbers", np.zeros(4), tagged.meta_of("numbers")
    )
    assert kept.meta_of("numbers").categorical is not None
