"""The mml-tpu launcher (the mml-exec analog, tools/bin/mml-exec:1-40)."""

from __future__ import annotations

import json
import os
import subprocess
import sys


def _run(*args, timeout=240):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU-relay dependence
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run(
        [sys.executable, "-m", "mmlspark_tpu", *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd="/root/repo",
    )


def test_env_on_cpu_mesh():
    res = _run("--cpu-mesh", "4", "env")
    assert res.returncode == 0, res.stderr
    info = json.loads(res.stdout)
    assert info["num_devices"] == 4
    assert info["platform"] == "cpu"


def test_config_listing():
    res = _run("config")
    assert res.returncode == 0, res.stderr
    conf = json.loads(res.stdout)
    assert conf["native_cc"]["value"] == "c++"
    assert "doc" in conf["cache_dir"]


def test_run_script(tmp_path):
    script = tmp_path / "user.py"
    script.write_text(
        "import sys\n"
        "from mmlspark_tpu.data.dataset import Dataset\n"
        "ds = Dataset({'a': [1.0, 2.0]})\n"
        "print('rows', ds.num_rows, 'argv', sys.argv[1:])\n"
    )
    res = _run("run", str(script), "--flag", "x")
    assert res.returncode == 0, res.stderr
    assert "rows 2 argv ['--flag', 'x']" in res.stdout


def test_zoo_list_and_download(tmp_path):
    res = _run(
        "zoo", "list",
        "--local-repo", str(tmp_path / "repo"),
        "--remote", "/root/repo/models/zoo_repo",
    )
    assert res.returncode == 0, res.stderr
    assert "ResNet20_Blobs" in res.stdout
    res = _run(
        "zoo", "download", "ResNet20_Blobs",
        "--local-repo", str(tmp_path / "repo"),
        "--remote", "/root/repo/models/zoo_repo",
    )
    assert res.returncode == 0, res.stderr
    assert "ResNet20_Blobs ->" in res.stdout


def test_multihost_env_contract(monkeypatch):
    """launch-pod.sh's env vars reach jax.distributed.initialize."""
    calls = {}

    import mmlspark_tpu.parallel.mesh as mesh

    class FakeDistributed:
        @staticmethod
        def initialize(coordinator_address, num_processes, process_id):
            calls.update(
                addr=coordinator_address, n=num_processes, pid=process_id
            )

    import jax

    monkeypatch.setenv("MMLSPARK_TPU_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("MMLSPARK_TPU_NUM_PROCESSES", "4")
    monkeypatch.setenv("MMLSPARK_TPU_PROCESS_ID", "2")
    monkeypatch.setattr(jax, "distributed", FakeDistributed)
    mesh.initialize_distributed()
    assert calls == {"addr": "10.0.0.1:8476", "n": 4, "pid": 2}


def test_evidence_flash_probe_gates_off_tpu():
    """`mml-tpu evidence flash` reaches the proof tool; on a CPU-only
    backend the tool's probe refuses with exit 2 (never hangs)."""
    r = _run("evidence", "flash")
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    assert "no TPU backend" in r.stdout
