"""Data-plane tests: decode op, readers (zip traversal, seeded subsample),
CTF format, fixed-shape batch feed."""

import io
import os
import zipfile

import numpy as np
import pytest
from PIL import Image

from mmlspark_tpu.core.exceptions import SchemaError
from mmlspark_tpu.data.ctf import dataset_to_ctf_lines, read_ctf, write_ctf
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.data.feed import (
    MASK_COL,
    batch_iterator,
    bucket_by_length,
    stack_column,
    to_device,
)
from mmlspark_tpu.data.readers import (
    read_binary_files,
    read_csv,
    read_images,
    stream_images,
)
from mmlspark_tpu.ops.decode import decode_image, native_available


def _write_image(path, h=8, w=6, color=(10, 20, 30), fmt="PNG"):
    rgb = np.zeros((h, w, 3), np.uint8)
    rgb[:] = color
    Image.fromarray(rgb).save(path, fmt)


@pytest.fixture
def image_dir(tmp_path):
    d = tmp_path / "imgs"
    d.mkdir()
    _write_image(d / "a.png", color=(255, 0, 0))
    _write_image(d / "b.jpg", fmt="JPEG", color=(0, 255, 0))
    sub = d / "sub"
    sub.mkdir()
    _write_image(sub / "c.png", color=(0, 0, 255))
    (d / "notes.txt").write_bytes(b"not an image")
    return str(d)


def test_native_decoder_builds():
    # The production path is the C++ op; the toolchain is in the image.
    assert native_available()


def test_decode_bgr_convention():
    buf = io.BytesIO()
    rgb = np.zeros((4, 5, 3), np.uint8)
    rgb[..., 0] = 200  # pure red
    Image.fromarray(rgb).save(buf, "PNG")
    out = decode_image(buf.getvalue())
    assert out.shape == (4, 5, 3)
    assert out[0, 0, 2] == 200 and out[0, 0, 0] == 0  # red lands in channel 2


def test_read_binary_files_recursive(image_dir):
    ds = read_binary_files(image_dir)
    assert ds.num_rows == 4  # includes notes.txt
    assert all(isinstance(b, bytes) for b in ds["bytes"])
    flat = read_binary_files(image_dir, recursive=False)
    assert flat.num_rows == 3


def test_read_images_drops_non_decodable(image_dir):
    ds = read_images(image_dir)
    assert ds.num_rows == 3  # notes.txt dropped
    row = ds["image"][0]
    assert row.data.dtype == np.uint8 and row.channels == 3
    assert ds.meta_of("image").image is not None


def test_zip_traversal(tmp_path):
    zpath = tmp_path / "bundle.zip"
    with zipfile.ZipFile(zpath, "w") as zf:
        zf.writestr("one.txt", b"alpha")
        zf.writestr("nested/two.txt", b"beta")
    ds = read_binary_files(str(tmp_path))
    assert ds.num_rows == 2
    assert any(p.endswith("nested/two.txt") for p in ds["path"])


def test_seeded_subsample_deterministic(tmp_path):
    d = tmp_path / "many"
    d.mkdir()
    for i in range(60):
        (d / f"f{i:03d}.bin").write_bytes(bytes([i]))
    a = read_binary_files(str(d), sample_ratio=0.5, seed=7)
    b = read_binary_files(str(d), sample_ratio=0.5, seed=7)
    assert list(a["path"]) == list(b["path"])
    assert 10 < a.num_rows < 50
    c = read_binary_files(str(d), sample_ratio=0.5, seed=8)
    assert list(c["path"]) != list(a["path"])
    # per-file decision is independent of the listing -> subset relation holds
    sub = read_binary_files(str(d), sample_ratio=0.25, seed=7)
    assert sub.num_rows < a.num_rows


def test_stream_images_chunks(image_dir):
    chunks = list(stream_images(image_dir, chunk_rows=2))
    assert sum(c.num_rows for c in chunks) == 3
    assert chunks[0].num_rows == 2


def test_csv_reader(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text("x,y\n1,a\n2,b\n")
    ds = read_csv(str(p))
    assert ds.num_rows == 2 and list(ds["y"]) == ["a", "b"]


def test_ctf_round_trip():
    ds = Dataset(
        {
            "label": np.array([0.0, 1.0, 2.0]),
            "features": np.array(
                [[0.0, 1.5, 0.0, 2.0], [3.0, 0.0, 0.0, 0.0], [0.0, 0.0, 0.25, 1.0]]
            ),
        }
    )
    lines = dataset_to_ctf_lines(ds)
    assert lines[0] == "|label 0 |features 1:1.5 3:2"
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "data.ctf")
        write_ctf(ds, path)
        back = read_ctf(path, feature_dim=4)
        np.testing.assert_allclose(back["features"], ds["features"])
        np.testing.assert_allclose(back["label"], ds["label"])


def test_ctf_dense_features():
    ds = Dataset({"label": np.array([1.0]), "features": np.array([[1.0, 0.0, 2.5]])})
    (line,) = dataset_to_ctf_lines(ds, features_form="dense")
    assert line == "|label 1 |features 1 0 2.5"


def test_batch_iterator_fixed_shapes():
    ds = Dataset({"x": np.arange(10, dtype=np.float32).reshape(10, 1)})
    batches = list(batch_iterator(ds, ["x"], batch_size=4))
    assert len(batches) == 3
    for b in batches:
        assert b["x"].shape == (4, 1)  # tail padded — shape stable
    assert batches[-1][MASK_COL].sum() == 2
    dropped = list(batch_iterator(ds, ["x"], batch_size=4, drop_remainder=True))
    assert len(dropped) == 2


def test_batch_iterator_shuffle_deterministic():
    ds = Dataset({"x": np.arange(8)})
    a = [b["x"] for b in batch_iterator(ds, ["x"], 8, shuffle_seed=3)]
    b = [b["x"] for b in batch_iterator(ds, ["x"], 8, shuffle_seed=3)]
    np.testing.assert_array_equal(a[0], b[0])
    assert not np.array_equal(a[0], np.arange(8))


def test_stack_column_object_vectors():
    ds = Dataset({"v": [np.ones(3), np.zeros(3)]})
    out = stack_column(ds, "v")
    assert out.shape == (2, 3)
    ragged = Dataset({"v": [np.ones(3), np.zeros(5)]})
    with pytest.raises(SchemaError):
        stack_column(ragged, "v")


def test_bucket_by_length():
    ds = Dataset(
        {"seq": [np.ones(2), np.ones(7), np.ones(3), np.ones(8)], "id": [0, 1, 2, 3]}
    )
    groups = bucket_by_length(ds, "seq", [4, 8])
    assert [b for b, _ in groups] == [4, 8]
    b4 = dict(groups)[4]
    assert b4["seq"].shape == (2, 4)  # padded to bucket
    assert list(b4["id"]) == [0, 2]
    with pytest.raises(SchemaError):
        bucket_by_length(ds, "seq", [4])


def test_to_device_sharded():
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("data",))
    from mmlspark_tpu.data.feed import data_sharding

    batch = {"x": np.arange(16.0).reshape(16, 1)}
    out = to_device(batch, data_sharding(mesh))
    assert out["x"].shape == (16, 1)
    assert len(out["x"].sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(out["x"]), batch["x"])


def test_transform_stream_matches_batch(image_dir):
    """Streaming pipeline (reference structured-streaming leg): chunked
    stream -> fitted per-row pipeline == one batch transform over the
    concatenated input."""
    from mmlspark_tpu.core.stage import Pipeline
    from mmlspark_tpu.stages.image import ImageTransformer, UnrollImage

    pipe = Pipeline([
        ImageTransformer(output_col="scaled").resize(height=4, width=4),
        UnrollImage(input_col="scaled", output_col="features"),
    ])
    batch = read_images(image_dir)
    fitted = pipe.fit(batch)

    streamed = list(
        fitted.transform_stream(stream_images(image_dir, chunk_rows=2))
    )
    assert len(streamed) == 2  # 3 images in chunks of 2
    got = np.concatenate([np.asarray(c["features"]) for c in streamed])
    want = np.asarray(fitted.transform(batch)["features"])
    np.testing.assert_array_equal(got, want)
