"""Data-prep stage tests (reference analog: per-module Verify* suites for
pipeline-stages, clean-missing-data, data-conversion, partition-sample,
summarize-data, multi-column-adapter, ensemble)."""

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.ensemble import EnsembleByKey
from mmlspark_tpu.stages.prep import (
    Cacher,
    CheckpointData,
    ClassBalancer,
    CleanMissingData,
    DataConversion,
    DropColumns,
    MultiColumnAdapter,
    PartitionSample,
    Repartition,
    SelectColumns,
    SummarizeData,
    Timer,
)
from mmlspark_tpu.stages.value_indexer import ValueIndexer


def test_select_drop_repartition(basic_dataset):
    sel = SelectColumns(cols=["numbers", "words"]).transform(basic_dataset)
    assert sel.columns == ["numbers", "words"]
    drp = DropColumns(cols=["flags"]).transform(basic_dataset)
    assert "flags" not in drp
    rep = Repartition(n=4).transform(basic_dataset)
    assert rep.num_partitions == 4
    assert Cacher().transform(basic_dataset) is basic_dataset


def test_drop_missing_column_rejected(basic_dataset):
    with pytest.raises(Exception):
        DropColumns(cols=["nope"]).transform(basic_dataset)


def test_checkpoint_data(tmp_path, basic_dataset):
    out = CheckpointData(
        checkpoint_dir=str(tmp_path / "ck"), remove_checkpoint=True
    ).transform(basic_dataset)
    assert out.num_rows == basic_dataset.num_rows
    assert not (tmp_path / "ck").exists()


def test_class_balancer():
    ds = Dataset({"label": ["a"] * 6 + ["b"] * 2})
    model = ClassBalancer(input_col="label").fit(ds)
    out = model.transform(ds)
    w = out["weight"]
    assert w[0] == 1.0 and w[-1] == 3.0  # 6/6 and 6/2


def test_timer_wraps_and_records(basic_dataset):
    timer = Timer(stage=SelectColumns(cols=["numbers"]))
    out = timer.transform(basic_dataset)
    assert out.columns == ["numbers"]
    assert timer.records and timer.records[0]["seconds"] >= 0
    est_timer = Timer(stage=ValueIndexer(input_col="words", output_col="i"))
    out2 = est_timer.transform(basic_dataset)
    assert "i" in out2.columns
    assert [r["op"] for r in est_timer.records] == ["fit", "transform"]


def test_clean_missing_data_modes():
    ds = Dataset({"x": np.array([1.0, np.nan, 3.0]),
                  "y": np.array([np.nan, 10.0, 20.0])})
    mean_model = CleanMissingData(input_cols=["x", "y"]).fit(ds)
    out = mean_model.transform(ds)
    assert out["x"][1] == 2.0 and out["y"][0] == 15.0
    med = CleanMissingData(input_cols=["x"], cleaning_mode="Median").fit(ds)
    assert med.transform(ds)["x"][1] == 2.0
    cust = CleanMissingData(
        input_cols=["x"], cleaning_mode="Custom", custom_value=-1.0
    ).fit(ds)
    assert cust.transform(ds)["x"][1] == -1.0
    with pytest.raises(FriendlyError):
        CleanMissingData(input_cols=["x"], cleaning_mode="Custom").fit(ds)


def test_data_conversion_casts(basic_dataset):
    out = DataConversion(cols=["numbers"], convert_to="double").transform(
        basic_dataset
    )
    assert out["numbers"].dtype == np.float64
    s = DataConversion(cols=["numbers"], convert_to="string").transform(
        basic_dataset
    )
    assert list(s["numbers"]) == ["0", "1", "2", "3"]


def test_data_conversion_date_round_trip():
    ds = Dataset({"when": ["2017-06-04 10:30:00", "2018-01-01 00:00:00"]})
    as_date = DataConversion(cols=["when"], convert_to="date").transform(ds)
    assert as_date["when"].dtype.kind == "M"
    back = DataConversion(cols=["when"], convert_to="string").transform(as_date)
    assert list(back["when"]) == ["2017-06-04 10:30:00", "2018-01-01 00:00:00"]


def test_data_conversion_categorical_round_trip():
    ds = Dataset({"c": ["x", "y", "x"]})
    cat = DataConversion(cols=["c"], convert_to="toCategorical").transform(ds)
    assert cat.meta_of("c").categorical is not None
    cleared = DataConversion(cols=["c"], convert_to="clearCategorical").transform(cat)
    assert cleared.meta_of("c").categorical is None
    assert list(cleared["c"]) == ["x", "y", "x"]


def test_partition_sample_modes():
    ds = Dataset({"x": np.arange(100)})
    head = PartitionSample(mode="Head", count=7).transform(ds)
    assert head.num_rows == 7 and list(head["x"]) == list(range(7))
    pct = PartitionSample(mode="RandomSample", percent=0.2, seed=1).transform(ds)
    assert pct.num_rows == 20
    absolute = PartitionSample(
        mode="RandomSample", random_sample_mode="Absolute", count=15, seed=1
    ).transform(ds)
    assert absolute.num_rows == 15
    assigned = PartitionSample(mode="AssignToPartition", num_parts=4).transform(ds)
    assert set(assigned["Partition"]) == {0, 1, 2, 3}
    assert assigned.num_partitions == 4


def test_summarize_data(basic_dataset):
    stats = SummarizeData().transform(basic_dataset)
    assert stats.num_rows == len(basic_dataset.columns)
    row = {c: stats[c][0] for c in stats.columns}  # 'numbers' row
    assert row["Feature"] == "numbers"
    assert row["Count"] == 4 and row["Min"] == 0 and row["Max"] == 3
    assert "P50" in stats.columns
    counts_only = SummarizeData(basic=False, sample=False,
                                percentiles=False).transform(basic_dataset)
    assert "Min" not in counts_only.columns


def test_multi_column_adapter(basic_dataset):
    adapter = MultiColumnAdapter(
        base_stage=ValueIndexer(),
        input_cols=["words", "flags"],
        output_cols=["words_i", "flags_i"],
    )
    out = adapter.transform(basic_dataset)
    assert "words_i" in out.columns and "flags_i" in out.columns
    with pytest.raises(FriendlyError):
        MultiColumnAdapter(
            base_stage=ValueIndexer(), input_cols=["a"], output_cols=[]
        ).transform(basic_dataset)


def test_ensemble_by_key_collapse_and_broadcast():
    ds = Dataset({
        "key": ["a", "a", "b"],
        "score": np.array([1.0, 3.0, 10.0]),
        "vec": np.array([[1.0, 0.0], [3.0, 2.0], [5.0, 5.0]]),
    })
    collapsed = EnsembleByKey(keys=["key"], cols=["score", "vec"]).transform(ds)
    assert collapsed.num_rows == 2
    got = dict(zip(collapsed["key"], collapsed["score_avg"]))
    assert got == {"a": 2.0, "b": 10.0}
    vecs = dict(zip(collapsed["key"], collapsed["vec_avg"]))
    np.testing.assert_array_equal(vecs["a"], [2.0, 1.0])
    broadcast = EnsembleByKey(
        keys=["key"], cols=["score"], collapse_group=False
    ).transform(ds)
    assert broadcast.num_rows == 3
    assert list(broadcast["score_avg"]) == [2.0, 2.0, 10.0]


def test_clean_missing_zero_config_skips_non_numeric():
    ds = Dataset({"s": ["a", None, "b"], "n": np.array([1.0, np.nan, 3.0])})
    out = CleanMissingData().fit(ds).transform(ds)
    assert out["n"][1] == 2.0 and list(out["s"]) == ["a", None, "b"]
    with pytest.raises(FriendlyError):
        CleanMissingData(input_cols=["s"]).fit(ds)
