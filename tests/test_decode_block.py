"""Fused multi-token decode blocks (ISSUE 5 tentpole).

The contract under test (docs/SERVING.md "Decode blocks"): the engine's
fused block — one ``lax.scan`` of up to T greedy micro-steps per
dispatch, with on-device sampling, position advance, and a live/EOS/
budget mask — emits BYTE-IDENTICAL token streams to single-request
``generate()`` for every block size on the power-of-two ladder, across
ragged prompts, mid-block EOS, mid-block budget exhaustion, and mid-run
joins; compiles at most ``num_decode_blocks`` distinct XLA programs;
and performs at most ONE host sync per block.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.models.generate import make_decode_block
from mmlspark_tpu.serve import ServeEngine
from mmlspark_tpu.serve.metrics import ServeMetrics
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new, eos_id=None):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new,
                   eos_id=eos_id)
    return np.asarray(out)[0]


# -- parity: fused blocks vs generate() ------------------------------------


# tier-1 keeps the block=4 case (the cheapest one that exercises a real
# multi-token scan, ladder shrink, and mid-run join); the T=1 engine and
# the full ladder run as `slow` via tools/ci.sh's dedicated parity step
@pytest.mark.parametrize("block", [
    pytest.param(1, marks=pytest.mark.slow),
    4,
    pytest.param(32, marks=pytest.mark.slow),
])
def test_block_parity_ragged_prompts_and_budgets(lm, block):
    """T∈{1,4,32} engines emit generate()'s exact tokens over ragged
    prompts and heterogeneous budgets (blocks shrink near each slot's
    budget), including a mid-run submit() join."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:4], row[:1], row[:9], row[:6], row[:2]]
    budgets = [10, 7, 3, 12, 5]

    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=block)
    assert engine.decode_block == block
    results, rids = {}, []
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        # three requests up front ...
        for p, n in zip(prompts[:3], budgets[:3]):
            rids.append(engine.submit(p, max_new_tokens=n))
        for _ in range(2):
            results.update({r.id: r for r in engine.step()})
        # ... two more join MID-RUN, while earlier requests are decoding
        for p, n in zip(prompts[3:], budgets[3:]):
            rids.append(engine.submit(p, max_new_tokens=n))
        while engine.busy:
            results.update({r.id: r for r in engine.step()})

    for rid, p, n in zip(rids, prompts, budgets):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, n),
            err_msg=f"block={block} request={rid}",
        )
        assert results[rid].generated == n
    assert engine.decode_compile_count <= engine.num_decode_blocks


@pytest.mark.parametrize("block", [
    4,
    pytest.param(32, marks=pytest.mark.slow),
])
def test_block_parity_mid_block_eos(lm, block):
    """A request hitting EOS mid-block goes dead ON DEVICE (pads for
    the rest of the block), retires at the boundary, and its stream
    still matches generate() with the same eos_id byte for byte."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :3])
    # pick an eos the trained model actually emits a few tokens in, so
    # the stop lands strictly inside a T>1 block
    free_run = _ref(m, v, prompt, 12)
    eos = int(free_run[len(prompt) + 2])
    # generate() keeps the padded full-length array; the engine returns
    # prompt + tokens up to and including EOS — trim the ref to match
    full = _ref(m, v, prompt, 12, eos_id=eos)
    stop = len(prompt) + int(np.argmax(full[len(prompt):] == eos))
    want = full[:stop + 1]

    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=block)
    rid = engine.submit(prompt, max_new_tokens=12, eos_id=eos)
    res = engine.run()[rid]
    np.testing.assert_array_equal(np.asarray(res.tokens), want)
    assert res.status == "completed"
    # the EOS token itself IS emitted (generate()'s advance semantics)
    assert int(res.tokens[-1]) == eos
    assert res.generated < 12


def test_mid_block_budget_exhaustion_direct_program(lm):
    """The raw block program (no engine ladder clamp shielding it):
    a row whose remaining budget is SMALLER than the scan length dies
    mid-block on the device budget mask — real tokens up to the budget,
    pads after, finished flag down — matching generate()'s stream."""
    m, v, ids = lm
    from mmlspark_tpu.models.generate import init_cache, _cached_apply

    prompt = np.asarray(ids[0, :5])
    budget = 3  # vs scan length 8: exhausts strictly inside the block
    t = 8
    want = _ref(m, v, prompt, budget + 1)  # +1: first token via prefill

    cache = init_cache(m, v, 1, 32)
    logits, cache = _cached_apply(m, v, jnp.asarray(prompt)[None], cache, 0)
    first = int(np.asarray(
        jnp.argmax(logits[0, len(prompt) - 1].astype(jnp.float32))
    ))
    assert first == int(want[len(prompt)])

    block_fn = make_decode_block(m, pad_id=0)
    p = len(prompt)
    toks, live, _, pos = block_fn(
        v, cache,
        jnp.asarray([p], jnp.int32),          # next write position
        jnp.asarray([True]),                   # live
        jnp.asarray([first], jnp.int32),       # last token
        jnp.asarray([budget], jnp.int32),      # remaining budget < t
        jnp.asarray([-1], jnp.int32),          # no EOS
        t,
    )
    toks = np.asarray(toks)[0]
    assert toks.shape == (t,)
    np.testing.assert_array_equal(toks[:budget], want[p + 1:p + 1 + budget])
    assert not bool(np.asarray(live)[0])       # finished inside the block
    assert (toks[budget:] == 0).all()          # pads after budget death
    assert int(np.asarray(pos)[0]) == p + budget  # frozen once dead


@pytest.mark.slow  # trains its own RoPE model; ci.sh's parity step runs it
def test_true_32_scan_with_rope(lm):
    """A genuine T=32 scan (not a ladder shrink): a RoPE model's
    cache_len can exceed max_len, leaving room for a 32-token block."""
    m = _tiny(pos_embedding="rope")
    v, ids = _train_lm(m)
    prompt = np.asarray(ids[0, :3])
    want = _ref(m, v, prompt, 40)

    engine = ServeEngine(m, v, slots=2, cache_len=64, decode_block=32)
    rid = engine.submit(prompt, max_new_tokens=40)
    res = engine.run()[rid]
    np.testing.assert_array_equal(np.asarray(res.tokens), want)
    # the first full block really ran at T=32 (min_rem=39 after the
    # prefill token -> ladder picks 32)
    assert "32" in engine.metrics.decode_blocks


# -- one host sync per block -----------------------------------------------


def test_at_most_one_host_sync_per_block(lm, monkeypatch):
    """Counts device->host transfers (``jax.device_get`` calls plus any
    ``np.asarray`` over a ``jax.Array``) during the decode phase: one
    request decoding 16 tokens through T=8 blocks must sync at most
    twice — the (S, T) token block and the finished vector ride ONE
    fetch per block."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :4])
    engine = ServeEngine(m, v, slots=1, cache_len=32, decode_block=8)
    rid = engine.submit(prompt, max_new_tokens=17)  # 1 prefill + 16 decode

    syncs = {"n": 0}
    real_device_get = jax.device_get
    real_asarray = np.asarray

    def counting_device_get(x, *a, **kw):
        syncs["n"] += 1
        return real_device_get(x, *a, **kw)

    def counting_asarray(x, *a, **kw):
        if isinstance(x, jax.Array):
            syncs["n"] += 1
        return real_asarray(x, *a, **kw)

    monkeypatch.setattr(jax, "device_get", counting_device_get)
    monkeypatch.setattr(np, "asarray", counting_asarray)
    res = engine.run()[rid]
    monkeypatch.undo()

    np.testing.assert_array_equal(
        np.asarray(res.tokens), _ref(m, v, prompt, 17)
    )
    # 16 decode tokens / blocks of 8 = 2 blocks -> at most 2 synced
    # fetches (1 per block), where the T=1 engine would have paid 16
    assert syncs["n"] <= 2, f"host syncs: {syncs['n']} (> 1 per block)"


# -- ladder / config -------------------------------------------------------


def test_decode_block_ladder_and_validation(lm):
    m, v, _ = lm
    with pytest.raises(FriendlyError, match="decode_block"):
        ServeEngine(m, v, slots=1, cache_len=32, decode_block=0)
    # non-power-of-two floors onto the ladder
    e = ServeEngine(m, v, slots=1, cache_len=32, decode_block=5)
    assert e.decode_block == 4 and e.num_decode_blocks == 3
    # block sizes clamp to min remaining budget (the parity rule)
    assert e._block_size(1) == 1
    assert e._block_size(3) == 2
    assert e._block_size(4) == 4
    assert e._block_size(100) == 4  # never past decode_block
    e1 = ServeEngine(m, v, slots=1, cache_len=32, decode_block=1)
    assert e1.num_decode_blocks == 1  # T=1 engine: the old contract


# -- metrics: per-token figures divide by tokens emitted -------------------


def test_metrics_tokens_emitted_equal_path_for_t1():
    a = ServeMetrics("m", slots=2)
    b = ServeMetrics("m", slots=2)
    # T=1 step: default tokens_emitted == n_active, explicit must match
    a.record_decode(2, 0.004)
    b.record_decode(2, 0.004, tokens_emitted=2, block=1)
    da, db = a.to_dict(), b.to_dict()
    assert da["per_token_ms"] == db["per_token_ms"] == 2.0
    assert da["per_token_ms_p50"] == db["per_token_ms_p50"]

    # T=8 block emitting 13 real tokens across 2 slots: per-token
    # divides by 13, not by n_active or by slots*T
    c = ServeMetrics("m", slots=2, decode_block=8)
    c.record_decode(2, 0.013, tokens_emitted=13, block=8)
    dc = c.to_dict()
    assert dc["per_token_ms"] == 1.0
    assert dc["decode_block"] == 8
    assert dc["decode_blocks"] == {"8": 1}


def test_metrics_tokens_per_tick():
    ms = ServeMetrics("m", slots=4, decode_block=8)
    ms.sample_tick(0, 4, 0.01, tokens_emitted=12)
    ms.sample_tick(0, 2, 0.01, tokens_emitted=4)
    d = ms.to_dict()
    assert d["tokens_per_tick"] == 8.0
    assert d["ticks"] == 2
