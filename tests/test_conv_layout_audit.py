"""Conv dimension-number audit (docs/PERFORMANCE.md tuning lever #3).

MXU efficiency on TPU depends on convolutions lowering to XLA's preferred
layout: NHWC activations x HWIO kernels -> NHWC, with bf16 operands so
the MXU runs native precision. This pins the property statically (lower,
not compile) for both conv backbones — a regression to NCHW or a silent
f32 upcast of the conv inputs shows up here long before an MFU number
can.
"""

import re

import jax
import jax.numpy as jnp
import pytest

from mmlspark_tpu.models import build_model

_PREFERRED = "[b, 0, 1, f]x[0, 1, i, o]->[b, 0, 1, f]"


@pytest.mark.parametrize(
    "name,kwargs,size",
    [
        ("resnet20_cifar10", {}, 32),
        ("resnet50", {"input_size": 64}, 64),
    ],
)
def test_convs_lower_nhwc_hwio_bf16(name, kwargs, size):
    graph = build_model(name, **kwargs)
    variables = graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3), jnp.float32)
    )
    txt = jax.jit(graph.apply).lower(
        variables, jnp.zeros((2, size, size, 3), jnp.bfloat16)
    ).as_text()

    n_convs = txt.count("stablehlo.convolution")
    assert n_convs > 10, f"{name}: expected a conv stack, saw {n_convs}"

    dnums = set(
        re.findall(r"dim_numbers = (\[[^\]]*\]x\[[^\]]*\]->\[[^\]]*\])", txt)
    )
    assert dnums == {_PREFERRED}, f"{name}: non-preferred conv layouts {dnums}"

    # every conv consumes bf16 operands (activations AND kernels): the
    # weights are cast to the compute dtype rather than pulling the MXU
    # up to f32
    operand_types = re.findall(
        r"stablehlo.convolution.*?: \(tensor<([^>]*)>, tensor<([^>]*)>\)",
        txt,
    )
    assert len(operand_types) == n_convs
    bad = [t for t in operand_types if not (t[0].endswith("xbf16") and
                                            t[1].endswith("xbf16"))]
    assert not bad, f"{name}: non-bf16 conv operands {bad[:3]}"
