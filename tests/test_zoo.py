"""Model-zoo / downloader tests (reference analog: DownloaderSuite)."""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models import build_model
from mmlspark_tpu.models.zoo import (
    ModelDownloader,
    ModelSchema,
    Repository,
    publish_model,
)
from mmlspark_tpu.stages.dnn_model import TPUModel


@pytest.fixture(scope="module")
def remote_repo(tmp_path_factory):
    """A 'remote' repo holding a published TPUModel saved-stage payload."""
    root = str(tmp_path_factory.mktemp("remote_repo"))
    g = build_model("mlp", num_outputs=2, hidden=(4,))
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 3)))
    stage = TPUModel.from_graph(
        g, v, "mlp", model_config={"num_outputs": 2, "hidden": (4,)},
        input_col="features",
    )
    payload = os.path.join(root, "_stage_payload")
    stage.save(payload)
    publish_model(
        root, "TinyMLP", payload,
        layer_names=tuple(g.layer_names), model_type="classifier",
        dataset="toy",
    )
    return root


def test_manifest_and_schema(remote_repo):
    repo = Repository(remote_repo)
    schemas = list(repo.list_schemas())
    assert len(schemas) == 1
    s = schemas[0]
    assert s.name == "TinyMLP" and s.layer_names[-1] == "z"
    assert repo.get_schema("TinyMLP").hash == s.hash
    with pytest.raises(FriendlyError):
        repo.get_schema("NoSuchModel")


def test_download_verify_and_cache(tmp_path, remote_repo):
    local = str(tmp_path / "local")
    dl = ModelDownloader(local, remote=remote_repo)
    schema = dl.download_by_name("TinyMLP")
    path = dl.local_path(schema)
    assert os.path.isdir(path)
    # meta written locally; second download is a cache hit (no remote needed)
    dl2 = ModelDownloader(local, remote=None)
    cached = dl2.download_by_name("TinyMLP")
    assert cached.hash == schema.hash
    # the payload round-trips into a working inference stage
    model = TPUModel(input_col="features", model_name="mlp").set_model_location(path)
    out = model.transform(Dataset({"features": np.zeros((2, 3))}))
    assert out["scores"].shape == (2, 2)


def test_corrupt_download_detected(tmp_path, remote_repo):
    local = str(tmp_path / "local")
    dl = ModelDownloader(local, remote=remote_repo)
    schema = dl.download_by_name("TinyMLP")
    # corrupt one payload file -> verification fails -> re-download repairs
    victim = None
    for root, _d, files in os.walk(dl.local_path(schema)):
        for f in files:
            victim = os.path.join(root, f)
            break
        if victim:
            break
    with open(victim, "ab") as f:
        f.write(b"tampered")
    assert not dl._verify(schema)
    repaired = dl.download_by_name("TinyMLP")
    assert dl._verify(repaired)


def test_torn_download_raises_both_hashes_and_deletes_partial(tmp_path):
    """A download that fails sha256 verification must raise the typed
    error naming BOTH hashes (expected vs actual) and DELETE the torn
    payload — a lingering partial would be re-hashed and re-raised
    forever on every later download_by_name instead of re-fetched."""
    import json

    remote = str(tmp_path / "remote")
    payload = tmp_path / "weights.bin"
    payload.write_bytes(b"trained weights v1")
    schema = publish_model(remote, "Torn", str(payload))
    # tamper the published payload AFTER hashing: the fetched bytes can
    # no longer match the manifest hash (a torn/corrupted transfer)
    with open(os.path.join(remote, schema.uri), "ab") as f:
        f.write(b"...torn mid-transfer")

    local = str(tmp_path / "local")
    dl = ModelDownloader(local, remote=remote)
    with pytest.raises(FriendlyError) as ei:
        dl.download_by_name("Torn")
    msg = str(ei.value)
    assert schema.hash in msg, "error must name the expected hash"
    from mmlspark_tpu.models.zoo import _sha256_path

    actual = _sha256_path(os.path.join(remote, schema.uri))
    assert actual in msg, "error must name the actual hash"
    # the partial payload is gone and no stale meta was written
    assert not os.path.exists(dl.local_path(schema))
    assert not os.path.exists(os.path.join(local, "Torn.meta"))
    # repairing the remote repairs the client: next download succeeds
    payload2 = tmp_path / "weights2.bin"
    payload2.write_bytes(
        open(os.path.join(remote, schema.uri), "rb").read()
    )
    fixed = publish_model(remote, "Torn", str(payload2))
    got = dl.download_by_name("Torn")
    assert got.hash == fixed.hash and dl._verify(got)
    # sanity: the meta JSON on disk round-trips
    with open(os.path.join(local, "Torn.meta")) as f:
        assert json.load(f)["hash"] == fixed.hash


class _FlakyRepository(Repository):
    """Remote whose payload reads fail N times before succeeding —
    the injected stand-in for a transient network/storage blip."""

    def __init__(self, root, fail_times):
        super().__init__(root)
        self.fails_left = fail_times
        self.payload_reads = 0

    def _read(self, rel):
        if rel.endswith(".bin"):  # payload reads only, not MANIFEST
            self.payload_reads += 1
            if self.fails_left > 0:
                self.fails_left -= 1
                raise OSError("injected transient read failure")
        return super()._read(rel)


def _publish_file_payload(tmp_path, name="Retry"):
    remote = str(tmp_path / "remote")
    payload = tmp_path / "weights.bin"
    payload.write_bytes(b"trained weights v1")
    return remote, publish_model(remote, name, str(payload))


def test_transient_download_failure_is_retried(tmp_path):
    """ISSUE 18 satellite: a transient fetch failure costs one extra
    fetch, not a failed job — the capped deterministic retry loop
    absorbs it and the verified payload lands."""
    remote, schema = _publish_file_payload(tmp_path)
    repo = _FlakyRepository(remote, fail_times=2)
    dl = ModelDownloader(str(tmp_path / "local"), remote=repo,
                         retry_backoff_s=0.0)
    got = dl.download_by_name("Retry")
    assert got.hash == schema.hash and dl._verify(got)
    assert repo.payload_reads == 3  # 2 failures + the success


def test_transient_verification_failure_is_retried(tmp_path):
    """One corrupted transfer (sha256 mismatch) deletes the partial
    and re-fetches; the second, clean transfer verifies."""

    class _CorruptOnce(Repository):
        def _read(self, rel):
            data = super()._read(rel)
            if rel.endswith(".bin") and not getattr(
                    self, "_flipped", False):
                self._flipped = True
                return data + b"\x00"
            return data

    remote, schema = _publish_file_payload(tmp_path)
    dl = ModelDownloader(str(tmp_path / "local"),
                         remote=_CorruptOnce(remote),
                         retry_backoff_s=0.0)
    got = dl.download_by_name("Retry")
    assert got.hash == schema.hash and dl._verify(got)


def test_retry_limit_exhaustion_surfaces_last_error(tmp_path):
    """Past ``retry_limit`` the LAST failure surfaces unchanged — the
    loop must not swallow the typed error or spin forever."""
    remote, _schema = _publish_file_payload(tmp_path)
    repo = _FlakyRepository(remote, fail_times=100)
    dl = ModelDownloader(str(tmp_path / "local"), remote=repo,
                         retry_limit=2, retry_backoff_s=0.0)
    with pytest.raises(OSError, match="injected transient"):
        dl.download_by_name("Retry")
    assert repo.payload_reads == 3  # 1 initial + 2 retries

    with pytest.raises(FriendlyError, match="retry_limit"):
        ModelDownloader(str(tmp_path / "local2"), retry_limit=-1)


def test_schema_json_round_trip():
    s = ModelSchema(name="m", uri="m.bin", hash="ab", size=3,
                    layer_names=("a", "z"), input_node="input")
    s2 = ModelSchema.from_json(s.to_json())
    assert s2 == s


def test_http_repository(tmp_path, remote_repo):
    """The http(s) repo path served over a real localhost HTTP server
    (reference DefaultModelRepo is an HTTP MANIFEST repo,
    ModelDownloader.scala:109-155)."""
    import http.server
    import threading

    handler = functools.partial(
        http.server.SimpleHTTPRequestHandler, directory=remote_repo
    )
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        repo = Repository(url)
        schemas = list(repo.list_schemas())
        assert [s.name for s in schemas] == ["TinyMLP"]
        dl = ModelDownloader(str(tmp_path / "local"), remote=url)
        schema = dl.download_by_name("TinyMLP")
        assert os.path.isdir(dl.local_path(schema))
    finally:
        server.shutdown()
        thread.join()


def test_http_download_rejects_path_traversal(tmp_path, remote_repo):
    """A malicious remote file listing must not write outside the local
    repo (code-review finding)."""
    import functools as _ft
    import http.server
    import threading

    # corrupt the sidecar with a traversal entry
    with open(os.path.join(remote_repo, "_stage_payload.files"), "a") as f:
        f.write("../../evil.txt\n")
    handler = _ft.partial(
        http.server.SimpleHTTPRequestHandler, directory=remote_repo
    )
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        dl = ModelDownloader(str(tmp_path / "local"), remote=url)
        with pytest.raises(FriendlyError, match="unsafe path"):
            dl.download_by_name("TinyMLP")
        assert not (tmp_path / "evil.txt").exists()
    finally:
        server.shutdown()


# -- committed payload integrity ------------------------------------------

_ZOO_REPO = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "models", "zoo_repo",
)


@pytest.mark.parametrize(
    "name,datagen",
    [("ResNet20_Blobs", "blob_images"), ("ResNet20_Bars", "bar_images")],
)
def test_committed_payload_scores(tmp_path, name, datagen):
    """Every payload committed under models/zoo_repo must download through
    the sha256-verified path, load, and still separate its own data
    distribution — catching payload/datagen drift at unit-test speed
    rather than in the example tier."""
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.testing import datagen as dg

    downloader = ModelDownloader(str(tmp_path), remote=_ZOO_REPO)
    schema = downloader.download_by_name(name)
    assert schema.layer_names, "committed payloads must carry layer names"
    stage = PipelineStage.load(downloader.local_path(schema))

    imgs, y = getattr(dg, datagen)(96, seed=123)
    x = np.stack(imgs).astype(np.float32) / 255.0
    scored = stage.transform(Dataset({"image": x}))
    acc = float((np.asarray(scored["scores"]).argmax(1) == y).mean())
    assert acc > 0.9, f"{name} committed payload scores {acc} on {datagen}"


def test_committed_real_backbone_scores_real_digits(tmp_path):
    """The real-capability payload (ResNet20_Digits04, trained on the
    sklearn handwritten-digit scans 0-4 with shift augmentation) must
    download through the sha256 path, carry its recorded held-out
    accuracy in the meta, and still score unregistered real digits."""
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.data.sample_data import load_digit_images

    downloader = ModelDownloader(str(tmp_path), remote=_ZOO_REPO)
    schema = downloader.download_by_name("ResNet20_Digits04")
    assert schema.layer_names
    assert schema.extra.get("test_accuracy", 0) > 0.9
    assert "real" in schema.dataset or "digits" in schema.dataset
    stage = PipelineStage.load(downloader.local_path(schema))

    imgs, y = load_digit_images(
        (0, 1, 2, 3, 4), max_shift=int(schema.extra["max_shift"]), seed=555
    )
    x = imgs[:256].astype(np.float32) / 255.0
    scored = stage.transform(Dataset({"image": x}))
    acc = float((np.asarray(scored["scores"]).argmax(1) == y[:256]).mean())
    assert acc > 0.9, f"real backbone scores {acc} on unregistered digits"


def test_evidence_backbone_accuracy_off_ceiling(tmp_path):
    """ResNet20_Digits10 exists to keep the zoo's quality evidence
    falsifiable: 10 classes at a 25% label budget land the recorded
    held-out accuracy OFF the 1.0 ceiling (a saturated number cannot
    distinguish a good backbone from a memorized one), while still being
    high enough to prove the conv stack learns real scans."""
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.data.sample_data import load_digit_images

    downloader = ModelDownloader(str(tmp_path), remote=_ZOO_REPO)
    schema = downloader.download_by_name("ResNet20_Digits10")
    acc = schema.extra.get("test_accuracy", None)
    assert acc is not None
    assert 0.75 < acc < 1.0, f"evidence accuracy saturated or weak: {acc}"
    assert schema.extra.get("train_label_budget", "").startswith("25%")

    # the payload itself scores unregistered scans of ALL ten classes
    stage = PipelineStage.load(downloader.local_path(schema))
    imgs, y = load_digit_images(
        tuple(range(10)), max_shift=int(schema.extra["max_shift"]), seed=556
    )
    x = imgs[:256].astype(np.float32) / 255.0
    scored = stage.transform(Dataset({"image": x}))
    live = float((np.asarray(scored["scores"]).argmax(1) == y[:256]).mean())
    assert live > 0.75, f"evidence backbone scores {live} live"
