"""Weight-only int8 quantization (ops/quantize.py + TPUModel.weight_quant).

A TPU-native addition with no reference counterpart (2017 CNTK inference
is f32 JNI): device-resident kernels stored int8 per-channel, dequantized
to bf16 inside the jitted forward. The gates below keep it honest — exact
pass-through for small tensors, bounded reconstruction error, a ~4x
stored-bytes win, and near-perfect score agreement on the real-data zoo
backbone.
"""

import numpy as np
import pytest

from mmlspark_tpu.ops.quantize import (
    dequantize_weights,
    quantize_weights,
    quantized_bytes,
)


def test_roundtrip_error_bounded_per_channel():
    rng = np.random.default_rng(0)
    # channels with wildly different magnitudes: per-channel scales must
    # keep relative error small everywhere; a per-tensor scale would not
    w = rng.normal(size=(64, 128)).astype(np.float32)
    w *= np.logspace(-3, 2, 128)[None, :].astype(np.float32)
    q = quantize_weights({"k": w})
    back = np.asarray(dequantize_weights(q, dtype=np.float32)["k"])
    scale = np.abs(w).max(axis=0) / 127.0
    assert np.all(np.abs(back - w) <= scale[None, :] * 0.51 + 1e-9)


def test_bf16_leaves_are_quantized():
    """bfloat16 kernels (the repo's own bf16-resident lever) must NOT be
    silently skipped: ml_dtypes' bfloat16 has numpy kind 'V', so a naive
    dtype-kind check would pass them through unquantized."""
    import jax.numpy as jnp

    w = np.random.default_rng(2).normal(size=(128, 64)).astype(np.float32)
    q = quantize_weights({"k": np.asarray(jnp.asarray(w, jnp.bfloat16))})
    assert isinstance(q["k"], dict), "bf16 leaf skipped by quantizer"
    back = np.asarray(dequantize_weights(q, dtype=np.float32)["k"])
    assert np.abs(back - w).max() < 0.05


def test_small_and_1d_tensors_pass_through():
    tree = {
        "bias": np.ones(64, np.float32),          # 1-D
        "tiny": np.ones((8, 8), np.float32),      # < min size
        "ints": np.arange(12).reshape(3, 4),      # non-float
    }
    q = quantize_weights(tree)
    for k in tree:
        np.testing.assert_array_equal(q[k], tree[k])


def test_stored_bytes_shrink_4x():
    w = np.random.default_rng(1).normal(size=(256, 256)).astype(np.float32)
    q = quantize_weights({"k": w})
    stored, f32 = quantized_bytes(q)
    assert f32 == w.size * 4
    assert stored < f32 / 3.8  # int8 + per-channel scales


@pytest.mark.parametrize("quant", ["none", "int8"])
def test_tpumodel_weight_quant_scores_agree(quant, tmp_path):
    """TPUModel(weight_quant='int8') on the committed real-data backbone:
    argmax agreement with the f32 path stays near-perfect and accuracy
    holds on unregistered scans."""
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.data.dataset import Dataset
    from mmlspark_tpu.data.sample_data import load_digit_images
    from mmlspark_tpu.models.zoo import ModelDownloader

    import os

    zoo = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "models", "zoo_repo",
    )
    dl = ModelDownloader(str(tmp_path), remote=zoo)
    schema = dl.download_by_name("ResNet20_Digits10")
    stage = PipelineStage.load(dl.local_path(schema))
    imgs, y = load_digit_images(tuple(range(10)), max_shift=4, seed=321)
    x = imgs[:200].astype(np.float32) / 255.0
    ds = Dataset({"image": x})

    base_raw = np.asarray(stage.transform(ds)["scores"])
    base = base_raw.argmax(1)
    if quant == "none":
        acc = float((base == y[:200]).mean())
        assert acc > 0.75, acc
        return
    stage.weight_quant = "int8"
    q_raw = np.asarray(stage.transform(ds)["scores"])
    # the quantized path must actually have engaged: int8 reconstruction
    # perturbs the logits (identical outputs would mean a stale cache
    # silently served the f32 weights)
    assert not np.array_equal(q_raw, base_raw)
    q_scores = q_raw.argmax(1)
    agree = float((q_scores == base).mean())
    assert agree >= 0.97, f"int8 argmax agreement {agree}"
    acc = float((q_scores == y[:200]).mean())
    assert acc > 0.75, f"int8 accuracy {acc}"


def test_image_featurizer_preserves_weight_quant(tmp_path):
    """ImageFeaturizer copies its TPUModel (explicit params included), so
    a quantized backbone stays quantized through the transfer-learning
    path — features shift slightly but stay strongly aligned."""
    import os

    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.data.dataset import Dataset
    from mmlspark_tpu.data.sample_data import load_digit_images
    from mmlspark_tpu.models.zoo import ModelDownloader
    from mmlspark_tpu.stages.image import ImageFeaturizer

    zoo = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "models", "zoo_repo",
    )
    dl = ModelDownloader(str(tmp_path), remote=zoo)
    stage = PipelineStage.load(
        dl.local_path(dl.download_by_name("ResNet20_Digits10"))
    )
    imgs, _ = load_digit_images(tuple(range(10)), max_shift=4, seed=9)
    ds = Dataset({"image": imgs[:64].astype(np.float32) / 255.0})

    def feats(quant):
        stage.weight_quant = quant
        f = ImageFeaturizer(model=stage, cut_output_layers=1)
        return np.asarray(f.transform(ds)["features"], np.float32)

    f32 = feats("none")
    q8 = feats("int8")
    assert f32.shape == q8.shape and f32.ndim == 2
    assert not np.array_equal(f32, q8), "int8 did not engage through copy"
    num = (f32 * q8).sum(axis=1)
    den = np.linalg.norm(f32, axis=1) * np.linalg.norm(q8, axis=1) + 1e-9
    assert float((num / den).min()) > 0.99, "features diverged"
