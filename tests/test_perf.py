"""Device-level performance analytics (core/perf): XLA cost analysis
with its interpreter fallback, MFU / bandwidth attribution arithmetic,
the Chrome/Perfetto trace exporter's validity + determinism, SLO window
arithmetic on synthetic clocks, and the serving integration — analytics
and SLO monitoring enabled must keep the one-host-sync-per-block
contract and the compile_guard pins unchanged on BOTH the single-device
and the 2x2-mesh engine (the ISSUE 8 acceptance bar)."""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.perf import (
    DevicePeak,
    PerfAnalytics,
    ProgramCost,
    SloMonitor,
    SloTargets,
    analyze_jit_cost,
    device_peak,
    export_chrome_trace,
    parse_slo_spec,
)
from mmlspark_tpu.core.telemetry import (
    FlightRecorder,
    Histogram,
    MetricRegistry,
    SpanTracer,
)
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.serve import ServeEngine
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new)
    return np.asarray(out)[0]


# -- cost analysis: real programs and the unavailable fallback -------------


def test_analyze_jit_cost_real_program_no_compile():
    """Lowering a real jitted fn yields analytic flops WITHOUT
    populating the executable cache — cost analysis must never count as
    a compile against the guard pins."""
    fn = jax.jit(lambda x: jnp.sum(x @ x.T))
    cost = analyze_jit_cost(fn, jnp.zeros((8, 8), jnp.float32))
    assert cost.source == "xla"
    assert cost.flops is not None and cost.flops > 0
    assert cost.bytes_accessed is not None and cost.bytes_accessed > 0
    assert fn._cache_size() == 0  # traced, never backend-compiled


class _RaisingJit:
    def lower(self, *a, **kw):
        raise RuntimeError("backend says no")


class _EmptyLowered:
    def cost_analysis(self):
        return {}


class _EmptyCostJit:
    def lower(self, *a, **kw):
        return _EmptyLowered()


def test_analyze_jit_cost_degrades_to_unavailable():
    """A backend whose lowering raises, or whose cost model answers
    nothing, degrades to source="unavailable" — never an exception."""
    c1 = analyze_jit_cost(_RaisingJit(), np.zeros((2, 2)))
    assert c1 == ProgramCost.unavailable()
    c2 = analyze_jit_cost(_EmptyCostJit(), np.zeros((2, 2)))
    assert c2.source == "unavailable"
    assert c2.flops is None and c2.bytes_accessed is None


def test_perf_analytics_with_unavailable_cost_yields_none_mfu():
    pa = PerfAnalytics(
        n_devices=1, peak=DevicePeak(1e12, 1e11, "table", "test")
    )
    pa.register_program("decode[T=4]", ProgramCost.unavailable())
    pa.record_dispatch("decode[T=4]", 0.01, tokens=4)
    pa.record_tick(0.02)
    s = pa.summary()
    assert s["mfu"] is None and s["hbm_bw_util_pct"] is None
    fam = s["families"]["decode[T=4]"]
    assert fam["cost_source"] == "unavailable"
    assert fam["mfu"] is None and fam["dispatches"] == 1
    # the time split still works: 0.01s device of 0.02s tick
    assert s["device_time_pct"] == 50.0
    assert s["device_time_s"] == 0.01 and s["host_time_s"] == 0.01


def test_perf_analytics_mfu_and_bandwidth_arithmetic():
    """Exact attribution: flops x dispatches / device_s against the
    declared peak."""
    reg = MetricRegistry()
    pa = PerfAnalytics(
        registry=reg, n_devices=1,
        peak=DevicePeak(1e12, 1e11, "table", "test"),
    )
    pa.register_program("decode[T=8]", ProgramCost(1e9, 1e9, "xla"))
    pa.register_program("decode[T=8]", ProgramCost(5e55, 5e55, "xla"))
    pa.record_dispatch("decode[T=8]", 0.01, tokens=8)  # 1e11 flop/s
    assert pa.summary()["mfu"] == pytest.approx(0.1)
    assert pa.summary()["hbm_bw_util_pct"] == pytest.approx(100.0)
    # registration is first-wins: the 5e55 re-register was ignored
    assert pa.summary()["families"]["decode[T=8]"]["flops"] == 1e9
    assert not pa.wants_program("decode[T=8]")
    assert pa.wants_program("prefill[16]")
    # gauges landed in the shared registry
    d = reg.to_dict()
    assert d["perf.decode[T=8].mfu"] == pytest.approx(0.1)
    assert d["perf.mfu"] == pytest.approx(0.1)
    # a dispatch for a family never registered still attributes time
    pa.record_dispatch("mystery", 0.02)
    assert pa.summary()["families"]["mystery"]["cost_source"] == (
        "unavailable"
    )
    assert pa.device_seconds() == pytest.approx(0.03)


def test_device_peak_env_override_and_table_prefix(monkeypatch):
    monkeypatch.delenv("MMLTPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("MMLTPU_PEAK_HBM_BYTES_PER_S", raising=False)

    class FakeTpu:
        device_kind = "TPU v5p chip"

    p = device_peak(FakeTpu())
    assert p.source == "table" and p.flops_per_s == 459e12

    # the CPU backend of this suite is not in the table -> nominal
    assert device_peak().source == "nominal"

    monkeypatch.setenv("MMLTPU_PEAK_FLOPS", "2e12")
    p2 = device_peak(FakeTpu())
    assert p2.source == "env"
    assert p2.flops_per_s == 2e12
    assert p2.hbm_bytes_per_s == 2765e9  # unset half keeps the table


# -- SLO monitor: window arithmetic on a synthetic clock -------------------


def test_slo_monitor_burns_sheds_and_recovers():
    rec = FlightRecorder()
    reg = MetricRegistry()
    t = {"now": 0.0}
    mon = SloMonitor(
        SloTargets(ttft_p99_ms=50.0, window_s=10.0, min_samples=3),
        recorder=rec, registry=reg, clock=lambda: t["now"],
    )
    # below min_samples: two terrible samples cannot trip the alert
    mon.observe_ttft(500.0)
    mon.observe_ttft(600.0)
    st = mon.evaluate(tick=0)
    assert not st["burning"] and not mon.should_shed
    # third sample crosses min_samples -> violation + shed + ONE event
    mon.observe_ttft(700.0)
    st = mon.evaluate(tick=1)
    assert st["burning"] and mon.should_shed
    assert st["violations"][0]["slo"] == "ttft_p99_ms"
    assert st["violations"][0]["value"] == 700.0
    mon.evaluate(tick=2)  # still burning: no second violation event
    names = [e["name"] for e in rec.events()
             if e["name"].startswith("slo_")]
    assert names == ["slo_violation"]
    assert mon.violations_total == 2  # but every burning tick counts
    assert reg.to_dict()["slo.burning"] == 1
    # samples age out of the 10s window -> recovered, shed clears
    t["now"] = 11.0
    st = mon.evaluate(tick=3)
    assert not st["burning"] and not mon.should_shed
    assert st["window"]["ttft_samples"] == 0
    names = [e["name"] for e in rec.events()
             if e["name"].startswith("slo_")]
    assert names == ["slo_violation", "slo_recovered"]
    assert reg.to_dict()["slo.burning"] == 0


def test_slo_monitor_error_rate_budget_and_per_token():
    t = {"now": 0.0}
    mon = SloMonitor(
        SloTargets(error_rate=0.2, per_token_p99_ms=5.0,
                   window_s=100.0, min_samples=5),
        clock=lambda: t["now"],
    )
    for _ in range(4):
        mon.observe_finish(True)
    mon.observe_finish(False)
    st = mon.evaluate()
    assert not st["burning"]  # 1/5 = 0.2 is AT budget, not over it
    mon.observe_finish(False)
    st = mon.evaluate()
    assert st["burning"]
    assert [v["slo"] for v in st["violations"]] == ["error_rate"]
    assert st["violations"][0]["value"] == pytest.approx(2 / 6, abs=1e-4)
    # per-token joins as a second simultaneous violation
    for _ in range(5):
        mon.observe_per_token(9.0)
    st = mon.evaluate()
    assert {v["slo"] for v in st["violations"]} == {
        "error_rate", "per_token_p99_ms"
    }


def test_slo_monitor_state_before_first_evaluate():
    mon = SloMonitor(SloTargets(ttft_p99_ms=10.0))
    st = mon.state()
    assert st["declared"] is True and st["burning"] is False
    assert st["targets"]["ttft_p99_ms"] == 10.0
    with pytest.raises(FriendlyError, match="SloTargets"):
        SloMonitor({"ttft_p99_ms": 10.0})


def test_parse_slo_spec():
    t = parse_slo_spec(
        " ttft_p99_ms=50, per_token_p99_ms=5 ,error_rate=0.05,"
        "window_s=30,min_samples=2"
    )
    assert t.ttft_p99_ms == 50.0 and t.per_token_p99_ms == 5.0
    assert t.error_rate == 0.05 and t.window_s == 30.0
    assert t.min_samples == 2 and t.declared()
    with pytest.raises(FriendlyError, match="unknown SLO key"):
        parse_slo_spec("latency=5")
    with pytest.raises(FriendlyError, match="needs a number"):
        parse_slo_spec("ttft_p99_ms=fast")
    with pytest.raises(FriendlyError, match="key=value"):
        parse_slo_spec("ttft_p99_ms")
    with pytest.raises(FriendlyError, match="declares no target"):
        parse_slo_spec("window_s=30")


# -- histogram bucket export + Prometheus exposition -----------------------


def test_histogram_bucket_bounds_align_with_counts():
    h = Histogram("t", lo=1.0, hi=100.0, growth=2.0)
    bounds, counts = h.bucket_bounds(), h.bucket_counts()
    assert len(bounds) == len(counts) == h.n_buckets
    assert bounds[0] == 1.0 and bounds[-1] == "+Inf"
    assert bounds[1:-1] == [2.0 ** i for i in range(1, h.n_buckets - 1)]
    h.record(0.5)    # underflow -> bucket 0
    h.record(5.0)
    h.record(1e9)    # overflow -> the +Inf bucket
    counts = h.bucket_counts()
    assert counts[0] == 1 and counts[-1] == 1
    assert sum(counts) == h.count == 3
    # summary exports the full range while the overflow bucket is hot
    sb = h.summary()["buckets"]
    assert sb["counts"] == counts
    assert len(sb["bounds"]) == len(sb["counts"])
    assert sb["bounds"][-1] == "+Inf"
    # ...and trims trailing empties when it is not
    h2 = Histogram("t2", lo=1.0, hi=100.0, growth=2.0)
    h2.record(1.5)
    sb2 = h2.summary()["buckets"]
    assert 0 < len(sb2["counts"]) < h2.n_buckets
    assert len(sb2["bounds"]) == len(sb2["counts"])
    assert sb2["counts"][-1] == 1 and sum(sb2["counts"]) == 1
    json.dumps(h.summary())  # "+Inf" keeps the dict JSON-serializable


def test_prometheus_exposition_format():
    r = MetricRegistry()
    r.counter("serve.submitted").inc(3)
    r.gauge("perf.mfu").set(0.25)
    r.gauge("empty.gauge")  # never set -> skipped
    h = r.histogram("serve.ttft_ms")
    for v in (1.0, 10.0, 100.0):
        h.record(v)
    text = r.to_prometheus()
    lines = text.splitlines()
    assert "# TYPE serve_submitted_total counter" in lines
    assert "serve_submitted_total 3" in lines
    assert "# TYPE perf_mfu gauge" in lines
    assert "perf_mfu 0.25" in lines
    assert not any("empty_gauge" in ln and not ln.startswith("#")
                   for ln in lines)
    # histogram: cumulative buckets ending at +Inf == count
    buckets = [ln for ln in lines
               if ln.startswith("serve_ttft_ms_bucket{")]
    assert buckets, text
    vals = [float(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert vals == sorted(vals)  # cumulative -> non-decreasing
    assert buckets[-1].startswith('serve_ttft_ms_bucket{le="+Inf"}')
    assert vals[-1] == 3.0
    assert "serve_ttft_ms_count 3" in lines
    assert "serve_ttft_ms_sum 111" in lines


# -- trace export: validity + deterministic ordering -----------------------


def _synthetic_recorder():
    rec = FlightRecorder()
    tracer = SpanTracer(rec)
    s = tracer.span("request", tick=0, id=3)
    s.event("queued", tick=0)
    s.event("admitted", tick=0, slot=0)
    rec.record("dispatch", tick=0, family="prefill[8]", ms=2.0, tokens=1)
    rec.record("dispatch", tick=1, family="decode[T=4]", ms=1.5,
               tokens=4)
    rec.record("tick", tick=1, ms=4.0, tokens=4)
    rec.record("retrace", tick=1, signature="f32[4]")
    s.end("completed", tick=1, generated=4)
    s2 = tracer.span("request", tick=1, id=4)  # never ends: open slice
    s2.event("queued", tick=1)
    return rec


def test_chrome_trace_layout_and_determinism(tmp_path):
    rec = _synthetic_recorder()
    doc = export_chrome_trace(rec, path=str(tmp_path / "trace.json"))
    # byte-identical re-export: ordering is fully deterministic
    doc2 = export_chrome_trace(rec)
    assert json.dumps(doc, sort_keys=True) == json.dumps(
        doc2, sort_keys=True
    )
    on_disk = json.loads((tmp_path / "trace.json").read_text())
    assert json.dumps(on_disk, sort_keys=True) == json.dumps(
        doc, sort_keys=True
    )

    evs = doc["traceEvents"]
    assert doc["otherData"]["t0_unix"] == pytest.approx(
        rec.t0_unix, abs=1e-3
    )
    for e in evs:
        assert {"name", "ph", "pid", "tid", "ts"} <= set(e)
        assert e["ph"] in ("M", "X", "i")
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float))
    # metadata leads, then strictly ts-ordered events
    n_meta = sum(1 for e in evs if e["ph"] == "M")
    assert all(e["ph"] == "M" for e in evs[:n_meta])
    rest = evs[n_meta:]
    assert all(rest[i]["ts"] <= rest[i + 1]["ts"]
               for i in range(len(rest) - 1))
    # request tracks: closed span carries its terminal status, open
    # span exports a zero-duration slice
    req = {e["name"]: e for e in rest
           if e["ph"] == "X" and e["name"].startswith("request ")}
    assert set(req) == {"request 3 [completed]", "request 4"}
    assert req["request 3 [completed]"]["pid"] == 1
    assert req["request 3 [completed]"]["dur"] > 0
    assert req["request 4"]["dur"] == 0.0
    # engine tracks: dispatch slices named by family, the tick slice,
    # and everything else as instants
    fams = {e["name"] for e in rest if e["ph"] == "X" and e["pid"] == 2
            and e["tid"] == 1}
    assert fams == {"prefill[8]", "decode[T=4]"}
    assert any(e["name"] == "tick 1" and e["ph"] == "X" and
               e["tid"] == 0 for e in rest)
    assert any(e["name"] == "retrace" and e["ph"] == "i" and
               e["tid"] == 2 for e in rest)
    # timestamps anchor to the unix epoch (microseconds)
    assert abs(rest[0]["ts"] / 1e6 - time.time()) < 3600


def test_chrome_trace_from_real_engine(lm):
    m, v, ids = lm
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=2)
    rids = [engine.submit(np.asarray(ids[0, :4]), max_new_tokens=5)
            for _ in range(2)]
    res = engine.run()
    assert all(res[r].status == "completed" for r in rids)
    doc = export_chrome_trace(engine.recorder)
    evs = doc["traceEvents"]
    req = [e for e in evs if e["ph"] == "X"
           and e["name"].startswith("request ")]
    assert len(req) == 2
    assert all("[completed]" in e["name"] for e in req)
    assert any(e["ph"] == "X" and e["name"].startswith("decode[T=")
               for e in evs)
    assert any(e["ph"] == "X" and e["name"].startswith("prefill[")
               for e in evs)


# -- serving integration: the contracts hold WITH analytics + SLO ----------


def test_analytics_keep_sync_and_compile_contracts(lm, monkeypatch):
    """THE acceptance bar: with cost analytics AND an SLO monitor
    enabled, one request decoding 16 tokens through T=8 blocks still
    pays at most one synced fetch per block, and the compile-count pins
    hold — the once-per-family lowering fires inside this window and
    must not sync or compile."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :4])
    engine = ServeEngine(
        m, v, slots=1, cache_len=32, decode_block=8,
        slo="ttft_p99_ms=60000,per_token_p99_ms=60000,error_rate=0.99",
    )
    rid = engine.submit(prompt, max_new_tokens=17)  # 1 prefill + 16 dec

    syncs = {"n": 0}
    real_device_get = jax.device_get
    real_asarray = np.asarray

    def counting_device_get(x, *a, **kw):
        syncs["n"] += 1
        return real_device_get(x, *a, **kw)

    def counting_asarray(x, *a, **kw):
        if isinstance(x, jax.Array):
            syncs["n"] += 1
        return real_asarray(x, *a, **kw)

    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        monkeypatch.setattr(jax, "device_get", counting_device_get)
        monkeypatch.setattr(np, "asarray", counting_asarray)
        res = engine.run()[rid]
        monkeypatch.undo()

    np.testing.assert_array_equal(
        np.asarray(res.tokens), _ref(m, v, prompt, 17)
    )
    assert syncs["n"] <= 2, f"host syncs: {syncs['n']} (> 1 per block)"

    d = engine.metrics.to_dict()
    fams = d["perf_families"]
    decode_fams = [f for f in fams if f.startswith("decode[T=")]
    assert decode_fams and any(f.startswith("prefill[") for f in fams)
    for f in fams.values():
        assert f["dispatches"] >= 1
    # the CPU backend's cost model answers, so MFU is a number here
    assert all(f["cost_source"] == "xla" for f in fams.values())
    assert isinstance(d["mfu"], float)
    assert isinstance(d["device_time_pct"], float)
    assert d["slo"]["declared"] is True and d["slo_burning"] == 0


def test_analytics_keep_contracts_sharded(lm, monkeypatch):
    """Same bar on the 2x2 (data, model) mesh: the sharded programs'
    cost analysis rides the existing sync points too."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :4])
    engine = ServeEngine(
        m, v, slots=2, cache_len=32, decode_block=4,
        mesh={"data": 2, "model": 2},
        slo="ttft_p99_ms=60000,error_rate=0.99",
    )
    rid = engine.submit(prompt, max_new_tokens=9)  # 1 prefill + 8 dec

    syncs = {"n": 0}
    real_device_get = jax.device_get
    real_asarray = np.asarray

    def counting_device_get(x, *a, **kw):
        syncs["n"] += 1
        return real_device_get(x, *a, **kw)

    def counting_asarray(x, *a, **kw):
        if isinstance(x, jax.Array):
            syncs["n"] += 1
        return real_asarray(x, *a, **kw)

    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        monkeypatch.setattr(jax, "device_get", counting_device_get)
        monkeypatch.setattr(np, "asarray", counting_asarray)
        res = engine.run()[rid]
        monkeypatch.undo()

    np.testing.assert_array_equal(
        np.asarray(res.tokens), _ref(m, v, prompt, 9)
    )
    assert syncs["n"] <= 2, f"host syncs: {syncs['n']} (> 1 per block)"
    fams = engine.metrics.to_dict()["perf_families"]
    assert any(f.startswith("decode[T=") for f in fams)
    assert all(f["cost_source"] == "xla" for f in fams.values())


def test_slo_shed_suppresses_admissions_but_completes(lm):
    """An impossible TTFT target trips shedding while a request is in
    flight (queue holds, nothing admitted) — but an idle engine always
    admits, so every request still completes."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    engine = ServeEngine(
        m, v, slots=1, cache_len=32, max_queue=8, decode_block=2,
        slo="ttft_p99_ms=0.000001,min_samples=1,window_s=600",
    )
    rids = [engine.submit(row[:4], max_new_tokens=8) for _ in range(3)]
    res = engine.run()
    assert all(res[r].status == "completed" for r in rids)
    d = engine.metrics.to_dict()
    assert d["slo_violations_total"] > 0
    assert d["slo_shed_ticks_total"] > 0
    assert d["slo"]["burning"] is True
    names = {e["name"] for e in engine.recorder.events()}
    assert "slo_violation" in names and "slo_shed" in names


def test_engine_rejects_bad_slo_spec(lm):
    m, v, _ = lm
    with pytest.raises(FriendlyError, match="unknown SLO key"):
        ServeEngine(m, v, slots=1, cache_len=32, slo="latency=5")
