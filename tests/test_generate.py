"""Autoregressive generation over the causal transformer family.

The reference has no generative model (SURVEY §5); generate() is part of
the long-context capability upgrade, so its tests are behavioral: a tiny
LM overfit on a periodic stream must CONTINUE the period, greedy decode
must be deterministic, and every attention configuration (window, GQA,
RoPE) must decode through the same utility.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.models import beam_search, build_model, generate

PERIOD = 4  # token stream cycles 1,2,3,4,1,2,...


def _train_lm(m, steps=60, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


@pytest.mark.parametrize("config", [
    {},                                            # plain learned-pos
    {"window": 6},                                 # sliding window
    {"pos_embedding": "rope", "kv_heads": 1},      # RoPE + MQA
])
def test_overfit_lm_continues_the_period(config):
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=32, **config)
    v, ids = _train_lm(m)
    prompt = ids[:, :8]
    out = np.asarray(generate(m, v, prompt, max_new_tokens=8))
    want = (np.arange(16) % PERIOD) + 1
    np.testing.assert_array_equal(out[0], want)


@pytest.mark.parametrize("config", [
    {},                                            # plain learned-pos
    {"window": 6},                                 # rolled window cache
    {"pos_embedding": "rope", "kv_heads": 1},      # RoPE + MQA
    {"window": 6, "kv_heads": 1},                  # rolled cache + GQA
])
def test_kv_cache_matches_recompute_oracle(config):
    """The cached decode (one-token steps against preallocated K/V
    buffers) must produce the same tokens as the O(T²) full-recompute
    path — per config, since window masking, GQA buffer geometry, and
    RoPE offset tables are each their own cached code path."""
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=32, **config)
    v, ids = _train_lm(m, steps=30)
    prompt = ids[:, :5]
    kv = np.asarray(generate(m, v, prompt, max_new_tokens=9))
    rc = np.asarray(generate(m, v, prompt, max_new_tokens=9,
                             kv_cache=False))
    np.testing.assert_array_equal(kv, rc)
    # sampling consumes the SAME rng stream on both paths
    skv = np.asarray(generate(m, v, prompt, max_new_tokens=9,
                              temperature=0.8, rng=jax.random.PRNGKey(7)))
    src = np.asarray(generate(m, v, prompt, max_new_tokens=9,
                              temperature=0.8, rng=jax.random.PRNGKey(7),
                              kv_cache=False))
    np.testing.assert_array_equal(skv, src)


def test_greedy_is_deterministic_and_sampling_needs_rng():
    m = build_model("transformer_lm", vocab_size=8, d_model=16, heads=2,
                    depth=1, max_len=24)
    v, ids = _train_lm(m, steps=5)
    prompt = ids[:, :4]
    a = np.asarray(generate(m, v, prompt, max_new_tokens=6))
    b = np.asarray(generate(m, v, prompt, max_new_tokens=6))
    np.testing.assert_array_equal(a, b)
    with pytest.raises(FriendlyError, match="rng"):
        generate(m, v, prompt, max_new_tokens=2, temperature=0.7)
    # sampling path runs and keeps the prompt intact
    s = np.asarray(generate(m, v, prompt, max_new_tokens=6,
                            temperature=0.7,
                            rng=jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(s[:, :4], np.asarray(prompt))


def test_generate_guards():
    m = build_model("transformer_lm", vocab_size=8, d_model=16, heads=2,
                    depth=1, max_len=8)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    prompt = jnp.zeros((1, 6), jnp.int32)
    with pytest.raises(FriendlyError, match="position table"):
        generate(m, v, prompt, max_new_tokens=4)  # 10 > max_len 8
    with pytest.raises(FriendlyError, match=">= 1"):
        generate(m, v, prompt, max_new_tokens=0)
    bidir = build_model("transformer_lm", vocab_size=8, d_model=16,
                        heads=2, depth=1, max_len=8, causal=False)
    bv = bidir.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(FriendlyError, match="causal"):
        generate(bidir, bv, prompt, max_new_tokens=1)


def test_rope_generates_past_trained_max_len():
    """RoPE has no position table: generation may run past max_len (the
    structural-extrapolation property, impossible with learned pos)."""
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=16, pos_embedding="rope")
    v, ids = _train_lm(m, seq=16)
    out = np.asarray(generate(m, v, ids, max_new_tokens=8))  # 24 > 16
    want = (np.arange(24) % PERIOD) + 1
    np.testing.assert_array_equal(out[0], want)


def test_eos_stops_rows_and_pads_the_tail():
    """eos_id: the trained model walks the period 1,2,3,4,...; stopping
    at eos_id=3 must keep tokens up to AND including the first 3, then
    pad — identically on the cache path and the recompute oracle."""
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=32)
    v, ids = _train_lm(m)
    prompt = ids[:, :8]  # ends ...3,4 → continuation 1,2,3,4,...
    kv = np.asarray(generate(m, v, prompt, max_new_tokens=8, eos_id=3))
    want = np.concatenate([
        np.asarray(prompt)[0], [1, 2, 3, 0, 0, 0, 0, 0],
    ])
    np.testing.assert_array_equal(kv[0], want)
    rc = np.asarray(generate(m, v, prompt, max_new_tokens=8, eos_id=3,
                             kv_cache=False))
    np.testing.assert_array_equal(kv, rc)
    # pad_id is honored for the tail fill
    pk = np.asarray(generate(m, v, prompt, max_new_tokens=8, eos_id=3,
                             pad_id=7))
    np.testing.assert_array_equal(
        pk[0], np.concatenate([np.asarray(prompt)[0],
                               [1, 2, 3, 7, 7, 7, 7, 7]])
    )


def test_rolled_window_cache_long_generation():
    """A sliding-window model generating far past both its window and
    its trained max_len: the decode carry holds O(window) K/V (the
    rolled circular buffers), RoPE extrapolates structurally, and the
    learned period must continue across many buffer wrap-arounds."""
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=16, window=8, pos_embedding="rope")
    v, ids = _train_lm(m, seq=16)
    out = np.asarray(generate(m, v, ids, max_new_tokens=32))  # 48 >> W=8
    want = (np.arange(48) % PERIOD) + 1
    np.testing.assert_array_equal(out[0], want)


def test_top_k_and_top_p_sampling():
    """top_k=1 collapses sampling to greedy; a tight nucleus on a
    peaked (trained) model does too; loose filters reproduce the
    unfiltered stream rng-for-rng; guards reject meaningless configs."""
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=32)
    v, ids = _train_lm(m)
    prompt = ids[:, :8]
    greedy = np.asarray(generate(m, v, prompt, max_new_tokens=8))
    k1 = np.asarray(generate(m, v, prompt, max_new_tokens=8,
                             temperature=1.0, top_k=1,
                             rng=jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(k1, greedy)
    # the overfit model is sharply peaked: a 0.5 nucleus holds only the
    # top token, so nucleus sampling = greedy here
    p_small = np.asarray(generate(m, v, prompt, max_new_tokens=8,
                                  temperature=1.0, top_p=0.5,
                                  rng=jax.random.PRNGKey(1)))
    np.testing.assert_array_equal(p_small, greedy)
    # loose filters change nothing about the sampled stream
    base = np.asarray(generate(m, v, prompt, max_new_tokens=8,
                               temperature=1.3,
                               rng=jax.random.PRNGKey(2)))
    loose = np.asarray(generate(m, v, prompt, max_new_tokens=8,
                                temperature=1.3, top_k=8, top_p=1.0,
                                rng=jax.random.PRNGKey(2)))
    np.testing.assert_array_equal(base, loose)
    with pytest.raises(FriendlyError, match="temperature"):
        generate(m, v, prompt, max_new_tokens=2, top_k=2)
    with pytest.raises(FriendlyError, match="top_k"):
        generate(m, v, prompt, max_new_tokens=2, temperature=1.0,
                 top_k=9, rng=jax.random.PRNGKey(0))
    with pytest.raises(FriendlyError, match="top_p"):
        generate(m, v, prompt, max_new_tokens=2, temperature=1.0,
                 top_p=1.5, rng=jax.random.PRNGKey(0))


def test_generate_rejects_moe_recompute_and_negative_temperature():
    m = build_model("transformer_lm", vocab_size=8, d_model=16, heads=2,
                    depth=1, max_len=16)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(FriendlyError, match="temperature"):
        generate(m, v, jnp.zeros((1, 4), jnp.int32), max_new_tokens=2,
                 temperature=-0.5, rng=jax.random.PRNGKey(0))
    # MoE decodes on the kv-cache path (r5); only the pad-filled
    # recompute buffer stays rejected (capacity routing over pads is
    # not causal). Full MoE generation semantics: tests/test_moe.py.
    moe = build_model("transformer_lm_moe", vocab_size=8, d_model=16,
                      heads=2, depth=1, max_len=16, n_experts=2)
    mv = moe.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    out = generate(moe, mv, jnp.zeros((1, 4), jnp.int32), max_new_tokens=2)
    assert out.shape == (1, 6)
    with pytest.raises(FriendlyError, match="kv_cache"):
        generate(moe, mv, jnp.zeros((1, 4), jnp.int32), max_new_tokens=2,
                 kv_cache=False)


# -- beam search ------------------------------------------------------------


def test_beam_one_equals_greedy():
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=32, window=6)
    v, ids = _train_lm(m, steps=30)
    prompt = ids[:, :5]
    greedy = np.asarray(generate(m, v, prompt, max_new_tokens=9))
    beam1 = np.asarray(beam_search(m, v, prompt, max_new_tokens=9,
                                   beams=1))
    np.testing.assert_array_equal(beam1, greedy)


def test_beam_full_width_is_exhaustive_at_two_steps():
    """With K = V beams and N = 2 steps, beam search IS exhaustive: step
    1 keeps every first token, step 2 scores all V² continuations. The
    best beam must therefore equal the brute-force argmax of the
    teacher-forced log-prob sum over all V² sequences — on an untrained
    model whose greedy path has no reason to be globally optimal."""
    V = 6
    m = build_model("transformer_lm", vocab_size=V, d_model=16, heads=2,
                    depth=1, max_len=12)
    v = m.init(jax.random.PRNGKey(4), jnp.zeros((1, 4), jnp.int32))
    prompt = jnp.asarray([[1, 2, 3, 4], [5, 0, 1, 2]], jnp.int32)
    b, p = prompt.shape
    got = np.asarray(beam_search(m, v, prompt, max_new_tokens=2, beams=V))

    # brute force: score every (t1, t2) continuation teacher-forced
    cands = np.stack(np.meshgrid(np.arange(V), np.arange(V),
                                 indexing="ij"), -1).reshape(-1, 2)
    best = np.zeros((b, 2), np.int32)
    for row in range(b):
        seqs = np.concatenate(
            [np.tile(np.asarray(prompt[row])[None], (V * V, 1)), cands],
            axis=1,
        )
        lg = np.asarray(m.apply(v, jnp.asarray(seqs)), np.float32)
        lp = jax.nn.log_softmax(jnp.asarray(lg), axis=-1)
        lp = np.asarray(lp)
        scores = (
            lp[np.arange(V * V), p - 1, cands[:, 0]]
            + lp[np.arange(V * V), p, cands[:, 1]]
        )
        best[row] = cands[scores.argmax()]
    np.testing.assert_array_equal(got[:, p:], best)


def test_beam_eos_and_return_all():
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=32)
    v, ids = _train_lm(m)
    prompt = ids[:, :8]
    out = np.asarray(beam_search(m, v, prompt, max_new_tokens=8,
                                 beams=3, eos_id=3))
    want = np.concatenate([np.asarray(prompt)[0],
                           [1, 2, 3, 0, 0, 0, 0, 0]])
    np.testing.assert_array_equal(out[0], want)
    seqs, scores = beam_search(m, v, prompt, max_new_tokens=4, beams=3,
                               return_all=True)
    assert seqs.shape == (1, 3, 12) and scores.shape == (1, 3)
    s = np.asarray(scores)
    assert np.all(s[:, :-1] >= s[:, 1:])  # sorted best-first
    np.testing.assert_array_equal(np.asarray(seqs)[0, 0, :8],
                                  np.asarray(prompt)[0])


def test_beam_guards_and_moe():
    m = build_model("transformer_lm", vocab_size=8, d_model=16, heads=2,
                    depth=1, max_len=16)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(FriendlyError, match="beams"):
        beam_search(m, v, prompt, max_new_tokens=2, beams=0)
    with pytest.raises(FriendlyError, match="vocab"):
        beam_search(m, v, prompt, max_new_tokens=2, beams=9)
    moe = build_model("transformer_lm_moe", vocab_size=8, d_model=16,
                      heads=2, depth=1, max_len=16, n_experts=2)
    mv = moe.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    out = beam_search(moe, mv, prompt, max_new_tokens=3, beams=2)
    assert out.shape == (1, 7)


def test_init_cache_friendly_errors():
    """cache_geometry raises the typed error — never a bare KeyError —
    when a graph lacks heads metadata or a cache-accepting block's
    variables lack the fused qkv kernel (the decode-API fuzz contract)."""
    from mmlspark_tpu.models.generate import init_cache

    m = build_model("transformer_lm", vocab_size=8, d_model=16, heads=2,
                    depth=1, max_len=8)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    init_cache(m, v, 1, 8)  # healthy baseline

    del m.extra["heads"]  # build_model returns a fresh graph per call
    with pytest.raises(FriendlyError, match="heads"):
        init_cache(m, v, 1, 8)

    m2 = build_model("transformer_lm", vocab_size=8, d_model=16, heads=2,
                     depth=1, max_len=8)
    v2 = dict(v)
    block = next(name for name, _ in m2.blocks
                 if "attn" in v2.get(name, {}).get("params", {}))
    v2[block] = {"params": {}}  # strip the attn/qkv path
    with pytest.raises(FriendlyError, match="qkv"):
        init_cache(m2, v2, 1, 8)
