"""Fault-tolerant training drills (ISSUE 14 tentpole).

The contract under test (docs/TRAINING.md "Failure semantics"): the
seeded fault harness (``core/faults.py``) drives the trainer's four
``train.*`` hook sites and the trainer answers with — bit-exact resume
from the atomically committed checkpoint after a ``kill`` (single
device AND 2x2 data x model mesh; a torn checkpoint write keeps the
previous checkpoint restorable); in-graph grad-anomaly QUARANTINE (a
NaN batch skips the update without advancing params or the optimizer
step count, is counted, and N consecutive bad steps abort with a
flight-recorder dump); capped deterministic retry for transients that
is invisible to the final params; graceful DEGRADATION down the
power-of-two gradient-accumulation ladder on RESOURCE_EXHAUSTED; and
elastic resume at a reduced data-parallel width. The train ->
checkpoint -> ServeEngine round-trip closes the loop: a checkpoint
written by the trainer serves bit-identically to ``generate()`` under
the serving compile pins.
"""

from __future__ import annotations

import json
import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import (
    EngineKilled,
    Fault,
    FaultInjector,
    parse_fault_spec,
)
from mmlspark_tpu.core.integrity import CheckpointCorruption, flip_bit_in_dir
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.train.resilience import (
    AtomicCheckpointStore,
    next_accum_rung,
)
from mmlspark_tpu.train.trainer import (
    SPMDTrainer,
    TrainConfig,
    _make_optimizer,
    _merge_variables,
    _split_variables,
)


def _two_blob_data(n=96, d=8, seed=0):
    rng = np.random.default_rng(seed)
    half = n // 2
    x = np.concatenate(
        [rng.normal(-1.5, 1.0, (half, d)), rng.normal(1.5, 1.0, (half, d))]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(half), np.ones(half)]).astype(np.int32)
    perm = rng.permutation(n)
    return x[perm], y[perm]


def _cfg(**kw):
    base = dict(epochs=2, batch_size=32, learning_rate=1e-2,
                shuffle=False, log_every=1, retry_backoff_s=0.0)
    base.update(kw)
    return TrainConfig(**base)


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- hook-site registration (satellite: unknown sites error usefully) ------


def test_unknown_site_error_lists_train_sites():
    with pytest.raises(FriendlyError, match=r"train\.step"):
        Fault("train.bogus", "kill")
    with pytest.raises(FriendlyError, match=r"train\.checkpoint"):
        FaultInjector(site_rates={"train.bogus": {"kill": 1.0}})


def test_parse_fault_spec_accepts_and_lists_train_sites():
    inj = parse_fault_spec(
        "seed=3,train.step:transient=0.5,train.data:poison=0.25,"
        "train.checkpoint:kill=0.1,train.restore:transient=0.1"
    )
    assert set(inj.site_rates) == {
        "train.step", "train.data", "train.checkpoint", "train.restore",
    }
    with pytest.raises(FriendlyError, match=r"train\.restore"):
        parse_fault_spec("train.bogus:kill=1.0")


def test_next_accum_rung_power_of_two_ladder():
    assert next_accum_rung(1, batch=32, n_data=8) == 2
    assert next_accum_rung(2, batch=32, n_data=8) == 4
    assert next_accum_rung(4, batch=32, n_data=8) is None  # 1 row/shard
    assert next_accum_rung(1, batch=8, n_data=8) is None


# -- disabled / inert hooks change nothing ---------------------------------


def test_inert_injector_is_bit_identical_to_disabled():
    """An injector that never fires must not perturb training: the
    quarantine is in-graph either way, and the hooks are pure host
    checks — params and history come out bit-identical."""
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    t_off = SPMDTrainer(g, _cfg())
    v_off = t_off.train(x, y)
    t_on = SPMDTrainer(g, _cfg(), faults=FaultInjector([]))
    v_on = t_on.train(x, y)
    _assert_trees_equal(v_off, v_on)
    assert [h["loss"] for h in t_off.history] == \
        [h["loss"] for h in t_on.history]


# -- kill -> bit-exact resume ----------------------------------------------


@pytest.mark.parametrize(
    "mesh_axes", [None, {"data": 2, "model": 2}],
    ids=["default-mesh", "2x2-data-model"],
)
def test_kill_and_resume_bit_exact(tmp_path, mesh_axes):
    """The headline drill: crash at step 3 of 6, resume, and the final
    params AND the stitched loss curve are bit-identical to a run that
    never crashed."""
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    t_full = SPMDTrainer(g, _cfg(mesh_axes=mesh_axes))
    v_full = t_full.train(x, y)

    ck = dict(checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1,
              mesh_axes=mesh_axes)
    crashed = SPMDTrainer(
        g, _cfg(**ck),
        faults=FaultInjector([Fault("train.step", "kill", tick=3)]),
    )
    with pytest.raises(EngineKilled):
        crashed.train(x, y)
    assert [h["step"] for h in crashed.history] == [0, 1, 2]

    resumed = SPMDTrainer(g, _cfg(**ck))
    v_res = resumed.train(x, y)
    assert [h["step"] for h in resumed.restored_history] == [0, 1, 2]
    assert [h["step"] for h in resumed.history] == [3, 4, 5]
    full_curve = [h["loss"] for h in t_full.history]
    stitched = [h["loss"] for h in
                resumed.restored_history + resumed.history]
    np.testing.assert_array_equal(full_curve, stitched)
    _assert_trees_equal(v_full, v_res)


def test_torn_checkpoint_keeps_previous_restorable(tmp_path):
    """A crash INSIDE the checkpoint write (between payload and
    manifest commit) must leave the previous checkpoint as latest; the
    resumed run is still bit-identical to an uninterrupted one."""
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    v_full = SPMDTrainer(g, _cfg()).train(x, y)

    ck = dict(checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1)
    crashed = SPMDTrainer(
        g, _cfg(**ck),
        faults=FaultInjector([Fault("train.checkpoint", "kill", tick=2)]),
    )
    with pytest.raises(EngineKilled):
        crashed.train(x, y)
    store = AtomicCheckpointStore(str(tmp_path / "ck"))
    assert store.steps() == [0, 1]  # step 2's write is torn debris
    assert store.latest_step() == 1
    assert not (tmp_path / "ck" / "step-2.json").exists()

    resumed = SPMDTrainer(g, _cfg(**ck))
    v_res = resumed.train(x, y)
    assert resumed.history[0]["step"] == 2  # replays exactly one step
    _assert_trees_equal(v_full, v_res)


def _crash_with_checkpoints(tmp_path, g, x, y):
    """Crash at step 3 with checkpoint_every=1: steps 0..2 committed."""
    ck = dict(checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1)
    crashed = SPMDTrainer(
        g, _cfg(**ck),
        faults=FaultInjector([Fault("train.step", "kill", tick=3)]),
    )
    with pytest.raises(EngineKilled):
        crashed.train(x, y)
    return ck


def test_bit_flipped_checkpoint_raises_typed_error_and_quarantines(tmp_path):
    """Silent-corruption drill (ISSUE 18 satellite): one flipped bit
    in the latest payload makes ``restore()`` raise the typed error
    naming BOTH hashes before orbax reads anything; the manifest is
    quarantined (renamed ``.corrupt``) so the previous checkpoint
    becomes latest."""
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    ck = _crash_with_checkpoints(tmp_path, g, x, y)
    ckdir = tmp_path / "ck"
    store = AtomicCheckpointStore(str(ckdir))
    assert store.latest_step() == 2
    manifest = json.loads((ckdir / "step-2.json").read_text())
    flip_bit_in_dir(str(ckdir / "payload-2"), 5)

    cfg = _cfg(**ck)
    p0, r0 = _split_variables(
        jax.device_get(g.init(jax.random.PRNGKey(cfg.seed),
                              jnp.asarray(x[:1]))))
    tx = _make_optimizer(cfg, 6)
    target = {
        "params": p0, "rest": r0,
        "opt_state": jax.device_get(tx.init(p0)),
        "anomaly": {"streak": np.zeros((), np.int32),
                    "total": np.zeros((), np.int32)},
    }
    with pytest.raises(CheckpointCorruption) as exc:
        store.restore(target)
    assert exc.value.step == 2
    assert exc.value.expected == manifest["payload_sha256"]
    assert exc.value.actual != exc.value.expected
    assert exc.value.expected in str(exc.value)
    assert exc.value.actual in str(exc.value)
    # the corrupt step is quarantined, not deleted: the manifest moves
    # aside for the post-mortem and the store's view drops to step 1
    assert (ckdir / "step-2.json.corrupt").exists()
    assert not (ckdir / "step-2.json").exists()
    assert AtomicCheckpointStore(str(ckdir)).latest_step() == 1


def test_bit_flipped_checkpoint_resume_falls_back_bit_exact(tmp_path):
    """The trainer-level recovery: a resume that hits the corrupted
    checkpoint counts the failure, records the event with both hashes,
    retries onto the PREVIOUS committed checkpoint, and finishes
    bit-identical to a run that never crashed."""
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    v_full = SPMDTrainer(g, _cfg()).train(x, y)
    ck = _crash_with_checkpoints(tmp_path, g, x, y)
    ckdir = tmp_path / "ck"
    manifest = json.loads((ckdir / "step-2.json").read_text())
    flip_bit_in_dir(str(ckdir / "payload-2"), 9)

    resumed = SPMDTrainer(g, _cfg(**ck))
    v_res = resumed.train(x, y)
    fails = resumed.telemetry.counter("train.integrity.checksum_failures")
    assert fails.value == 1
    ev = [e for e in resumed.recorder.events()
          if e["name"] == "integrity.checksum_failure"]
    assert len(ev) == 1
    assert ev[0]["attrs"]["expected"] == manifest["payload_sha256"]
    assert ev[0]["attrs"]["actual"] != ev[0]["attrs"]["expected"]
    # fell back to step 1 and replayed 2..5 — bit-identical finish
    assert [h["step"] for h in resumed.restored_history] == [0, 1]
    assert resumed.history[0]["step"] == 2
    _assert_trees_equal(v_full, v_res)


# -- grad-anomaly quarantine -----------------------------------------------


def test_anomaly_skips_update_without_advancing(tmp_path):
    """One poisoned batch in a one-step run: params, rest, AND the
    optimizer's own step count must come back exactly at their initial
    values — the update was skipped, not applied-and-reverted-late —
    and the skip is counted once."""
    x, y = _two_blob_data(n=32)
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    cfg = _cfg(epochs=1, checkpoint_dir=str(tmp_path / "ck"),
               checkpoint_every=1)
    # host copy: the trainer donates the device buffers it is handed
    init = jax.device_get(g.init(jax.random.PRNGKey(cfg.seed),
                                 jnp.asarray(x[:1])))
    tr = SPMDTrainer(
        g, cfg, faults=FaultInjector([Fault("train.data", "poison",
                                            tick=0)]),
    )
    trained = tr.train(x, y, init_variables=init)
    _assert_trees_equal(init, trained)
    assert tr.telemetry.counter("train.anomalies_skipped").value == 1
    assert [h["step"] for h in tr.history] == [0]  # not double-advanced
    assert not np.isfinite(tr.history[0]["loss"])
    assert any(e["name"] == "anomaly" for e in tr.recorder.events())

    # the checkpoint carries the proof: the optimizer step count (the
    # only integer leaf in adam's state) is still 0, and the anomaly
    # carries persisted as (streak=1, total=1)
    p0, r0 = _split_variables(jax.device_get(init))
    tx = _make_optimizer(cfg, 1)
    target = {
        "params": p0, "rest": r0,
        "opt_state": jax.device_get(tx.init(p0)),
        "anomaly": {"streak": np.zeros((), np.int32),
                    "total": np.zeros((), np.int32)},
    }
    state, _, step = AtomicCheckpointStore(str(tmp_path / "ck")).restore(
        target
    )
    assert step == 0
    int_leaves = [
        leaf for leaf in jax.tree_util.tree_leaves(state["opt_state"])
        if np.issubdtype(np.asarray(leaf).dtype, np.integer)
    ]
    assert int_leaves and all(int(leaf) == 0 for leaf in int_leaves)
    assert int(state["anomaly"]["streak"]) == 1
    assert int(state["anomaly"]["total"]) == 1


def test_anomaly_streak_aborts_with_recorder_dump(caplog):
    """N consecutive quarantined steps must abort with a FriendlyError
    AND dump the flight recorder (the black-box contract)."""
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    tr = SPMDTrainer(
        g, _cfg(epochs=1, anomaly_limit=3),
        faults=FaultInjector([Fault("train.data", "poison", times=10)]),
    )
    with caplog.at_level(logging.ERROR, logger="mmlspark_tpu.core.telemetry"):
        with pytest.raises(FriendlyError, match="consecutive anomalous"):
            tr.train(x, y)
    assert "flight recorder dump" in caplog.text
    anomalies = [e for e in tr.recorder.events() if e["name"] == "anomaly"]
    assert len(anomalies) == 3
    assert anomalies[-1]["attrs"]["streak"] == 3
    assert tr.telemetry.counter("train.anomalies_skipped").value == 3


def test_grad_norm_explosion_quarantined():
    """max_grad_norm turns a finite-but-exploding step into an anomaly:
    with a sub-noise threshold every step is quarantined, so params
    never move from init."""
    x, y = _two_blob_data(n=32)
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    cfg = _cfg(epochs=1, max_grad_norm=1e-9, anomaly_limit=0)
    init = jax.device_get(g.init(jax.random.PRNGKey(cfg.seed),
                                 jnp.asarray(x[:1])))
    tr = SPMDTrainer(g, cfg, faults=None)  # quarantine is always in-graph
    trained = tr.train(x, y, init_variables=init)
    _assert_trees_equal(init, trained)
    assert tr.telemetry.counter("train.anomalies_skipped").value == 1


# -- transient retry / stall -----------------------------------------------


def test_transient_retries_are_invisible_to_results():
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    v_clean = SPMDTrainer(g, _cfg()).train(x, y)
    inj = FaultInjector(
        [Fault("train.step", "transient", times=2),
         Fault("train.data", "transient", times=1),
         Fault("train.step", "stall", times=1)],
        stall_s=0.001,
    )
    tr = SPMDTrainer(g, _cfg(), faults=inj)
    v_faulted = tr.train(x, y)
    _assert_trees_equal(v_clean, v_faulted)
    assert tr.telemetry.counter("train.retries_total").value == 3
    assert inj.counts.get("stall") == 1
    retries = [e for e in tr.recorder.events() if e["name"] == "retry"]
    assert len(retries) == 3


def test_transient_beyond_retry_limit_escapes():
    from mmlspark_tpu.core.faults import TransientFault

    x, y = _two_blob_data(n=32)
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    tr = SPMDTrainer(
        g, _cfg(epochs=1, retry_limit=2),
        faults=FaultInjector([Fault("train.step", "transient", times=5)]),
    )
    with pytest.raises(TransientFault):
        tr.train(x, y)
    assert tr.telemetry.counter("train.retries_total").value == 2


# -- RESOURCE_EXHAUSTED -> accumulation ladder -----------------------------


def test_oom_degrades_down_accumulation_ladder():
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    tr = SPMDTrainer(
        g, _cfg(),
        faults=FaultInjector([Fault("train.step", "oom", tick=1)]),
    )
    tr.train(x, y)
    assert tr.telemetry.gauge("train.grad_accum").value == 2
    degraded = [e for e in tr.recorder.events() if e["name"] == "degraded"]
    assert degraded and degraded[0]["attrs"]["grad_accum"] == 2
    assert [h["step"] for h in tr.history] == [0, 1, 2, 3, 4, 5]
    assert all(np.isfinite(h["loss"]) for h in tr.history)


def test_oom_with_ladder_exhausted_aborts():
    x, y = _two_blob_data(n=16)
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    tr = SPMDTrainer(
        g, _cfg(epochs=1, batch_size=8),  # 1 row per data shard already
        faults=FaultInjector([Fault("train.step", "oom", tick=0)]),
    )
    with pytest.raises(FriendlyError, match="ladder"):
        tr.train(x, y)


# -- elastic resume at reduced data-parallel width -------------------------


def test_elastic_resume_at_reduced_data_width(tmp_path):
    """Crash at data=4, resume at data=2: the deterministic data order
    (same global batch, same shuffle seed) lets the narrower mesh pick
    up at the exact step the checkpoint committed."""
    x, y = _two_blob_data()
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    ck = dict(checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1)
    crashed = SPMDTrainer(
        g, _cfg(mesh_axes={"data": 4}, **ck),
        faults=FaultInjector([Fault("train.step", "kill", tick=3)]),
    )
    with pytest.raises(EngineKilled):
        crashed.train(x, y)

    resumed = SPMDTrainer(g, _cfg(mesh_axes={"data": 2}, **ck))
    v = resumed.train(x, y)
    assert [h["step"] for h in resumed.history] == [3, 4, 5]
    assert all(np.isfinite(h["loss"]) for h in resumed.history)
    for leaf in jax.tree_util.tree_leaves(v):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_elastic_resume_rejects_incompatible_geometry(tmp_path):
    """A resume whose batch rounding changes steps_per_epoch would
    silently replay or skip data — it must be refused instead."""
    x, y = _two_blob_data(n=96)
    g = build_model("mlp", num_outputs=2, hidden=(8,))
    ck = dict(checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1)
    SPMDTrainer(g, _cfg(epochs=1, batch_size=32, **ck)).train(x, y)
    bad = SPMDTrainer(g, _cfg(epochs=2, batch_size=48, **ck))
    with pytest.raises(FriendlyError, match="steps_per_epoch"):
        bad.train(x, y)


# -- train -> checkpoint -> serve round-trip -------------------------------


def test_train_checkpoint_serve_roundtrip(tmp_path):
    """A checkpoint written by the trainer, restored through the store's
    own recipe in a 'fresh process' (init-derived target), must serve
    bit-identically to ``generate()`` over the trained variables —
    under the serving compile pins."""
    from mmlspark_tpu.serve import ServeEngine
    from mmlspark_tpu.testing.compile_guard import serve_compile_guard

    graph = build_model("transformer_lm", vocab_size=8, d_model=32,
                        heads=2, depth=2, max_len=32)
    ids = np.repeat(((np.arange(16)[None, :] % 4) + 1), 8, axis=0)
    ids = ids.astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    cfg = _cfg(epochs=2, batch_size=4, learning_rate=5e-2, log_every=100,
               mesh_axes={"data": 2},
               checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=0)
    trainer = SPMDTrainer(graph, cfg)
    trained = trainer.train(ids, labels)

    # resume target rebuilt from scratch — nothing reused from the
    # trainer object, exactly what a respawned process would hold
    init = graph.init(jax.random.PRNGKey(cfg.seed), jnp.asarray(ids[:1]))
    p0, r0 = _split_variables(jax.device_get(init))
    total_steps = 4  # 8 rows / batch 4 x 2 epochs
    tx = _make_optimizer(cfg, total_steps)
    target = {
        "params": p0, "rest": r0,
        "opt_state": jax.device_get(tx.init(p0)),
        "anomaly": {"streak": np.zeros((), np.int32),
                    "total": np.zeros((), np.int32)},
    }
    store = AtomicCheckpointStore(str(tmp_path / "ck"))
    state, meta, step = store.restore(target)
    assert step == total_steps - 1
    assert int(meta["steps_per_epoch"]) == 2
    _assert_trees_equal(
        state["params"], _split_variables(jax.device_get(trained))[0]
    )

    variables = _merge_variables(state["params"], state["rest"])
    prompt = ids[0, :4]
    ref = np.asarray(
        generate(graph, trained, prompt[None], 8)
    )[0]
    engine = ServeEngine(graph, variables, slots=2, cache_len=32,
                         decode_block=4)
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        rid = engine.submit(prompt, max_new_tokens=8)
        res = engine.run()[rid]
    assert res.status == "completed"
    np.testing.assert_array_equal(np.asarray(res.tokens), ref)
