"""ONNX import: wire-format decode, op conversion, node-name surgery.

The test encodes real ONNX protobuf bytes with a minimal writer (the
mirror of the importer's wire decoder), so the round-trip exercises the
actual serialized format — no onnx package needed, matching the importer's
zero-dependency design.
"""

import struct

import numpy as np
import pytest

import jax.numpy as jnp

from mmlspark_tpu.models.onnx_import import load_onnx


# -- minimal protobuf writer -------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field(num: int, wt: int, payload: bytes) -> bytes:
    return _varint(num << 3 | wt) + payload


def _msg(num: int, body: bytes) -> bytes:
    return _field(num, 2, _varint(len(body)) + body)


def _s(num: int, s: str) -> bytes:
    b = s.encode()
    return _field(num, 2, _varint(len(b)) + b)


def _i(num: int, v: int) -> bytes:
    return _field(num, 0, _varint(v & (1 << 64) - 1))


def _f(num: int, v: float) -> bytes:
    return _field(num, 5, struct.pack("<f", v))


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dt = {np.dtype("float32"): 1, np.dtype("int64"): 7,
          np.dtype("int32"): 6}[arr.dtype]
    body = b"".join(_i(1, d) for d in arr.shape)
    body += _i(2, dt) + _s(8, name)
    body += _field(9, 2, _varint(arr.nbytes) + arr.tobytes())
    return body


def attr(name: str, *, i=None, f=None, ints=None, t=None) -> bytes:
    body = _s(1, name)
    if i is not None:
        body += _i(3, i)
    if f is not None:
        body += _f(2, f)
    if ints is not None:
        body += b"".join(_i(8, v) for v in ints)
    if t is not None:
        body += _msg(5, t)
    return body


def node(op: str, inputs, outputs, name="", attrs=()) -> bytes:
    body = b"".join(_s(1, x) for x in inputs)
    body += b"".join(_s(2, x) for x in outputs)
    body += _s(3, name) + _s(4, op)
    body += b"".join(_msg(5, a) for a in attrs)
    return body


def value_info(name: str, shape) -> bytes:
    dims = b"".join(_msg(1, _i(1, d)) for d in shape)
    tensor_type = _i(1, 1) + _msg(2, dims)
    return _s(1, name) + _msg(2, _msg(1, tensor_type))


def model_proto(nodes, initializers, inputs, outputs,
                gname="test") -> bytes:
    g = b"".join(_msg(1, n) for n in nodes)
    g += _s(2, gname)
    g += b"".join(_msg(5, t) for t in initializers)
    g += b"".join(_msg(11, v) for v in inputs)
    g += b"".join(_msg(12, v) for v in outputs)
    return _i(1, 8) + _msg(7, g)  # ir_version + graph


# -- fixtures ----------------------------------------------------------------

@pytest.fixture
def mlp_onnx(rng):
    """x(2,4) -> Gemm(w1 4x8,b1) -> Relu -> Gemm(w2 8x3,b2): weights + bytes."""
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    data = model_proto(
        nodes=[
            node("Gemm", ["x", "w1", "b1"], ["h"], name="fc1"),
            node("Relu", ["h"], ["hr"], name="relu1"),
            node("Gemm", ["hr", "w2", "b2"], ["z"], name="z"),
        ],
        initializers=[
            tensor_proto("w1", w1), tensor_proto("b1", b1),
            tensor_proto("w2", w2), tensor_proto("b2", b2),
        ],
        inputs=[value_info("x", (2, 4))],
        outputs=[value_info("z", (2, 3))],
    )
    return data, (w1, b1, w2, b2)


def test_mlp_roundtrip(mlp_onnx, rng):
    data, (w1, b1, w2, b2) = mlp_onnx
    graph = load_onnx(data)
    assert graph.layer_names == ["fc1", "relu1", "z"]
    assert graph.input_shape == (4,)
    x = rng.normal(size=(2, 4)).astype(np.float32)
    out = graph.apply(graph.init(), jnp.asarray(x))
    expect = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(np.asarray(out), expect, atol=1e-5, rtol=1e-5)


def test_cut_at_node(mlp_onnx, rng):
    data, (w1, b1, *_) = mlp_onnx
    graph = load_onnx(data)
    x = rng.normal(size=(2, 4)).astype(np.float32)
    # stop mid-graph by name (AsComposite equivalent)
    hidden = graph.apply(graph.init(), jnp.asarray(x), output_node="relu1")
    np.testing.assert_allclose(
        np.asarray(hidden), np.maximum(x @ w1 + b1, 0), atol=1e-5, rtol=1e-5
    )
    # and as a truncated graph
    head = graph.cut("fc1")
    assert head.layer_names == ["fc1"]
    np.testing.assert_allclose(
        np.asarray(head.apply(head.init(), jnp.asarray(x))),
        x @ w1 + b1, atol=1e-5, rtol=1e-5,
    )


def test_conv_bn_pool_net(rng):
    """NCHW conv -> BatchNorm -> Relu -> MaxPool -> Flatten -> Gemm."""
    w = rng.normal(size=(3, 1, 3, 3)).astype(np.float32) * 0.5
    scale = np.abs(rng.normal(size=(3,))).astype(np.float32)
    bias = rng.normal(size=(3,)).astype(np.float32)
    mean = rng.normal(size=(3,)).astype(np.float32) * 0.1
    var = np.abs(rng.normal(size=(3,))).astype(np.float32) + 0.5
    fc = rng.normal(size=(3 * 4 * 4, 5)).astype(np.float32)
    data = model_proto(
        nodes=[
            node("Conv", ["x", "w"], ["c"], name="conv1",
                 attrs=[attr("pads", ints=[1, 1, 1, 1]),
                        attr("strides", ints=[1, 1]),
                        attr("kernel_shape", ints=[3, 3])]),
            node("BatchNormalization",
                 ["c", "scale", "bias", "mean", "var"], ["bn"],
                 name="bn1", attrs=[attr("epsilon", f=1e-5)]),
            node("Relu", ["bn"], ["r"], name="relu1"),
            node("MaxPool", ["r"], ["p"], name="pool1",
                 attrs=[attr("kernel_shape", ints=[2, 2]),
                        attr("strides", ints=[2, 2])]),
            node("Flatten", ["p"], ["flat"], name="flat"),
            node("Gemm", ["flat", "fc"], ["z"], name="z"),
        ],
        initializers=[
            tensor_proto("w", w), tensor_proto("scale", scale),
            tensor_proto("bias", bias), tensor_proto("mean", mean),
            tensor_proto("var", var), tensor_proto("fc", fc),
        ],
        inputs=[value_info("x", (1, 1, 8, 8))],
        outputs=[value_info("z", (1, 5))],
    )
    graph = load_onnx(data)
    x = rng.normal(size=(1, 1, 8, 8)).astype(np.float32)
    out = np.asarray(graph.apply(graph.init(), jnp.asarray(x)))

    # numpy reference
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    c = np.zeros((1, 3, 8, 8), np.float32)
    for o in range(3):
        for i_ in range(1):
            for yy in range(8):
                for xx in range(8):
                    c[0, o, yy, xx] += np.sum(
                        xp[0, i_, yy:yy + 3, xx:xx + 3] * w[o, i_]
                    )
    bn = (c - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1) + 1e-5
    ) * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
    r = np.maximum(bn, 0)
    p = r.reshape(1, 3, 4, 2, 4, 2).max(axis=(3, 5))
    expect = p.reshape(1, -1) @ fc
    np.testing.assert_allclose(out, expect, atol=1e-3, rtol=1e-3)


def test_reshape_constant_and_softmax(rng):
    shape_c = np.array([2, 6], np.int64)
    data = model_proto(
        nodes=[
            node("Reshape", ["x", "shape"], ["r"], name="reshape"),
            node("Softmax", ["r"], ["z"], name="z",
                 attrs=[attr("axis", i=-1)]),
        ],
        initializers=[tensor_proto("shape", shape_c)],
        inputs=[value_info("x", (2, 2, 3))],
        outputs=[value_info("z", (2, 6))],
    )
    graph = load_onnx(data)
    x = rng.normal(size=(2, 2, 3)).astype(np.float32)
    out = np.asarray(graph.apply(graph.init(), jnp.asarray(x)))
    flat = x.reshape(2, 6)
    e = np.exp(flat - flat.max(-1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(-1, keepdims=True),
                               atol=1e-5, rtol=1e-5)


def test_unsupported_op_message():
    from mmlspark_tpu.core.exceptions import FriendlyError

    data = model_proto(
        nodes=[node("TotallyMadeUp", ["x"], ["z"], name="z")],
        initializers=[],
        inputs=[value_info("x", (1, 2))],
        outputs=[value_info("z", (1, 2))],
    )
    graph = load_onnx(data)
    with pytest.raises(FriendlyError, match="TotallyMadeUp"):
        graph.apply(graph.init(), jnp.zeros((1, 2), jnp.float32))


def test_tpu_model_runs_onnx_graph(mlp_onnx, tmp_path, rng):
    """TPUModel.from_graph works unchanged on an imported graph."""
    from mmlspark_tpu.data.dataset import Dataset
    from mmlspark_tpu.stages.dnn_model import TPUModel

    data, (w1, b1, w2, b2) = mlp_onnx
    path = tmp_path / "mlp.onnx"
    path.write_bytes(data)
    graph = load_onnx(str(path))
    model = TPUModel.from_graph(
        graph, graph.init(), model_name="onnx", input_col="feats",
        batch_size=8,
    )
    model.set(model_config={"path": str(path)})
    x = rng.normal(size=(6, 4)).astype(np.float32)
    ds = Dataset({"feats": x})
    out = model.transform(ds)
    expect = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(
        np.stack(out["scores"]), expect, atol=1e-4, rtol=1e-4
    )
