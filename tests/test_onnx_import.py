"""ONNX import: wire-format decode, op conversion, node-name surgery.

The test encodes real ONNX protobuf bytes with a minimal writer (the
mirror of the importer's wire decoder), so the round-trip exercises the
actual serialized format — no onnx package needed, matching the importer's
zero-dependency design.
"""

import struct

import numpy as np
import pytest

import jax.numpy as jnp

from mmlspark_tpu.models.onnx_import import load_onnx


# -- minimal protobuf writer -------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field(num: int, wt: int, payload: bytes) -> bytes:
    return _varint(num << 3 | wt) + payload


def _msg(num: int, body: bytes) -> bytes:
    return _field(num, 2, _varint(len(body)) + body)


def _s(num: int, s: str) -> bytes:
    b = s.encode()
    return _field(num, 2, _varint(len(b)) + b)


def _i(num: int, v: int) -> bytes:
    return _field(num, 0, _varint(v & (1 << 64) - 1))


def _f(num: int, v: float) -> bytes:
    return _field(num, 5, struct.pack("<f", v))


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dt = {np.dtype("float32"): 1, np.dtype("int64"): 7,
          np.dtype("int32"): 6}[arr.dtype]
    body = b"".join(_i(1, d) for d in arr.shape)
    body += _i(2, dt) + _s(8, name)
    body += _field(9, 2, _varint(arr.nbytes) + arr.tobytes())
    return body


def attr(name: str, *, i=None, f=None, ints=None, t=None, s=None,
         strings=None) -> bytes:
    body = _s(1, name)
    if i is not None:
        body += _i(3, i)
    if f is not None:
        body += _f(2, f)
    if ints is not None:
        body += b"".join(_i(8, v) for v in ints)
    if t is not None:
        body += _msg(5, t)
    if s is not None:
        body += _s(4, s)
    if strings is not None:
        for v in strings:
            body += _s(9, v)
    return body


def node(op: str, inputs, outputs, name="", attrs=()) -> bytes:
    body = b"".join(_s(1, x) for x in inputs)
    body += b"".join(_s(2, x) for x in outputs)
    body += _s(3, name) + _s(4, op)
    body += b"".join(_msg(5, a) for a in attrs)
    return body


def value_info(name: str, shape) -> bytes:
    dims = b"".join(_msg(1, _i(1, d)) for d in shape)
    tensor_type = _i(1, 1) + _msg(2, dims)
    return _s(1, name) + _msg(2, _msg(1, tensor_type))


def model_proto(nodes, initializers, inputs, outputs,
                gname="test", opset=None) -> bytes:
    g = b"".join(_msg(1, n) for n in nodes)
    g += _s(2, gname)
    g += b"".join(_msg(5, t) for t in initializers)
    g += b"".join(_msg(11, v) for v in inputs)
    g += b"".join(_msg(12, v) for v in outputs)
    out = _i(1, 8) + _msg(7, g)  # ir_version + graph
    if opset is not None:  # opset_import: default domain, given version
        out += _msg(8, _s(1, "") + _i(2, opset))
    return out


# -- fixtures ----------------------------------------------------------------

@pytest.fixture
def mlp_onnx(rng):
    """x(2,4) -> Gemm(w1 4x8,b1) -> Relu -> Gemm(w2 8x3,b2): weights + bytes."""
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    data = model_proto(
        nodes=[
            node("Gemm", ["x", "w1", "b1"], ["h"], name="fc1"),
            node("Relu", ["h"], ["hr"], name="relu1"),
            node("Gemm", ["hr", "w2", "b2"], ["z"], name="z"),
        ],
        initializers=[
            tensor_proto("w1", w1), tensor_proto("b1", b1),
            tensor_proto("w2", w2), tensor_proto("b2", b2),
        ],
        inputs=[value_info("x", (2, 4))],
        outputs=[value_info("z", (2, 3))],
    )
    return data, (w1, b1, w2, b2)


def test_mlp_roundtrip(mlp_onnx, rng):
    data, (w1, b1, w2, b2) = mlp_onnx
    graph = load_onnx(data)
    assert graph.layer_names == ["fc1", "relu1", "z"]
    assert graph.input_shape == (4,)
    x = rng.normal(size=(2, 4)).astype(np.float32)
    out = graph.apply(graph.init(), jnp.asarray(x))
    expect = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(np.asarray(out), expect, atol=1e-5, rtol=1e-5)


def test_cut_at_node(mlp_onnx, rng):
    data, (w1, b1, *_) = mlp_onnx
    graph = load_onnx(data)
    x = rng.normal(size=(2, 4)).astype(np.float32)
    # stop mid-graph by name (AsComposite equivalent)
    hidden = graph.apply(graph.init(), jnp.asarray(x), output_node="relu1")
    np.testing.assert_allclose(
        np.asarray(hidden), np.maximum(x @ w1 + b1, 0), atol=1e-5, rtol=1e-5
    )
    # and as a truncated graph
    head = graph.cut("fc1")
    assert head.layer_names == ["fc1"]
    np.testing.assert_allclose(
        np.asarray(head.apply(head.init(), jnp.asarray(x))),
        x @ w1 + b1, atol=1e-5, rtol=1e-5,
    )


def test_conv_bn_pool_net(rng):
    """NCHW conv -> BatchNorm -> Relu -> MaxPool -> Flatten -> Gemm."""
    w = rng.normal(size=(3, 1, 3, 3)).astype(np.float32) * 0.5
    scale = np.abs(rng.normal(size=(3,))).astype(np.float32)
    bias = rng.normal(size=(3,)).astype(np.float32)
    mean = rng.normal(size=(3,)).astype(np.float32) * 0.1
    var = np.abs(rng.normal(size=(3,))).astype(np.float32) + 0.5
    fc = rng.normal(size=(3 * 4 * 4, 5)).astype(np.float32)
    data = model_proto(
        nodes=[
            node("Conv", ["x", "w"], ["c"], name="conv1",
                 attrs=[attr("pads", ints=[1, 1, 1, 1]),
                        attr("strides", ints=[1, 1]),
                        attr("kernel_shape", ints=[3, 3])]),
            node("BatchNormalization",
                 ["c", "scale", "bias", "mean", "var"], ["bn"],
                 name="bn1", attrs=[attr("epsilon", f=1e-5)]),
            node("Relu", ["bn"], ["r"], name="relu1"),
            node("MaxPool", ["r"], ["p"], name="pool1",
                 attrs=[attr("kernel_shape", ints=[2, 2]),
                        attr("strides", ints=[2, 2])]),
            node("Flatten", ["p"], ["flat"], name="flat"),
            node("Gemm", ["flat", "fc"], ["z"], name="z"),
        ],
        initializers=[
            tensor_proto("w", w), tensor_proto("scale", scale),
            tensor_proto("bias", bias), tensor_proto("mean", mean),
            tensor_proto("var", var), tensor_proto("fc", fc),
        ],
        inputs=[value_info("x", (1, 1, 8, 8))],
        outputs=[value_info("z", (1, 5))],
    )
    graph = load_onnx(data)
    x = rng.normal(size=(1, 1, 8, 8)).astype(np.float32)
    out = np.asarray(graph.apply(graph.init(), jnp.asarray(x)))

    # numpy reference
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    c = np.zeros((1, 3, 8, 8), np.float32)
    for o in range(3):
        for i_ in range(1):
            for yy in range(8):
                for xx in range(8):
                    c[0, o, yy, xx] += np.sum(
                        xp[0, i_, yy:yy + 3, xx:xx + 3] * w[o, i_]
                    )
    bn = (c - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1) + 1e-5
    ) * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
    r = np.maximum(bn, 0)
    p = r.reshape(1, 3, 4, 2, 4, 2).max(axis=(3, 5))
    expect = p.reshape(1, -1) @ fc
    np.testing.assert_allclose(out, expect, atol=1e-3, rtol=1e-3)


def test_reshape_constant_and_softmax(rng):
    shape_c = np.array([2, 6], np.int64)
    data = model_proto(
        nodes=[
            node("Reshape", ["x", "shape"], ["r"], name="reshape"),
            node("Softmax", ["r"], ["z"], name="z",
                 attrs=[attr("axis", i=-1)]),
        ],
        initializers=[tensor_proto("shape", shape_c)],
        inputs=[value_info("x", (2, 2, 3))],
        outputs=[value_info("z", (2, 6))],
    )
    graph = load_onnx(data)
    x = rng.normal(size=(2, 2, 3)).astype(np.float32)
    out = np.asarray(graph.apply(graph.init(), jnp.asarray(x)))
    flat = x.reshape(2, 6)
    e = np.exp(flat - flat.max(-1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(-1, keepdims=True),
                               atol=1e-5, rtol=1e-5)


def test_unsupported_op_message():
    from mmlspark_tpu.core.exceptions import FriendlyError

    data = model_proto(
        nodes=[node("TotallyMadeUp", ["x"], ["z"], name="z")],
        initializers=[],
        inputs=[value_info("x", (1, 2))],
        outputs=[value_info("z", (1, 2))],
    )
    graph = load_onnx(data)
    with pytest.raises(FriendlyError, match="TotallyMadeUp"):
        graph.apply(graph.init(), jnp.zeros((1, 2), jnp.float32))


def test_tpu_model_runs_onnx_graph(mlp_onnx, tmp_path, rng):
    """TPUModel.from_graph works unchanged on an imported graph."""
    from mmlspark_tpu.data.dataset import Dataset
    from mmlspark_tpu.stages.dnn_model import TPUModel

    data, (w1, b1, w2, b2) = mlp_onnx
    path = tmp_path / "mlp.onnx"
    path.write_bytes(data)
    graph = load_onnx(str(path))
    model = TPUModel.from_graph(
        graph, graph.init(), model_name="onnx", input_col="feats",
        batch_size=8,
    )
    model.set(model_config={"path": str(path)})
    x = rng.normal(size=(6, 4)).astype(np.float32)
    ds = Dataset({"feats": x})
    out = model.transform(ds)
    expect = np.maximum(x @ w1 + b1, 0) @ w2 + b2
    np.testing.assert_allclose(
        np.stack(out["scores"]), expect, atol=1e-4, rtol=1e-4
    )


# -- recurrent ops (LSTM / GRU / Slice) -------------------------------------


def _torch_lstm_to_onnx_weights(m, reverse_too=False):
    """torch gate order is i,f,g,o; ONNX is i,o,f,c — reorder."""
    import torch

    def reorder(wmat):
        i, f, g, o = torch.chunk(wmat, 4, dim=0)
        return torch.cat([i, o, f, g], dim=0).detach().numpy()

    suffixes = ["", "_reverse"] if reverse_too else [""]
    w = np.stack([
        reorder(getattr(m, f"weight_ih_l0{s}")) for s in suffixes
    ])
    r = np.stack([
        reorder(getattr(m, f"weight_hh_l0{s}")) for s in suffixes
    ])
    b = np.stack([
        np.concatenate([
            reorder(getattr(m, f"bias_ih_l0{s}")),
            reorder(getattr(m, f"bias_hh_l0{s}")),
        ])
        for s in suffixes
    ])
    return w.astype(np.float32), r.astype(np.float32), b.astype(np.float32)


@pytest.mark.parametrize("bidirectional", [False, True])
def test_lstm_matches_torch(rng, bidirectional):
    """Golden check against torch's independent LSTM implementation."""
    import torch

    s_len, batch, d_in, hidden = 9, 3, 5, 7
    torch.manual_seed(0)
    m = torch.nn.LSTM(d_in, hidden, bidirectional=bidirectional)
    x = rng.normal(size=(s_len, batch, d_in)).astype(np.float32)
    with torch.no_grad():
        want, (want_h, want_c) = m(torch.from_numpy(x))
    w, r, b = _torch_lstm_to_onnx_weights(m, reverse_too=bidirectional)
    dirs = 2 if bidirectional else 1

    direction = "bidirectional" if bidirectional else "forward"
    nodes = [node("LSTM", ["x", "W", "R", "B"], ["y", "yh", "yc"],
                  name="lstm",
                  attrs=[attr("hidden_size", i=hidden),
                         attr("direction", s=direction)])]
    model = model_proto(
        nodes,
        [tensor_proto("W", w), tensor_proto("R", r), tensor_proto("B", b)],
        [value_info("x", (s_len, batch, d_in))],
        [value_info("y", (s_len, dirs, batch, hidden))],
    )
    g = load_onnx(model)
    y = np.asarray(g.apply(g.init(), jnp.asarray(x)))
    # ONNX Y is (S, D, B, H); torch returns (S, B, D*H)
    got = np.moveaxis(y, 1, 2).reshape(s_len, batch, dirs * hidden)
    np.testing.assert_allclose(got, want.numpy(), rtol=2e-5, atol=2e-5)


def test_gru_matches_torch(rng):
    import torch

    s_len, batch, d_in, hidden = 8, 2, 4, 6
    torch.manual_seed(1)
    m = torch.nn.GRU(d_in, hidden)
    x = rng.normal(size=(s_len, batch, d_in)).astype(np.float32)
    with torch.no_grad():
        want, _ = m(torch.from_numpy(x))

    # torch gate order is r,z,n; ONNX is z,r,h — reorder, and torch's
    # reset-gate application matches linear_before_reset=1
    def reorder(wmat):
        import torch as t

        r_, z, n = t.chunk(wmat, 3, dim=0)
        return t.cat([z, r_, n], dim=0).detach().numpy()

    w = np.stack([reorder(m.weight_ih_l0)]).astype(np.float32)
    r = np.stack([reorder(m.weight_hh_l0)]).astype(np.float32)
    b = np.stack([
        np.concatenate([reorder(m.bias_ih_l0), reorder(m.bias_hh_l0)])
    ]).astype(np.float32)

    nodes = [node("GRU", ["x", "W", "R", "B"], ["y", "yh"], name="gru",
                  attrs=[attr("hidden_size", i=hidden),
                         attr("linear_before_reset", i=1)])]
    model = model_proto(
        nodes,
        [tensor_proto("W", w), tensor_proto("R", r), tensor_proto("B", b)],
        [value_info("x", (s_len, batch, d_in))],
        [value_info("y", (s_len, 1, batch, hidden))],
    )
    g = load_onnx(model)
    y = np.asarray(g.apply(g.init(), jnp.asarray(x)))[:, 0]
    np.testing.assert_allclose(y, want.numpy(), rtol=2e-5, atol=2e-5)


def test_slice_op(rng):
    x = rng.normal(size=(4, 6)).astype(np.float32)
    nodes = [node("Slice", ["x", "starts", "ends", "axes", "steps"], ["y"],
                  name="sl")]
    inits = [
        tensor_proto("starts", np.array([1], np.int64)),
        tensor_proto("ends", np.array([5], np.int64)),
        tensor_proto("axes", np.array([1], np.int64)),
        tensor_proto("steps", np.array([2], np.int64)),
    ]
    g = load_onnx(model_proto(
        nodes, inits, [value_info("x", (4, 6))], [value_info("y", (4, 2))]
    ))
    y = np.asarray(g.apply(g.init(), jnp.asarray(x)))
    np.testing.assert_allclose(y, x[:, 1:5:2])


def test_bilstm_tagger_roundtrip(rng):
    """Notebook-304 shape: embedding-fed BiLSTM + per-token projection,
    cut-at-node surgery preserved through the recurrent op."""
    s_len, batch, d_in, hidden, n_tags = 12, 2, 8, 16, 5
    w = rng.normal(size=(2, 4 * hidden, d_in)).astype(np.float32) * 0.3
    r = rng.normal(size=(2, 4 * hidden, hidden)).astype(np.float32) * 0.3
    proj = rng.normal(size=(2 * hidden, n_tags)).astype(np.float32) * 0.3
    nodes = [
        node("LSTM", ["x", "W", "R"], ["y", "yh", "yc"], name="bilstm",
             attrs=[attr("hidden_size", i=hidden),
                    attr("direction", s="bidirectional")]),
        node("Transpose", ["y"], ["yt"], name="t",
             attrs=[attr("perm", ints=[0, 2, 1, 3])]),
        node("Reshape", ["yt", "shape"], ["flat"], name="merge"),
        node("MatMul", ["flat", "proj"], ["logits"], name="tags"),
    ]
    inits = [
        tensor_proto("W", w), tensor_proto("R", r),
        tensor_proto("proj", proj),
        tensor_proto("shape", np.array([s_len, batch, 2 * hidden],
                                       np.int64)),
    ]
    g = load_onnx(model_proto(
        nodes, inits,
        [value_info("x", (s_len, batch, d_in))],
        [value_info("logits", (s_len, batch, n_tags))],
    ))
    x = rng.normal(size=(s_len, batch, d_in)).astype(np.float32)
    out = np.asarray(g.apply(g.init(), jnp.asarray(x)))
    assert out.shape == (s_len, batch, n_tags)
    # node-name surgery works through the LSTM (layer_names cut)
    hidden_states = np.asarray(
        g.apply(g.init(), jnp.asarray(x), output_node="bilstm")
    )
    assert hidden_states.shape == (s_len, 2, batch, hidden)


def test_lstm_reverse_direction(rng):
    """direction="reverse" must scan backward — torch bidirectional's
    second direction is the golden reference for the reversed pass."""
    import torch

    s_len, batch, d_in, hidden = 7, 2, 4, 5
    torch.manual_seed(2)
    m = torch.nn.LSTM(d_in, hidden, bidirectional=True)
    x = rng.normal(size=(s_len, batch, d_in)).astype(np.float32)
    with torch.no_grad():
        want, _ = m(torch.from_numpy(x))
    want_rev = want.numpy()[:, :, hidden:]  # torch's reverse-direction half

    w, r, b = _torch_lstm_to_onnx_weights(m, reverse_too=True)
    # single-direction model built from ONLY the reverse weights
    w1, r1, b1 = w[1:2], r[1:2], b[1:2]
    nodes = [node("LSTM", ["x", "W", "R", "B"], ["y"], name="rev",
                  attrs=[attr("hidden_size", i=hidden),
                         attr("direction", s="reverse")])]
    g = load_onnx(model_proto(
        nodes,
        [tensor_proto("W", w1), tensor_proto("R", r1),
         tensor_proto("B", b1)],
        [value_info("x", (s_len, batch, d_in))],
        [value_info("y", (s_len, 1, batch, hidden))],
    ))
    y = np.asarray(g.apply(g.init(), jnp.asarray(x)))[:, 0]
    np.testing.assert_allclose(y, want_rev, rtol=2e-5, atol=2e-5)


def test_lstm_direction_weight_mismatch_errors(rng):
    w = rng.normal(size=(2, 16, 3)).astype(np.float32)
    r = rng.normal(size=(2, 16, 4)).astype(np.float32)
    nodes = [node("LSTM", ["x", "W", "R"], ["y"], name="bad",
                  attrs=[attr("hidden_size", i=4)])]  # forward but dirs=2
    g = load_onnx(model_proto(
        nodes, [tensor_proto("W", w), tensor_proto("R", r)],
        [value_info("x", (5, 1, 3))], [value_info("y", (5, 2, 1, 4))],
    ))
    with pytest.raises(Exception, match="weight dirs"):
        g.apply(g.init(), jnp.zeros((5, 1, 3), jnp.float32))


def test_lstm_custom_activations_rejected(rng):
    w = rng.normal(size=(1, 16, 3)).astype(np.float32)
    r = rng.normal(size=(1, 16, 4)).astype(np.float32)
    nodes = [node("LSTM", ["x", "W", "R"], ["y"], name="acts",
                  attrs=[attr("hidden_size", i=4),
                         attr("activations",
                              strings=["Relu", "Tanh", "Tanh"])])]
    g = load_onnx(model_proto(
        nodes, [tensor_proto("W", w), tensor_proto("R", r)],
        [value_info("x", (5, 1, 3))], [value_info("y", (5, 1, 1, 4))],
    ))
    with pytest.raises(Exception, match="activations"):
        g.apply(g.init(), jnp.zeros((5, 1, 3), jnp.float32))


def test_transformer_support_ops(rng):
    """Ops external (torch-style) transformer exports lean on: Split,
    Cast, Neg, Where, ReduceSum, fused LayerNormalization (opset 17)."""
    x = rng.normal(size=(2, 6)).astype(np.float32)
    scale = rng.normal(size=(6,)).astype(np.float32)
    bias = rng.normal(size=(6,)).astype(np.float32)
    data = model_proto(
        nodes=[
            node("LayerNormalization", ["x", "scale", "bias"], ["ln"],
                 name="ln", attrs=[attr("axis", i=-1)]),
            node("Split", ["ln"], ["a", "b"], name="split",
                 attrs=[attr("axis", i=1)]),
            node("Neg", ["a"], ["na"], name="na"),
            node("Cast", ["cond_i"], ["cond"], name="cond",
                 attrs=[attr("to", i=9)]),
            node("Where", ["cond", "na", "b"], ["w"], name="w"),
            # to=6: int32 (float64 would silently stay f32 under jax's
            # default x64-disabled config)
            node("Cast", ["w"], ["wc"], name="wc", attrs=[attr("to", i=6)]),
            node("ReduceSum", ["wc"], ["z"], name="z",
                 attrs=[attr("axes", ints=[1])]),
        ],
        initializers=[
            tensor_proto("scale", scale),
            tensor_proto("bias", bias),
            tensor_proto(
                "cond_i", np.array([[1, 0, 1]], np.int32)
            ),
        ],
        inputs=[value_info("x", (2, 6))],
        outputs=[value_info("z", (2, 1))],
    )
    graph = load_onnx(data)
    out = np.asarray(graph.apply(graph.init(), jnp.asarray(x)))

    mu = x.mean(-1, keepdims=True)
    ln = (x - mu) / np.sqrt(x.var(-1, keepdims=True) + 1e-5) * scale + bias
    a, b = ln[:, :3], ln[:, 3:]
    w = np.where(np.array([[True, False, True]]), -a, b).astype(np.int32)
    expect = w.sum(axis=1, keepdims=True)
    assert out.dtype == np.int32
    np.testing.assert_array_equal(out, expect)


def test_split_uneven_opset_gate(rng):
    """Split with no sizes and an indivisible dim: opset>=18 defines
    ceil-sized chunks with a smaller tail; earlier opsets required an
    even split (onnxruntime errors on them), so the importer refuses
    rather than silently diverging. Unknown opset stays lenient."""
    x = rng.normal(size=(2, 7)).astype(np.float32)

    def build(opset):
        return model_proto(
            nodes=[node("Split", ["x"], ["a", "b"], name="split",
                        attrs=[attr("axis", i=1)])],
            initializers=[],
            inputs=[value_info("x", (2, 7))],
            outputs=[value_info("a", (2, 4))],
            opset=opset,
        )

    g18 = load_onnx(build(18))
    assert g18.opset == 18
    np.testing.assert_allclose(
        np.asarray(g18.apply(g18.init(), jnp.asarray(x))), x[:, :4]
    )

    g13 = load_onnx(build(13))
    with pytest.raises(Exception, match="not divisible"):
        g13.apply(g13.init(), jnp.asarray(x))

    g_unknown = load_onnx(build(None))
    assert g_unknown.opset is None
    np.testing.assert_allclose(
        np.asarray(g_unknown.apply(g_unknown.init(), jnp.asarray(x))),
        x[:, :4],
    )


def test_shape_chain_constant_folds(rng):
    """torch's dynamic-reshape idiom: Shape -> Gather -> Unsqueeze ->
    Concat -> Reshape. Shapes are static under tracing, so the chain
    folds to constants and the Reshape target resolves."""
    x = rng.normal(size=(2, 3, 4)).astype(np.float32)
    data = model_proto(
        nodes=[
            node("Shape", ["x"], ["sh"], name="sh"),
            node("Gather", ["sh", "idx0"], ["b"], name="b",
                 attrs=[attr("axis", i=0)]),
            node("Unsqueeze", ["b", "ax0"], ["bu"], name="bu"),
            node("Concat", ["bu", "minus1"], ["tgt"], name="tgt",
                 attrs=[attr("axis", i=0)]),
            node("Reshape", ["x", "tgt"], ["flat"], name="flat"),
            node("Expand", ["one_row", "row_shape"], ["ones2"],
                 name="ones2"),
            node("Mul", ["flat", "ones2"], ["z"], name="z"),
        ],
        initializers=[
            tensor_proto("idx0", np.array(0, np.int64)),
            tensor_proto("ax0", np.array([0], np.int64)),
            tensor_proto("minus1", np.array([-1], np.int64)),
            tensor_proto("one_row", np.ones((1, 1), np.float32)),
            tensor_proto("row_shape", np.array([1, 12], np.int64)),
        ],
        inputs=[value_info("x", (2, 3, 4))],
        outputs=[value_info("z", (2, 12))],
    )
    graph = load_onnx(data)
    out = np.asarray(graph.apply(graph.init(), jnp.asarray(x)))
    np.testing.assert_allclose(out, x.reshape(2, 12), atol=1e-6)

    # the chain must also survive jit (static shapes, no tracers leak
    # into the Reshape target)
    import jax

    jout = np.asarray(
        jax.jit(lambda v, t: graph.apply(v, t))(graph.init(),
                                                jnp.asarray(x))
    )
    np.testing.assert_allclose(jout, x.reshape(2, 12), atol=1e-6)
