"""Replicated serving control plane (ISSUE 10 tentpole).

The contract under test (docs/SERVING.md "Replicated serving"): a
``ReplicaSet`` of health-checked ``ServeEngine`` replicas behind one
``submit()/run()`` facade survives replica kills, failed health probes,
hedged duplicates, and drains — and every final token stream stays
BIT-IDENTICAL to ``generate()`` (the no-failure oracle), exactly one
result per submitted request. Failover restores the killed replica
from its last PERIODIC snapshot and re-routes in-flight requests
through the emitted-prefix resume path; hedging is
first-committed-wins with wasted-token accounting; drain migrates
pending requests losslessly. Per-replica invariants (compile-count
pins, one host sync per decode block) hold exactly as on an
unsupervised engine — asserted under ``serve_compile_guard`` on
single-device AND 2x2-mesh replicas.

Satellites ride here too: EngineKilled parks device resources
deterministically (pool drained, paged refcounts consistent, step()
refuses, in-process restore works); the ``serve.snapshot`` fault makes
a torn checkpoint non-restorable (the previous one survives); the
paged + prefix-cache engine on a 2x2 mesh round-trips
snapshot/restore under an active fault schedule with refcount totals
equal to mapped references.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import (
    EngineKilled,
    Fault,
    FaultInjector,
    TransientFault,
    parse_fault_spec,
)
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.serve import ReplicaSet, ServeEngine
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new)
    return np.asarray(out)[0]


class _FakeClock:
    """Injectable supervisor clock: hedging deadlines and stall probes
    advance only when the test says so."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def _assert_parity(m, v, results, gids, prompts, max_new):
    assert len(results) == len(gids)
    for gid, p in zip(gids, prompts):
        res = results[gid]
        assert res.status == "completed", f"gid={gid}: {res.status}"
        np.testing.assert_array_equal(
            np.asarray(res.tokens), _ref(m, v, p, max_new),
            err_msg=f"gid={gid}",
        )


def _assert_engine_pins(engine):
    """Per-replica compile pins: never more programs than the design
    ceilings, whatever the supervisor did around the engine."""
    assert engine.decode_compile_count <= engine.num_decode_blocks
    assert engine.prefill_compile_count <= engine.num_prefill_buckets


# -- routing ---------------------------------------------------------------


def test_routing_parity_and_load_split(lm):
    """Baseline: two replicas behind the facade serve a staggered
    arrival schedule bit-identically to ``generate()``, both replicas
    take work, and each engine's compile pins hold under the guard."""
    m, v, ids = lm
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=8, decode_block=4, retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7, 6, 8)]
    gids = []
    with serve_compile_guard(rs.engine(0), min_decode=1, min_prefill=1), \
            serve_compile_guard(rs.engine(1), min_decode=1,
                                min_prefill=1):
        it = iter(prompts)
        pending = True
        while pending or rs.busy:
            for _ in range(2):
                p = next(it, None)
                if p is None:
                    pending = False
                    break
                gids.append(rs.submit(p, 6))
            rs.step()
        results = rs.run()
    _assert_parity(m, v, results, gids, prompts, 6)
    per = rs.metrics_dict()["per_replica"]
    assert per["replica0"]["submitted"] > 0
    assert per["replica1"]["submitted"] > 0
    assert rs.replica_failovers_total == 0


def test_submit_validation_and_global_ids(lm):
    m, v, _ids = lm
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=2, retry_backoff_s=0.0)
    with pytest.raises(FriendlyError, match="non-empty"):
        rs.submit(np.zeros(0, np.int32), 4)
    g0 = rs.submit([1, 2, 3], 4)
    g1 = rs.submit([1, 2, 3], 4)
    assert (g0, g1) == (0, 1)  # global ids, replica-independent
    with pytest.raises(FriendlyError, match="replicas must be"):
        ReplicaSet(m, v, replicas=0)
    with pytest.raises(FriendlyError, match="hedge_ms"):
        ReplicaSet(m, v, replicas=2, hedge_ms=-1.0)
    with pytest.raises(FriendlyError, match="managed by ReplicaSet"):
        ReplicaSet(m, v, replicas=2, replica=0)


# -- failover --------------------------------------------------------------


def _kill_drill(m, v, ids, mesh=None):
    """The acceptance drill: kill replica 0 mid-decode-block; run()
    must still complete EVERY request bit-identically to a no-failure
    run, with per-replica compile pins intact. Mixed budgets make some
    requests complete between the snapshot and the kill, so the
    reconciliation's exactly-once cancel path runs too."""
    inj = FaultInjector([Fault("serve.decode", "kill", tick=3,
                               replica=0)])
    rs = ReplicaSet(m, v, replicas=2, slots=4, cache_len=32,
                    max_queue=8, decode_block=2, mesh=mesh,
                    snapshot_every_ticks=2, faults=inj,
                    retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7, 6, 8)]
    budgets = [12, 3, 12, 3, 12, 12]
    gids = [rs.submit(p, b) for p, b in zip(prompts, budgets)]
    results = rs.run()
    assert rs.replica_failovers_total == 1
    assert len(results) == len(gids)
    for gid, p, b in zip(gids, prompts, budgets):
        assert results[gid].status == "completed"
        np.testing.assert_array_equal(
            np.asarray(results[gid].tokens), _ref(m, v, p, b),
            err_msg=f"mesh={mesh} gid={gid}",
        )
    for i in range(2):
        _assert_engine_pins(rs.engine(i))
    assert rs.replica_state(0) in ("healthy", "degraded")
    md = rs.metrics_dict()
    assert md["replica_failovers_total"] == 1
    assert md["per_replica"]["replica0"]["failovers"] == 1


def test_kill_failover_bit_identical_single_device(lm):
    m, v, ids = lm
    _kill_drill(m, v, ids, mesh=None)


def test_kill_failover_bit_identical_2x2_mesh(lm):
    m, v, ids = lm
    _kill_drill(m, v, ids, mesh={"data": 2, "model": 2})


def test_health_probe_fault_fails_over(lm):
    """An injected failure at the ``serve.health`` site IS a failed
    probe: the replica quarantines and rebuilds; requests complete
    bit-identically on the survivors + the restored replica."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.health", "transient",
                               replica=0)])
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=8, decode_block=2,
                    snapshot_every_ticks=1, faults=inj,
                    retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    gids = [rs.submit(p, 8) for p in prompts]
    results = rs.run()
    assert rs.replica_failovers_total == 1
    _assert_parity(m, v, results, gids, prompts, 8)


def test_max_failovers_caps_the_rebuild_loop(lm):
    """A deterministic crash that fires on every rebuilt engine must
    not spin forever: past ``max_failovers`` the supervisor raises the
    typed error instead of burning another restore."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.decode", "kill", times=10)])
    rs = ReplicaSet(m, v, replicas=1, slots=2, cache_len=32,
                    max_queue=4, decode_block=2, max_failovers=2,
                    snapshot_every_ticks=1, faults=inj,
                    retry_backoff_s=0.0)
    rs.submit(np.asarray(ids[0, :5]), 8)
    with pytest.raises(FriendlyError, match="max_failovers"):
        rs.run()
    assert rs.replica_failovers_total == 3  # 2 absorbed + the fatal one


# -- hedging ---------------------------------------------------------------


def test_hedging_first_committed_wins_exactly_once(lm):
    """Past the hedge deadline (injected clock) the request duplicates
    onto the second replica; the first copy to commit wins, the loser
    cancels, its emitted tokens count as waste — and the caller sees
    EXACTLY one result, bit-identical to ``generate()``."""
    m, v, ids = lm
    clk = _FakeClock()
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=8, decode_block=2, hedge_ms=50.0,
                    clock=clk, snapshot_every_ticks=None,
                    retry_backoff_s=0.0)
    p = np.asarray(ids[0, :6])
    gid = rs.submit(p, 12)
    rs.step()               # below the deadline: no hedge yet
    assert rs.hedges_total == 0
    clk.t = 0.2             # 200ms queue age > 50ms hedge deadline
    results = rs.run()
    assert rs.hedges_total == 1
    assert rs.hedge_wasted_tokens_total > 0
    assert list(results) == [gid]
    np.testing.assert_array_equal(
        np.asarray(results[gid].tokens), _ref(m, v, p, 12))
    md = rs.metrics_dict()
    assert md["hedges_total"] == 1
    assert md["hedge_wasted_tokens_total"] == rs.hedge_wasted_tokens_total
    # the losing copy was cancelled on exactly one engine
    cancelled = sum(
        md["per_replica"][f"replica{i}"]["cancelled_total"]
        for i in range(2)
    )
    assert cancelled == 1


def test_hedge_needs_a_second_live_replica(lm):
    """With nowhere to duplicate to, the hedge deadline passes without
    effect — no duplicate, no waste, one result."""
    m, v, ids = lm
    clk = _FakeClock()
    rs = ReplicaSet(m, v, replicas=1, slots=2, cache_len=32,
                    max_queue=8, decode_block=2, hedge_ms=1.0,
                    clock=clk, retry_backoff_s=0.0)
    gid = rs.submit(np.asarray(ids[0, :5]), 6)
    clk.t = 10.0
    results = rs.run()
    assert rs.hedges_total == 0
    assert results[gid].status == "completed"


# -- drain -----------------------------------------------------------------


def test_drain_under_load_migrates_bit_identically(lm):
    """Zero-loss drain mid-run: replica 0's pending requests migrate
    to replica 1 with their emitted prefixes, every stream finishes
    bit-identically, and the drained replica takes no new work."""
    m, v, ids = lm
    rs = ReplicaSet(m, v, replicas=2, slots=4, cache_len=32,
                    max_queue=8, decode_block=2,
                    snapshot_every_ticks=2, retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7)]
    gids = [rs.submit(p, 12) for p in prompts]
    rs.step()
    rs.step()
    before = rs.engine(1).metrics.submitted
    rs.drain(0)
    assert rs.replica_state(0) in ("draining", "drained")
    assert rs.engine(1).metrics.submitted > before  # migration landed
    g_late = rs.submit(prompts[0], 12)   # routes around the drain
    results = rs.run()
    assert rs.replica_state(0) == "drained"
    assert rs.drains_total == 1
    _assert_parity(m, v, results, gids + [g_late],
                   prompts + [prompts[0]], 12)
    with pytest.raises(FriendlyError, match="already"):
        rs.drain(0)


def test_drain_last_replica_finishes_in_place(lm):
    """With no survivor to migrate to, the draining replica serves its
    own backlog to completion, then retires; further submits reject."""
    m, v, ids = lm
    rs = ReplicaSet(m, v, replicas=1, slots=2, cache_len=32,
                    max_queue=8, decode_block=2, retry_backoff_s=0.0)
    p = np.asarray(ids[0, :5])
    gid = rs.submit(p, 8)
    rs.drain(0)
    results = rs.run()
    np.testing.assert_array_equal(
        np.asarray(results[gid].tokens), _ref(m, v, p, 8))
    rs.step()  # idle draining replica retires on the next tick
    assert rs.replica_state(0) == "drained"
    assert rs.drains_total == 1
    with pytest.raises(FriendlyError, match="no live replica"):
        rs.submit(p, 4)


# -- run() bound -----------------------------------------------------------


def test_run_bound_stalls_open_requests(lm):
    """Hitting max_ticks retires every open request as ``"stalled"``
    with whatever its best copy had emitted, attached to the typed
    error — never a silent drop."""
    m, v, ids = lm
    rs = ReplicaSet(m, v, replicas=1, slots=2, cache_len=32,
                    max_queue=8, decode_block=2, retry_backoff_s=0.0)
    p = np.asarray(ids[0, :5])
    gid = rs.submit(p, 16)
    with pytest.raises(FriendlyError, match="max_ticks") as ei:
        rs.run(max_ticks=1)
    res = ei.value.results[gid]
    assert res.status == "stalled"
    assert res.generated > 0
    np.testing.assert_array_equal(
        np.asarray(res.tokens)[:len(p)], p)
    assert not rs.busy


# -- satellite: EngineKilled parks device resources ------------------------


def test_engine_killed_parks_resources_deterministically(lm):
    """The kill regression (satellite a): an EngineKilled escaping
    run() leaves NO leased slot behind — on a paged pool every slot
    mapping is released (refcount totals drop to the prefix cache's
    own references) — the dead engine refuses further steps, and an
    in-process restore of its last checkpoint completes every stream
    bit-identically."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.decode", "kill", tick=2)])
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=2, paged=True, prefix_cache=True,
                         snapshot_every_ticks=1, faults=inj,
                         retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (9, 4, 11)]
    rids = [engine.submit(p, 6) for p in prompts]
    with pytest.raises(EngineKilled):
        engine.run()
    assert engine.pool.leased_count == 0
    pg = engine.pool.snapshot()
    refs = sum(pg["npages"]) + sum(
        len(e["pages"]) for e in pg["prefix_entries"])
    assert sum(pg["refcounts"]) == refs
    assert sum(pg["npages"]) == 0  # no slot holds a mapping
    with pytest.raises(FriendlyError, match="killed"):
        engine.step()
    assert engine.cancel(rids[0]) is None
    assert engine.steal_all() == []
    snap = engine.last_snapshot
    assert snap is not None
    rebuilt = ServeEngine.restore(snap, m, v, slots=2, max_queue=8,
                                  decode_block=2, paged=True,
                                  prefix_cache=True)
    results = rebuilt.run()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 6),
            err_msg=f"request={rid}")


# -- satellite: torn checkpoints are not restorable ------------------------


def test_snapshot_fault_keeps_previous_checkpoint(lm):
    """A fault at the ``serve.snapshot`` site models a checkpoint
    failing MID-WRITE: checkpoint() reports the failure and
    ``last_snapshot`` keeps the previous COMPLETE one — which still
    restores bit-identically."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.snapshot", "transient", tick=3)])
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                         decode_block=2, faults=inj,
                         retry_backoff_s=0.0)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9)]
    rids = [engine.submit(p, 10) for p in prompts]
    engine.step()
    engine.step()
    good = engine.checkpoint()           # tick 2: clean write
    assert good is not None
    assert engine.metrics.snapshots_total == 1
    engine.step()
    torn = engine.checkpoint()           # tick 3: fault mid-write
    assert torn is None
    assert engine.last_snapshot is good  # previous checkpoint survives
    assert engine.metrics.snapshot_failures_total == 1
    rebuilt = ServeEngine.restore(engine.last_snapshot, m, v, slots=2,
                                  max_queue=8, decode_block=2)
    results = rebuilt.run()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 10),
            err_msg=f"request={rid}")


def test_parse_fault_spec_site_rates():
    """``site:kind=rate`` keys scope a rate to ONE hook site — the
    snapshot-failure drill's spelling."""
    inj = parse_fault_spec("seed=5,serve.snapshot:transient=1.0")
    inj.fire("serve.decode", tick=0)     # other sites: silent
    inj.fire("serve.health", tick=0)
    with pytest.raises(TransientFault):
        inj.fire("serve.snapshot", tick=0)
    with pytest.raises(FriendlyError, match="site"):
        parse_fault_spec("seed=5,nope.site:transient=0.5")
    with pytest.raises(FriendlyError, match="seed"):
        parse_fault_spec("serve.snapshot:transient=0.5")


# -- satellite: paged + prefix on a 2x2 mesh, faulted round-trip -----------


def test_paged_prefix_mesh_snapshot_roundtrip_under_faults(lm):
    """Snapshot/restore of a paged + prefix-cache engine on a 2x2 mesh
    while a fault schedule is ACTIVE: the mid-run checkpoint is
    auditable (refcount totals == mapped references), the restored
    engine finishes every stream bit-identically, and the audit holds
    again after the restored run."""
    m, v, ids = lm
    inj = FaultInjector([
        Fault("serve.prefill", "transient", times=2),
        Fault("serve.decode", "transient", tick=2),
    ])
    kwargs = dict(slots=2, cache_len=32, max_queue=8, decode_block=2,
                  paged=True, prefix_cache=True,
                  mesh={"data": 2, "model": 2}, retry_backoff_s=0.0)
    engine = ServeEngine(m, v, faults=inj, **kwargs)
    prompts = [np.asarray(ids[0, :n]) for n in (9, 9, 11)]
    rids = [engine.submit(p, 6) for p in prompts]
    engine.step()
    engine.step()
    snap = engine.snapshot()
    pg = snap["paging"]
    refs = sum(pg["npages"]) + sum(
        len(e["pages"]) for e in pg["prefix_entries"])
    assert sum(pg["refcounts"]) == refs
    json.dumps(snap)  # the checkpoint must stay JSON-able
    rebuilt = ServeEngine.restore(snap, m, v, **kwargs)
    results = rebuilt.run()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, 6),
            err_msg=f"request={rid}")
    pg2 = rebuilt.pool.snapshot()
    refs2 = sum(pg2["npages"]) + sum(
        len(e["pages"]) for e in pg2["prefix_entries"])
    assert sum(pg2["refcounts"]) == refs2
    _assert_engine_pins(rebuilt)


# -- metrics surface -------------------------------------------------------


def test_metrics_dict_schema(lm):
    """The keys tools/check_metrics_schema.py gates on the --replicas
    demo line, plus the per-replica nesting."""
    m, v, ids = lm
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=8, decode_block=2, retry_backoff_s=0.0)
    gid = rs.submit(np.asarray(ids[0, :5]), 4)
    rs.run()
    md = rs.metrics_dict()
    for key in ("replicas", "hedge_ms", "supervisor_ticks", "submitted",
                "completed", "failed", "expired", "stalled",
                "tokens_generated", "tokens_per_sec", "wall_s",
                "replica_failovers_total", "hedges_total",
                "hedge_wasted_tokens_total", "drains_total",
                "per_replica"):
        assert key in md, key
    assert md["replicas"] == 2
    assert md["completed"] == 1
    assert set(md["per_replica"]) == {"replica0", "replica1"}
    for sub in md["per_replica"].values():
        for key in ("state", "failovers", "snapshots_total",
                    "cancelled_total", "degraded_mode",
                    "decode_compile_count", "prefill_compile_count"):
            assert key in sub, key
    json.dumps(md, default=str)  # the CLI prints it as one JSON line
    # per-replica registry namespacing: replica0's serve counters carry
    # the prefix, so N expositions concatenate without collisions
    names = rs.engine(0).metrics.registry.names()
    assert any(n.startswith("replica0.serve.") for n in names)
    assert gid == 0
