"""Sequence/context parallelism correctness: ring and Ulysses attention
must match the dense single-device reference exactly (up to float
tolerance), including gradients, and the transformer family must train
under a dp×sp×tp mesh with TP sharding rules applied.

The reference has no long-context support at all (SURVEY.md §5), so these
are capability-upgrade tests — the 8-device CPU mesh is the local[*]
analog (TestBase, core/test/base/.../TestBase.scala:36).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.ops.attention import dense_attention
from mmlspark_tpu.parallel import (
    TRANSFORMER_TP_RULES,
    make_mesh,
    ring_attention,
    ulysses_attention,
)
from mmlspark_tpu.parallel.sharding import build_param_shardings, spec_for_path


def _qkv(rng, b=2, s=16, h=4, d=8):
    shape = (b, s, h, d)
    return (
        jnp.asarray(rng.normal(size=shape), jnp.float32),
        jnp.asarray(rng.normal(size=shape), jnp.float32),
        jnp.asarray(rng.normal(size=shape), jnp.float32),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(rng, causal):
    q, k, v = _qkv(rng)
    mesh = make_mesh({"seq": 8})
    expect = dense_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(rng, causal):
    q, k, v = _qkv(rng, h=4)
    mesh = make_mesh({"seq": 4})
    expect = dense_attention(q, k, v, causal=causal)
    got = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def _gqa_qkv(rng, b=2, s=16, h=4, hk=2, d=8):
    return (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32),
        jnp.asarray(rng.normal(size=(b, s, hk, d)), jnp.float32),
        jnp.asarray(rng.normal(size=(b, s, hk, d)), jnp.float32),
    )


@pytest.mark.parametrize("window", [None, 6])
def test_ring_gqa_matches_dense(rng, window):
    """GQA through the ring (round 5): narrow kv chunks rotate, the
    repeat to query heads happens inside the local update — output must
    equal the dense GQA reference, window included."""
    q, k, v = _gqa_qkv(rng)
    mesh = make_mesh({"seq": 8})
    expect = dense_attention(q, k, v, causal=True, window=window)
    got = ring_attention(q, k, v, mesh, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_gqa_matches_dense(rng):
    q, k, v = _gqa_qkv(rng, h=4, hk=2)
    mesh = make_mesh({"seq": 2})
    expect = dense_attention(q, k, v, causal=True)
    got = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_ring_gqa_gradients_match_dense(rng):
    q, k, v = _gqa_qkv(rng, s=8)
    mesh = make_mesh({"seq": 4})

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_gqa_head_mismatch_is_friendly(rng):
    """ADVICE r4: direct callers get a FriendlyError, not a trace-time
    einsum shape mismatch deep in the inner body."""
    from mmlspark_tpu.core.exceptions import FriendlyError

    q, _, _ = _qkv(rng, h=4)
    _, k3, v3 = _gqa_qkv(rng, hk=3)  # 3 does not divide 4
    mesh = make_mesh({"seq": 4})
    with pytest.raises(FriendlyError, match="heads"):
        ring_attention(q, k3, v3, mesh, causal=True)
    with pytest.raises(FriendlyError, match="heads"):
        ulysses_attention(q, k3, v3, mesh, causal=True)


def test_ring_with_data_axis(rng):
    # dp × sp composition: batch on 'data', sequence on 'seq'
    q, k, v = _qkv(rng, b=4, s=8)
    mesh = make_mesh({"data": 2, "seq": 4})
    expect = dense_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_ring_gradients_match_dense(rng):
    q, k, v = _qkv(rng, b=1, s=8, h=2, d=4)
    mesh = make_mesh({"seq": 4})

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gd, gr):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=1e-4, rtol=1e-4)


def test_ring_rejects_bad_seq_len(rng):
    from mmlspark_tpu.core.exceptions import FriendlyError

    q, k, v = _qkv(rng, s=12)  # 12 % 8 != 0
    mesh = make_mesh({"seq": 8})
    with pytest.raises(FriendlyError):
        ring_attention(q, k, v, mesh)


def test_transformer_impls_agree(rng):
    from mmlspark_tpu.models import build_model

    ids = jnp.asarray(rng.integers(0, 64, size=(2, 16)), jnp.int32)
    mesh = make_mesh({"seq": 4})
    outs = {}
    for impl in ("dense", "ring", "ulysses"):
        graph = build_model(
            "transformer_lm", vocab_size=64, d_model=32, heads=4, depth=2,
            max_len=16, attn_impl=impl, mesh=None if impl == "dense" else mesh,
        )
        variables = graph.init(jax.random.PRNGKey(0), ids)
        outs[impl] = np.asarray(graph.apply(variables, ids))
    # same params (same init seed), same math -> same logits
    np.testing.assert_allclose(outs["ring"], outs["dense"], atol=2e-2,
                               rtol=2e-2)
    np.testing.assert_allclose(outs["ulysses"], outs["dense"], atol=2e-2,
                               rtol=2e-2)


def test_tp_sharding_rules():
    mesh = make_mesh({"data": 2, "model": 4})
    spec = spec_for_path("block0/attn/qkv/kernel", TRANSFORMER_TP_RULES, mesh)
    assert tuple(spec) == (None, "model")
    spec = spec_for_path("block0/attn/attn_out/kernel", TRANSFORMER_TP_RULES,
                         mesh)
    assert tuple(spec) == ("model", None)
    # vocab-parallel embedding: rows over 'model' (pairs with the
    # column-sharded lm head — no cross-shard reduction between them)
    assert tuple(spec_for_path("embed/token/embedding",
                               TRANSFORMER_TP_RULES, mesh)) == ("model", None)
    assert tuple(spec_for_path("z/head/kernel",
                               TRANSFORMER_TP_RULES, mesh)) == (None, "model")
    # unmatched -> replicated
    assert tuple(spec_for_path("some/unknown/param",
                               TRANSFORMER_TP_RULES, mesh)) == ()
    # uneven dims degrade to replicated instead of failing
    params = {"x": {"qkv": {"kernel": jnp.zeros((8, 6))}}}  # 6 % 4 != 0
    sh = build_param_shardings(params, mesh, TRANSFORMER_TP_RULES)
    assert tuple(sh["x"]["qkv"]["kernel"].spec) == (None, None)


def test_trainer_dp_sp_tp(rng):
    """Full training step over a data×seq×model mesh with ring attention
    and Megatron-style param sharding — the multi-chip north star shape."""
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

    mesh_axes = {"data": 2, "seq": 2, "model": 2}
    mesh = make_mesh(mesh_axes)
    graph = build_model(
        "transformer_lm", vocab_size=32, d_model=16, heads=4, depth=1,
        max_len=8, attn_impl="ring", mesh=mesh,
    )
    x = rng.integers(0, 32, size=(8, 8)).astype(np.int32)
    y = np.roll(x, -1, axis=1)
    trainer = SPMDTrainer(
        graph,
        TrainConfig(
            epochs=2, batch_size=4, learning_rate=1e-2, mesh_axes=mesh_axes,
            param_rules=TRANSFORMER_TP_RULES, log_every=1, shuffle=False,
        ),
    )
    variables = trainer.train(x, y)
    losses = [h["loss"] for h in trainer.history if "loss" in h]
    assert losses and all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # it actually learns
    out = graph.apply(variables, jnp.asarray(x[:2]))
    assert out.shape == (2, 8, 32)


def test_ulysses_flash_inner_matches_dense(rng, monkeypatch):
    """The REAL TPU branch of _ulysses_inner must agree with dense: the
    backend check is monkeypatched to take the flash path and the flash
    kernel forced into interpret mode (its compiled/interpreted bodies are
    identical), so the exact code path that runs on TPU executes here."""
    from functools import partial

    import jax

    import mmlspark_tpu.ops.flash_attention as fa
    import mmlspark_tpu.parallel.context_parallel as cp
    from mmlspark_tpu.parallel.mesh import make_mesh

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        fa, "flash_attention",
        partial(fa.flash_attention, block=16, interpret=True),
    )
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 32, 8, 8)), jnp.float32)
        for _ in range(3)
    )
    mesh = make_mesh({"seq": 8})
    got = np.asarray(cp.ulysses_attention(q, k, v, mesh, causal=True))
    want = np.asarray(dense_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_and_ulysses_sliding_window_match_dense():
    """window threads through both sequence-parallel paths: each shard's
    block masks reproduce the dense windowed function exactly."""
    mesh = make_mesh({"seq": 4})
    rng = np.random.default_rng(9)
    S, W = 32, 9
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, S, 4, 8)), jnp.float32)
        for _ in range(3)
    )
    want = np.asarray(dense_attention(q, k, v, causal=True, window=W))
    got_ring = np.asarray(
        ring_attention(q, k, v, mesh, causal=True, window=W)
    )
    got_uly = np.asarray(
        ulysses_attention(q, k, v, mesh, causal=True, window=W)
    )
    np.testing.assert_allclose(got_ring, want, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(got_uly, want, atol=1e-5, rtol=1e-5)


def test_ring_window_step_bound():
    """Windowed ring attention drops whole rotations: the live-rotation
    count is independent of device index and O(window / chunk)."""
    from mmlspark_tpu.parallel.context_parallel import _ring_window_steps

    # no window / non-causal: every rotation runs
    assert _ring_window_steps(8, 16, None, True) == 8
    assert _ring_window_steps(8, 16, 64, False) == 8
    # window inside one chunk: the own chunk + one older neighbor
    assert _ring_window_steps(8, 16, 1, True) == 1
    assert _ring_window_steps(8, 16, 16, True) == 2
    # window spanning chunks; never exceeds n. window=17 from the oldest
    # query row (pos i*c) reaches pos i*c - 16: still chunk i-1 -> 2
    # rotations; 18 reaches i*c - 17: chunk i-2 -> 3
    assert _ring_window_steps(8, 16, 17, True) == 2
    assert _ring_window_steps(8, 16, 18, True) == 3
    assert _ring_window_steps(8, 16, 1000, True) == 8


@pytest.mark.parametrize("window", [1, 5, 8, 9, 24])
def test_ring_window_skipped_rotations_exact(window):
    """Correctness across the skip boundary: windows smaller than, equal
    to, and spanning the per-device chunk (S=32 over 4 devices -> chunk
    8) all reproduce the dense windowed function."""
    mesh = make_mesh({"seq": 4})
    rng = np.random.default_rng(15)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 32, 4, 8)), jnp.float32)
        for _ in range(3)
    )
    want = np.asarray(dense_attention(q, k, v, causal=True, window=window))
    got = np.asarray(
        ring_attention(q, k, v, mesh, causal=True, window=window)
    )
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_ring_window_gradients_match_dense():
    """Differentiability through the TRUNCATED scan (n_steps < n): a
    broken transpose of the shortened rotation loop would surface here."""
    mesh = make_mesh({"seq": 4})
    rng = np.random.default_rng(16)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 32, 4, 8)), jnp.float32)
        for _ in range(3)
    )
    g = jnp.asarray(rng.normal(size=q.shape), jnp.float32)
    W = 9  # 2 of 4 rotations live

    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            ring_attention(q, k, v, mesh, causal=True, window=W) * g),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(
            dense_attention(q, k, v, causal=True, window=W) * g),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )
