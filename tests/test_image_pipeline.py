"""Image pipeline tests: ImageTransformer op semantics, UnrollImage,
ImageFeaturizer headless features, ImageSetAugmenter (reference analog:
ImageTransformerSuite, ImageFeaturizerSuite)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.schema import ImageRow
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models import build_model
from mmlspark_tpu.ops import image_ops
from mmlspark_tpu.stages.dnn_model import TPUModel
from mmlspark_tpu.stages.image import (
    ImageFeaturizer,
    ImageSetAugmenter,
    ImageTransformer,
    UnrollImage,
)


def _img(h=8, w=6, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (h, w, 3), dtype=np.uint8)


def _image_ds(n=3, h=8, w=6):
    rows = [ImageRow(path=f"img{i}", data=_img(h, w, seed=i)) for i in range(n)]
    return Dataset({"image": rows, "idx": np.arange(n)})


# -- op semantics ------------------------------------------------------------


def test_resize_shapes_and_identity():
    img = _img(8, 6)
    out = image_ops.resize(img, 16, 12)
    assert out.shape == (16, 12, 3) and out.dtype == np.uint8
    same = image_ops.resize(img, 8, 6)
    np.testing.assert_array_equal(same, img)


def test_crop_bounds():
    img = _img(8, 6)
    out = image_ops.crop(img, 1, 2, 4, 3)
    np.testing.assert_array_equal(out, img[2:6, 1:4])
    with pytest.raises(FriendlyError):
        image_ops.crop(img, 4, 4, 10, 10)


def test_gray_uses_bgr_weights():
    img = np.zeros((2, 2, 3), np.uint8)
    img[..., 2] = 100  # pure red in BGR
    gray = image_ops.color_format(img, "gray")
    assert gray.shape == (2, 2, 1)
    assert abs(int(gray[0, 0, 0]) - 30) <= 1  # 0.299 * 100


def test_blur_constant_invariant():
    img = np.full((6, 6, 3), 77, np.uint8)
    np.testing.assert_array_equal(image_ops.blur(img, 3, 3), img)
    out = image_ops.gaussian_kernel(img.astype(np.uint8), 5, 1.2)
    np.testing.assert_array_equal(out, img)


def test_threshold_kinds():
    img = np.array([[[10, 100, 200]]], np.uint8)
    assert list(image_ops.threshold(img, 99, 255, "binary")[0, 0]) == [0, 255, 255]
    assert list(image_ops.threshold(img, 99, 255, "trunc")[0, 0]) == [10, 99, 99]
    assert list(image_ops.threshold(img, 99, 255, "tozero")[0, 0]) == [0, 100, 200]


def test_flip_codes():
    img = _img(4, 4)
    np.testing.assert_array_equal(image_ops.flip(img, 1), img[:, ::-1])
    np.testing.assert_array_equal(image_ops.flip(img, 0), img[::-1])
    np.testing.assert_array_equal(image_ops.flip(img, -1), img[::-1, ::-1])


# -- ImageTransformer stage --------------------------------------------------


def test_transformer_pipeline_and_round_trip(tmp_path):
    ds = _image_ds()
    t = ImageTransformer().resize(12, 10).crop(1, 1, 8, 8).flip(1)
    out = t.transform(ds)
    assert all(r.data.shape == (8, 8, 3) for r in out["image"])
    t.save(str(tmp_path / "it"))
    loaded = PipelineStage.load(str(tmp_path / "it"))
    out2 = loaded.transform(ds)
    np.testing.assert_array_equal(out["image"][0].data, out2["image"][0].data)


def test_transformer_accepts_binary_and_drops_bad():
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(_img(5, 5)[:, :, ::-1]).save(buf, "PNG")
    ds = Dataset({"image": [buf.getvalue(), b"garbage bytes here"],
                  "tag": ["good", "bad"]})
    out = ImageTransformer().resize(4, 4).transform(ds)
    assert out.num_rows == 1 and out["tag"][0] == "good"


def test_unknown_op_rejected():
    ds = _image_ds(1)
    t = ImageTransformer()
    t.stages = [{"op": "sharpen"}]
    with pytest.raises(FriendlyError):
        t.transform(ds)


# -- UnrollImage -------------------------------------------------------------


def test_unroll_chw_layout():
    img = _img(2, 3)
    ds = Dataset({"image": [ImageRow("p", img)]})
    out = UnrollImage().transform(ds)
    vec = out["unrolled"][0]
    assert vec.shape == (2 * 3 * 3,)
    # CHW: first H*W entries are channel 0 (B plane), row-major
    np.testing.assert_array_equal(
        vec[: 2 * 3], img[:, :, 0].reshape(-1).astype(np.float64)
    )


def test_unroll_requires_uniform_sizes():
    ds = Dataset({"image": [ImageRow("a", _img(2, 2)), ImageRow("b", _img(3, 3))]})
    with pytest.raises(FriendlyError):
        UnrollImage().transform(ds)


# -- ImageFeaturizer ---------------------------------------------------------


@pytest.fixture(scope="module")
def resnet_stage():
    g = build_model("resnet20_cifar10", width=8)
    v = g.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    return TPUModel.from_graph(
        g, v, "resnet20_cifar10", model_config={"width": 8},
        input_col="image", output_col="scores",
    )


def test_featurizer_headless_features(resnet_stage):
    ds = _image_ds(n=4, h=20, w=30)  # wrong size on purpose -> auto-resize
    feats = ImageFeaturizer(model=resnet_stage, cut_output_layers=1).transform(ds)
    assert feats["features"].shape == (4, 32)  # pool features, width 8 * 4
    assert list(feats["idx"]) == [0, 1, 2, 3]
    scores = ImageFeaturizer(model=resnet_stage, cut_output_layers=0).transform(ds)
    assert scores["features"].shape == (4, 10)


def test_featurizer_cut_out_of_range(resnet_stage):
    with pytest.raises(FriendlyError):
        ImageFeaturizer(model=resnet_stage, cut_output_layers=99).transform(
            _image_ds(1)
        )


# -- ImageSetAugmenter -------------------------------------------------------


def test_augmenter_unions_flips():
    ds = _image_ds(n=2)
    out = ImageSetAugmenter(flip_left_right=True, flip_up_down=True).transform(ds)
    assert out.num_rows == 6
    orig = ds["image"][0].data
    lr = out["image"][2].data
    np.testing.assert_array_equal(lr, orig[:, ::-1])


def test_typoed_op_param_surfaces_error():
    ds = _image_ds(1)
    t = ImageTransformer()
    t.stages = [{"op": "crop", "x": 0, "hight": 5, "width": 5}]  # typo
    with pytest.raises(FriendlyError):
        t.transform(ds)


def test_image_transformer_all_rows_failing_raises():
    """Per-row containment drops corrupt rows, but EVERY row failing is
    systemic (dead backend, bad op config reaching runtime) and must
    surface as a FriendlyError naming the cause, not an empty dataset
    (found via a notebook kernel where jax had no usable backend)."""
    import pytest

    from mmlspark_tpu.core.exceptions import FriendlyError
    from mmlspark_tpu.core.schema import ImageRow
    from mmlspark_tpu.stages.image import ImageTransformer

    ds = Dataset({
        "image": [ImageRow(path=str(i), data=np.zeros((8, 8, 3), np.uint8))
                  for i in range(3)],
    })
    t = ImageTransformer(input_col="image", output_col="out").resize(4, 4)
    boom = lambda img, *a: (_ for _ in ()).throw(RuntimeError("backend dead"))
    t._compile_ops = lambda: [(boom, [])]
    with pytest.raises(
        FriendlyError, match="all 3 rows that reached the op pipeline"
    ):
        t.transform(ds)

    # rows dropped at DECODE never reach the op pipeline and must not be
    # counted as op failures; the message reports both tallies
    ds_mixed = Dataset({
        "image": [
            b"not an image", b"also not an image",
            ImageRow(path="ok", data=np.zeros((8, 8, 3), np.uint8)),
        ],
    })
    t3 = ImageTransformer(input_col="image", output_col="out").resize(4, 4)
    t3._compile_ops = lambda: [(boom, [])]
    with pytest.raises(
        FriendlyError,
        match=r"all 1 rows that reached the op pipeline.*2 dropped at decode",
    ):
        t3.transform(ds_mixed)

    # one corrupt row among good ones still degrades to a drop
    t2 = ImageTransformer(input_col="image", output_col="out").resize(4, 4)
    real = t2._compile_ops()
    calls = {"n": 0}
    def flaky(img, *a):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("corrupt row")
        return real[0][0](img, *real[0][1])
    t2._compile_ops = lambda: [(flaky, [])]
    out = t2.transform(ds)
    assert out.num_rows == 2
