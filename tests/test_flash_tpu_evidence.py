"""Flash-kernel TPU evidence (VERDICT r3 missing #3).

Two layers of proof that the Pallas flash kernels are real TPU kernels,
not interpreter-only constructs:

- on a real TPU backend, run a compiled (interpret=False) numerics check
  directly (skipped on the CPU test mesh — the unit suite covers the same
  code path in interpreter mode);
- whenever a committed ``FLASH_TPU_EVIDENCE.json`` exists (produced by
  ``tools/flash_tpu_evidence.py`` on the chip), validate its contract:
  compiled mode, bf16 tolerances met for forward and all three grads in
  both masking modes, and a non-empty block-sweep timing table.
"""

import json
import os

import jax
import numpy as np
import pytest

_EVIDENCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "FLASH_TPU_EVIDENCE.json",
)


def _on_tpu() -> bool:
    from mmlspark_tpu.core.env import is_tpu

    return is_tpu()


@pytest.mark.skipif(
    not _on_tpu(),
    reason="compiled flash kernels need the real chip; the CPU mesh "
    "exercises the same kernels in interpreter mode",
)
def test_flash_compiled_matches_reference_on_tpu():
    import jax.numpy as jnp

    from mmlspark_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 512, 4, 64)), jnp.bfloat16)
        for _ in range(3)
    )
    out = np.asarray(
        jax.jit(lambda q, k, v: flash_attention(q, k, v, interpret=False))(
            q, k, v
        ),
        np.float32,
    )
    qf, kf, vf = (np.asarray(t, np.float32) for t in (q, k, v))
    s = np.einsum("bqhd,bkhd->bhqk", qf, kf) * (64 ** -0.5)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", p, vf)
    assert float(np.max(np.abs(out - want))) <= 1e-2


@pytest.mark.skipif(
    not os.path.exists(_EVIDENCE),
    reason="no committed FLASH_TPU_EVIDENCE.json yet (tunnel never "
    "healthy in-session); produced by tools/flash_tpu_evidence.py",
)
def test_flash_tpu_evidence_artifact_contract():
    with open(_EVIDENCE, encoding="utf-8") as f:
        ev = json.load(f)
    assert ev["compiled"] is True and ev["interpret_mode"] is False
    assert "tpu" in ev["device_kind"].lower() or "v5" in ev["device_kind"]
    # the gate is SCALE-NORMALIZED error (max abs err / max(1, max|want|)):
    # both the kernel's bf16 output and the XLA reference's MXU matmuls
    # carry precision relative to magnitude, and causal attention emits
    # O(3) magnitudes in early rows — see _scaled_err in the tool.
    tol = ev["tolerance"]
    for mode in ("full", "causal"):
        n = ev["numerics"][mode]
        assert n["fwd_scaled_err"] <= tol
        assert n["fwd_max_abs_err"] > 0  # recorded raw, not gated
        for key in ("dq", "dk", "dv"):
            assert n[f"{key}_scaled_err"] <= tol
    # present in artifacts recorded after sliding-window + GQA landed
    if "window_gqa" in ev["numerics"]:
        wg = ev["numerics"]["window_gqa"]
        assert wg["fwd_scaled_err"] <= tol
        assert wg["window"] >= 1 and wg["kv_heads"] >= 1
        for key in ("dq", "dk", "dv"):
            assert wg[f"{key}_scaled_err"] <= tol
    blocks = {k: t for k, t in ev["timing"].items()
              if k.startswith("block_")}
    assert blocks, "block sweep missing"
    for blk, t in blocks.items():
        assert t["fwd_ms"] > 0 and t["fwd_bwd_ms"] > 0, blk
    # present only in artifacts recorded after the scan-chained timing
    # harness landed (per-call walls over the axon relay measure tunnel
    # latency, not the kernel; the chained harness amortizes it out)
    if "xla_reference" in ev["timing"]:
        assert ev["timing"]["xla_reference"]["fwd_ms"] > 0
        for blk, t in blocks.items():
            assert t["vs_xla_fwd_speedup"] > 0, blk
