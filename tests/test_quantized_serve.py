"""Quantized decode hot path (ISSUE 11 tentpole).

The contract under test (docs/PERFORMANCE.md "Quantized decode"):
``kv_dtype="int8"`` swaps the pools' bf16 K/V slabs for int8 stores
plus f32 quantization scales — per-(slot, kv-head) in the dense pool,
per-(page, kv-head) in the paged pool — and the flash-decode kernels
dequantize in-VMEM off the scalar-prefetch channel, so HBM streams
half the bytes while the online-softmax math stays f32. NOTHING the
serving engine guarantees moves: compile-count pins, one host sync per
block, page accounting, prefix-cache copy-on-extend (which must copy
scales WITH pages), and freed leases reset their scale state. The bf16
dense pool stays the accuracy oracle: parity is a token-flip budget,
not bit-identity. Runs on the 8 virtual CPU devices
``tests/conftest.py`` forces.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.models import build_model
from mmlspark_tpu.ops.flash_attention import flash_decode, paged_flash_decode
from mmlspark_tpu.ops.quantize import kv_cache_bytes
from mmlspark_tpu.serve import ServeEngine
from mmlspark_tpu.serve.cache_pool import (
    SlotCachePool,
    kv_head_scales,
    quantize_kv,
    validate_kv_dtype,
)
from mmlspark_tpu.serve.paging import PagedCachePool
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4

#: accepted greedy-stream divergence vs the bf16 oracle at smoke scale:
#: one int8 rounding flip near an argmax tie cascades for the rest of
#: the stream (greedy decode re-feeds its own tokens), so the budget
#: prices the cascade, not per-token error
FLIP_BUDGET = 0.25


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def raw_lm():
    """Random-init model — enough for pool/accounting/validation
    tests, which never compare token streams."""
    m = _tiny()
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    return m, v


@pytest.fixture(scope="module")
def lm():
    """Trained model for the parity soaks: confident logits make the
    flip budget meaningful instead of measuring argmax ties."""
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    m = _tiny()
    v, ids = overfit_periodic_lm(m, steps=30, seq=16, period=PERIOD)
    return m, v, ids


def _flip_rate(streams_a: dict, streams_b: dict) -> float:
    flips = total = 0
    for key in streams_a:
        a, b = list(streams_a[key]), list(streams_b[key])
        n = min(len(a), len(b))
        flips += sum(x != y for x, y in zip(a[:n], b[:n]))
        flips += abs(len(a) - len(b))  # early-EOS divergence counts
        total += max(len(a), len(b))
    return flips / max(total, 1)


def _fake_linear_cache(pool, length, seed=0):
    """A synthetic batch-1 linear cache matching ``write_prefill``'s
    input — deterministic values so quantize/dequantize round-trips
    are content-checkable without a model."""
    rng = np.random.default_rng(seed)
    cache = {}
    paged = isinstance(pool, PagedCachePool)
    for name, entry in pool.buffers.items():
        pk = entry[0]
        # paged stores are (num_pages, hk, page_size, d); dense slabs
        # are (slots, cache_len, hk, d)
        hk = pk.shape[1] if paged else pk.shape[2]
        d = pk.shape[3]
        k = rng.normal(size=(1, length, hk, d)).astype(np.float32)
        v = rng.normal(size=(1, length, hk, d)).astype(np.float32)
        cache[name] = (jnp.asarray(k, jnp.bfloat16),
                       jnp.asarray(v, jnp.bfloat16))
    return cache


# -- validation ------------------------------------------------------------


def test_kv_dtype_validation():
    with pytest.raises(FriendlyError, match="kv_dtype"):
        validate_kv_dtype("fp8", {"b0": (2, 16)})
    # int8 packs VREG lanes pairwise: head_dim must be even
    with pytest.raises(FriendlyError, match="even"):
        validate_kv_dtype("int8", {"b0": (2, 15)})
    validate_kv_dtype("int8", {"b0": (2, 16)})  # fine
    validate_kv_dtype("bf16", {"b0": (2, 15)})  # bf16 never restricted


def test_engine_rejects_bad_kv_dtype(raw_lm):
    m, v = raw_lm
    with pytest.raises(FriendlyError, match="kv_dtype"):
        ServeEngine(m, v, slots=2, cache_len=32, kv_dtype="int4")


def test_run_demo_rejects_odd_head_dim():
    """The CLI surface: ``serve --kv-dtype int8`` on a model whose
    head_dim is odd must die with a FriendlyError at build time, not a
    kernel shape error mid-decode."""
    from mmlspark_tpu.serve.demo import run_demo

    with pytest.raises(FriendlyError, match="even"):
        run_demo(slots=2, n_requests=1, max_new_tokens=2, d_model=30,
                 heads=2, cache_len=32, kv_dtype="int8")


# -- kernel parity ---------------------------------------------------------


def test_flash_decode_int8_parity():
    """The dense int8 kernel against the bf16 kernel on identical
    tensors: dequantizing through per-(row, kv-head) scales in-VMEM
    must land within the quantization error budget."""
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    b, L, h, hk, d = 4, 32, 2, 2, 16
    q = jax.random.normal(keys[0], (b, 1, h, d), jnp.bfloat16)
    k = jax.random.normal(keys[1], (b, L, hk, d), jnp.bfloat16)
    v = jax.random.normal(keys[2], (b, L, hk, d), jnp.bfloat16)
    lengths = jnp.asarray([32, 17, 8, 1], jnp.int32)
    ks = kv_head_scales(k, axes=(1, 3))  # (b, hk)
    vs = kv_head_scales(v, axes=(1, 3))
    qk = quantize_kv(k, ks[:, None, :])
    qv = quantize_kv(v, vs[:, None, :])
    ref = flash_decode(q, k, v, lengths)
    got = flash_decode(q, qk, qv, lengths, k_scale=ks, v_scale=vs)
    assert got.dtype == ref.dtype
    err = float(jnp.max(jnp.abs(
        ref.astype(jnp.float32) - got.astype(jnp.float32))))
    assert err <= 0.0625, f"int8 dense decode error {err}"


def test_flash_decode_int8_requires_scales():
    b, L, h, d = 2, 16, 2, 16
    q = jnp.zeros((b, 1, h, d), jnp.bfloat16)
    k = jnp.zeros((b, L, h, d), jnp.int8)
    lengths = jnp.full((b,), L, jnp.int32)
    with pytest.raises(ValueError, match="scale"):
        flash_decode(q, k, k, lengths)


def test_paged_flash_decode_int8_parity():
    """The paged int8 kernel against the paged bf16 kernel: page faces
    dequantize through their PER-PAGE scales, scatter layout and page
    indirection identical on both sides."""
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    b, h, hk, d, ps, max_pages = 3, 2, 2, 16, 8, 4
    L = ps * max_pages
    num_pages = b * max_pages
    q = jax.random.normal(keys[0], (b, 1, h, d), jnp.bfloat16)
    k = jax.random.normal(keys[1], (b, L, hk, d), jnp.bfloat16)
    v = jax.random.normal(keys[2], (b, L, hk, d), jnp.bfloat16)
    lengths = jnp.asarray([32, 19, 6], jnp.int32)
    # unique physical page per (row, logical page); stores hold the
    # linear cache re-laid-out as (num_pages, hk, page_size, d)
    pt = jnp.arange(num_pages, dtype=jnp.int32).reshape(b, max_pages)
    kp = k.reshape(b, max_pages, ps, hk, d).transpose(0, 1, 3, 2, 4)
    vp = v.reshape(b, max_pages, ps, hk, d).transpose(0, 1, 3, 2, 4)
    kp = kp.reshape(num_pages, hk, ps, d)
    vp = vp.reshape(num_pages, hk, ps, d)
    ks = kv_head_scales(kp, axes=(2, 3))  # (num_pages, hk)
    vs = kv_head_scales(vp, axes=(2, 3))
    qkp = jnp.clip(jnp.round(
        kp.astype(jnp.float32) / ks[:, :, None, None]
    ), -127, 127).astype(jnp.int8)
    qvp = jnp.clip(jnp.round(
        vp.astype(jnp.float32) / vs[:, :, None, None]
    ), -127, 127).astype(jnp.int8)
    ref = paged_flash_decode(q, kp, vp, lengths, pt)
    got = paged_flash_decode(q, qkp, qvp, lengths, pt,
                             k_scale=ks, v_scale=vs)
    assert got.dtype == ref.dtype
    err = float(jnp.max(jnp.abs(
        ref.astype(jnp.float32) - got.astype(jnp.float32))))
    assert err <= 0.0625, f"int8 paged decode error {err}"


# -- pool scale-state lifecycle --------------------------------------------


def test_dense_free_resets_scales(raw_lm):
    """A freed dense lease returns its quantization scales to the 1.0
    init — quarantine/preemption must not leak one tenant's
    calibration into the next."""
    m, v = raw_lm
    pool = SlotCachePool(m, v, slots=2, cache_len=32, kv_dtype="int8")
    cache = _fake_linear_cache(pool, 8)
    slot = pool.lease()
    pool.write_prefill(slot, cache, 8)
    for _k, _v, ks, vs in pool.buffers.values():
        assert not np.allclose(np.asarray(ks[slot]), 1.0)
        assert not np.allclose(np.asarray(vs[slot]), 1.0)
    pool.free(slot)
    for _k, _v, ks, vs in pool.buffers.values():
        np.testing.assert_allclose(np.asarray(ks[slot]), 1.0)
        np.testing.assert_allclose(np.asarray(vs[slot]), 1.0)


def test_paged_free_returns_pages_int8(raw_lm):
    m, v = raw_lm
    pool = PagedCachePool(m, v, slots=2, cache_len=32, kv_dtype="int8")
    assert pool.snapshot()["kv_dtype"] == "int8"
    slot = pool.lease()
    pool.write_prefill(slot, _fake_linear_cache(pool, 12), 12)
    assert pool.pages_free < pool.pages_allocatable
    pool.free(slot)
    assert pool.pages_free == pool.pages_allocatable


def test_gather_prefix_int8_roundtrip(raw_lm):
    """write_prefill quantizes into pages; gather_prefix dequantizes
    back to a linear bf16 cache — the round trip must reproduce the
    source within the per-page quantization budget."""
    m, v = raw_lm
    pool = PagedCachePool(m, v, slots=2, cache_len=32, kv_dtype="int8",
                          prefix_cache=True)
    length = 12  # page 0 full, page 1 partial
    cache = _fake_linear_cache(pool, length, seed=3)
    slot = pool.lease()
    seq = np.arange(length, dtype=np.int32) % 8
    pool.write_prefill(slot, cache, length)
    pool.prefix_insert(slot, seq)
    entry = pool._prefix[seq.tobytes()]
    out = pool.gather_prefix(entry, length)
    for name, (gk, gv) in out.items():
        assert gk.dtype == jnp.bfloat16
        for got, src in ((gk, cache[name][0]), (gv, cache[name][1])):
            np.testing.assert_allclose(
                np.asarray(got[0, :length], np.float32),
                np.asarray(src[0, :length], np.float32),
                atol=0.06, err_msg=f"block={name}",
            )
    pool.free(slot)


def test_copy_on_extend_copies_scales(raw_lm):
    """A CoW-privatized page is only faithful WITH its quantization
    scales: the copy must land the source page's scale rows on the new
    physical page, and a mid-page resume keeps the registered scale
    (the already-written half decodes through it)."""
    m, v = raw_lm
    pool = PagedCachePool(m, v, slots=2, cache_len=32, kv_dtype="int8",
                          prefix_cache=True)
    ps = pool.page_size
    length = ps + 4  # page 1 shared AND partial
    seq = np.arange(length, dtype=np.int32) % 8
    s0 = pool.lease()
    pool.write_prefill(s0, _fake_linear_cache(pool, length, seed=5), length)
    pool.prefix_insert(s0, seq)
    pool.free(s0)
    entry = pool._prefix[seq.tobytes()]
    s1 = pool.lease()
    assert pool.map_prefix(s1, entry, length)
    shared_phys = int(pool._pt_host[s1, 1])
    name0 = next(iter(pool.buffers))
    want_ks = np.asarray(pool.buffers[name0][3][shared_phys])
    # the resume's write frontier enters the shared partial page
    pool.write_prefill(
        s1, _fake_linear_cache(pool, 2 * ps, seed=6), 2 * ps, start=length
    )
    assert pool.cow_copies == 1
    new_phys = int(pool._pt_host[s1, 1])
    assert new_phys != shared_phys
    np.testing.assert_allclose(
        np.asarray(pool.buffers[name0][3][new_phys]), want_ks,
        err_msg="CoW must carry the source page's k-scales",
    )
    # the entry's original page kept ITS scales too
    np.testing.assert_allclose(
        np.asarray(pool.buffers[name0][3][shared_phys]), want_ks)
    pool.free(s1)


# -- accounting ------------------------------------------------------------


def test_kv_cache_bytes_and_metrics(raw_lm):
    """int8 pools report ~half the bf16 baseline (scale leaves cost a
    few percent back) and the engine's metrics carry kv_dtype + the
    smaller per-device figure."""
    m, v = raw_lm
    bf16 = ServeEngine(m, v, slots=2, cache_len=32)
    int8 = ServeEngine(m, v, slots=2, cache_len=32, kv_dtype="int8")
    stored, baseline = kv_cache_bytes(int8.pool.buffers)
    assert stored < baseline
    assert baseline > 1.6 * stored  # ~2x minus the scale-leaf overhead
    d8, d16 = int8.metrics.to_dict(), bf16.metrics.to_dict()
    assert d8["kv_dtype"] == "int8" and d16["kv_dtype"] == "bf16"
    assert (d8["cache_pool_bytes_per_device"]
            < d16["cache_pool_bytes_per_device"])


# -- engine parity vs the bf16 oracle --------------------------------------


def _drive(m, v, prompts, budgets, **kw):
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=16, **kw)
    streams, rids, results = {}, [], {}
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        for i, (p, n) in enumerate(zip(prompts, budgets)):
            rids.append(engine.submit(p, max_new_tokens=n))
            if i % 2:
                results.update({r.id: r for r in engine.step()})
        results.update(engine.run())
    for i, rid in enumerate(rids):
        streams[i] = list(np.asarray(results[rid].tokens)[len(prompts[i]):])
    assert engine.decode_compile_count <= engine.num_decode_blocks
    assert engine.prefill_compile_count <= engine.num_prefill_buckets
    return engine, streams


@pytest.mark.slow  # ci.sh's int8 gate runs the full file unfiltered
def test_dense_engine_int8_within_flip_budget(lm):
    m, v, ids = lm
    lengths = [4, 1, 12, 7, 8, 3]
    prompts = [np.asarray(ids[0, :n]) for n in lengths]
    budgets = [6] * len(prompts)
    _, oracle = _drive(m, v, prompts, budgets)
    eng, got = _drive(m, v, prompts, budgets, kv_dtype="int8")
    rate = _flip_rate(oracle, got)
    assert rate <= FLIP_BUDGET, f"dense int8 flip rate {rate}"
    # drained engine returned every slot, scales reset with them
    for _k, _v, ks, vs in eng.pool.buffers.values():
        np.testing.assert_allclose(np.asarray(ks), 1.0)


@pytest.mark.slow  # ci.sh's int8 gate runs the full file unfiltered
def test_paged_engine_int8_within_flip_budget(lm):
    m, v, ids = lm
    lengths = [4, 9, 2, 12, 6, 3]
    prompts = [np.asarray(ids[0, :n]) for n in lengths]
    budgets = [5] * len(prompts)
    _, oracle = _drive(m, v, prompts, budgets)
    eng, got = _drive(m, v, prompts, budgets, kv_dtype="int8",
                      paged=True)
    rate = _flip_rate(oracle, got)
    assert rate <= FLIP_BUDGET, f"paged int8 flip rate {rate}"
    assert eng.pool.pages_free == eng.pool.pages_allocatable


@pytest.mark.slow  # ci.sh's int8 gate runs the full file unfiltered
def test_quantized_weights_engine_parity(lm):
    """Weight-only int8 on top of int8 KV — the full quantized hot
    path — still lands inside the flip budget and keeps the pins."""
    m, v, ids = lm
    prompts = [np.asarray(ids[0, :n]) for n in (4, 8, 3, 11)]
    budgets = [6] * len(prompts)
    _, oracle = _drive(m, v, prompts, budgets)
    _, got = _drive(m, v, prompts, budgets, kv_dtype="int8",
                    quantize_weights=True)
    rate = _flip_rate(oracle, got)
    assert rate <= FLIP_BUDGET, f"quantized-weights flip rate {rate}"


@pytest.mark.slow  # ci.sh's int8 gate runs the full file unfiltered
def test_mesh_soak_int8_2x2(lm):
    """The sharded soak: bf16 and int8 paged engines on the SAME 2x2
    (data, model) mesh, same raggedy traffic with mid-run joins —
    stream divergence inside the flip budget, compile pins intact,
    pages drained, and the int8 pool's per-device bytes strictly under
    the bf16 pool's."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [np.asarray(p, np.int32)
               for p in (row[:4], row[:9], row[:2], row[:11], row[:6])]
    budgets = [6, 5, 4, 6, 5]

    def drive(**kw):
        engine = ServeEngine(m, v, slots=4, cache_len=32, max_queue=8,
                             decode_block=4, mesh="data=2,model=2",
                             paged=True, num_pages=24, **kw)
        streams, rids = {}, []
        with serve_compile_guard(engine, min_decode=1, min_prefill=1):
            for p, n in zip(prompts[:3], budgets[:3]):
                rids.append(engine.submit(p, max_new_tokens=n))
            results = {}
            for _ in range(2):
                results.update({r.id: r for r in engine.step()})
            for p, n in zip(prompts[3:], budgets[3:]):  # mid-run joins
                rids.append(engine.submit(p, max_new_tokens=n))
            while engine.busy:
                results.update({r.id: r for r in engine.step()})
        for i, rid in enumerate(rids):
            streams[i] = list(
                np.asarray(results[rid].tokens)[len(prompts[i]):])
        return engine, streams

    bf16_eng, oracle = drive()
    int8_eng, got = drive(kv_dtype="int8")
    rate = _flip_rate(oracle, got)
    assert rate <= FLIP_BUDGET, f"2x2 mesh int8 flip rate {rate}"
    assert int8_eng.decode_compile_count <= int8_eng.num_decode_blocks
    assert (int8_eng.pool.device_bytes_per_device()
            < bf16_eng.pool.device_bytes_per_device())
    assert int8_eng.pool.pages_free == int8_eng.pool.pages_allocatable
    assert int8_eng.metrics.to_dict()["kv_dtype"] == "int8"
