"""Classical-ML layer tests: ValueIndexer, Featurize, TrainClassifier,
TrainRegressor, evaluators, FindBestModel, TextFeaturizer (reference analog:
VerifyTrainClassifier/VerifyComputeModelStatistics/VerifyFeaturize suites)."""

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.eval_metrics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    binary_auc,
)
from mmlspark_tpu.stages.featurize import AssembleFeatures, Featurize
from mmlspark_tpu.stages.find_best import FindBestModel
from mmlspark_tpu.stages.text import TextFeaturizer
from mmlspark_tpu.stages.train_classifier import TrainClassifier
from mmlspark_tpu.stages.train_regressor import TrainRegressor
from mmlspark_tpu.stages.value_indexer import IndexToValue, ValueIndexer


# -- ValueIndexer ------------------------------------------------------------


def test_value_indexer_round_trip():
    ds = Dataset({"cat": ["b", "a", None, "b", "c"]})
    model = ValueIndexer(input_col="cat", output_col="idx").fit(ds)
    assert model.levels == ["a", "b", "c"] and model.has_null
    out = model.transform(ds)
    assert list(out["idx"]) == [1, 0, 3, 1, 2]  # null -> trailing index
    back = IndexToValue(input_col="idx", output_col="orig").transform(out)
    assert list(back["orig"]) == ["b", "a", None, "b", "c"]


def test_value_indexer_unseen_level():
    model = ValueIndexer(input_col="cat", output_col="idx").fit(
        Dataset({"cat": ["a", "b"]})
    )
    with pytest.raises(FriendlyError):
        model.transform(Dataset({"cat": ["z"]}))


def test_value_indexer_numeric_levels():
    ds = Dataset({"n": np.array([30, 10, 20, 10])})
    model = ValueIndexer(input_col="n", output_col="idx").fit(ds)
    assert model.levels == [10, 20, 30]
    assert list(model.transform(ds)["idx"]) == [2, 0, 1, 0]


# -- Featurize ---------------------------------------------------------------


def test_assemble_features_mixed_types():
    ds = Dataset(
        {
            "num": np.array([1.0, 2.0, 3.0]),
            "text": ["red apple", "green pear", "red pear"],
            "flag": np.array([True, False, True]),
        }
    )
    model = AssembleFeatures(number_of_features=64).fit(ds)
    out = model.transform(ds)
    feats = out["features"]
    # 1 numeric + selected text slots (<=4 distinct tokens) + 1 bool
    assert feats.shape[0] == 3
    assert 4 <= feats.shape[1] <= 6
    assert model.feature_dim == feats.shape[1]


def test_featurize_na_drop():
    ds = Dataset({"num": np.array([1.0, np.nan, 3.0]), "other": [10.0, 20.0, 30.0]})
    out = Featurize().fit(ds).transform(ds)
    assert out.num_rows == 2  # NaN row dropped


def test_featurize_categorical_one_hot():
    ds = Dataset({"cat": ["x", "y", "x"]})
    indexed = ValueIndexer(input_col="cat", output_col="cat_idx").fit(ds).transform(ds)
    sub = indexed.select("cat_idx")
    out = AssembleFeatures().fit(sub).transform(sub)
    np.testing.assert_array_equal(
        out["features"], [[1.0, 0.0], [0.0, 1.0], [1.0, 0.0]]
    )


def test_featurize_datetime():
    ds = Dataset({"ts": np.array(["2017-06-04T10:30:00", "2018-01-01T00:00:00"],
                                 dtype="datetime64[s]")})
    model = AssembleFeatures(standardize=False).fit(ds)
    feats = model.transform(ds)["features"]
    assert feats.shape == (2, 7)
    assert feats[0, 0] == 2017 and feats[1, 2] == 1  # year, month


def test_featurize_standardization():
    ds = Dataset({"num": np.array([10.0, 20.0, 30.0])})
    out = AssembleFeatures().fit(ds).transform(ds)
    col = out["features"][:, 0]
    assert abs(col.mean()) < 1e-12 and abs(col.std() - 1.0) < 1e-12
    raw = AssembleFeatures(standardize=False).fit(ds).transform(ds)
    np.testing.assert_array_equal(raw["features"][:, 0], [10.0, 20.0, 30.0])


# -- TrainClassifier / TrainRegressor ---------------------------------------


def _census_like(n=240, seed=1):
    """Mixed-type classification data (Adult-Census-like shape: numeric +
    categorical strings, string label — notebook 101 config)."""
    rng = np.random.default_rng(seed)
    age = rng.uniform(18, 80, n)
    hours = rng.uniform(10, 60, n)
    edu = rng.choice(["hs", "college", "phd"], n)
    score = (age - 40) / 20 + (hours - 35) / 15 + (edu == "phd") * 1.5
    label = np.where(score + rng.normal(0, 0.4, n) > 0, ">50K", "<=50K")
    return Dataset({
        "age": age, "hours": hours, "education": list(edu),
        "income": list(label),
    })


def test_train_classifier_end_to_end():
    ds = _census_like()
    model = TrainClassifier(label_col="income", epochs=25,
                            learning_rate=5e-2).fit(ds)
    out = model.transform(ds)
    assert set(out["scored_labels"]) <= {">50K", "<=50K"}
    acc = (np.asarray(out["scored_labels"]) ==
           np.asarray(ds["income"])).mean()
    assert acc > 0.8
    probs = out["scored_probabilities"]
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-6)


def test_train_classifier_metadata_enables_zero_config_eval():
    ds = _census_like(n=160)
    model = TrainClassifier(label_col="income", epochs=15,
                            learning_rate=5e-2).fit(ds)
    scored = model.transform(ds)
    stats = ComputeModelStatistics().transform(scored)
    assert "accuracy" in stats.columns and "AUC" in stats.columns
    assert 0.0 <= stats["AUC"][0] <= 1.0
    per = ComputePerInstanceStatistics().transform(scored)
    assert "log_loss" in per.columns and per["log_loss"].min() >= 0


def test_train_classifier_explicit_labels():
    ds = _census_like(n=80)
    model = TrainClassifier(
        label_col="income", labels=[">50K", "<=50K"], epochs=2
    ).fit(ds)
    assert model.levels == [">50K", "<=50K"]


def test_train_regressor_end_to_end():
    rng = np.random.default_rng(0)
    n = 200
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    y = 3 * a - 2 * b + 1
    ds = Dataset({"a": a, "b": b, "target": y})
    model = TrainRegressor(label_col="target", epochs=80,
                           learning_rate=0.05).fit(ds)
    scored = model.transform(ds)
    stats = ComputeModelStatistics().transform(scored)
    assert stats["R^2"][0] > 0.9
    per = ComputePerInstanceStatistics().transform(scored)
    assert "L1_loss" in per.columns and "L2_loss" in per.columns


def test_train_classifier_round_trip(tmp_path):
    ds = _census_like(n=80)
    model = TrainClassifier(label_col="income", epochs=3).fit(ds)
    model.save(str(tmp_path / "tc"))
    loaded = PipelineStage.load(str(tmp_path / "tc"))
    a = model.transform(ds)
    b = loaded.transform(ds)
    assert list(a["scored_labels"]) == list(b["scored_labels"])


# -- FindBestModel -----------------------------------------------------------


def test_find_best_model():
    ds = _census_like(n=160)
    weak = TrainClassifier(label_col="income", epochs=1,
                           learning_rate=1e-4).fit(ds)
    strong = TrainClassifier(label_col="income", epochs=25,
                             learning_rate=5e-2).fit(ds)
    best = FindBestModel(models=[weak, strong]).fit(ds)
    assert best.best_model is strong
    assert best.all_model_metrics.num_rows == 2
    out = best.transform(ds)
    assert "scored_labels" in out.columns


# -- evaluators (unit-level) -------------------------------------------------


def test_binary_auc_perfect_and_random():
    y = np.array([0, 0, 1, 1])
    auc_perfect, roc = binary_auc(y, np.array([0.1, 0.2, 0.8, 0.9]))
    assert auc_perfect > 0.99
    assert roc.shape[1] == 2
    auc_bad, _ = binary_auc(y, np.array([0.9, 0.8, 0.2, 0.1]))
    assert auc_bad < 0.01


def test_compute_stats_requires_metadata(basic_dataset):
    with pytest.raises(Exception):
        ComputeModelStatistics().transform(basic_dataset)


# -- TextFeaturizer ----------------------------------------------------------


def test_text_featurizer_idf():
    ds = Dataset({"text": ["cat sat mat", "cat sat", "dog ran park"] * 3})
    model = TextFeaturizer(input_col="text", output_col="tf",
                           num_features=256).fit(ds)
    out = model.transform(ds)
    assert out["tf"].shape[0] == 9
    # idf: 'cat' (6 docs) must weigh less than 'park' (3 docs)
    assert out["tf"].shape[1] >= 5


def test_text_featurizer_ngram_and_stopwords():
    ds = Dataset({"text": ["the cat sat on the mat", "a dog in the park"]})
    model = TextFeaturizer(
        input_col="text", output_col="tf", remove_stop_words=True,
        use_ngram=True, n_gram_length=2, use_idf=False, num_features=128,
    ).fit(ds)
    out = model.transform(ds)
    # "cat sat", "sat mat", "dog park" bigrams after stopword removal
    assert out["tf"].shape[1] == 3


def test_text_featurizer_pretokenized():
    ds = Dataset({"toks": [["a", "b"], ["b", "c"]]})
    model = TextFeaturizer(input_col="toks", output_col="tf",
                           use_idf=False).fit(ds)
    assert model.transform(ds)["tf"].sum() == 4


def test_auc_alignment_numeric_labels():
    """Numeric labels whose repr-sort differs from value-sort (2.0 vs 10.0):
    AUC must follow the model's level order, not repr order."""
    rng = np.random.default_rng(3)
    n = 200
    x = rng.normal(size=n)
    lab = np.where(x + rng.normal(0, 0.3, n) > 0, 10.0, 2.0)
    ds = Dataset({"x": x, "label": lab})
    model = TrainClassifier(label_col="label", epochs=25,
                            learning_rate=5e-2).fit(ds)
    scored = model.transform(ds)
    stats = ComputeModelStatistics().transform(scored)
    assert stats["AUC"][0] > 0.9  # would be ~1-AUC if misaligned


def test_sequence_mse_loss_respects_kind():
    """3-D logits with loss='mse' must NOT silently switch to softmax."""
    import jax.numpy as jnp

    from mmlspark_tpu.train.trainer import masked_loss

    logits = jnp.ones((2, 3, 1))
    labels = jnp.ones((2, 3))
    loss = masked_loss("mse", logits, labels, jnp.array([True, True]))
    assert float(loss) == 0.0  # perfect predictions -> zero MSE


def test_tokenize_real_csv_missing_values():
    """pandas encodes missing cells of a string column as float NaN;
    tokenize must treat them as empty (found via the real Titanic
    fixture's embarked column) and stringify other non-str scalars."""
    from mmlspark_tpu.utils.text import tokenize

    assert tokenize(float("nan")) == []
    assert tokenize(None) == []
    assert tokenize(3) == ["3"]
    assert tokenize("A b") == ["a", "b"]
