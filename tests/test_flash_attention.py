"""Pallas flash-attention kernel vs the dense XLA reference.

On the CPU test mesh the kernel runs in interpreter mode — the identical
kernel body that compiles for TPU, so the blockwise math (streaming
softmax, causal/padding masks, VMEM scratch carry across the K grid) is
exercised everywhere.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.ops.attention import dense_attention
from mmlspark_tpu.ops.flash_attention import flash_attention


def _qkv(rng, b=2, s=32, h=2, d=8):
    shape = (b, s, h, d)
    return tuple(
        jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(rng, causal):
    q, k, v = _qkv(rng)
    expect = dense_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_flash_padding_seq_not_multiple_of_block(rng):
    # S=20 with block 16 -> padded to 32; padded keys must be masked out
    q, k, v = _qkv(rng, s=20)
    expect = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_flash_gradients_match_dense(rng):
    q, k, v = _qkv(rng, b=1, s=16, h=2, d=4)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block=8) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_under_jit(rng):
    q, k, v = _qkv(rng, s=16)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, block=8))
    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(dense_attention(q, k, v)),
        atol=1e-5, rtol=1e-5,
    )


def test_transformer_flash_impl(rng):
    from mmlspark_tpu.models import build_model

    ids = jnp.asarray(rng.integers(0, 64, size=(2, 16)), jnp.int32)
    dense_g = build_model("transformer_lm", vocab_size=64, d_model=32,
                          heads=4, depth=1, max_len=16, attn_impl="dense")
    flash_g = build_model("transformer_lm", vocab_size=64, d_model=32,
                          heads=4, depth=1, max_len=16, attn_impl="flash")
    variables = dense_g.init(jax.random.PRNGKey(0), ids)
    np.testing.assert_allclose(
        np.asarray(flash_g.apply(variables, ids)),
        np.asarray(dense_g.apply(variables, ids)),
        atol=2e-2, rtol=2e-2,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_padded_seq(rng, causal):
    """Backward at a sequence length that is NOT a block multiple: padded
    rows/keys must contribute exactly zero gradient."""
    q, k, v = _qkv(rng, s=11)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block=8) ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4
        )


def test_flash_gradients_multiblock(rng):
    """Grid accumulation across several q/k blocks in both bwd kernels."""
    q, k, v = _qkv(rng, b=1, s=32)
    w = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block=8) * w)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4
        )


def _dense_window_ref(q, k, v, window):
    import jax
    import jax.numpy as jnp
    import numpy as np

    S = q.shape[1]
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d ** -0.5)
    qpos = np.arange(S)[:, None]
    kpos = np.arange(S)[None, :]
    keep = (kpos <= qpos) & (kpos > qpos - window)
    s = jnp.where(keep[None, None], s, -1e30)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize("S,block,window", [
    (64, 16, 16),   # window == block
    (64, 16, 24),   # window spans block boundary
    (40, 16, 7),    # window < block, padded sequence
    (96, 32, 96),   # window == full length (degenerates to causal)
])
def test_sliding_window_forward_matches_dense(S, block, window):
    rng = np.random.default_rng(5)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, S, 2, 16)), jnp.float32)
        for _ in range(3)
    )
    got = jax.jit(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=window, block=block
        )
    )(q, k, v)
    want = _dense_window_ref(q, k, v, window)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
    )


def test_sliding_window_grads_match_dense():
    S, block, window = 48, 16, 20
    rng = np.random.default_rng(6)
    q, k, v, g = (
        jnp.asarray(rng.normal(size=(1, S, 2, 16)), jnp.float32)
        for _ in range(4)
    )
    gf = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, window=window, block=block) * g),
        argnums=(0, 1, 2),
    ))(q, k, v)
    gr = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(_dense_window_ref(q, k, v, window) * g),
        argnums=(0, 1, 2),
    ))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4
        )


def test_window_requires_causal_and_positive():
    q = jnp.ones((1, 8, 1, 4), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, window=4)
    with pytest.raises(ValueError, match=">= 1"):
        flash_attention(q, q, q, causal=True, window=0)


def test_transformer_lm_sliding_window():
    """window plumbs from the model builder through the flash kernel and
    changes the function (a token outside the window stops influencing
    the current position's logits)."""
    from mmlspark_tpu.models.registry import build_model

    m = build_model("transformer_lm", vocab_size=32, d_model=16, heads=2,
                    depth=1, max_len=24, attn_impl="flash", window=4)
    assert m.extra["window"] == 4
    x = jnp.asarray(np.arange(24)[None] % 32, jnp.int32)
    vars_ = m.init(jax.random.PRNGKey(0), x)
    base = np.asarray(jax.jit(m.apply)(vars_, x))
    # perturb a token 8 positions back: outside window=4 for the last pos
    x2 = np.array(x)
    x2[0, 24 - 9] = (x2[0, 24 - 9] + 1) % 32
    out2 = np.asarray(jax.jit(m.apply)(vars_, jnp.asarray(x2)))
    assert np.allclose(base[0, -1], out2[0, -1], atol=1e-5)
    # ...but inside the window it does influence
    x3 = np.array(x)
    x3[0, 24 - 2] = (x3[0, 24 - 2] + 1) % 32
    out3 = np.asarray(jax.jit(m.apply)(vars_, jnp.asarray(x3)))
    assert not np.allclose(base[0, -1], out3[0, -1], atol=1e-5)


def test_window_uniform_across_dense_and_flash():
    """window is one feature across impls: the dense path and the flash
    kernel produce the same windowed function for identical params."""
    from mmlspark_tpu.models.registry import build_model

    x = jnp.asarray(np.arange(16)[None] % 32, jnp.int32)
    outs = {}
    for impl in ("dense", "flash"):
        m = build_model("transformer_lm", vocab_size=32, d_model=16,
                        heads=2, depth=1, max_len=16, attn_impl=impl,
                        window=5)
        vars_ = m.init(jax.random.PRNGKey(0), x)  # same seed -> same params
        outs[impl] = np.asarray(
            jax.jit(m.apply)(vars_, x), np.float32
        )
    np.testing.assert_allclose(outs["dense"], outs["flash"],
                               atol=2e-2, rtol=2e-2)  # bf16 activations


def test_dense_window_requires_causal():
    from mmlspark_tpu.ops.attention import dense_attention

    q = jnp.ones((1, 8, 1, 4), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        dense_attention(q, q, q, window=4)


@pytest.mark.parametrize("h_q,h_kv,causal,window", [
    (4, 1, False, None),   # MQA
    (4, 2, True, None),    # GQA causal
    (6, 2, True, 20),      # GQA + sliding window
])
def test_gqa_matches_repeated_dense(h_q, h_kv, causal, window):
    """K/V with fewer heads: kernel output and all three grads match the
    dense reference run on explicitly repeated K/V (with the repeated
    grads summed back per kv head)."""
    from mmlspark_tpu.ops.attention import dense_attention

    S, d = 48, 16
    rng = np.random.default_rng(12)
    q = jnp.asarray(rng.normal(size=(2, S, h_q, d)), jnp.float32)
    k, v = (
        jnp.asarray(rng.normal(size=(2, S, h_kv, d)), jnp.float32)
        for _ in range(2)
    )
    g = jnp.asarray(rng.normal(size=q.shape), jnp.float32)
    kw = dict(causal=causal, window=window)

    got = jax.jit(lambda q, k, v: flash_attention(q, k, v, block=16, **kw)
                  )(q, k, v)
    want = jax.jit(lambda q, k, v: dense_attention(q, k, v, **kw)
                   )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)

    gf = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, block=16, **kw) * g),
        argnums=(0, 1, 2),
    ))(q, k, v)
    gr = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(dense_attention(q, k, v, **kw) * g),
        argnums=(0, 1, 2),
    ))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
            err_msg=name,
        )


def test_gqa_rejects_non_dividing_heads():
    q = jnp.ones((1, 8, 3, 4), jnp.float32)
    kv = jnp.ones((1, 8, 2, 4), jnp.float32)
    with pytest.raises(ValueError, match="heads"):
        flash_attention(q, kv, kv)


def test_transformer_lm_gqa():
    """kv_heads plumbs through the builder: the qkv projection shrinks
    and the model still runs forward+grad under flash and dense."""
    from mmlspark_tpu.models.registry import build_model

    x = jnp.asarray(np.arange(16)[None] % 32, jnp.int32)
    for impl in ("dense", "flash"):
        m = build_model("transformer_lm", vocab_size=32, d_model=16,
                        heads=4, depth=1, max_len=16, attn_impl=impl,
                        kv_heads=2)
        assert m.extra["kv_heads"] == 2
        vars_ = m.init(jax.random.PRNGKey(0), x)
        kernel = vars_["block0"]["params"]["attn"]["qkv"]["kernel"]
        assert kernel.shape[-1] == (4 + 2 * 2) * 4  # (h + 2*hk) * d
        loss = jax.jit(lambda p, m=m: jnp.mean(
            m.apply(p, x).astype(jnp.float32) ** 2))
        g = jax.jit(jax.grad(loss))(vars_)
        assert float(loss(vars_)) > 0
        assert jax.tree_util.tree_reduce(
            lambda a, b: a + float(jnp.sum(jnp.abs(b))), g, 0.0) > 0

    from mmlspark_tpu.core.exceptions import ParamError
    with pytest.raises(ParamError, match="kv_heads"):
        build_model("transformer_lm", vocab_size=32, d_model=16, heads=4,
                    depth=1, max_len=16, kv_heads=3)


def test_rope_relative_position_invariance():
    """<rope(q,p), rope(k,p')> depends only on p - p': shifting both
    positions by a constant leaves every pairwise dot product unchanged."""
    from mmlspark_tpu.ops.rope import apply_rope

    rng = np.random.default_rng(13)
    q, k = (
        jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
        for _ in range(2)
    )
    def dots(shift):
        pos = jnp.arange(8) + shift
        qr = apply_rope(q, pos)
        kr = apply_rope(k, pos)
        return np.asarray(jnp.einsum("bqhd,bkhd->bhqk", qr, kr))
    np.testing.assert_allclose(dots(0), dots(100), atol=1e-4, rtol=1e-4)


def test_rope_preserves_norm_and_dtype():
    from mmlspark_tpu.ops.rope import apply_rope

    rng = np.random.default_rng(14)
    x = jnp.asarray(rng.normal(size=(2, 16, 2, 8)), jnp.bfloat16)
    r = apply_rope(x)
    assert r.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x, np.float32), axis=-1),
        np.linalg.norm(np.asarray(r, np.float32), axis=-1),
        atol=2e-1, rtol=2e-2,  # bf16 storage
    )
    with pytest.raises(ValueError, match="even"):
        apply_rope(jnp.ones((1, 4, 1, 5), jnp.float32))


def test_transformer_lm_rope():
    """pos_embedding='rope': no learned position table in the params,
    forward+grad runs, and the ONNX exporter handles it (r5 — full
    round-trip parity lives in tests/test_onnx_export.py)."""
    from mmlspark_tpu.core.exceptions import ParamError
    from mmlspark_tpu.models.onnx_export import export_onnx
    from mmlspark_tpu.models.registry import build_model

    m = build_model("transformer_lm", vocab_size=32, d_model=16, heads=2,
                    depth=1, max_len=16, attn_impl="flash",
                    pos_embedding="rope")
    assert m.extra["pos_embedding"] == "rope"
    x = jnp.asarray(np.arange(16)[None] % 32, jnp.int32)
    vars_ = m.init(jax.random.PRNGKey(0), x)
    assert "pos" not in vars_["embed"]["params"]
    loss = jax.jit(lambda p: jnp.mean(
        m.apply(p, x).astype(jnp.float32) ** 2))
    assert float(loss(vars_)) > 0
    g = jax.jit(jax.grad(loss))(vars_)
    assert jax.tree_util.tree_reduce(
        lambda a, b: a + float(jnp.sum(jnp.abs(b))), g, 0.0) > 0
    assert len(export_onnx(m, vars_, (1, 16))) > 0  # exports since r5
    with pytest.raises(ParamError, match="pos_embedding"):
        build_model("transformer_lm", vocab_size=32, d_model=16, heads=2,
                    depth=1, max_len=16, pos_embedding="alibi")
