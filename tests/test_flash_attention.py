"""Pallas flash-attention kernel vs the dense XLA reference.

On the CPU test mesh the kernel runs in interpreter mode — the identical
kernel body that compiles for TPU, so the blockwise math (streaming
softmax, causal/padding masks, VMEM scratch carry across the K grid) is
exercised everywhere.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.ops.attention import dense_attention
from mmlspark_tpu.ops.flash_attention import flash_attention


def _qkv(rng, b=2, s=32, h=2, d=8):
    shape = (b, s, h, d)
    return tuple(
        jnp.asarray(rng.normal(size=shape), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(rng, causal):
    q, k, v = _qkv(rng)
    expect = dense_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_flash_padding_seq_not_multiple_of_block(rng):
    # S=20 with block 16 -> padded to 32; padded keys must be masked out
    q, k, v = _qkv(rng, s=20)
    expect = dense_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_flash_gradients_match_dense(rng):
    q, k, v = _qkv(rng, b=1, s=16, h=2, d=4)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block=8) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_under_jit(rng):
    q, k, v = _qkv(rng, s=16)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, block=8))
    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(dense_attention(q, k, v)),
        atol=1e-5, rtol=1e-5,
    )


def test_transformer_flash_impl(rng):
    from mmlspark_tpu.models import build_model

    ids = jnp.asarray(rng.integers(0, 64, size=(2, 16)), jnp.int32)
    dense_g = build_model("transformer_lm", vocab_size=64, d_model=32,
                          heads=4, depth=1, max_len=16, attn_impl="dense")
    flash_g = build_model("transformer_lm", vocab_size=64, d_model=32,
                          heads=4, depth=1, max_len=16, attn_impl="flash")
    variables = dense_g.init(jax.random.PRNGKey(0), ids)
    np.testing.assert_allclose(
        np.asarray(flash_g.apply(variables, ids)),
        np.asarray(dense_g.apply(variables, ids)),
        atol=2e-2, rtol=2e-2,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_padded_seq(rng, causal):
    """Backward at a sequence length that is NOT a block multiple: padded
    rows/keys must contribute exactly zero gradient."""
    q, k, v = _qkv(rng, s=11)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block=8) ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4
        )


def test_flash_gradients_multiblock(rng):
    """Grid accumulation across several q/k blocks in both bwd kernels."""
    q, k, v = _qkv(rng, b=1, s=32)
    w = jnp.asarray(rng.normal(size=(1, 32, 2, 8)), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block=8) * w)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4
        )
