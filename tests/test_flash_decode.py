"""Length-aware flash-decode kernel (ops/flash_attention.flash_decode).

The contract under test: single-token split-KV attention over slot
caches matches the dense reference over RAGGED per-row live lengths —
including the degenerate rows (length 0 -> zeros, length == cache_len
-> full read) — across dtypes, GQA groupings, and block counts, with the
per-row masking geometry shared with ``ops/attention.py``
(``decode_live_lengths``) and ONE home for both NEG_INF conventions.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.ops.attention import (
    KERNEL_NEG_INF,
    NEG_INF,
    causal_block_mask,
    decode_live_lengths,
    dense_attention,
    mask_value,
)
from mmlspark_tpu.ops.flash_attention import _decode_block, flash_decode


def _qkv(b, L, h, hk, d, dtype, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), dtype)
    k = jnp.asarray(rng.normal(size=(b, L, hk, d)), dtype)
    v = jnp.asarray(rng.normal(size=(b, L, hk, d)), dtype)
    return q, k, v


def _dense_ref(q, k, v, lengths):
    # live length L means positions [0, L): a query "at" position L-1
    # under the causal mask (length 0 -> q_offset -1 masks everything,
    # the fully-masked row dense_attention answers with zeros)
    return dense_attention(
        q, k, v, causal=True, q_offset=jnp.asarray(lengths) - 1
    )


# -- parity over ragged live lengths ----------------------------------------


@pytest.mark.parametrize("dtype,tol", [
    (jnp.float32, 1e-5),
    (jnp.bfloat16, 1e-2),  # the acceptance bound: bf16 in, f32 softmax
])
def test_parity_ragged_lengths(dtype, tol):
    L = 32
    q, k, v = _qkv(6, L, 4, 4, 16, dtype)
    lengths = jnp.asarray([0, 1, 5, 17, L - 1, L], jnp.int32)
    out = flash_decode(q, k, v, lengths)
    ref = _dense_ref(q, k, v, lengths)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=tol, rtol=tol,
    )
    # length 0: no live positions at all -> exact zeros, same as the
    # dense fully-masked convention
    assert not np.asarray(out[0]).any()


def test_parity_multi_block_and_ragged_tail():
    # block=8 over L=30 streams multiple KV blocks, and 30 has no
    # power-of-two tiling — the divisor/padded-tail path
    L = 30
    q, k, v = _qkv(5, L, 2, 2, 8, jnp.float32, seed=1)
    lengths = jnp.asarray([0, 3, 11, 29, L], jnp.int32)
    out = flash_decode(q, k, v, lengths, block=8)
    ref = _dense_ref(q, k, v, lengths)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("hk", [1, 2])  # MQA and grouped
def test_gqa_parity(hk):
    L = 16
    q, k, v = _qkv(4, L, 4, hk, 8, jnp.bfloat16, seed=2)
    lengths = jnp.asarray([1, 7, 12, L], jnp.int32)
    out = flash_decode(q, k, v, lengths, block=8)
    ref = _dense_ref(q, k, v, lengths)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=1e-2, rtol=1e-2,
    )


def test_one_program_serves_every_length():
    """The serving invariant at kernel scope: lengths are TRACED (the
    scalar-prefetch channel), so one jitted program serves every ragged
    pattern — recompiles per length vector would defeat the engine's
    compile-once decode tick."""
    from mmlspark_tpu.testing.compile_guard import compile_guard

    L = 16
    q, k, v = _qkv(3, L, 2, 2, 8, jnp.bfloat16, seed=3)
    f = jax.jit(lambda q, k, v, n: flash_decode(q, k, v, n, block=8))
    with compile_guard(f._cache_size, max_programs=1, min_programs=1,
                       label="flash_decode"):
        for lens in ([1, 2, 3], [L, 0, 5], [7, 7, 7]):
            lengths = jnp.asarray(lens, jnp.int32)
            out = f(q, k, v, lengths)
            ref = _dense_ref(q, k, v, lengths)
            np.testing.assert_allclose(
                np.asarray(out, np.float32), np.asarray(ref, np.float32),
                atol=1e-2, rtol=1e-2,
            )


def test_lengths_clip_to_cache_len():
    # defensive contract: lengths beyond the buffer read the whole
    # buffer, never out of bounds
    L = 8
    q, k, v = _qkv(2, L, 2, 2, 8, jnp.float32, seed=4)
    out = flash_decode(q, k, v, jnp.asarray([L + 50, 2], jnp.int32))
    ref = _dense_ref(q, k, v, jnp.asarray([L, 2]))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_decode_block_prefers_exact_divisors():
    # an exact divisor means the cache streams with NO pad copy — the
    # serving hot path never duplicates its K/V buffers
    assert _decode_block(32, 128) == 32    # whole cache in one block
    assert _decode_block(256, 128) == 128
    assert _decode_block(48, 32) == 24     # largest divisor <= block
    assert _decode_block(30, 8) == 8       # no divisor in [8, 8]: padded


def test_validation_errors():
    q, k, v = _qkv(2, 8, 4, 2, 8, jnp.bfloat16)
    with pytest.raises(ValueError, match="one dtype"):
        flash_decode(q.astype(jnp.float32), k, v, jnp.ones(2, jnp.int32))
    with pytest.raises(ValueError, match="SINGLE query"):
        flash_decode(jnp.concatenate([q, q], 1), k, v,
                     jnp.ones(2, jnp.int32))
    with pytest.raises(ValueError, match="heads"):
        flash_decode(q, k[:, :, :1].repeat(3, 2), v[:, :, :1].repeat(3, 2),
                     jnp.ones(2, jnp.int32))
    with pytest.raises(ValueError, match="lengths"):
        flash_decode(q, k, v, jnp.ones((3,), jnp.int32))


# -- shared masking geometry ------------------------------------------------


def test_decode_live_lengths_contract():
    # scalar pos broadcasts; per-row passes through; both are pos + 1
    np.testing.assert_array_equal(
        np.asarray(decode_live_lengths(4, 3)), [5, 5, 5]
    )
    np.testing.assert_array_equal(
        np.asarray(decode_live_lengths(jnp.asarray([0, 2, 9]), 3)),
        [1, 3, 10],
    )


def test_mask_value_single_home():
    import mmlspark_tpu.ops.flash_attention as fa

    assert mask_value(kernel=False) == NEG_INF == float("-inf")
    assert mask_value(kernel=True) == KERNEL_NEG_INF == -1e30
    # flash kernels use the one kernel-side constant, not a third copy
    assert fa.NEG_INF == KERNEL_NEG_INF


def test_causal_block_mask_per_row_with_window():
    """Per-row q_offset combined with window=W (the previously untested
    corner): each row of the (B, 1, Q, K) mask must equal the scalar
    mask built at that row's offset."""
    B, Q, K, W = 4, 2, 12, 5
    offsets = jnp.asarray([0, 3, 7, 10])
    got = causal_block_mask(Q, K, offsets, 0, window=W)
    assert got.shape == (B, 1, Q, K)
    for b in range(B):
        want = causal_block_mask(Q, K, int(offsets[b]), 0, window=W)
        np.testing.assert_array_equal(
            np.asarray(got[b, 0]), np.asarray(want)
        )


def test_per_row_mask_requires_scalar_kv_offset():
    with pytest.raises(ValueError, match="scalar kv_offset"):
        causal_block_mask(1, 4, jnp.asarray([0, 1]), jnp.asarray([0, 1]))
