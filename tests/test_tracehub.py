"""Fleet-wide distributed tracing (core/tracehub) — the TelemetryHub
merge plane and its live surface.

The contract under test (docs/OBSERVABILITY.md "Distributed
tracing"): N flight recorders + metric registries merge into ONE
globally-ordered timeline, ONE deterministic Perfetto trace whose
``trace_id``-bound flow arrows cross replica tracks (hand-offs,
failover replays, hedge twins), ONE label-based Prometheus exposition
(``{replica="0"}`` labels instead of name-prefix namespacing), and a
detector sweep that alerts exactly once per standing condition. The
hub reads host-side state only: attaching it adds ZERO new XLA
programs and zero extra host syncs per decode block, on a single
device and on a 2x2 mesh — pinned under ``serve_compile_guard``.
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import Fault, FaultInjector
from mmlspark_tpu.core.telemetry import (
    FlightRecorder,
    MetricRegistry,
    SpanTracer,
    _prom_escape_label_value,
)
from mmlspark_tpu.core.tracehub import (
    ALERT_KINDS,
    MetricsServer,
    TelemetryHub,
    _RegistryView,
)
from mmlspark_tpu.models import build_model
from mmlspark_tpu.serve import DisaggFleet, ReplicaSet, ServeEngine
from mmlspark_tpu.testing.compile_guard import serve_compile_guard

PERIOD = 4


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


@pytest.fixture(scope="module")
def lm():
    m = build_model("transformer_lm", vocab_size=8, d_model=32, heads=2,
                    depth=2, max_len=32)
    v, ids = _train_lm(m)
    return m, v, ids


# -- registry views ---------------------------------------------------------


def test_registry_view_prefix_strip_exclude_and_readonly():
    inner = MetricRegistry()
    inner.counter("modellm.serve.completed").inc(4)
    inner.counter("multimodel.faults_injected").inc(1)
    inner.counter("replica0.serve.completed").inc(2)
    inner.gauge("perf.mfu").set(0.5)

    # prefix view: restricted to the namespace, names stripped
    v = _RegistryView(inner, prefix="modellm.")
    assert v.names() == ["serve.completed"]
    assert v.get("serve.completed").value == 4
    assert v.to_dict() == {"serve.completed": 4}

    # strip view: EVERY name survives, the prefix folds away where
    # present (perf.* passes through untouched)
    s = _RegistryView(inner, strip_prefix="replica0.")
    assert "serve.completed" in s.names() and "perf.mfu" in s.names()
    assert s.get("serve.completed").value == 2

    # exclusion filters on ORIGINAL names — "multimodel." must not be
    # caught by a "model" prefix match
    e = _RegistryView(inner, exclude_prefixes=("modellm.",))
    assert "multimodel.faults_injected" in e.names()
    assert not any(n.startswith("modellm.") for n in e.names())

    with pytest.raises(FriendlyError, match="read-only"):
        v.counter("new.metric")


def test_hub_rejects_unknown_thresholds():
    with pytest.raises(FriendlyError, match="unknown detector"):
        TelemetryHub(thresholds={"typo_threshold": 1})


# -- source registration / generations --------------------------------------


def test_add_source_idempotent_and_generation_bump():
    hub = TelemetryHub()
    rec = FlightRecorder()
    s1 = hub.add_source("replica0", recorder=rec)
    assert hub.add_source("replica0", recorder=rec) is s1
    assert s1.display == "replica0" and "gen" not in s1.labels
    # a NEW recorder under the same name is a rebuilt engine: next
    # generation, disambiguated display + gen label
    s2 = hub.add_source("replica0", recorder=FlightRecorder())
    assert s2 is not s1
    assert s2.display == "replica0#1" and s2.labels["gen"] == "1"
    with pytest.raises(FriendlyError, match="recorder"):
        hub.add_source("empty")


# -- merged timeline --------------------------------------------------------


def test_merged_events_interleave_and_dump_header(tmp_path):
    hub = TelemetryHub()
    a, b = FlightRecorder(), FlightRecorder()
    hub.add_source("a", recorder=a)
    hub.add_source("b", recorder=b)
    for i in range(4):
        (a if i % 2 == 0 else b).record("ev", tick=i)
    merged = hub.merged_events()
    ours = [ev for ev in merged if ev["src"] in ("a", "b")]
    # wall-clock order == recording order, regardless of which
    # recorder each event landed on
    assert [ev["tick"] for ev in ours] == [0, 1, 2, 3]
    assert [ev["src"] for ev in ours] == ["a", "b", "a", "b"]
    assert all("wall" in ev and "t" in ev for ev in ours)

    path = tmp_path / "events.jsonl"
    hub.dump_events(str(path))
    lines = path.read_text().splitlines()
    header = json.loads(lines[0])
    assert header["header"] == "telemetry_hub"
    assert set(header["t0_unix"]) == {"hub", "a", "b"}
    assert header["events"] == len(lines) - 1
    assert header["dropped"] == 0


def test_request_chains_span_inheritance_and_control_events():
    hub = TelemetryHub()
    r0, r1 = FlightRecorder(), FlightRecorder()
    hub.add_source("sup", recorder=r0)
    hub.add_source("rep", recorder=r1)
    r0.record("routed", trace="g0", replica=1)
    span = SpanTracer(r1).span("request", id=0, trace="g0")
    span.event("prefill")
    span.end("completed")
    chains = hub.request_chains()
    names = [ev["name"] for ev in chains["g0"]]
    # the control event joins the span's events: the routed hop plus
    # the full lifecycle, span events INHERITING the start's trace id
    assert names == ["routed", "start", "prefill", "completed"]
    assert {ev["src"] for ev in chains["g0"]} == {"sup", "rep"}


# -- merged prometheus ------------------------------------------------------


def test_merged_prom_one_type_header_with_labels():
    hub = TelemetryHub()
    ra, rb = MetricRegistry(), MetricRegistry()
    ra.counter("serve.completed").inc(3)
    rb.counter("serve.completed").inc(5)
    hub.add_source("r0", registry=ra, labels={"replica": "0"})
    hub.add_source("r1", registry=rb, labels={"replica": "1"})
    prom = hub.to_prometheus()
    assert prom.count("# TYPE serve_completed_total counter") == 1
    assert 'serve_completed_total{replica="0"} 3' in prom
    assert 'serve_completed_total{replica="1"} 5' in prom


def test_prom_label_value_escaping_round_trip():
    """Backslash/quote/newline in a label value survive the exposition:
    escape -> parse-back -> the original string, and the emitted line
    never tears (one sample per physical line)."""
    evil = 'mo"del\\v1\nline2'
    escaped = _prom_escape_label_value(evil)
    assert "\n" not in escaped
    # the format's own unescape rules invert the escape exactly
    unescaped = (
        escaped.replace("\\n", "\n")
        .replace('\\"', '"')
        .replace("\\\\", "\\")
    )
    assert unescaped == evil

    reg = MetricRegistry()
    reg.counter("serve.completed").inc(1)
    hub = TelemetryHub()
    hub.add_source("m", registry=reg, labels={"model": evil})
    prom = hub.to_prometheus()
    sample = [ln for ln in prom.splitlines()
              if ln.startswith("serve_completed_total{")]
    assert len(sample) == 1
    inside = sample[0].split("{", 1)[1].rsplit("}", 1)[0]
    assert inside == f'model="{escaped}"'


# -- detectors --------------------------------------------------------------


def test_detectors_fire_once_per_condition():
    hub = TelemetryHub(thresholds={"queue_high": 4})
    reg = MetricRegistry()
    reg.counter("retrace.serve.decode").inc(40)
    h = reg.histogram("serve.tick_ms")
    for _ in range(25):
        h.record(1.0)
    h.record(5000.0)  # p99 blows past 50x p50
    rec = FlightRecorder()
    for _ in range(3):
        rec.record("dispatch", family="decode[T=2]", ms=1.0)
    hub.add_source(
        "r0", recorder=rec, registry=reg,
        stats=lambda: {"queue_depth": 9, "decode_blocks": 2},
    )
    # uneven SLO burn needs >= 2 sources disagreeing
    ra, rb = MetricRegistry(), MetricRegistry()
    ra.gauge("slo.burning").set(1)
    rb.gauge("slo.burning").set(0)
    hub.add_source("r1", registry=ra)
    hub.add_source("r2", registry=rb)

    kinds = {a["kind"] for a in hub.detect()}
    assert kinds == {
        "retrace_storm", "tick_p99_drift", "queue_watermark",
        "host_sync_regression", "slo_burn_spread",
    }
    # every alert raised its counter and landed on the hub's recorder
    for kind in kinds:
        assert hub.registry.counter(f"alerts.{kind}").value == 1
    alert_events = [ev for ev in hub.recorder.events()
                    if ev["name"] == "alert"]
    assert len(alert_events) == len(kinds)
    # a standing condition fires ONCE per hub lifetime — a scrape loop
    # re-running detect() must not re-count it
    assert hub.detect() == []
    assert hub.registry.counter("alerts.retrace_storm").value == 1


def test_detectors_quiet_on_healthy_source():
    hub = TelemetryHub()
    reg = MetricRegistry()
    reg.counter("retrace.serve.decode").inc(3)
    rec = FlightRecorder()
    rec.record("dispatch", family="decode[T=2]", ms=1.0)
    hub.add_source(
        "r0", recorder=rec, registry=reg,
        stats=lambda: {"queue_depth": 1, "decode_blocks": 1},
    )
    assert hub.detect() == []
    assert all(
        hub.registry.counter(f"alerts.{k}").value == 0
        for k in ALERT_KINDS
    )


# -- live surface -----------------------------------------------------------


def test_metrics_server_endpoints_on_ephemeral_port():
    hub = TelemetryHub()
    reg = MetricRegistry()
    reg.counter("serve.completed").inc(2)
    hub.add_source("r0", registry=reg, labels={"replica": "0"})
    with MetricsServer(hub, port=0) as server:
        assert server.port > 0
        base = f"http://{server.host}:{server.port}"

        body = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert 'serve_completed_total{replica="0"} 2' in body
        assert "# TYPE alerts_retrace_storm_total counter" in body

        health = json.loads(
            urllib.request.urlopen(f"{base}/healthz").read())
        assert health["status"] == "ok"
        assert "r0" in health["sources"]
        assert set(health["alerts"]) == set(ALERT_KINDS)

        doc = json.loads(
            urllib.request.urlopen(f"{base}/traces").read())
        assert doc["otherData"]["generator"].endswith("TelemetryHub")

        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/nope")
        assert err.value.code == 404
    # closed: the port no longer answers
    with pytest.raises(OSError):
        urllib.request.urlopen(f"{base}/healthz", timeout=0.5)


# -- zero-overhead pin ------------------------------------------------------


def _drive_with_hub(m, v, ids, mesh):
    """Serve a batch with the hub attached and SCRAPED MID-RUN; the
    engine's compile pins and the one-host-sync-per-block invariant
    must hold exactly as they do without the hub."""
    eng = ServeEngine(m, v, slots=2, cache_len=32, max_queue=8,
                      decode_block=4, mesh=mesh)
    hub = TelemetryHub()
    hub.attach_engine(eng)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7)]
    with serve_compile_guard(eng, min_decode=1, min_prefill=1):
        for p in prompts:
            eng.submit(p, 6)
        done = 0
        while done < len(prompts):
            done += len(eng.step())
            # the read-side merge runs between ticks, like a scrape
            hub.to_prometheus()
            hub.merged_events()
        hub.export_trace()
        assert hub.detect() == []
    # one device_get per fused decode block — the hub's own
    # host-sync detector agrees with the raw event count
    syncs = sum(
        1 for ev in eng.recorder.events()
        if ev["name"] == "dispatch"
        and str(ev.get("attrs", {}).get("family", "")).startswith("decode")
    )
    assert syncs == sum(eng.metrics.decode_blocks.values())
    assert hub.registry.counter("alerts.host_sync_regression").value == 0


def test_hub_zero_new_programs_single_device(lm):
    m, v, ids = lm
    _drive_with_hub(m, v, ids, mesh=None)


@pytest.mark.slow  # ci.sh's tracing gate runs the full file unfiltered
def test_hub_zero_new_programs_2x2_mesh(lm):
    m, v, ids = lm
    _drive_with_hub(m, v, ids, mesh={"data": 2, "model": 2})


# -- fleet flows: hand-off, failover, hedge ---------------------------------


def _flow_events(doc):
    return [e for e in doc["traceEvents"] if e["ph"] in ("s", "t", "f")]


def test_fleet_handoff_flow_arrows_and_deterministic_export(lm, tmp_path):
    m, v, ids = lm
    fleet = DisaggFleet(m, v, prefill_replicas=1, decode_replicas=1,
                        slots=2, cache_len=32, max_queue=8,
                        decode_block=4, retry_backoff_s=0.0)
    hub = TelemetryHub()
    hub.attach_fleet(fleet)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4)]
    gids = [fleet.submit(p, 6) for p in prompts]
    results = fleet.run()
    assert all(results[g].status == "completed" for g in gids)

    doc = hub.export_trace()
    pid_of = {s.display: s.pid for s in hub.sources()}
    flows = _flow_events(doc)
    # every request prefilled on one replica and decoded on another:
    # one flow chain per trace id, arrows CROSSING the two tracks
    by_trace = {}
    for ev in flows:
        by_trace.setdefault(ev["id"], []).append(ev)
    assert set(by_trace) == {f"f{g}" for g in gids}
    for trace, evs in by_trace.items():
        phases = [e["ph"] for e in sorted(evs, key=lambda e: e["ts"])]
        assert phases[0] == "s" and phases[-1] == "f", (trace, phases)
        pids = {e["pid"] for e in evs}
        assert pid_of["prefill0"] in pids and pid_of["decode1"] in pids
        finish = [e for e in evs if e["ph"] == "f"]
        assert all(e.get("bp") == "e" for e in finish)
        # arrows anchor on request tracks, not engine-plane tracks
        assert all(e["tid"] >= 10 for e in evs)

    # the merged chain holds both sides of the hand-off
    chains = hub.request_chains()
    for g in gids:
        srcs = {ev["src"] for ev in chains[f"f{g}"]}
        assert {"fleet", "prefill0", "decode1"} <= srcs
        names = {ev["name"] for ev in chains[f"f{g}"]}
        assert "handoff_routed" in names and "handed_off" in names

    # byte-identical re-export: same hub state, same bytes
    p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
    hub.export_trace(path=str(p1))
    hub.export_trace(path=str(p2))
    assert p1.read_bytes() == p2.read_bytes()


def test_failover_replay_joins_the_original_trace(lm):
    """Kill replica 0 mid-run: the replayed request's span on the
    REBUILT engine (a new hub generation) carries the same trace id as
    the original submit, so the chain and the flow arrows survive the
    failover."""
    m, v, ids = lm
    inj = FaultInjector([Fault("serve.decode", "kill", tick=3,
                               replica=0)])
    rs = ReplicaSet(m, v, replicas=2, slots=4, cache_len=32,
                    max_queue=8, decode_block=2,
                    snapshot_every_ticks=2, faults=inj,
                    retry_backoff_s=0.0)
    hub = TelemetryHub()
    hub.attach_replicaset(rs)
    prompts = [np.asarray(ids[0, :n]) for n in (5, 9, 4, 7)]
    gids = [rs.submit(p, 8) for p in prompts]
    results = rs.run()
    assert rs.replica_failovers_total == 1
    assert all(results[g].status == "completed" for g in gids)

    displays = [s.display for s in hub.sources()]
    assert "replica0#1" in displays  # the rebuilt engine's generation
    chains = hub.request_chains()
    replayed = [
        t for t, evs in chains.items()
        if any(ev["src"].startswith("replica0#") for ev in evs)
    ]
    assert replayed, f"no chain reached the rebuilt replica: {displays}"
    for t in replayed:
        srcs = {ev["src"] for ev in chains[t]}
        # the SAME trace id spans the supervisor's routing, a pre-kill
        # source, and the post-failover rebuild
        assert "supervisor" in srcs and "replica0#1" in srcs
    # the rebuilt replica's fragment joins the flow chain
    doc = hub.export_trace()
    flow_traces = {e["id"] for e in _flow_events(doc)}
    assert set(replayed) <= flow_traces


def test_hedge_twin_shares_the_trace(lm):
    m, v, ids = lm

    class _FakeClock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = _FakeClock()
    rs = ReplicaSet(m, v, replicas=2, slots=2, cache_len=32,
                    max_queue=8, decode_block=2, hedge_ms=50.0,
                    clock=clk, snapshot_every_ticks=None,
                    retry_backoff_s=0.0)
    hub = TelemetryHub()
    hub.attach_replicaset(rs)
    gid = rs.submit(np.asarray(ids[0, :6]), 12)
    rs.step()
    clk.t = 0.2  # stale enough to hedge
    results = rs.run()
    assert rs.hedges_total == 1
    assert results[gid].status == "completed"
    chain = hub.request_chains()[f"g{gid}"]
    # both copies of the request ran under ONE trace id, on different
    # replicas, and the hedge control event names that id too
    assert {"replica0", "replica1"} <= {ev["src"] for ev in chain}
    assert "hedge" in {ev["name"] for ev in chain}
    starts = [ev for ev in chain if ev["name"] == "start"]
    assert len(starts) >= 2
    doc = hub.export_trace()
    hedge_flow = [e for e in _flow_events(doc) if e["id"] == f"g{gid}"]
    assert {e["pid"] for e in hedge_flow} == {
        s.pid for s in hub.sources()
        if s.display in ("replica0", "replica1")
    }
