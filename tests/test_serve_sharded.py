"""Mesh-sharded serving (ISSUE 6 tentpole).

The contract under test (docs/SERVING.md "Sharded serving"): a
``ServeEngine`` built with ``mesh=`` runs the SAME bucketed-prefill +
fused-decode-block programs partitioned by GSPMD over a (data, model)
device mesh — slot-batched state over the data axis, params by the
Megatron ``TRANSFORMER_TP_RULES`` over the model axis — and everything
the single-device engine guarantees carries over: token streams
BYTE-IDENTICAL to ``generate()`` across ragged prompts / mid-run joins /
mid-block death, buffer donation, the compile-count pins
(``decode_compile_count <= num_decode_blocks``, prefill <= buckets),
one host sync per block, and typed errors for invalid topologies.
Runs on the 8 virtual CPU devices ``tests/conftest.py`` forces.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.models import build_model, generate
from mmlspark_tpu.parallel import (
    TRANSFORMER_TP_RULES,
    make_mesh,
    parse_mesh_axes,
    unmatched_param_paths,
)
from mmlspark_tpu.serve import ServeEngine
from mmlspark_tpu.testing.compile_guard import (
    compile_guard,
    serve_compile_guard,
)

PERIOD = 4


def _train_lm(m, steps=30, seq=16):
    from mmlspark_tpu.testing.datagen import overfit_periodic_lm

    return overfit_periodic_lm(m, steps=steps, seq=seq, period=PERIOD)


def _tiny(**kw):
    cfg = dict(vocab_size=8, d_model=32, heads=2, depth=2, max_len=32)
    cfg.update(kw)
    return build_model("transformer_lm", **cfg)


@pytest.fixture(scope="module")
def lm():
    m = _tiny()
    v, ids = _train_lm(m)
    return m, v, ids


def _ref(m, v, prompt, max_new, eos_id=None):
    out = generate(m, v, np.asarray(prompt, np.int32)[None], max_new,
                   eos_id=eos_id)
    return np.asarray(out)[0]


# -- mesh spec parsing -----------------------------------------------------


def test_parse_mesh_axes():
    assert parse_mesh_axes("data=4,model=2") == {"data": 4, "model": 2}
    assert parse_mesh_axes(" data=-1 , model=2 ") == {"data": -1,
                                                     "model": 2}
    with pytest.raises(FriendlyError, match="mesh spec"):
        parse_mesh_axes("data:4")
    with pytest.raises(FriendlyError, match="mesh spec"):
        parse_mesh_axes("")


# -- topology validation ---------------------------------------------------


def test_slots_not_divisible_by_data_axis_raises(lm):
    m, v, _ = lm
    with pytest.raises(FriendlyError, match="multiple of the mesh"):
        ServeEngine(m, v, slots=3, cache_len=32,
                    mesh={"data": 2, "model": 2})


# -- parity: sharded engine == single-device generate() --------------------


@pytest.mark.parametrize("mesh_axes", [
    {"data": 2, "model": 2},
    pytest.param({"data": 4}, marks=pytest.mark.slow),
    pytest.param({"data": 1, "model": 2}, marks=pytest.mark.slow),
])
def test_sharded_parity_ragged_prompts_and_joins(lm, mesh_axes):
    """The sharded engine emits generate()'s exact tokens over ragged
    prompts and heterogeneous budgets, including mid-run submit()
    joins, with the compile-count pins holding under the mesh."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    prompts = [row[:4], row[:1], row[:9], row[:6], row[:2]]
    budgets = [10, 7, 3, 12, 5]

    engine = ServeEngine(m, v, slots=4, cache_len=32, max_queue=8,
                         decode_block=4, mesh=mesh_axes)
    assert engine.mesh is not None
    results, rids = {}, []
    with serve_compile_guard(engine, min_decode=1, min_prefill=1):
        for p, n in zip(prompts[:3], budgets[:3]):
            rids.append(engine.submit(p, max_new_tokens=n))
        for _ in range(2):
            results.update({r.id: r for r in engine.step()})
        # two more join MID-RUN while earlier requests are decoding
        for p, n in zip(prompts[3:], budgets[3:]):
            rids.append(engine.submit(p, max_new_tokens=n))
        while engine.busy:
            results.update({r.id: r for r in engine.step()})

    for rid, p, n in zip(rids, prompts, budgets):
        np.testing.assert_array_equal(
            np.asarray(results[rid].tokens), _ref(m, v, p, n),
            err_msg=f"mesh={mesh_axes} request={rid}",
        )
    assert engine.decode_compile_count <= engine.num_decode_blocks
    assert engine.prefill_compile_count <= engine.num_prefill_buckets


@pytest.mark.slow  # ci.sh's sharded gate runs the full file unfiltered
def test_sharded_mid_block_eos(lm):
    """A request hitting EOS mid-block under a 2x2 mesh dies on device
    and matches generate() with the same eos_id byte for byte."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :3])
    free_run = _ref(m, v, prompt, 12)
    eos = int(free_run[len(prompt) + 2])
    full = _ref(m, v, prompt, 12, eos_id=eos)
    stop = len(prompt) + int(np.argmax(full[len(prompt):] == eos))
    want = full[:stop + 1]

    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=4,
                         mesh={"data": 2, "model": 2})
    rid = engine.submit(prompt, max_new_tokens=12, eos_id=eos)
    res = engine.run()[rid]
    np.testing.assert_array_equal(np.asarray(res.tokens), want)
    assert res.status == "completed"
    assert int(res.tokens[-1]) == eos


# -- compile-count: NamedSharding args register zero new programs ----------


@pytest.mark.slow  # ci.sh's sharded gate runs the full file unfiltered
def test_sharded_retick_compiles_zero_new_programs(lm):
    """The satellite regression: once a sharded engine has served one
    wave of traffic, serving MORE traffic with the same shapes compiles
    ZERO new XLA programs — committed NamedSharding args re-enter the
    cached programs instead of registering as new signatures (the raw
    jax signature cache would grow here; ProgramCountingJit must not)."""
    m, v, ids = lm
    row = np.asarray(ids[0])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=4,
                         mesh={"data": 2, "model": 2})
    rid = engine.submit(row[:4], max_new_tokens=9)
    engine.run()

    with compile_guard(
        lambda: engine.decode_compile_count, max_programs=0,
        label="sharded re-tick decode",
    ), compile_guard(
        lambda: engine.prefill_compile_count, max_programs=0,
        label="sharded re-tick prefill",
    ):
        rid = engine.submit(row[:4], max_new_tokens=9)
        res = engine.run()[rid]
    np.testing.assert_array_equal(
        np.asarray(res.tokens), _ref(m, v, row[:4], 9)
    )


@pytest.mark.slow  # ci.sh's sharded gate runs the full file unfiltered
def test_sharded_one_host_sync_per_block(lm, monkeypatch):
    """The one-device_get-per-block contract survives sharding: 8
    decode tokens through T=4 blocks = at most 2 synced fetches
    (device_put of per-tick inputs must not count as a sync)."""
    m, v, ids = lm
    prompt = np.asarray(ids[0, :4])
    engine = ServeEngine(m, v, slots=2, cache_len=32, decode_block=4,
                         mesh={"data": 2, "model": 2})
    rid = engine.submit(prompt, max_new_tokens=9)  # 1 prefill + 8 decode

    syncs = {"n": 0}
    real_device_get = jax.device_get
    real_asarray = np.asarray

    def counting_device_get(x, *a, **kw):
        syncs["n"] += 1
        return real_device_get(x, *a, **kw)

    def counting_asarray(x, *a, **kw):
        if isinstance(x, jax.Array):
            syncs["n"] += 1
        return real_asarray(x, *a, **kw)

    monkeypatch.setattr(jax, "device_get", counting_device_get)
    monkeypatch.setattr(np, "asarray", counting_asarray)
    res = engine.run()[rid]
    monkeypatch.undo()

    np.testing.assert_array_equal(
        np.asarray(res.tokens), _ref(m, v, prompt, 9)
    )
    assert syncs["n"] <= 2, f"host syncs: {syncs['n']} (> 1 per block)"


# -- expiry under the mesh: device row dies, slot re-leases ----------------


def test_sharded_expire_active_slot_device_state(lm):
    """The expire-active regression on a 2x2 mesh: an ACTIVE request
    expiring mid-decode leaves its sharded live-mask row dead and its
    position zeroed, the survivor keeps byte parity, and the freed slot
    re-leases cleanly in the same run."""
    m, v, ids = lm
    engine = ServeEngine(m, v, slots=2, cache_len=32, max_queue=4,
                         decode_block=1, mesh={"data": 2, "model": 2})
    prompt_b = np.asarray(ids[0, :5])
    rid_a = engine.submit(np.asarray(ids[0, :4]), max_new_tokens=12,
                          deadline_ticks=2)
    rid_b = engine.submit(prompt_b, max_new_tokens=10)
    results = {r.id: r for r in engine.step()}  # tick 0: both admitted
    slot_a = next(s for s, st in engine._sched.active.items()
                  if st.req.id == rid_a)
    while rid_a not in results:
        results.update({r.id: r for r in engine.step()})
    assert results[rid_a].status == "expired"
    # the sharded (data-axis-split) pool state agrees: row dead, pos 0
    assert not bool(np.asarray(jax.device_get(engine.pool.live))[slot_a])
    assert int(np.asarray(jax.device_get(
        engine.pool.positions))[slot_a]) == 0
    # re-lease the freed slot under the mesh while B keeps decoding
    rid_c = engine.submit(np.asarray(ids[0, :6]), max_new_tokens=4)
    results.update(engine.run())
    assert results[rid_b].status == "completed"
    np.testing.assert_array_equal(
        np.asarray(results[rid_b].tokens), _ref(m, v, prompt_b, 10)
    )
    assert results[rid_c].status == "completed"
    np.testing.assert_array_equal(
        np.asarray(results[rid_c].tokens),
        _ref(m, v, np.asarray(ids[0, :6]), 4),
    )


# -- telemetry: mesh topology in the metrics surfaces ----------------------


def test_sharded_metrics_mesh_keys(lm):
    m, v, _ = lm
    engine = ServeEngine(m, v, slots=4, cache_len=32,
                         mesh={"data": 2, "model": 2})
    d = engine.metrics.to_dict()
    assert d["mesh_shape"] == {"data": 2, "model": 2}
    assert d["mesh_devices"] == 4
    # K+V pairs over depth blocks, slot rows split 2-way over the data
    # axis: per-device bytes must be a strict fraction of the total pool
    total = sum(
        a.size * a.dtype.itemsize
        for pair in engine.pool.buffers.values() for a in pair
    )
    assert 0 < d["cache_pool_bytes_per_device"] < total

    single = ServeEngine(m, v, slots=4, cache_len=32)
    ds = single.metrics.to_dict()
    assert ds["mesh_shape"] == {} and ds["mesh_devices"] == 1
    assert ds["cache_pool_bytes_per_device"] >= total


# -- rule coverage audit ---------------------------------------------------


def test_tp_rule_coverage_transformer_lm(lm):
    """Every transformer_lm param path matches SOME rule (embedding,
    unembed, norms included) — the whole-model audit is one call."""
    m, v, _ = lm
    assert unmatched_param_paths(v, TRANSFORMER_TP_RULES) == []
    # an unknown param is reported by its full path
    extra = {"novel": {"params": {"adapter": {"kernel": jnp.zeros((4, 4))}}}}
    missing = unmatched_param_paths(extra, TRANSFORMER_TP_RULES)
    assert missing == ["novel/params/adapter/kernel"]


def test_embedding_and_head_rules_shard(lm):
    """The extended rules place the vocab-parallel pair: embedding rows
    and lm_head columns over the model axis, norms replicated."""
    from mmlspark_tpu.parallel import build_param_shardings

    m, v, _ = lm
    mesh = make_mesh({"data": 2, "model": 2},
                     devices=jax.devices()[:4])
    sh = build_param_shardings(v, mesh, TRANSFORMER_TP_RULES)
    assert tuple(sh["embed"]["params"]["token"]["embedding"].spec) == \
        ("model", None)
    assert tuple(sh["z"]["params"]["head"]["kernel"].spec) == \
        (None, "model")
    assert tuple(sh["z"]["params"]["ln_f"]["scale"].spec) == ()
    assert tuple(sh["embed"]["params"]["pos"].spec) == ()
