"""Native C++ CTF parser: availability, parity with the Python parser,
fallback behavior (reference analog: the external cntk binary's native
text reader consuming DataConversion's exported CTF files)."""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.data.ctf import _read_ctf_native, read_ctf, write_ctf
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.ops.native_build import load_native


def test_native_ctf_builds():
    # The production path is the C++ op; the toolchain is in the image.
    assert load_native("ctf") is not None


def _sample_ds(n=50, d=16, seed=0):
    rng = np.random.default_rng(seed)
    feats = rng.normal(size=(n, d))
    feats[rng.random((n, d)) < 0.6] = 0.0  # sparsity
    return Dataset({
        "label": rng.integers(0, 5, n).astype(np.float64),
        "features": feats,
    })


@pytest.mark.parametrize("form", ["sparse", "dense"])
def test_native_matches_python_parser(tmp_path, form):
    ds = _sample_ds()
    path = str(tmp_path / "data.ctf")
    write_ctf(ds, path, features_form=form)
    dim = 16 if form == "sparse" else None
    native = _read_ctf_native(path, dim, "label", "features")
    assert native is not None, "native parser did not engage"
    # exact: the native parser reads float64, same as the Python path
    np.testing.assert_array_equal(native["features"], ds["features"])
    np.testing.assert_array_equal(native["label"], ds["label"])


def test_all_zero_sparse_rows(tmp_path):
    # regression: rows whose sparse field is empty (all-zero vectors) must
    # densify to zeros, not read uninitialized memory
    ds = Dataset({
        "label": np.array([1.0, 0.0]),
        "features": np.zeros((2, 8)),
    })
    path = str(tmp_path / "z.ctf")
    write_ctf(ds, path)  # sparse form -> '|features ' with no values
    out = read_ctf(path, feature_dim=8)
    np.testing.assert_array_equal(out["features"], np.zeros((2, 8)))
    np.testing.assert_array_equal(out["label"], ds["label"])
    native = _read_ctf_native(path, 8, "label", "features")
    assert native is not None
    np.testing.assert_array_equal(native["features"], np.zeros((2, 8)))


def test_read_ctf_uses_native_and_round_trips(tmp_path):
    ds = _sample_ds(n=20, d=8, seed=3)
    path = str(tmp_path / "d.ctf")
    write_ctf(ds, path)
    back = read_ctf(path, feature_dim=8)
    np.testing.assert_array_equal(back["features"], ds["features"])


def test_multidim_labels(tmp_path):
    ds = Dataset({
        "label": np.array([[1.0, 0.0], [0.0, 1.0]]),
        "features": np.array([[0.5, 0.0], [0.0, 2.0]]),
    })
    path = str(tmp_path / "m.ctf")
    write_ctf(ds, path, features_form="dense")
    out = read_ctf(path)
    assert out["label"].shape == (2, 2)
    np.testing.assert_allclose(out["label"], ds["label"])


def test_malformed_falls_back_with_error(tmp_path):
    path = str(tmp_path / "bad.ctf")
    with open(path, "w") as f:
        f.write("|label 1 |wrongname 0:1\n")
    with pytest.raises(FriendlyError):
        read_ctf(path, feature_dim=4)


def test_sparse_without_dim_errors(tmp_path):
    ds = _sample_ds(n=4, d=4)
    path = str(tmp_path / "s.ctf")
    write_ctf(ds, path)  # sparse features
    with pytest.raises(FriendlyError):
        read_ctf(path)  # no feature_dim


def test_empty_file(tmp_path):
    path = str(tmp_path / "e.ctf")
    open(path, "w").close()
    out = read_ctf(path, feature_dim=4)
    assert out.num_rows == 0


def test_empty_file_python_fallback(tmp_path, monkeypatch):
    # the pure-Python path (toolchain-less hosts) must handle empty files
    # identically to the native path
    import mmlspark_tpu.data.ctf as ctf_mod

    monkeypatch.setattr(ctf_mod, "_read_ctf_native",
                        lambda *a, **k: None)
    path = str(tmp_path / "e.ctf")
    open(path, "w").close()
    out = ctf_mod.read_ctf(path, feature_dim=4)
    assert out.num_rows == 0


def test_python_fallback_matches_native(tmp_path, monkeypatch):
    import mmlspark_tpu.data.ctf as ctf_mod

    ds = _sample_ds(n=10, d=6, seed=4)
    path = str(tmp_path / "p.ctf")
    write_ctf(ds, path)
    native = ctf_mod.read_ctf(path, feature_dim=6)
    monkeypatch.setattr(ctf_mod, "_read_ctf_native",
                        lambda *a, **k: None)
    python = ctf_mod.read_ctf(path, feature_dim=6)
    np.testing.assert_array_equal(native["features"], python["features"])
    np.testing.assert_array_equal(native["label"], python["label"])


def test_native_throughput_smoke(tmp_path):
    # not a benchmark assert — just exercise a larger file through the
    # native path end to end
    ds = _sample_ds(n=2000, d=64, seed=9)
    path = str(tmp_path / "big.ctf")
    write_ctf(ds, path)
    out = read_ctf(path, feature_dim=64)
    assert out.num_rows == 2000
    np.testing.assert_array_equal(out["features"], ds["features"])
    assert os.path.getsize(path) > 100_000


def test_duplicate_field_last_occurrence_wins(tmp_path, monkeypatch):
    """Native parser must match the Python dict semantics: a repeated
    field name keeps the LAST occurrence."""
    path = str(tmp_path / "dup.ctf")
    with open(path, "w") as f:
        f.write("|label 1 |features 0:1 |features 0:2\n")
    native = read_ctf(path, feature_dim=3)
    assert float(native["features"][0][0]) == 2.0
    # and the Python fallback agrees
    monkeypatch.setattr(
        "mmlspark_tpu.data.ctf._read_ctf_native", lambda *a: None
    )
    py = read_ctf(path, feature_dim=3)
    np.testing.assert_array_equal(
        np.asarray(native["features"]), np.asarray(py["features"])
    )


def test_tab_in_field_name_not_a_delimiter(tmp_path):
    """str.partition(' ') semantics: a tab does NOT end the field name, so
    '|features\\t0:1' is an unknown field -> FriendlyError either path."""
    path = str(tmp_path / "tab.ctf")
    with open(path, "w") as f:
        f.write("|label 1 |features\t0:1\n")
    with pytest.raises(FriendlyError):
        read_ctf(path, feature_dim=3)


def test_ragged_rows_raise_friendly_error(tmp_path, monkeypatch):
    """Python fallback wraps the np.stack width mismatch (ADVICE: was a
    raw ValueError)."""
    monkeypatch.setattr(
        "mmlspark_tpu.data.ctf._read_ctf_native", lambda *a: None
    )
    path = str(tmp_path / "ragged.ctf")
    with open(path, "w") as f:
        f.write("|label 1 |features 1 2 3\n|label 1 |features 1 2\n")
    with pytest.raises(FriendlyError, match="ragged"):
        read_ctf(path)


def test_bad_number_raises_friendly_error(tmp_path, monkeypatch):
    monkeypatch.setattr(
        "mmlspark_tpu.data.ctf._read_ctf_native", lambda *a: None
    )
    path = str(tmp_path / "badnum.ctf")
    with open(path, "w") as f:
        f.write("|label 1 |features 1 2:3 4\n")  # sparse token in dense field
    with pytest.raises(FriendlyError, match="malformed"):
        read_ctf(path)
