"""KV-cache decode TPU evidence contract (VERDICT r4 next #3).

``tools/decode_tpu_evidence.py`` runs on the chip (fired by the tunnel
pounce); whenever its committed artifact exists, validate what it
claims: compiled-path numerics parity and a per-token timing table where
the cache path beats the O(T²) recompute oracle.
"""

import json
import os

import pytest

_EVIDENCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "DECODE_TPU_EVIDENCE.json",
)


@pytest.mark.skipif(
    not os.path.exists(_EVIDENCE),
    reason="no committed DECODE_TPU_EVIDENCE.json yet",
)
def test_decode_evidence_contract():
    with open(_EVIDENCE, encoding="utf-8") as f:
        ev = json.load(f)
    assert "TPU" in ev["device_kind"]
    assert ev["numerics"]["prefill_logits_scaled_err"] <= 1e-2
    assert ev["numerics"]["greedy_token_agreement"] >= 0.95
    t = ev["timing"]
    for path in ("kv_cache", "recompute"):
        assert t[path]["per_token_ms"] > 0
        assert t[path]["t_n256_s"] >= t[path]["t_n64_s"]
    # the whole point of the cache: marginal token cost must win, and
    # per-token cost must be ~independent of generated length (the
    # difference harness already isolates the marginal cost; the ratio
    # documents the O(T) vs O(T^2) separation)
    assert t["kv_vs_recompute_speedup"] >= 1.5
