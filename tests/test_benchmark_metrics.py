"""Recorded-metrics benchmark regression — the reference's distinctive
TrainClassifier QA artifact: every (dataset, learner) combination retrains
and must reproduce the committed metrics file line-by-line
(VerifyTrainClassifier.scala:41-42,224-240 with benchmarkMetrics.csv).

Regenerate the fixture after intentional learner changes:
``python tools/make_benchmark_metrics.py``.
"""

import csv
import os

from mmlspark_tpu.testing.benchmark_metrics import run_matrix

FIXTURE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "fixtures", "benchmark_metrics.csv",
)

#: CPU-mesh reruns are deterministic, but leave float-op slack across
#: jax/XLA versions (the reference compares 2-decimal equality)
TOL = 0.015


def test_benchmark_metrics_match_recorded():
    with open(FIXTURE) as f:
        recorded = {
            (r["dataset"], r["learner"]): r for r in csv.DictReader(f)
        }
    rows = run_matrix()
    assert {(r.dataset, r.learner) for r in rows} == set(recorded), (
        "matrix shape changed; regenerate the fixture"
    )
    mismatches = []
    for r in rows:
        want = recorded[(r.dataset, r.learner)]
        if abs(r.accuracy - float(want["accuracy"])) > TOL:
            mismatches.append(
                f"{r.dataset}/{r.learner}: accuracy {r.accuracy:.4f} "
                f"!= recorded {want['accuracy']}"
            )
        if bool(want["auc"]) != bool(r.auc):
            mismatches.append(
                f"{r.dataset}/{r.learner}: AUC presence changed "
                f"(run {r.auc!r} vs recorded {want['auc']!r})"
            )
        elif want["auc"] and abs(float(r.auc) - float(want["auc"])) > TOL:
            mismatches.append(
                f"{r.dataset}/{r.learner}: AUC {r.auc} "
                f"!= recorded {want['auc']}"
            )
    assert not mismatches, "\n".join(mismatches)


REG_FIXTURE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "fixtures", "benchmark_metrics_regression.csv",
)


def test_regressor_benchmark_metrics_match_recorded():
    """TrainRegressor analog of the recorded matrix
    (VerifyTrainRegressor.scala's learner sweep)."""
    from mmlspark_tpu.testing.benchmark_metrics import run_regressor_matrix

    with open(REG_FIXTURE) as f:
        recorded = {
            (r["dataset"], r["learner"]): r for r in csv.DictReader(f)
        }
    rows = run_regressor_matrix()
    assert {(r.dataset, r.learner) for r in rows} == set(recorded), (
        "matrix shape changed; regenerate the fixture"
    )
    mismatches = []
    for r in rows:
        want = recorded[(r.dataset, r.learner)]
        if abs(r.r2 - float(want["r2"])) > TOL:
            mismatches.append(
                f"{r.dataset}/{r.learner}: R^2 {r.r2:.4f} "
                f"!= recorded {want['r2']}"
            )
        # RMSE is target-scale; compare relative to the recorded value
        if abs(r.rmse - float(want["rmse"])) > TOL * max(
            1.0, float(want["rmse"])
        ):
            mismatches.append(
                f"{r.dataset}/{r.learner}: RMSE {r.rmse:.4f} "
                f"!= recorded {want['rmse']}"
            )
    assert not mismatches, "\n".join(mismatches)
