"""Example-script tier: every examples/e*.py must run headless within the
per-notebook timeout — the analog of the reference's local notebook tests
(tools/notebook/tester/TestNotebooksLocally.py: each sample notebook
executes via nbconvert with a 600 s timeout)."""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "examples")
)
import harness  # noqa: E402

# ignore PROC_SHARD here: the pytest tier always covers every example
EXAMPLES = harness.discover([], use_shard=False)


def test_examples_discovered():
    assert len(EXAMPLES) >= 7


@pytest.mark.parametrize(
    "path", EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES]
)
def test_example_runs(path):
    ok, dt, detail = harness.run_one(path)
    assert ok, f"{os.path.basename(path)} failed after {dt:.1f}s: {detail}"
