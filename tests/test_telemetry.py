"""Unified telemetry plane (core/telemetry): deterministic histogram
quantiles, the flight recorder's ring-buffer + dump-on-error contract,
span lifecycles, and the retrace watchdog — plus the serve wiring
(``--telemetry-dir`` artifacts, ``record_reject`` wall-clock fix,
``snapshot()`` table records)."""

import json
import logging
import random
import time

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.telemetry import (
    FlightRecorder,
    Histogram,
    MetricRegistry,
    RetraceWatchdog,
    SpanTracer,
)
from mmlspark_tpu.serve.metrics import ServeMetrics

# -- histogram primitives ---------------------------------------------------


def test_histogram_percentiles_are_order_independent():
    """Same samples in ANY arrival order -> byte-identical summaries;
    that determinism is the whole point of log-bucketed bins."""
    rng = random.Random(7)
    samples = [rng.lognormvariate(2.0, 1.5) for _ in range(500)]
    summaries = []
    for _ in range(3):
        rng.shuffle(samples)
        h = Histogram("t")
        for v in samples:
            h.record(v)
        summaries.append(h.summary())
    assert summaries[0] == summaries[1] == summaries[2]


def test_histogram_relative_error_bounded_by_growth():
    rng = random.Random(3)
    samples = [rng.uniform(0.5, 400.0) for _ in range(2000)]
    h = Histogram("t", growth=1.1)
    for v in samples:
        h.record(v)
    for p in (50, 95, 99):
        exact = float(np.percentile(samples, p))
        est = h.percentile(p)
        assert abs(est - exact) / exact < 0.12, (p, est, exact)
    # count/sum/min/max are exact, not bucketed
    assert h.count == len(samples)
    assert h.min == min(samples) and h.max == max(samples)
    assert h.sum == pytest.approx(sum(samples))


def test_histogram_edges_and_empty():
    h = Histogram("t")
    assert h.percentile(50) is None and h.mean is None
    h.record(0.0)  # underflow bucket: values <= lo
    assert h.percentile(50) == 0.0  # clamped into exact [min, max]
    h2 = Histogram("t2")
    h2.record(1e12)  # overflow bucket: clamped to exact max
    assert h2.percentile(99) == 1e12
    with pytest.raises(FriendlyError):
        Histogram("bad", lo=0.0)
    with pytest.raises(FriendlyError):
        Histogram("bad", growth=1.0)


def test_registry_get_or_create_and_type_conflict():
    r = MetricRegistry()
    c = r.counter("a")
    c.inc(3)
    assert r.counter("a") is c and r.counter("a").value == 3
    r.gauge("g").set(2.5)
    r.histogram("h").record(10.0)
    with pytest.raises(FriendlyError, match="already registered"):
        r.histogram("a")
    d = r.to_dict()
    assert d["a"] == 3 and d["g"] == 2.5
    # histograms expand to <name>_{count,mean,p50,p95,p99}
    assert d["h_count"] == 1 and d["h_p50"] == 10.0
    json.dumps(d)
    names = {m.name for m in r.snapshot(model="m", group="test")}
    assert names == {"a", "g", "h"}


# -- flight recorder + spans ------------------------------------------------


def test_flight_recorder_ring_keeps_last_n():
    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("ev", tick=i)
    evs = rec.events()
    assert len(evs) == 8
    assert [e["tick"] for e in evs] == list(range(12, 20))
    assert rec.dropped == 12
    lines = rec.dump().strip().splitlines()
    # line 0 is the dump header carrying the wall-clock anchor
    header = json.loads(lines[0])
    assert header["header"] == "flight_recorder"
    assert header["events"] == 8 and header["dropped"] == 12
    assert abs((rec.t0_unix + time.monotonic()) - time.time()) < 1.0
    assert len(lines) == 9 and json.loads(lines[1])["tick"] == 12


def test_flight_recorder_dumps_on_friendly_error(tmp_path):
    rec = FlightRecorder()
    rec.record("before", tick=1, detail="context")
    path = tmp_path / "crash.jsonl"
    with pytest.raises(FriendlyError, match="boom"):
        with rec.dump_on_friendly_error(str(path)):
            rec.record("during", tick=2)
            raise FriendlyError("boom")
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert lines[0]["header"] == "flight_recorder"
    assert [e["name"] for e in lines[1:]] == ["before", "during"]
    # non-matching exceptions pass through without a dump
    with pytest.raises(ValueError):
        with rec.dump_on_friendly_error(str(tmp_path / "no.jsonl")):
            raise ValueError("not friendly")
    assert not (tmp_path / "no.jsonl").exists()


def test_span_lifecycle_and_idempotent_end():
    rec = FlightRecorder()
    tracer = SpanTracer(rec)
    s = tracer.span("request", tick=0, id=7)
    s.event("queued", tick=0, queue_depth=1)
    s.end("completed", tick=3, generated=4)
    s.end("completed", tick=9)  # second end is a no-op
    evs = rec.events()
    assert [e["name"] for e in evs] == ["start", "queued", "completed"]
    assert all(e["span"] == s.id and e["span_name"] == "request"
               for e in evs)
    assert evs[-1]["attrs"]["duration_ms"] >= 0.0
    assert tracer.span("request").id != s.id  # process-unique ids


# -- retrace watchdog -------------------------------------------------------


def test_retrace_watchdog_fires_once_per_new_shape(caplog):
    import jax
    import jax.numpy as jnp

    reg = MetricRegistry()
    rec = FlightRecorder()
    fn = jax.jit(lambda x: jnp.sum(x * 2))
    dog = RetraceWatchdog(fn, "unit", registry=reg, recorder=rec)

    with caplog.at_level(logging.INFO, logger="mmlspark_tpu.telemetry"):
        dog(jnp.zeros((4,), jnp.float32))   # first program: INFO
        assert dog.compilations == 1 and dog.retraces == 0
        dog(jnp.ones((4,), jnp.float32))    # cache hit: silent
        assert dog.compilations == 1
        dog(jnp.zeros((8,), jnp.float32))   # NEW shape: the retrace
    assert dog.compilations == 2 and dog.retraces == 1
    warnings = [r for r in caplog.records
                if r.levelno == logging.WARNING and "retrace" in r.message]
    assert len(warnings) == 1
    assert "float32[8]" in warnings[0].message  # triggering signature
    assert reg.counter("retrace.unit").value == 2
    retrace_evs = [e for e in rec.events() if e["name"] == "retrace"]
    assert len(retrace_evs) == 2
    assert "float32[8]" in retrace_evs[-1]["attrs"]["signature"]
    # compile_guard's counting contract passes through the wrapper
    assert dog._cache_size() == 2


# -- serve wiring -----------------------------------------------------------


def test_record_reject_counts_toward_wall_clock():
    """A run that ends in rejections still happened: wall_s (tokens/sec's
    denominator) must span reject-only activity."""
    m = ServeMetrics(model="m", slots=2)
    m.record_reject()
    time.sleep(0.01)
    m.record_reject()
    d = m.to_dict()
    assert d["rejected"] == 2
    assert d["wall_s"] > 0.0


def test_snapshot_emits_non_scalar_metrics_as_tables():
    m = ServeMetrics(model="m", slots=2)
    m.prefill_buckets = {"8": 3, "16": 1}
    records = m.snapshot()
    tables = {r.name: r for r in records if r.group == "table"}
    assert "serve.prefill_buckets" in tables
    assert tables["serve.prefill_buckets"].value == {"8": 3, "16": 1}


def test_demo_writes_complete_spans_and_percentiles(tmp_path):
    """The acceptance path: ``serve --demo --telemetry-dir`` persists one
    COMPLETE span per request in events.jsonl and percentile keys in
    metrics.json (in-process here; tools/check_metrics_schema.py runs
    the same contract through the real CLI)."""
    from mmlspark_tpu.serve.demo import run_demo

    n_requests = 3
    out = run_demo(slots=2, n_requests=n_requests, max_new_tokens=3,
                   arrivals_per_tick=2, vocab=32, d_model=16, heads=2,
                   depth=1, cache_len=32, seed=0,
                   telemetry_dir=str(tmp_path))

    events = [json.loads(ln) for ln in
              (tmp_path / "events.jsonl").read_text().splitlines()]
    spans = {}
    for e in events:
        if e.get("span_name") == "request":
            spans.setdefault(e["span"], []).append(e["name"])
    assert len(spans) == n_requests
    for names in spans.values():
        # full lifecycle: queued -> admitted -> prefill[bucket] ->
        # decode ticks -> terminal status with duration
        assert names[0] == "start"
        assert {"queued", "admitted", "prefill"} <= set(names)
        assert names[-1] in ("completed", "expired")
    # the watchdog's warm-up compilations ride the same timeline
    assert any(e.get("name") == "retrace" for e in events)

    metrics = json.loads((tmp_path / "metrics.json").read_text())
    for key in ("ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99",
                "per_token_ms_p50", "per_token_ms_p95", "per_token_ms_p99",
                "tick_ms_p50", "tick_ms_p95", "tick_ms_p99"):
        assert isinstance(metrics[key], (int, float)), key
    assert metrics == json.loads(json.dumps(out, default=str))
