"""Word2Vec skip-gram featurizer (notebook-202 capability)."""

from __future__ import annotations

import numpy as np
import pytest

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.word2vec import Word2Vec


def topic_ds(n=200, seed=0):
    rng = np.random.default_rng(seed)
    topics = {
        "cook": "recipe kitchen oven bake flour sugar taste meal".split(),
        "scifi": "space alien ship galaxy laser robot planet star".split(),
    }
    docs, labels = [], []
    for _ in range(n):
        k = rng.choice(list(topics))
        words = list(rng.choice(topics[k], 10)) + list(
            rng.choice(["the", "a", "and"], 3)
        )
        rng.shuffle(words)
        docs.append(" ".join(words))
        labels.append(k)
    return Dataset({"text": docs, "label": labels})


@pytest.fixture(scope="module")
def fitted():
    ds = topic_ds()
    model = Word2Vec(
        input_col="text", vector_size=16, window=4, min_count=2, epochs=3
    ).fit(ds)
    return ds, model


def test_vocab_and_vector_shapes(fitted):
    _, model = fitted
    vecs = np.asarray(model.vectors)
    assert vecs.shape == (len(model.vocabulary), 16)


def test_embeddings_cluster_by_topic(fitted):
    """Words from the same topic must be nearer than cross-topic words —
    the property the notebook's findSynonyms cell demonstrates."""
    _, model = fitted
    syns = [w for w, _ in model.find_synonyms("oven", 4)]
    cook = set("recipe kitchen bake flour sugar taste meal".split())
    assert sum(w in cook for w in syns) >= 3, syns


def test_transform_averages_word_vectors(fitted):
    _, model = fitted
    vecs = np.asarray(model.vectors, np.float64)
    idx = {t: i for i, t in enumerate(model.vocabulary)}
    ds = Dataset({"text": ["oven bake flour"]})
    out = np.asarray(model.transform(ds)["features"])[0]
    want = vecs[[idx["oven"], idx["bake"], idx["flour"]]].mean(axis=0)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_unknown_and_empty_docs_are_zero_vectors(fitted):
    _, model = fitted
    ds = Dataset({"text": ["zzz qqq unknownwords", ""]})
    out = np.asarray(model.transform(ds)["features"])
    np.testing.assert_array_equal(out, 0.0)


def test_pretokenized_input(fitted):
    _, model = fitted
    as_text = np.asarray(model.transform(
        Dataset({"text": ["oven bake"]}))["features"])
    as_tokens = np.asarray(model.transform(
        Dataset({"text": [["oven", "bake"]]}))["features"])
    np.testing.assert_allclose(as_text, as_tokens)


def test_min_count_filters_vocab():
    ds = Dataset({"text": ["rare word once", "common common common word"]})
    model = Word2Vec(
        input_col="text", vector_size=4, window=2, min_count=2, epochs=1
    ).fit(ds)
    assert "rare" not in model.vocabulary
    assert "common" in model.vocabulary


def test_find_synonyms_unknown_word_errors(fitted):
    _, model = fitted
    with pytest.raises(FriendlyError):
        model.find_synonyms("notaword", 3)


def test_save_load_roundtrip(fitted, tmp_path):
    ds, model = fitted
    before = np.asarray(model.transform(ds)["features"])
    model.save(str(tmp_path / "w2v"))
    loaded = PipelineStage.load(str(tmp_path / "w2v"))
    after = np.asarray(loaded.transform(ds)["features"])
    np.testing.assert_allclose(before, after)
