// Native image decode op: JPEG/PNG/BMP bytes -> HWC uint8 BGR buffer.
//
// TPU-native equivalent of the reference's OpenCV JNI decode layer
// (reference: readers/src/main/scala/ImageReader.scala:45-63 `Imgcodecs.imdecode`,
// loaded through core/env/src/main/scala/NativeLoader.java). The reference
// decodes every image to 3-channel BGR CV_8U rows; this op keeps the exact
// same output convention so downstream byte-level semantics match.
//
// C ABI (consumed via ctypes from mmlspark_tpu/ops/decode.py):
//   int  mml_decode_image(const uint8_t* data, size_t len,
//                         int* h, int* w, int* c, uint8_t** out);
//       returns 0 on success (caller owns *out, free with mml_free),
//       nonzero on failure (corrupt/unsupported input -> row is dropped,
//       mirroring ImageReader.decode returning None).
//   void mml_free(uint8_t* p);
//   const char* mml_decoder_version();

#include <csetjmp>
#include <cstdint>
#include <cstdio>  // jpeglib.h needs FILE
#include <cstdlib>
#include <cstring>

#include <jpeglib.h>
#include <png.h>

namespace {

// ---------------------------------------------------------------- JPEG ----

struct JpegErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErrorMgr* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

int decode_jpeg(const uint8_t* data, size_t len, int* h, int* w, int* c,
                uint8_t** out) {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_error_exit;
  uint8_t* buffer = nullptr;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    std::free(buffer);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
#ifdef JCS_EXT_BGR
  cinfo.out_color_space = JCS_EXT_BGR;  // libjpeg-turbo: BGR directly
  const bool native_bgr = true;
#else
  cinfo.out_color_space = JCS_RGB;
  const bool native_bgr = false;
#endif
  jpeg_start_decompress(&cinfo);
  const int height = static_cast<int>(cinfo.output_height);
  const int width = static_cast<int>(cinfo.output_width);
  const int channels = static_cast<int>(cinfo.output_components);
  if (channels != 3 || height <= 0 || width <= 0) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  const size_t stride = static_cast<size_t>(width) * 3;
  buffer = static_cast<uint8_t*>(std::malloc(stride * height));
  if (!buffer) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = buffer + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  if (!native_bgr) {  // RGB -> BGR swap in place
    for (size_t i = 0; i < stride * height; i += 3) {
      uint8_t t = buffer[i];
      buffer[i] = buffer[i + 2];
      buffer[i + 2] = t;
    }
  }
  *h = height;
  *w = width;
  *c = 3;
  *out = buffer;
  return 0;
}

// ----------------------------------------------------------------- PNG ----

int decode_png(const uint8_t* data, size_t len, int* h, int* w, int* c,
               uint8_t** out) {
  png_image image;
  std::memset(&image, 0, sizeof(image));
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&image, data, len)) return 1;
  image.format = PNG_FORMAT_BGR;  // force 3-channel BGR like OpenCV
  const size_t stride = PNG_IMAGE_ROW_STRIDE(image);
  const size_t size = PNG_IMAGE_SIZE(image);
  uint8_t* buffer = static_cast<uint8_t*>(std::malloc(size));
  if (!buffer) {
    png_image_free(&image);
    return 1;
  }
  if (!png_image_finish_read(&image, nullptr, buffer,
                             static_cast<png_int_32>(stride), nullptr)) {
    png_image_free(&image);
    std::free(buffer);
    return 1;
  }
  *h = static_cast<int>(image.height);
  *w = static_cast<int>(image.width);
  *c = 3;
  *out = buffer;
  return 0;
}

// ----------------------------------------------------------------- BMP ----
// Minimal uncompressed 24/32-bit BMP support (BI_RGB), bottom-up or top-down.

uint32_t rd32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

int decode_bmp(const uint8_t* data, size_t len, int* h, int* w, int* c,
               uint8_t** out) {
  if (len < 54) return 1;
  const uint32_t offset = rd32(data + 10);
  const int32_t width = static_cast<int32_t>(rd32(data + 18));
  int32_t height = static_cast<int32_t>(rd32(data + 22));
  const uint16_t bpp = static_cast<uint16_t>(data[28] | (data[29] << 8));
  const uint32_t compression = rd32(data + 30);
  const bool top_down = height < 0;
  if (top_down) height = -height;
  if (compression != 0 || (bpp != 24 && bpp != 32) || width <= 0 ||
      height <= 0 || width > 1 << 20 || height > 1 << 20)
    return 1;
  const size_t src_stride = ((static_cast<size_t>(width) * bpp / 8) + 3) & ~3u;
  if (offset + src_stride * height > len) return 1;
  const size_t dst_stride = static_cast<size_t>(width) * 3;
  uint8_t* buffer = static_cast<uint8_t*>(std::malloc(dst_stride * height));
  if (!buffer) return 1;
  const int step = bpp / 8;
  for (int y = 0; y < height; ++y) {
    const int src_y = top_down ? y : height - 1 - y;
    const uint8_t* src = data + offset + src_stride * src_y;
    uint8_t* dst = buffer + dst_stride * y;
    for (int x = 0; x < width; ++x) {
      dst[x * 3 + 0] = src[x * step + 0];  // BMP rows are already BGR
      dst[x * 3 + 1] = src[x * step + 1];
      dst[x * 3 + 2] = src[x * step + 2];
    }
  }
  *h = height;
  *w = width;
  *c = 3;
  *out = buffer;
  return 0;
}

}  // namespace

extern "C" {

int mml_decode_image(const uint8_t* data, size_t len, int* h, int* w, int* c,
                     uint8_t** out) {
  if (!data || len < 8 || !h || !w || !c || !out) return 1;
  if (data[0] == 0xFF && data[1] == 0xD8) return decode_jpeg(data, len, h, w, c, out);
  if (data[0] == 0x89 && data[1] == 'P' && data[2] == 'N' && data[3] == 'G')
    return decode_png(data, len, h, w, c, out);
  if (data[0] == 'B' && data[1] == 'M') return decode_bmp(data, len, h, w, c, out);
  return 1;
}

void mml_free(uint8_t* p) { std::free(p); }

const char* mml_decoder_version() { return "mml-decode 1.0 (jpeg/png/bmp)"; }

}  // extern "C"
