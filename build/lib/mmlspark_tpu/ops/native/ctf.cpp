// Native CNTK-text-format (CTF) parser: file -> dense float32 matrices.
//
// TPU-native equivalent of the reference's native text reader: the external
// `cntk` binary parses the exported CTF file (`|label v ... |features i:v ...`
// lines, written by cntk-train/src/main/scala/DataConversion.scala:86-96)
// in C++ inside its reader block (BrainscriptBuilder.scala:94-101). Here the
// same format parses natively into host buffers ready for device feed.
//
// C ABI (consumed via ctypes from mmlspark_tpu/data/ctf.py):
//   int mml_parse_ctf(const char* path,
//                     const char* label_name, const char* feat_name,
//                     int feature_dim,          // >0 to densify sparse feats
//                     double** labels_out, int* label_width,
//                     double** feats_out, int* feat_width, long* rows);
//     returns 0 on success (caller owns both buffers, free with
//     mml_ctf_free); nonzero on any malformed/unsupported input, in which
//     case the caller falls back to the pure-Python parser for a precise
//     error message.
//   void mml_ctf_free(float* p);
//   const char* mml_ctf_version();

#include <cctype>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int kOk = 0;
constexpr int kIoError = 1;
constexpr int kBadField = 2;     // missing/sparse-label/unsupported form
constexpr int kRaggedRow = 3;    // row width differs from first row
constexpr int kBadNumber = 4;

struct Field {
  const char* begin = nullptr;
  const char* end = nullptr;
};

// find "|<name> values..." within [line, line_end); values exclude the name.
// Mirrors the Python fallback's dict semantics exactly: the field name runs
// to the first SPACE (' ' only — a tab stays part of the name, like
// str.partition(" ")), and when a name repeats the LAST occurrence wins.
bool find_field(const char* line, const char* line_end,
                const char* name, size_t name_len, Field* out) {
  bool found = false;
  const char* p = line;
  while (p < line_end) {
    const char* bar = static_cast<const char*>(
        memchr(p, '|', static_cast<size_t>(line_end - p)));
    if (!bar) break;
    const char* fname = bar + 1;
    const char* fend = fname;
    while (fend < line_end && *fend != ' ' && *fend != '|') ++fend;
    const char* vend = static_cast<const char*>(
        memchr(fend, '|', static_cast<size_t>(line_end - fend)));
    if (!vend) vend = line_end;
    if (static_cast<size_t>(fend - fname) == name_len &&
        memcmp(fname, name, name_len) == 0) {
      out->begin = fend;
      out->end = vend;
      found = true;  // keep scanning: last duplicate wins
    }
    p = vend;
  }
  return found;
}

// parse "v v v" (dense) or "i:v i:v" (sparse, dim>0) into row; returns
// parsed width for dense, dim for sparse, or -1 on error. An empty field
// with dim>0 yields dim zeros (an all-zero sparse vector — matches the
// Python parser's _parse_values("") semantics).
int parse_values(const Field& f, int dim, std::vector<double>* row) {
  const char* p = f.begin;
  bool sparse = false;
  bool first = true;
  size_t start = row->size();
  while (p < f.end) {
    while (p < f.end && isspace(static_cast<unsigned char>(*p))) ++p;
    if (p >= f.end) break;
    char* next = nullptr;
    if (first) {
      // detect sparse form from the first token
      const char* q = p;
      while (q < f.end && !isspace(static_cast<unsigned char>(*q))) {
        if (*q == ':') { sparse = true; break; }
        ++q;
      }
      if (sparse) {
        if (dim <= 0) return -1;  // sparse without a declared dim
        row->resize(start + static_cast<size_t>(dim), 0.0);
      }
      first = false;
    }
    if (sparse) {
      long idx = strtol(p, &next, 10);
      if (next == p || *next != ':' || idx < 0 || idx >= dim) return -1;
      p = next + 1;
      double v = strtod(p, &next);
      if (next == p) return -1;
      (*row)[start + static_cast<size_t>(idx)] = v;
      p = next;
    } else {
      double v = strtod(p, &next);
      if (next == p) return -1;
      row->push_back(v);
      p = next;
    }
  }
  if (row->size() == start && dim > 0) {
    row->resize(start + static_cast<size_t>(dim), 0.0);
  }
  return static_cast<int>(row->size() - start);
}

double* to_owned(const std::vector<double>& v) {
  size_t bytes = (v.empty() ? 1 : v.size()) * sizeof(double);
  double* out = static_cast<double*>(malloc(bytes));
  if (out && !v.empty()) memcpy(out, v.data(), v.size() * sizeof(double));
  return out;
}

}  // namespace

extern "C" {

int mml_parse_ctf(const char* path, const char* label_name,
                  const char* feat_name, int feature_dim,
                  double** labels_out, int* label_width,
                  double** feats_out, int* feat_width, long* rows_out) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return kIoError;
  std::string data;
  char buf[1 << 16];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), fp)) > 0) data.append(buf, n);
  bool read_err = ferror(fp) != 0;
  fclose(fp);
  if (read_err) return kIoError;

  const size_t lname_len = strlen(label_name);
  const size_t fname_len = strlen(feat_name);
  std::vector<double> labels, feats;
  int lw = -1, fw = -1;
  long rows = 0;

  const char* p = data.data();
  const char* end = p + data.size();
  while (p < end) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* line_end = nl ? nl : end;
    // skip blank lines
    const char* q = p;
    while (q < line_end && isspace(static_cast<unsigned char>(*q))) ++q;
    if (q < line_end) {
      Field lf, ff;
      if (!find_field(p, line_end, label_name, lname_len, &lf) ||
          !find_field(p, line_end, feat_name, fname_len, &ff)) {
        return kBadField;
      }
      // labels: dense only in the native fast path (the reference always
      // exports dense labels, DataConversion.scala:86-96)
      int got = parse_values(lf, -1, &labels);
      if (got < 0) return kBadField;
      if (lw == -1) lw = got;
      else if (got != lw) return kRaggedRow;
      got = parse_values(ff, feature_dim, &feats);
      if (got < 0) return kBadNumber;
      if (fw == -1) fw = got;
      else if (got != fw) return kRaggedRow;
      ++rows;
    }
    p = line_end + 1;
  }
  if (rows == 0) {
    // empty file: zero rows with unknown widths
    lw = 1;
    fw = feature_dim > 0 ? feature_dim : 0;
  } else if (lw <= 0 || fw <= 0) {
    // rows exist but some field never produced values (e.g. dense-empty
    // without a declared dim) — let the Python parser report it
    return kBadField;
  }
  *labels_out = to_owned(labels);
  *feats_out = to_owned(feats);
  if (!*labels_out || !*feats_out) {
    free(*labels_out);
    free(*feats_out);
    return kIoError;
  }
  *label_width = lw;
  *feat_width = fw;
  *rows_out = rows;
  return kOk;
}

void mml_ctf_free(double* p) { free(p); }

const char* mml_ctf_version() { return "mml-ctf-2"; }

}  // extern "C"
