"""Image decode: encoded bytes -> HWC uint8 BGR array.

Equivalent of reference ``ImageReader.decode``
(readers/src/main/scala/ImageReader.scala:45-63): OpenCV ``imdecode`` behind
JNI, always producing 3-channel BGR CV_8U; decode failure -> row dropped.

Primary path is the C++ op (mmlspark_tpu/ops/native/decode.cpp, via ctypes);
fallback is PIL (decodes RGB, converted to BGR here) so the framework works
without a toolchain — the native path is the production one.
"""

from __future__ import annotations

import ctypes
import io

import numpy as np

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.ops import native_build

_log = get_logger("decode")


def _decode_native(data: bytes) -> np.ndarray | None:
    lib = native_build.load_library()
    if lib is None:
        return None
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    out = ctypes.POINTER(ctypes.c_uint8)()
    rc = lib.mml_decode_image(
        data, len(data), ctypes.byref(h), ctypes.byref(w), ctypes.byref(c),
        ctypes.byref(out),
    )
    if rc != 0:
        return None
    try:
        n = h.value * w.value * c.value
        arr = np.ctypeslib.as_array(out, shape=(n,)).copy()
        return arr.reshape(h.value, w.value, c.value)
    finally:
        lib.mml_free(out)


def _decode_pil(data: bytes) -> np.ndarray | None:
    try:
        from PIL import Image
    except ImportError:  # pragma: no cover
        return None
    try:
        img = Image.open(io.BytesIO(data)).convert("RGB")
        rgb = np.asarray(img, dtype=np.uint8)
        return rgb[:, :, ::-1].copy()  # RGB -> BGR
    except Exception:
        return None


def decode_image(data: bytes) -> np.ndarray | None:
    """Decode to (H, W, 3) uint8 BGR, or None for non-decodable input (the
    caller drops the row, mirroring ImageReader.decode => None)."""
    if not isinstance(data, (bytes, bytearray)) or len(data) < 8:
        return None
    out = _decode_native(bytes(data))
    if out is None:
        # Fall back to PIL for formats the native op doesn't cover (GIF,
        # TIFF, WebP, CMYK JPEG, ...) so row counts do not depend on
        # whether a toolchain was available.
        out = _decode_pil(bytes(data))
    return out


def native_available() -> bool:
    return native_build.load_library() is not None


def encode_ppm(arr: np.ndarray) -> bytes:
    """Tiny BGR->PPM encoder used by tests/fixtures (no native dep)."""
    h, w, _ = arr.shape
    header = f"P6\n{w} {h}\n255\n".encode()
    return header + arr[:, :, ::-1].astype(np.uint8).tobytes()
