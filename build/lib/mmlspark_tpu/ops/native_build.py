"""Build + load machinery for the native ops libraries.

Plays the role of the reference's ``NativeLoader``
(core/env/src/main/scala/NativeLoader.java: extract shared lib from jar
resources, ``System.load`` once per JVM): here we compile each ``.cpp`` with
the system toolchain on first use, cache the ``.so`` next to the source, and
``ctypes.CDLL`` it once per process. Each library degrades gracefully: a
missing toolchain returns None and callers fall back to pure Python.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Callable

from mmlspark_tpu.core.logging_utils import get_logger

_log = get_logger("native")

_SRC_DIR = os.path.join(os.path.dirname(__file__), "native")


def _configure_decode(lib: ctypes.CDLL) -> None:
    lib.mml_decode_image.restype = ctypes.c_int
    lib.mml_decode_image.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
    ]
    lib.mml_free.restype = None
    lib.mml_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.mml_decoder_version.restype = ctypes.c_char_p


def _configure_ctf(lib: ctypes.CDLL) -> None:
    lib.mml_parse_ctf.restype = ctypes.c_int
    lib.mml_parse_ctf.argtypes = [
        ctypes.c_char_p,  # path
        ctypes.c_char_p,  # label field name
        ctypes.c_char_p,  # features field name
        ctypes.c_int,     # feature_dim (<=0: dense only)
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_long),
    ]
    lib.mml_ctf_free.restype = None
    lib.mml_ctf_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
    lib.mml_ctf_version.restype = ctypes.c_char_p


@dataclass
class _NativeLib:
    src: str
    so: str
    configure: Callable[[ctypes.CDLL], None]
    link_flags: list = field(default_factory=list)
    lock: threading.Lock = field(default_factory=threading.Lock)
    lib: ctypes.CDLL | None = None
    build_failed: bool = False


_LIBS: dict[str, _NativeLib] = {
    "decode": _NativeLib(
        src=os.path.join(_SRC_DIR, "decode.cpp"),
        so=os.path.join(_SRC_DIR, "libmmlimg.so"),
        configure=_configure_decode,
        link_flags=["-ljpeg", "-lpng"],
    ),
    "ctf": _NativeLib(
        src=os.path.join(_SRC_DIR, "ctf.cpp"),
        so=os.path.join(_SRC_DIR, "libmmlctf.so"),
        configure=_configure_ctf,
    ),
}


def _compile(entry: _NativeLib) -> bool:
    from mmlspark_tpu.core import config

    cmd = [
        config.get("native_cc"), "-O2", "-fPIC", "-shared", "-std=c++17",
        entry.src, "-o", entry.so, *entry.link_flags,
    ]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:  # no toolchain
        _log.warning("native build unavailable for %s: %s", entry.src, e)
        return False
    if res.returncode != 0:
        _log.warning("native build failed for %s:\n%s", entry.src,
                     res.stderr[-2000:])
        return False
    return True


def load_native(name: str) -> ctypes.CDLL | None:
    """Compile-if-needed and dlopen a registered native library; None if
    unavailable (callers fall back to pure Python)."""
    from mmlspark_tpu.core import config

    entry = _LIBS[name]
    with entry.lock:
        if entry.lib is not None:
            return entry.lib
        if entry.build_failed:
            return None
        if not config.get("native_build"):
            return None  # Python fallbacks by configuration
        if not os.path.exists(entry.so) or os.path.getmtime(
            entry.so
        ) < os.path.getmtime(entry.src):
            if not _compile(entry):
                entry.build_failed = True
                return None
        try:
            lib = ctypes.CDLL(entry.so)
        except OSError as e:
            _log.warning("native load failed for %s: %s", entry.so, e)
            entry.build_failed = True
            return None
        entry.configure(lib)
        entry.lib = lib
        return entry.lib


def load_library() -> ctypes.CDLL | None:
    """The image-decode library (legacy single-lib entry point)."""
    return load_native("decode")
