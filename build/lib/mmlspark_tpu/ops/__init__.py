"""Ops: native host-side kernels (image decode) and device-side image ops.

The reference's L0 native layer (SURVEY.md §2.10) split across:
- :mod:`mmlspark_tpu.ops.decode` — C++ decode op (OpenCV-imdecode equivalent)
- :mod:`mmlspark_tpu.ops.image_ops` — vectorized NHWC ops on device (the
  OpenCV geometric/filter ops re-expressed as XLA-compilable JAX functions)
"""
