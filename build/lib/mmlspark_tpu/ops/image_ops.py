"""Image ops: OpenCV-semantics transforms, host (per-row numpy) and device
(batched NHWC jax) paths.

The reference runs OpenCV ops inside a per-row UDF via JNI
(image-transformer/src/main/scala/ImageTransformer.scala:21-252:
resize/crop/colorFormat/blur/threshold/gaussianKernel/flip on BGR CV_8U
Mats). Here every op has:

- a numpy implementation on one HWC uint8 BGR image (exact, handles
  per-image sizes), used by ImageTransformer for ragged inputs, and
- where it matters for the hot path, a jax NHWC batch implementation that
  XLA fuses on device (resize for the featurizer feed).

Threshold type codes mirror OpenCV: binary, binary_inv, trunc, tozero,
tozero_inv. Flip codes mirror OpenCV: 0 = vertical (up/down), 1 =
horizontal (left/right), -1 = both.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError

# -- host (single image, HWC uint8) -----------------------------------------


def resize(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear resize (OpenCV INTER_LINEAR default semantics)."""
    import jax

    out = jax.image.resize(
        img.astype(np.float32),
        (height, width, img.shape[2]),
        method="bilinear",
    )
    return np.clip(np.asarray(out), 0, 255).round().astype(np.uint8)


def crop(img: np.ndarray, x: int, y: int, height: int, width: int) -> np.ndarray:
    h, w = img.shape[:2]
    if y + height > h or x + width > w or x < 0 or y < 0:
        raise FriendlyError(
            f"crop ({x},{y},{height},{width}) outside image {h}x{w}"
        )
    return img[y : y + height, x : x + width]


def color_format(img: np.ndarray, format: str) -> np.ndarray:
    """'gray' via OpenCV BGR weights; 'bgr' passthrough."""
    if format == "bgr":
        return img
    if format == "gray":
        b, g, r = img[..., 0], img[..., 1], img[..., 2]
        gray = 0.114 * b + 0.587 * g + 0.299 * r
        return np.clip(gray, 0, 255).round().astype(np.uint8)[..., None]
    raise FriendlyError(f"unknown color format '{format}'")


def _box_kernel(ky: int, kx: int) -> np.ndarray:
    return np.full((ky, kx), 1.0 / (ky * kx))


def _conv2d_same(img: np.ndarray, kernel: np.ndarray) -> np.ndarray:
    """Depthwise 2-D convolution, reflect-101 border (OpenCV default)."""
    ky, kx = kernel.shape
    py, px = ky // 2, kx // 2
    out = np.empty_like(img, dtype=np.float64)
    for c in range(img.shape[2]):
        padded = np.pad(
            img[..., c].astype(np.float64), ((py, py), (px, px)), mode="reflect"
        )
        acc = np.zeros(img.shape[:2], dtype=np.float64)
        for dy in range(ky):
            for dx in range(kx):
                acc += kernel[dy, dx] * padded[
                    dy : dy + img.shape[0], dx : dx + img.shape[1]
                ]
        out[..., c] = acc
    return np.clip(out, 0, 255).round().astype(np.uint8)


def blur(img: np.ndarray, ky: int, kx: int) -> np.ndarray:
    """Normalized box blur (OpenCV blur)."""
    return _conv2d_same(img, _box_kernel(int(ky), int(kx)))


def gaussian_kernel(img: np.ndarray, aperture: int, sigma: float) -> np.ndarray:
    """Gaussian filter (OpenCV GaussianBlur/filter2D w/ getGaussianKernel)."""
    n = int(aperture)
    if sigma <= 0:
        sigma = 0.3 * ((n - 1) * 0.5 - 1) + 0.8  # OpenCV default sigma rule
    ax = np.arange(n) - (n - 1) / 2.0
    g = np.exp(-(ax**2) / (2 * sigma**2))
    g /= g.sum()
    return _conv2d_same(img, np.outer(g, g))


THRESHOLD_TYPES = ("binary", "binary_inv", "trunc", "tozero", "tozero_inv")


def threshold(
    img: np.ndarray, thresh: float, max_val: float, kind: str = "binary"
) -> np.ndarray:
    f = img.astype(np.float64)
    if kind == "binary":
        out = np.where(f > thresh, max_val, 0.0)
    elif kind == "binary_inv":
        out = np.where(f > thresh, 0.0, max_val)
    elif kind == "trunc":
        out = np.minimum(f, thresh)
    elif kind == "tozero":
        out = np.where(f > thresh, f, 0.0)
    elif kind == "tozero_inv":
        out = np.where(f > thresh, 0.0, f)
    else:
        raise FriendlyError(
            f"unknown threshold type '{kind}'; one of {THRESHOLD_TYPES}"
        )
    return np.clip(out, 0, 255).round().astype(np.uint8)


def flip(img: np.ndarray, code: int = 1) -> np.ndarray:
    """OpenCV flip codes: 0 vertical, positive horizontal, negative both."""
    if code == 0:
        return img[::-1]
    if code > 0:
        return img[:, ::-1]
    return img[::-1, ::-1]


# -- device (batched NHWC) ---------------------------------------------------


def batch_resize_nhwc(batch, height: int, width: int):
    """Bilinear resize of an NHWC batch on device (jit/XLA path — the
    featurizer's resize-to-model-input feed)."""
    import jax

    n, _, _, c = batch.shape
    return jax.image.resize(
        batch, (n, height, width, c), method="bilinear"
    )


def batch_normalize_nhwc(batch, mean=None, std=None, scale=1.0 / 255.0):
    """uint8 NHWC -> float32 normalized (fused with the model by XLA)."""
    import jax.numpy as jnp

    x = batch.astype(jnp.float32) * scale
    if mean is not None:
        x = x - jnp.asarray(mean, jnp.float32)
    if std is not None:
        x = x / jnp.asarray(std, jnp.float32)
    return x
