"""ResNet family (TPU-first: NHWC, bfloat16 compute, MXU-sized convs).

Flagship inference model: ResNet-20 for CIFAR-10 — the model the reference's
north-star notebook evaluates (notebooks/samples/301 - CIFAR10 CNTK CNN
Evaluation.ipynb, `ConvNet_CIFAR10.model` via CNTKModel). ResNet-50 is the
transfer-learning featurizer (notebooks 303/305, ModelDownloader "ResNet50"
schema with ``layerNames`` cut points).

Design notes (pallas_guide / scaling-book mental model):
- NHWC layout end-to-end: XLA:TPU tiles the C dim onto lanes; channels are
  kept multiples of 8 where practical.
- compute in bfloat16, params + BN stats in float32 (Kaiming-style init).
- No Python control flow on data; blocks are static — jit traces once.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.graph import FINAL_NODE, NamedGraph
from mmlspark_tpu.models.registry import register_model


class ConvBN(nn.Module):
    """Conv + BatchNorm + optional ReLU, NHWC, bf16 compute."""

    features: int
    kernel: tuple[int, int] = (3, 3)
    strides: tuple[int, int] = (1, 1)
    use_relu: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(
            self.features,
            self.kernel,
            self.strides,
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
        )(x)
        if self.use_relu:
            x = nn.relu(x)
        return x


class ResBlock(nn.Module):
    """Basic (2-conv) residual block."""

    features: int
    strides: tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = ConvBN(self.features, strides=self.strides, dtype=self.dtype)(x, train)
        y = ConvBN(self.features, use_relu=False, dtype=self.dtype)(y, train)
        if residual.shape != y.shape:
            residual = ConvBN(
                self.features,
                kernel=(1, 1),
                strides=self.strides,
                use_relu=False,
                dtype=self.dtype,
            )(x, train)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1-3-1 bottleneck block (ResNet-50 style)."""

    features: int  # bottleneck width; output is 4x
    strides: tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = ConvBN(self.features, kernel=(1, 1), dtype=self.dtype)(x, train)
        y = ConvBN(self.features, strides=self.strides, dtype=self.dtype)(y, train)
        y = ConvBN(
            self.features * 4, kernel=(1, 1), use_relu=False, dtype=self.dtype
        )(y, train)
        if residual.shape != y.shape:
            residual = ConvBN(
                self.features * 4,
                kernel=(1, 1),
                strides=self.strides,
                use_relu=False,
                dtype=self.dtype,
            )(x, train)
        return nn.relu(y + residual)


class Stage(nn.Module):
    """A stack of residual blocks at one resolution."""

    block: Any
    features: int
    count: int
    first_strides: tuple[int, int]
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        for i in range(self.count):
            strides = self.first_strides if i == 0 else (1, 1)
            x = self.block(self.features, strides=strides, dtype=self.dtype)(
                x, train
            )
        return x


class GlobalPool(nn.Module):
    @nn.compact
    def __call__(self, x):
        return jnp.mean(x, axis=(1, 2))


class Logits(nn.Module):
    num_classes: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.num_classes, dtype=self.dtype, param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class Stem(nn.Module):
    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int]
    max_pool: bool
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = ConvBN(
            self.features, kernel=self.kernel, strides=self.strides, dtype=self.dtype
        )(x, train)
        if self.max_pool:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        return x


@register_model("resnet20_cifar10")
def resnet20_cifar10(num_classes: int = 10, width: int = 16) -> NamedGraph:
    """ResNet-20 (3 stages x 3 basic blocks) for 32x32 inputs — the CIFAR-10
    eval model of reference notebook 301."""
    dt = jnp.bfloat16
    blocks: list[tuple[str, Any]] = [
        ("stem", Stem(width, (3, 3), (1, 1), max_pool=False, dtype=dt)),
        ("stage1", Stage(ResBlock, width, 3, (1, 1), dtype=dt)),
        ("stage2", Stage(ResBlock, width * 2, 3, (2, 2), dtype=dt)),
        ("stage3", Stage(ResBlock, width * 4, 3, (2, 2), dtype=dt)),
        ("pool", GlobalPool()),
        (FINAL_NODE, Logits(num_classes, dtype=dt)),
    ]
    return NamedGraph(
        name="resnet20_cifar10", blocks=blocks, input_shape=(32, 32, 3)
    )


@register_model("resnet50")
def resnet50(num_classes: int = 1000, input_size: int = 224) -> NamedGraph:
    """ResNet-50 (bottleneck 3-4-6-3) — the transfer-learning featurizer of
    reference notebooks 303/305; cut at 'pool' for 2048-d features (the
    layerNames/cutOutputLayers mechanism, ImageFeaturizer.scala:122)."""
    dt = jnp.bfloat16
    blocks: list[tuple[str, Any]] = [
        ("stem", Stem(64, (7, 7), (2, 2), max_pool=True, dtype=dt)),
        ("stage1", Stage(BottleneckBlock, 64, 3, (1, 1), dtype=dt)),
        ("stage2", Stage(BottleneckBlock, 128, 4, (2, 2), dtype=dt)),
        ("stage3", Stage(BottleneckBlock, 256, 6, (2, 2), dtype=dt)),
        ("stage4", Stage(BottleneckBlock, 512, 3, (2, 2), dtype=dt)),
        ("pool", GlobalPool()),
        (FINAL_NODE, Logits(num_classes, dtype=dt)),
    ]
    return NamedGraph(
        name="resnet50",
        blocks=blocks,
        input_shape=(input_size, input_size, 3),
    )
