"""Pipeline-parallel transformer LM.

The trunk (a stack of identical transformer Blocks) is partitioned into
``pipe``-axis stages and executed with
:func:`mmlspark_tpu.parallel.pipeline.pipeline_apply`; the embedding and LM
head run data-parallel outside the pipeline (they are not homogeneous with
the trunk). No reference counterpart exists — the reference's only
parallelism is data parallelism (SURVEY.md §2.5); this is part of the
first-class distributed design the TPU build adds.

Duck-types :class:`~mmlspark_tpu.models.graph.NamedGraph` (init / apply /
layer_names / param_count) so :class:`~mmlspark_tpu.train.trainer.SPMDTrainer`
drives it unchanged — pass ``param_rules=PIPELINE_STAGE_RULES`` (plus a mesh
with a ``pipe`` axis) and the stacked stage params shard one-stage-per-rank.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import flax.linen as nn

from mmlspark_tpu.core.exceptions import FriendlyError, ParamError
from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.models.graph import FINAL_NODE
from mmlspark_tpu.models.registry import register_model
from mmlspark_tpu.models.transformer import Block, LMHead, TokenPosEmbed
from mmlspark_tpu.parallel.mesh import PIPELINE_AXIS

_log = get_logger("models.pipelined")


class _Stage(nn.Module):
    """One pipeline stage: ``layers`` consecutive transformer Blocks."""

    layers: int
    heads: int
    head_dim: int
    d_ff: int
    causal: bool

    @nn.compact
    def __call__(self, x):
        for i in range(self.layers):
            x = Block(self.heads, self.head_dim, self.d_ff, self.causal,
                      "dense", None, name=f"layer{i}")(x)
        return x


@dataclass
class PipelinedGraph:
    """NamedGraph-shaped wrapper whose trunk runs as a device pipeline.

    Variables layout: ``{"embed": ..., "stages": ..., "z": ...}`` where
    ``stages`` params carry a leading stacked dim of size ``n_stages``.
    """

    name: str
    embed: Any
    stage: Any
    head: Any
    n_stages: int
    n_microbatches: int
    mesh: Any
    input_shape: tuple = ()
    extra: dict = field(default_factory=dict)

    @property
    def layer_names(self) -> list[str]:
        return ["embed", "stages", FINAL_NODE]

    def init(self, rng, sample):
        r_embed, r_stage, r_head = jax.random.split(rng, 3)
        v_embed = self.embed.init({"params": r_embed}, sample)
        x = self.embed.apply(v_embed, sample)
        stage_rngs = jax.random.split(r_stage, self.n_stages)
        v_stages = jax.vmap(
            lambda r: self.stage.init({"params": r}, x)
        )(stage_rngs)
        # thread the sample through every stage so the head sees the true
        # trunk output shape (shapes are stage-invariant by construction)
        for i in range(self.n_stages):
            v_i = jax.tree_util.tree_map(lambda a, i=i: a[i], v_stages)
            x = self.stage.apply(v_i, x)
        v_head = self.head.init({"params": r_head}, x)
        return {"embed": v_embed, "stages": v_stages, FINAL_NODE: v_head}

    def apply(self, variables, x, output_node=None, train: bool = False,
              rngs=None, mask=None):
        from mmlspark_tpu.models.graph import resolve_node
        from mmlspark_tpu.parallel.pipeline import pipeline_apply

        stop = resolve_node(self.layer_names, output_node, self.name)
        h = self.embed.apply(variables["embed"], x)
        if stop == "embed":
            return (h, variables) if train else h
        b = h.shape[0]
        m = self._pick_microbatches(b)
        if m is None:
            # no valid microbatching for this batch (tiny init/probe
            # traces, or a batch not divisible into stage multiples):
            # sequential stage application — same math, no pipeline
            for i in range(self.n_stages):
                v_i = jax.tree_util.tree_map(
                    lambda a, i=i: a[i], variables["stages"]
                )
                h = self.stage.apply(v_i, h)
        else:
            mb = h.reshape((m, b // m) + h.shape[1:])
            out = pipeline_apply(
                lambda p, t: self.stage.apply(p, t),
                variables["stages"],
                mb,
                self.mesh,
            )
            h = out.reshape((b,) + out.shape[2:])
        if stop == "stages":
            return (h, variables) if train else h
        logits = self.head.apply(variables[FINAL_NODE], h)
        return (logits, variables) if train else logits

    def _pick_microbatches(self, batch: int) -> int | None:
        """Largest microbatch count <= n_microbatches that divides
        ``batch`` and is a stage-count multiple; None when the pipeline
        schedule cannot run (falls back to sequential stages)."""
        for m in range(min(self.n_microbatches, batch), 0, -1):
            if batch % m == 0 and m % self.n_stages == 0:
                return m
        if batch >= self.n_stages:
            _log.warning(
                "batch %d not divisible into %d-stage microbatches; "
                "running stages sequentially (no pipelining) — pick a "
                "batch size divisible by n_microbatches (%d)",
                batch, self.n_stages, self.n_microbatches,
            )
        return None

    def param_count(self, variables) -> int:
        from mmlspark_tpu.models.graph import count_params

        return count_params(variables)


@register_model("transformer_lm_pipelined")
def transformer_lm_pipelined(
    vocab_size: int = 1024,
    d_model: int = 128,
    heads: int = 4,
    depth: int = 4,
    d_ff: int = 0,
    max_len: int = 512,
    causal: bool = True,
    mesh: Any = None,
    n_stages: int | None = None,
    n_microbatches: int | None = None,
) -> PipelinedGraph:
    """Decoder-only LM whose blocks run pipeline-parallel over the
    ``pipe`` mesh axis. ``depth`` must divide evenly into ``n_stages``
    (default: the mesh's pipe-axis size)."""
    if mesh is None or PIPELINE_AXIS not in mesh.shape:
        raise FriendlyError(
            "transformer_lm_pipelined needs a mesh with a "
            f"'{PIPELINE_AXIS}' axis"
        )
    if d_model % heads:
        raise ParamError(f"d_model {d_model} not divisible by heads {heads}")
    n_stages = n_stages or mesh.shape[PIPELINE_AXIS]
    if n_stages != mesh.shape[PIPELINE_AXIS]:
        raise FriendlyError(
            f"n_stages {n_stages} != mesh '{PIPELINE_AXIS}' size "
            f"{mesh.shape[PIPELINE_AXIS]}"
        )
    if depth % n_stages:
        raise ParamError(
            f"depth {depth} not divisible by {n_stages} pipeline stages"
        )
    if n_microbatches is not None and (
        n_microbatches <= 0 or n_microbatches % n_stages
    ):
        raise ParamError(
            f"n_microbatches {n_microbatches} must be a positive multiple "
            f"of the pipeline depth {n_stages}"
        )
    d_ff = d_ff or 4 * d_model
    stage = _Stage(depth // n_stages, heads, d_model // heads, d_ff, causal)
    return PipelinedGraph(
        name="transformer_lm_pipelined",
        embed=TokenPosEmbed(vocab_size, d_model, max_len),
        stage=stage,
        head=LMHead(vocab_size),
        n_stages=n_stages,
        n_microbatches=n_microbatches or n_stages,
        mesh=mesh,
        input_shape=(max_len,),
        extra={"vocab_size": vocab_size, "causal": causal},
    )
