"""BiLSTM sequence tagger.

Capability target: reference notebook 304 (Medical Entity Extraction) runs a
downloaded opaque CNTK BiLSTM graph through CNTKModel with notebook-side
padding/embedding (SURVEY.md §5 "long-context": the reference has no sequence
parallelism; sequence handling is pad-to-max + per-token tagging). Here the
model is first-class: embedding -> bidirectional LSTM (lax.scan under the
hood via flax nn.RNN — compiler-friendly sequential control flow) -> per-token
logits. Long sequences shard over the mesh's data axis; sequence-dim sharding
for multi-chip is provided by the parallel layer (shard_map over tokens), an
upgrade beyond reference parity.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.graph import FINAL_NODE, NamedGraph
from mmlspark_tpu.models.registry import register_model


class TokenEmbed(nn.Module):
    vocab_size: int
    features: int

    @nn.compact
    def __call__(self, ids):
        # ids: (B, T) int32
        return nn.Embed(self.vocab_size, self.features, param_dtype=jnp.float32)(
            ids
        )


class BiLSTM(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x):
        # x: (B, T, E) -> (B, T, 2*features)
        fwd = nn.RNN(nn.OptimizedLSTMCell(self.features))
        bwd = nn.RNN(nn.OptimizedLSTMCell(self.features), reverse=True,
                     keep_order=True)
        return nn.Bidirectional(fwd, bwd)(x)


class TokenLogits(nn.Module):
    num_tags: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Dense(self.num_tags, dtype=self.dtype, param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


@register_model("bilstm_tagger")
def bilstm_tagger(
    vocab_size: int = 10000,
    embed_dim: int = 128,
    hidden: int = 128,
    num_tags: int = 8,
) -> NamedGraph:
    blocks: list[tuple[str, Any]] = [
        ("embed", TokenEmbed(vocab_size, embed_dim)),
        ("bilstm", BiLSTM(hidden)),
        (FINAL_NODE, TokenLogits(num_tags)),
    ]
    return NamedGraph(name="bilstm_tagger", blocks=blocks)
