"""MLP / linear model family.

The learners behind ``TrainClassifier``/``TrainRegressor``'s neural options
(reference supports Spark MLlib LogisticRegression / MultilayerPerceptron /
LinearRegression among its learner list, TrainClassifier.scala:45-52 and the
MLP input-layer resize logic at :167-174) and the CNTKLearner's default
BrainScript nets. Dense layers map straight onto the MXU.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from mmlspark_tpu.models.graph import FINAL_NODE, NamedGraph
from mmlspark_tpu.models.registry import register_model


class DenseRelu(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Dense(self.features, dtype=self.dtype, param_dtype=jnp.float32)(x)
        return nn.relu(x)


class DenseOut(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Dense(self.features, dtype=self.dtype, param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


@register_model("mlp")
def mlp(
    num_outputs: int = 2,
    hidden: Sequence[int] = (128, 128),
) -> NamedGraph:
    blocks: list[tuple[str, Any]] = [
        (f"hidden{i + 1}", DenseRelu(h)) for i, h in enumerate(hidden)
    ]
    blocks.append((FINAL_NODE, DenseOut(num_outputs)))
    return NamedGraph(name="mlp", blocks=blocks)


@register_model("linear")
def linear(num_outputs: int = 1) -> NamedGraph:
    """Single dense layer: logistic regression (with softmax/sigmoid applied
    by the loss/eval layer) or linear regression."""
    return NamedGraph(
        name="linear", blocks=[(FINAL_NODE, DenseOut(num_outputs))]
    )
