"""Named-node model graphs with cut-at-node support.

The reference's DNN stage does *graph surgery by node name*: pick an output
node by name or index and re-compose the net up to it
(``CNTKLib.AsComposite``, cntk-model/src/main/scala/CNTKModel.scala:97-108),
and the model-zoo schema publishes ``layerNames`` so ``ImageFeaturizer`` can
cut N layers from the top (image-featurizer/.../ImageFeaturizer.scala:122).
Node-name preservation is load-bearing (SURVEY.md §7 hard parts).

TPU-native re-expression: a model is an ordered sequence of *named blocks*
(flax modules). ``apply(..., output_node=name)`` runs the prefix ending at
that block — XLA then compiles exactly the prefix (dead code past the cut is
never traced), which is strictly cheaper than the reference's runtime
surgery.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

import jax

from mmlspark_tpu.core.exceptions import FriendlyError

#: conventional name of the final (logits) node — the reference's CNTK models
#: use "z" (notebook 301; CNTKModel.setOutputNodeName("z")).
FINAL_NODE = "z"


@dataclass
class NamedGraph:
    """An ordered, named-block model. ``blocks`` maps name -> flax module;
    order is the dataflow order."""

    name: str
    blocks: list[tuple[str, Any]]
    #: static metadata: expected input shape (per example, no batch dim)
    input_shape: tuple[int, ...] = ()
    #: dtype used for compute (bfloat16 keeps the MXU fed; params stay f32)
    compute_dtype: Any = None
    extra: dict = field(default_factory=dict)

    @property
    def layer_names(self) -> list[str]:
        """Ordered node names (the ModelSchema.layerNames analog,
        downloader/src/main/scala/Schema.scala:54-74)."""
        return [n for n, _ in self.blocks]

    def _check_node(self, node: str | int | None) -> str | None:
        return resolve_node(self.layer_names, node, self.name)

    def init(self, rng, sample):
        """Initialize per-block variables by threading a sample through."""
        variables: dict[str, Any] = {}
        x = sample
        for block_name, mod in self.blocks:
            rng, sub = jax.random.split(rng)
            v = mod.init({"params": sub}, x)
            # sown auxiliary losses are per-call values, not state
            v = {k: c for k, c in v.items() if k != "losses"}
            variables[block_name] = v
            x = mod.apply(v, x)
        return variables

    def apply(
        self,
        variables: dict[str, Any],
        x,
        output_node: str | int | None = None,
        train: bool = False,
        rngs: dict | None = None,
        mask=None,
    ):
        """Forward pass; stops at ``output_node`` when given (headless net).

        In train mode returns ``(out, updated_variables)`` where updated
        variables carry new batch statistics; in eval mode returns ``out``.
        ``mask`` (optional, (B,) 0/1 real-row mask) is forwarded to blocks
        whose ``__call__`` accepts it (e.g. MoE routing excludes padding).
        """
        stop = self._check_node(output_node)
        updated = dict(variables)
        for block_name, mod in self.blocks:
            v = variables[block_name]
            kwargs: dict[str, Any] = {}
            if _accepts_train(mod):
                kwargs["train"] = train
            if mask is not None and _accepts_kwarg(mod, "mask"):
                kwargs["mask"] = mask
            if train:
                has_stats = "batch_stats" in v
                # strip stale sown losses so each call sows fresh values
                v_in = {k: c for k, c in v.items() if k != "losses"}
                mutable = (["batch_stats"] if has_stats else []) + ["losses"]
                x, mutated = mod.apply(
                    v_in,
                    x,
                    mutable=mutable,
                    rngs=rngs,
                    **kwargs,
                )
                if mutated:
                    updated[block_name] = {**v_in, **mutated}
            else:
                x = mod.apply(v, x, **kwargs)
            if block_name == stop:
                break
        return (x, updated) if train else x

    def cut(self, node: str | int) -> "NamedGraph":
        """A new graph truncated after ``node`` (AsComposite equivalent)."""
        stop = self._check_node(node)
        idx = self.layer_names.index(stop)
        return NamedGraph(
            name=f"{self.name}@{stop}",
            blocks=self.blocks[: idx + 1],
            input_shape=self.input_shape,
            compute_dtype=self.compute_dtype,
            extra=dict(self.extra),
        )

    def param_count(self, variables) -> int:
        return count_params(variables)


def resolve_node(layer_names: Sequence[str], node: str | int | None,
                 graph_name: str) -> str | None:
    """Resolve an output-node selector (name or index, the CNTKModel
    setOutputNode variants, CNTKModel.scala:166-170) against ordered node
    names; raises FriendlyError for unknown selectors."""
    if node is None:
        return None
    if isinstance(node, int):
        try:
            return layer_names[node]
        except IndexError:
            raise FriendlyError(
                f"output node index {node} out of range for "
                f"{len(layer_names)} nodes"
            )
    if node not in layer_names:
        raise FriendlyError(
            f"no node '{node}' in graph '{graph_name}'; "
            f"nodes: {list(layer_names)}"
        )
    return node


def count_params(variables) -> int:
    """Total leaf element count of a variables pytree."""
    return sum(leaf.size for leaf in jax.tree_util.tree_leaves(variables))


def _accepts_kwarg(mod, name: str) -> bool:
    import inspect

    try:
        return name in inspect.signature(type(mod).__call__).parameters
    except (ValueError, TypeError):  # pragma: no cover
        return False


def _accepts_train(mod) -> bool:
    return _accepts_kwarg(mod, "train")
