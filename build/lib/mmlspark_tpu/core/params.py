"""Typed parameter system for pipeline stages.

Re-expression of the reference's param-constructor DSL
(core/contracts/src/main/scala/Params.scala:10-176 — ``MMLParams`` with
defaults + string-enum domains, ``HasInputCol``/``HasOutputCol`` etc.) as
Python descriptors. Every stage declares ``Param`` class attributes; values
live per-instance, defaults per-class, and the full param table is
introspectable (which powers serialization, ``explain_params`` and the
registry-wide fuzz tests, mirroring what codegen/fuzzing do with reflection in
the reference).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Sequence

import numpy as np

from mmlspark_tpu.core.exceptions import ParamError


class Param:
    """A typed, documented, validated stage parameter (descriptor).

    Mirrors reference ``ParamsHelpers``/``MMLParams`` behavior
    (core/contracts/src/main/scala/Params.scala:22-108):

    - ``default``: value used when unset (may be a zero-arg callable for
      mutable defaults),
    - ``domain``: string-enum domain — set membership enforced on assignment,
    - ``validator``: arbitrary predicate with message,
    - ``ptype``: optional type (or tuple of types) checked on assignment.
    """

    def __init__(
        self,
        doc: str = "",
        default: Any = None,
        *,
        ptype: type | tuple[type, ...] | None = None,
        domain: Sequence[str] | None = None,
        validator: Callable[[Any], bool] | None = None,
        validator_msg: str = "failed validation",
        required: bool = False,
    ):
        self.doc = doc
        self.default = default
        self.ptype = ptype
        self.domain = tuple(domain) if domain is not None else None
        self.validator = validator
        self.validator_msg = validator_msg
        self.required = required
        self.name: str = "<unbound>"

    def __set_name__(self, owner: type, name: str) -> None:
        self.name = name

    def get_default(self) -> Any:
        return self.default() if callable(self.default) else self.default

    def validate(self, value: Any, uid: str | None = None) -> Any:
        if value is None:
            return value
        if isinstance(value, (np.integer, np.floating, np.bool_)):
            # numpy scalars flow in naturally from Dataset columns
            value = value.item()
        if self.ptype is not None:
            # bool is an int subclass; keep int params from accepting True.
            if isinstance(value, bool) and self.ptype in (int, float):
                raise ParamError(
                    f"param '{self.name}': expected {self.ptype}, got bool", uid
                )
            if self.ptype in (int, float) and isinstance(value, (int, float)):
                if self.ptype is int and isinstance(value, float):
                    if not value.is_integer():
                        raise ParamError(
                            f"param '{self.name}': expected int, got "
                            f"non-integral float {value}",
                            uid,
                        )
                value = self.ptype(value)
            elif not isinstance(value, self.ptype):
                raise ParamError(
                    f"param '{self.name}': expected {self.ptype}, "
                    f"got {type(value).__name__}",
                    uid,
                )
        if self.domain is not None and value not in self.domain:
            raise ParamError(
                f"param '{self.name}': '{value}' not in domain {self.domain}", uid
            )
        if self.validator is not None and not self.validator(value):
            raise ParamError(
                f"param '{self.name}': {self.validator_msg} (got {value!r})", uid
            )
        return value

    # -- descriptor protocol ------------------------------------------------
    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if self.name in obj._param_values:
            return obj._param_values[self.name]
        default = self.get_default()
        if callable(self.default):
            # Materialize mutable defaults on first access so in-place
            # mutation (pipe.stages.append(...)) is not silently discarded.
            obj._param_values[self.name] = default
        return default

    def __set__(self, obj, value) -> None:
        obj._param_values[self.name] = self.validate(value, getattr(obj, "uid", None))


class HasParams:
    """Mixin giving a class a discoverable, copyable param table."""

    def __init__(self, **kwargs: Any):
        self._param_values: dict[str, Any] = {}
        self.set(**kwargs)

    @classmethod
    def params(cls) -> dict[str, Param]:
        """All declared params, base classes included (mro order)."""
        out: dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[k] = v
        return out

    def set(self, **kwargs: Any):
        """Chainable multi-param setter: ``stage.set(input_col="x", n=3)``."""
        table = self.params()
        for k, v in kwargs.items():
            if k not in table:
                raise ParamError(
                    f"unknown param '{k}' for {type(self).__name__}; "
                    f"known: {sorted(table)}",
                    getattr(self, "uid", None),
                )
            setattr(self, k, v)
        return self

    def get(self, name: str) -> Any:
        if name not in self.params():
            raise ParamError(f"unknown param '{name}'", getattr(self, "uid", None))
        return getattr(self, name)

    def is_set(self, name: str) -> bool:
        return name in self._param_values

    def param_values(self, *, include_defaults: bool = False) -> dict[str, Any]:
        """Explicitly-set values (optionally merged over defaults)."""
        if include_defaults:
            out = {k: p.get_default() for k, p in self.params().items()}
            out.update(self._param_values)
            return out
        return dict(self._param_values)

    def check_required(self) -> None:
        for name, p in self.params().items():
            if p.required and getattr(self, name) is None:
                raise ParamError(
                    f"required param '{name}' is not set",
                    getattr(self, "uid", None),
                )

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self.params().items()):
            state = (
                f"current: {self._param_values[name]!r}"
                if name in self._param_values
                else f"default: {p.get_default()!r}"
            )
            dom = f" (one of {list(p.domain)})" if p.domain else ""
            lines.append(f"{name}: {p.doc}{dom} ({state})")
        return "\n".join(lines)


# -- shared column-param mixins (reference Params.scala:110-176) -------------


class HasInputCol(HasParams):
    input_col = Param("name of the input column", "input", ptype=str)


class HasOutputCol(HasParams):
    output_col = Param("name of the output column", "output", ptype=str)


class HasInputCols(HasParams):
    input_cols = Param("names of the input columns", ptype=(list, tuple))


class HasOutputCols(HasParams):
    output_cols = Param("names of the output columns", ptype=(list, tuple))


class HasLabelCol(HasParams):
    label_col = Param("name of the label column", "label", ptype=str)


class HasFeaturesCol(HasParams):
    features_col = Param("name of the features column", "features", ptype=str)


def non_negative(v: Any) -> bool:
    return v >= 0


def positive(v: Any) -> bool:
    return v > 0


def in_unit_interval(v: Any) -> bool:
    return 0.0 <= v <= 1.0


def nonempty(v: Iterable) -> bool:
    return len(list(v)) > 0
