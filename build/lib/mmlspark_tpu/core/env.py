"""Accelerator / environment discovery.

The reference discovers workers by shelling to ``nvidia-smi -L`` and counting
lines (core/env/src/main/scala/EnvironmentUtils.scala:14-51); the worker count
drives MPI parallelism (CommandBuilders.scala:81). The TPU-native equivalent
is JAX device introspection — no subprocess, no parsing.
"""

from __future__ import annotations

import platform
from dataclasses import dataclass


def device_count() -> int:
    """Global accelerator count (EnvironmentUtils.GPUCount analog)."""
    import jax

    return jax.device_count()


def local_device_count() -> int:
    import jax

    return jax.local_device_count()


def process_count() -> int:
    """Number of controller processes (multi-host)."""
    import jax

    return jax.process_count()


def backend() -> str:
    import jax

    return jax.default_backend()


def is_tpu() -> bool:
    return backend() == "tpu"


@dataclass(frozen=True)
class TopologyInfo:
    """TPU topology introspection summary (replaces the reference's
    single-node GPU-count worldview with mesh-shaped facts)."""

    num_devices: int
    num_local_devices: int
    num_processes: int
    platform: str
    device_kind: str
    host_os: str


def topology() -> TopologyInfo:
    import jax

    devs = jax.devices()
    return TopologyInfo(
        num_devices=len(devs),
        num_local_devices=jax.local_device_count(),
        num_processes=jax.process_count(),
        platform=jax.default_backend(),
        device_kind=devs[0].device_kind if devs else "none",
        host_os=platform.system(),
    )


def describe() -> dict:
    """Topology as a plain dict (the launcher's ``mml-tpu env`` view)."""
    import dataclasses

    return dataclasses.asdict(topology())
