"""Metric value contracts (reference:
core/contracts/src/main/scala/Metrics.scala:7-46 — ``MetricData``,
``TypedMetric``, ``MetricGroup``). Evaluators surface metrics both as Dataset
rows (the primary UX, like the reference's metric DataFrames) and as these
structured records for logging."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class MetricData:
    """One named scalar or table metric attached to a model/stage uid."""

    name: str
    value: Any
    model: str | None = None
    group: str | None = None
    extra: dict = field(default_factory=dict)

    @staticmethod
    def create(name: str, value: float, model: str | None = None) -> "MetricData":
        return MetricData(name=name, value=float(value), model=model)

    @staticmethod
    def create_table(
        name: str, rows: dict, model: str | None = None
    ) -> "MetricData":
        return MetricData(name=name, value=rows, model=model, group="table")
