"""Exception hierarchy carrying stage uids.

Reference: core/contracts/src/main/scala/Exceptions.scala:10-35 (`MMLException`,
`FriendlyException`, `ParamException`).
"""

from __future__ import annotations


class MMLError(Exception):
    """Base error for the framework. Carries the uid of the stage that raised
    it, when known, so pipeline failures are attributable."""

    def __init__(self, message: str, uid: str | None = None):
        self.uid = uid
        super().__init__(f"[{uid}] {message}" if uid else message)


class FriendlyError(MMLError):
    """An error with a user-actionable message (bad input data, missing column,
    unsupported type) rather than an internal invariant violation."""


class ParamError(FriendlyError):
    """Invalid parameter value or combination."""


class SchemaError(FriendlyError):
    """Dataset schema does not match what a stage requires."""
