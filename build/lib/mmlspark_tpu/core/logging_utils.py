"""Namespaced logger factory (reference:
core/env/src/main/scala/Logging.scala:14-23, loggers namespaced
``mmlspark.*``)."""

from __future__ import annotations

import logging

NAMESPACE = "mmlspark_tpu"


def get_logger(name: str | None = None) -> logging.Logger:
    return logging.getLogger(f"{NAMESPACE}.{name}" if name else NAMESPACE)
