"""Column-metadata schema protocol.

Re-creates the reference's self-describing scored-dataset contract without
Spark's ``Metadata``: columns carry a :class:`ColumnMeta` record tagging them
as label / score / scored-labels / scored-probabilities for a given producing
model, carrying categorical levels, and marking image columns — so downstream
evaluators discover everything with zero configuration.

Reference: core/schema/src/main/scala/SparkSchema.scala:13-249,
SchemaConstants.scala:7-43, Categoricals.scala:16-342, ImageSchema.scala:9-37,
BinaryFileSchema.scala:9-32.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from mmlspark_tpu.core.exceptions import SchemaError

# -- SchemaConstants (reference SchemaConstants.scala:7-43) ------------------

MML_TAG = "mml"

#: ScoreColumnKind values
LABEL_KIND = "label"
SCORES_KIND = "scores"
SCORED_LABELS_KIND = "scored_labels"
SCORED_PROBABILITIES_KIND = "scored_probabilities"

#: ScoreValueKind values
CLASSIFICATION = "classification"
REGRESSION = "regression"

#: default score-model tag used when no uid is supplied
DEFAULT_MODEL = "model_0"

#: canonical output column names (reference SchemaConstants)
SCORES_COLUMN = "scores"
SCORED_LABELS_COLUMN = "scored_labels"
SCORED_PROBABILITIES_COLUMN = "scored_probabilities"


@dataclass(frozen=True)
class CategoricalMeta:
    """Categorical levels stored on a column (reference Categoricals.scala:
    ``CategoricalUtilities.setLevels/getLevels``); index <-> level lookup.

    ``levels[i]`` is the original value encoded as index ``i``; ``has_null``
    marks a trailing null level (null-aware ordering, ValueIndexer.scala:37-47).
    """

    levels: tuple
    has_null: bool = False

    @property
    def num_levels(self) -> int:
        return len(self.levels)

    def level_to_index(self) -> dict:
        return {lvl: i for i, lvl in enumerate(self.levels)}

    def index_to_level(self, idx: int):
        return self.levels[int(idx)]


@dataclass(frozen=True)
class ImageMeta:
    """Marks a column holding image rows (reference ImageSchema.scala:9-37:
    ``(path, height, width, type, bytes row-wise BGR)``). Here an image column
    is an object-array of :class:`mmlspark_tpu.core.schema.ImageRow` or a dense
    NHWC uint8 array; this meta records the canonical layout."""

    channels: int = 3
    layout: str = "HWC"  # row-major, BGR byte order to mirror OpenCV


@dataclass(frozen=True)
class ColumnMeta:
    """Everything the framework knows about a column beyond its dtype.

    ``kind``/``model``/``value_kind`` implement the score-column protocol
    (reference SparkSchema.scala:13-249): evaluators look up, for a given
    producing model, which column is the label / raw scores / predicted labels
    / probabilities and whether the task was classification or regression.
    """

    kind: Optional[str] = None  # one of the *_KIND constants
    model: Optional[str] = None  # uid of the producing model
    value_kind: Optional[str] = None  # CLASSIFICATION | REGRESSION
    categorical: Optional[CategoricalMeta] = None
    image: Optional[ImageMeta] = None
    extra: dict = field(default_factory=dict)

    def evolve(self, **changes: Any) -> "ColumnMeta":
        return dataclasses.replace(self, **changes)

    def is_empty(self) -> bool:
        return self == ColumnMeta()


@dataclass
class ImageRow:
    """One decoded image (reference ImageSchema.scala:9-20). ``data`` is HWC
    uint8, BGR channel order — matching the reference's OpenCV CV_8UC3 rows so
    byte-level parity tests against the reference semantics are possible."""

    path: str
    data: np.ndarray  # (H, W, C) uint8

    @property
    def height(self) -> int:
        return int(self.data.shape[0])

    @property
    def width(self) -> int:
        return int(self.data.shape[1])

    @property
    def channels(self) -> int:
        return int(self.data.shape[2]) if self.data.ndim == 3 else 1


@dataclass
class BinaryFileRow:
    """One whole binary file (reference BinaryFileSchema.scala:9-32)."""

    path: str
    data: bytes


# -- score-column tagging / discovery ----------------------------------------


def tag_column(meta: ColumnMeta | None, kind: str, model: str, value_kind: str | None):
    """Return a ColumnMeta tagging a column under the score protocol
    (reference SparkSchema.updateMetadata)."""
    base = meta or ColumnMeta()
    return base.evolve(kind=kind, model=model, value_kind=value_kind)


def _find_by_kind(dataset, kind: str, model: str | None) -> str | None:
    hits = []
    for name in dataset.columns:
        m = dataset.meta_of(name)
        if m.kind == kind and (model is None or m.model == model):
            hits.append(name)
    if not hits:
        return None
    if len(hits) > 1 and model is None:
        raise SchemaError(
            f"multiple columns tagged '{kind}' ({hits}); pass a model uid"
        )
    return hits[0]


def find_label_column(dataset, model: str | None = None) -> str | None:
    return _find_by_kind(dataset, LABEL_KIND, model)


def find_scores_column(dataset, model: str | None = None) -> str | None:
    return _find_by_kind(dataset, SCORES_KIND, model)


def find_scored_labels_column(dataset, model: str | None = None) -> str | None:
    return _find_by_kind(dataset, SCORED_LABELS_KIND, model)


def find_scored_probabilities_column(dataset, model: str | None = None) -> str | None:
    return _find_by_kind(dataset, SCORED_PROBABILITIES_KIND, model)


def get_score_value_kind(dataset, model: str | None = None) -> str | None:
    """The task type (classification/regression) recorded by the producing
    model (reference SparkSchema.getScoreValueKind)."""
    for name in dataset.columns:
        m = dataset.meta_of(name)
        if m.value_kind is not None and (model is None or m.model == model):
            return m.value_kind
    return None


def fresh_column_name(dataset, base: str) -> str:
    """A column name not already present (reference
    DatasetExtensions.findUnusedColumnName, DatasetExtensions.scala:11-60)."""
    if base not in dataset.columns:
        return base
    i = 1
    while f"{base}_{i}" in dataset.columns:
        i += 1
    return f"{base}_{i}"
