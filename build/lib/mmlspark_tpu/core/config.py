"""App-level configuration namespace (the ``MMLConfig`` analog).

Reference: core/env/src/main/scala/Configuration.scala:17-50 — a
typesafe-config namespace ``mmlspark.{sdk,cntk,tlc}`` layering reference
defaults under deployment overrides. The TPU-native tiers:

1. built-in defaults (this module),
2. a JSON config file — ``$MMLSPARK_TPU_CONFIG`` if set, else
   ``~/.config/mmlspark_tpu.json`` when present,
3. environment variables ``MMLSPARK_TPU_<KEY>`` (highest precedence),

resolved once per process and exposed through typed getters. Stage params
(core/params.py) remain the per-stage tier; TrainConfig the per-run tier —
this module is for process-wide knobs only.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

from mmlspark_tpu.core.exceptions import FriendlyError

_ENV_PREFIX = "MMLSPARK_TPU_"

#: built-in defaults: key -> (value, doc)
_DEFAULTS: dict[str, tuple[Any, str]] = {
    "cache_dir": (
        os.path.join(os.path.expanduser("~"), ".mmlspark_tpu"),
        "root for downloaded models and other caches",
    ),
    "model_repo": (
        "",
        "default remote model repo (path or http[s] URL); empty = none",
    ),
    "native_cc": ("c++", "compiler driver for the native ops"),
    "native_build": (
        True,
        "build native ops on first use (False = Python fallbacks only)",
    ),
    "profile_dir": (
        "",
        "default jax.profiler trace directory; empty = profiling off",
    ),
    "log_level": ("INFO", "root level for the mmlspark_tpu.* loggers"),
}

_lock = threading.Lock()
_resolved: dict[str, Any] | None = None


def _coerce(value: Any, like: Any) -> Any:
    if isinstance(like, bool):
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(like, int) and not isinstance(like, bool):
        return int(value)
    if isinstance(like, float):
        return float(value)
    return value


def _load() -> dict[str, Any]:
    global _resolved
    with _lock:
        if _resolved is not None:
            return _resolved
        conf = {k: v for k, (v, _doc) in _DEFAULTS.items()}
        path = os.environ.get(
            _ENV_PREFIX + "CONFIG",
            os.path.join(
                os.path.expanduser("~"), ".config", "mmlspark_tpu.json"
            ),
        )
        if os.path.exists(path):
            try:
                with open(path) as f:
                    file_conf = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                raise FriendlyError(f"bad config file {path}: {e}") from e
            for k, v in file_conf.items():
                if k not in conf:
                    raise FriendlyError(
                        f"unknown config key '{k}' in {path}; known: "
                        f"{sorted(conf)}"
                    )
                conf[k] = _coerce(v, _DEFAULTS[k][0])
        for k in conf:
            env = os.environ.get(_ENV_PREFIX + k.upper())
            if env is not None:
                conf[k] = _coerce(env, _DEFAULTS[k][0])
        _resolved = conf
        return conf


def get(key: str) -> Any:
    """Resolved value for ``key`` (defaults < config file < env)."""
    conf = _load()
    if key not in conf:
        raise FriendlyError(
            f"unknown config key '{key}'; known: {sorted(conf)}"
        )
    return conf[key]


def explain() -> dict[str, dict[str, Any]]:
    """Every key with its resolved value and doc (MMLConfig's
    introspectable namespace)."""
    conf = _load()
    return {
        k: {"value": conf[k], "doc": _DEFAULTS[k][1]} for k in sorted(conf)
    }


def reset() -> None:
    """Drop the resolved snapshot (tests / after env changes)."""
    global _resolved
    with _lock:
        _resolved = None
