"""Stage / model persistence.

Re-expression of the reference's constructor-reflection serializer
(core/serialize/src/main/scala/ConstructorWriter.scala:22-89,
Serializer.scala:51-58): rather than reflecting constructor types, we walk the
declared param table and dispatch on *value* type —

- JSON-able primitives -> ``stage.json``
- numpy / JAX arrays -> ``arrays.npz`` entries (the ``ByteArrayParam`` /
  tensor analog)
- nested stages and stage lists -> recursive sub-directories (the
  ``PipelineStageParam`` / ``TransformerArrayParam`` analog,
  core/serialize/src/main/scala/params/*.scala)
- Datasets -> column store + metadata JSON (the ``DataFrameParam`` analog)
- pytrees (nested dicts, e.g. flax model params) -> recursive encoding with
  array leaves in the npz payload

Round-trip contract: ``load(save(stage)).transform(ds)`` equals
``stage.transform(ds)`` — verified suite-wide by the fuzz tests (mirroring
RoundTripTestBase, core/test/base/.../TestBase.scala:179-255).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import numpy as np

from mmlspark_tpu.core.exceptions import MMLError
from mmlspark_tpu.core.schema import CategoricalMeta, ColumnMeta, ImageMeta
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.dataset import Dataset

FORMAT_VERSION = 1


class _Encoder:
    def __init__(self, root: str):
        self.root = root
        self.arrays: dict[str, np.ndarray] = {}
        self._n = 0

    def _array_key(self) -> str:
        self._n += 1
        return f"a{self._n:04d}"

    def encode(self, value: Any, path: str) -> Any:
        if value is None or isinstance(value, (bool, int, float, str)):
            return value
        if isinstance(value, (np.integer, np.floating, np.bool_)):
            return value.item()
        if isinstance(value, bytes):
            return {"__type__": "bytes", "hex": value.hex()}
        try:
            import jax

            if isinstance(value, jax.Array):
                value = np.asarray(value)
        except ImportError:  # pragma: no cover
            pass
        if isinstance(value, np.ndarray):
            key = self._array_key()
            self.arrays[key] = value
            return {"__type__": "ndarray", "key": key}
        if isinstance(value, tuple):
            return {
                "__type__": "tuple",
                "items": [
                    self.encode(v, f"{path}.{i}") for i, v in enumerate(value)
                ],
            }
        if isinstance(value, list):
            return [self.encode(v, f"{path}.{i}") for i, v in enumerate(value)]
        if isinstance(value, dict):
            if not all(isinstance(k, str) for k in value):
                return {
                    "__type__": "kvdict",
                    "items": [
                        [self.encode(k, f"{path}.k{i}"), self.encode(v, f"{path}.v{i}")]
                        for i, (k, v) in enumerate(value.items())
                    ],
                }
            return {
                "__type__": "dict",
                "items": {
                    k: self.encode(v, f"{path}.{k}") for k, v in value.items()
                },
            }
        if isinstance(value, PipelineStage):
            subdir = os.path.join(self.root, path)
            save_stage(value, subdir)
            return {"__type__": "stage", "dir": path}
        if isinstance(value, Dataset):
            subdir = os.path.join(self.root, path)
            save_dataset(value, subdir)
            return {"__type__": "dataset", "dir": path}
        if isinstance(value, (ColumnMeta, CategoricalMeta, ImageMeta)):
            return {
                "__type__": type(value).__name__,
                "fields": self.encode(dataclasses.asdict(value), path),
            }
        raise MMLError(
            f"cannot serialize param value of type {type(value).__name__} at {path}"
        )


class _Decoder:
    def __init__(self, root: str, arrays: Any):
        self.root = root
        self.arrays = arrays

    def decode(self, value: Any) -> Any:
        if isinstance(value, list):
            return [self.decode(v) for v in value]
        if not isinstance(value, dict):
            return value
        t = value.get("__type__")
        if t is None:
            return {k: self.decode(v) for k, v in value.items()}
        if t == "bytes":
            return bytes.fromhex(value["hex"])
        if t == "ndarray":
            return self.arrays[value["key"]]
        if t == "tuple":
            return tuple(self.decode(v) for v in value["items"])
        if t == "dict":
            return {k: self.decode(v) for k, v in value["items"].items()}
        if t == "kvdict":
            return {self.decode(k): self.decode(v) for k, v in value["items"]}
        if t == "stage":
            return load_stage(os.path.join(self.root, value["dir"]))
        if t == "dataset":
            return load_dataset(os.path.join(self.root, value["dir"]))
        if t in ("ColumnMeta", "CategoricalMeta", "ImageMeta"):
            fields = self.decode(value["fields"])
            return _meta_from_dict(t, fields)
        raise MMLError(f"unknown serialized type tag {t!r}")


def _meta_from_dict(tag: str, fields: dict) -> Any:
    if tag == "CategoricalMeta":
        return CategoricalMeta(
            levels=tuple(fields["levels"]), has_null=fields["has_null"]
        )
    if tag == "ImageMeta":
        return ImageMeta(**fields)
    cat = fields.get("categorical")
    img = fields.get("image")
    return ColumnMeta(
        kind=fields.get("kind"),
        model=fields.get("model"),
        value_kind=fields.get("value_kind"),
        categorical=(
            cat
            if isinstance(cat, (CategoricalMeta, type(None)))
            else CategoricalMeta(tuple(cat["levels"]), cat["has_null"])
        ),
        image=(
            img
            if isinstance(img, (ImageMeta, type(None)))
            else ImageMeta(**img)
        ),
        extra=fields.get("extra") or {},
    )


def save_stage(stage: PipelineStage, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    enc = _Encoder(path)
    params = {
        name: enc.encode(value, os.path.join("params", name))
        for name, value in stage.param_values().items()
    }
    spec = {
        "format_version": FORMAT_VERSION,
        "class": type(stage).__name__,
        "uid": stage.uid,
        "params": params,
    }
    if enc.arrays:
        np.savez(os.path.join(path, "arrays.npz"), **enc.arrays)
    with open(os.path.join(path, "stage.json"), "w") as f:
        json.dump(spec, f, indent=1)


def load_stage(path: str) -> PipelineStage:
    with open(os.path.join(path, "stage.json")) as f:
        spec = json.load(f)
    if spec["format_version"] > FORMAT_VERSION:
        raise MMLError(f"unsupported format version {spec['format_version']}")
    registry = PipelineStage.registry()
    cls_name = spec["class"]
    if cls_name not in registry:
        # Stage classes register at import time; pull in the full surface.
        import mmlspark_tpu.stages  # noqa: F401

        registry = PipelineStage.registry()
    if cls_name not in registry:
        raise MMLError(f"unknown stage class '{cls_name}' (not registered)")
    arrays_path = os.path.join(path, "arrays.npz")
    arrays: dict[str, np.ndarray] = {}
    if os.path.exists(arrays_path):
        with np.load(arrays_path, allow_pickle=True) as z:
            arrays = {k: z[k] for k in z.files}
    dec = _Decoder(path, arrays)
    stage = registry[cls_name]()
    stage.uid = spec["uid"]
    stage.set(**{k: dec.decode(v) for k, v in spec["params"].items()})
    return stage


# -- dataset persistence -----------------------------------------------------


# Column names are user-controlled; prefix npz keys so they can never collide
# with np.savez's own parameter names (e.g. a column literally named 'file').
_COL_PREFIX = "col::"


def save_dataset(dataset: Dataset, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    enc = _Encoder(path)
    plain: dict[str, np.ndarray] = {}
    pickled: dict[str, np.ndarray] = {}
    for name, arr in dataset._columns.items():
        (pickled if arr.dtype == object else plain)[_COL_PREFIX + name] = arr
    meta = {
        "format_version": FORMAT_VERSION,
        "num_partitions": dataset.num_partitions,
        "columns": dataset.columns,
        "meta": {
            name: enc.encode(dataset.meta_of(name), name)
            for name in dataset.columns
            if not dataset.meta_of(name).is_empty()
        },
    }
    if enc.arrays:
        np.savez(os.path.join(path, "meta_arrays.npz"), **enc.arrays)
    if plain:
        np.savez(os.path.join(path, "columns.npz"), **plain)
    if pickled:
        np.savez(os.path.join(path, "columns_obj.npz"), **{
            k: np.asarray(v, dtype=object) for k, v in pickled.items()
        })
    with open(os.path.join(path, "dataset.json"), "w") as f:
        json.dump(meta, f, indent=1)


def load_dataset(path: str) -> Dataset:
    with open(os.path.join(path, "dataset.json")) as f:
        meta = json.load(f)
    cols: dict[str, np.ndarray] = {}
    plain_path = os.path.join(path, "columns.npz")
    obj_path = os.path.join(path, "columns_obj.npz")
    meta_arrays_path = os.path.join(path, "meta_arrays.npz")
    if os.path.exists(plain_path):
        with np.load(plain_path) as z:
            cols.update({k.removeprefix(_COL_PREFIX): z[k] for k in z.files})
    if os.path.exists(obj_path):
        with np.load(obj_path, allow_pickle=True) as z:
            cols.update({k.removeprefix(_COL_PREFIX): z[k] for k in z.files})
    meta_arrays: dict[str, np.ndarray] = {}
    if os.path.exists(meta_arrays_path):
        with np.load(meta_arrays_path, allow_pickle=True) as z:
            meta_arrays = {k: z[k] for k in z.files}
    dec = _Decoder(path, meta_arrays)
    col_meta = {name: dec.decode(v) for name, v in meta.get("meta", {}).items()}
    ordered = {name: cols[name] for name in meta["columns"]}
    return Dataset(ordered, col_meta, num_partitions=meta.get("num_partitions", 1))
