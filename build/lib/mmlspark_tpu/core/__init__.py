"""Core runtime: param system, schema metadata protocol, stage base classes,
serialization. Mirrors the reference's ``src/core/`` layer (SURVEY.md §2.1)."""
