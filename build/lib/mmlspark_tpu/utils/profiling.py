"""Profiling hooks: jax.profiler traces around pipeline work.

The reference's only tracing is the Timer stage's wall-clock logging
(pipeline-stages/src/main/scala/Timer.scala:14-123) — no sampling profiler
exists (SURVEY.md §5). The TPU build keeps Timer and adds the natural
upgrade the survey calls for: XLA-level traces via ``jax.profiler``,
viewable in TensorBoard/Perfetto, capturing compilation, device compute,
and host↔device transfers.
"""

from __future__ import annotations

import contextlib
import os

from mmlspark_tpu.core.logging_utils import get_logger

_log = get_logger("profiling")


@contextlib.contextmanager
def trace_profile(log_dir: str, create_perfetto_link: bool = False):
    """Context manager writing a jax.profiler trace under ``log_dir``.

    Usage::

        with trace_profile("/tmp/trace"):
            model.transform(ds)   # device work captured
    """
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(
        log_dir, create_perfetto_link=create_perfetto_link
    ):
        yield log_dir
    _log.info("profiler trace written under %s", log_dir)


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the device trace (jax.profiler.TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
