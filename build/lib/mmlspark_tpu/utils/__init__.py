"""Small shared utilities."""
