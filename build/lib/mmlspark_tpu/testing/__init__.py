"""Test support library (shipped, like the reference's core/test/{base,
datagen,fuzzing} sbt projects — SURVEY.md §2/L9)."""
