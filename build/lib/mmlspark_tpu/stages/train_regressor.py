"""TrainRegressor — one-liner regression.

Reference: train-regressor/src/main/scala/TrainRegressor.scala:21-192 (label
cast to double, auto-Featurize, learner fit, score-column metadata with
regression value kind).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.params import HasLabelCol, Param, positive
from mmlspark_tpu.core.schema import (
    LABEL_KIND,
    REGRESSION,
    SCORED_LABELS_KIND,
    SCORES_KIND,
    ColumnMeta,
)
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.dnn_learner import DNNLearner
from mmlspark_tpu.stages.featurize import (
    DEFAULT_NUM_FEATURES,
    TREE_NN_NUM_FEATURES,
    Featurize,
)

LINEAR_REGRESSION = "linear_regression"
MLP_REGRESSOR = "mlp"
DECISION_TREE = "decision_tree"
RANDOM_FOREST = "random_forest"
GBT = "gbt"

#: learners featurized tree-style (small hash space, no OHE)
_TREE_LEARNERS = (DECISION_TREE, RANDOM_FOREST, GBT)


class TrainRegressor(Estimator, HasLabelCol):
    model = Param(
        "learner: built-in name or custom Estimator", LINEAR_REGRESSION
    )
    number_of_features = Param("hash space (None = learner-aware default)")
    epochs = Param("epochs", 30, ptype=int, validator=positive)
    batch_size = Param("global batch size", 256, ptype=int, validator=positive)
    learning_rate = Param("learning rate", 1e-2, ptype=float)
    optimizer = Param("optimizer", "momentum",
                      domain=("adam", "adamw", "sgd", "momentum"))
    hidden = Param("hidden sizes for the mlp learner", (128,))
    seed = Param("rng seed", 0, ptype=int)
    steps_per_dispatch = Param(
        "optimizer steps per compiled call (NN learners)", 1, ptype=int,
        validator=positive,
    )
    # tree knobs (pass-through to the histogram learners)
    max_depth = Param("tree depth", 5, ptype=int, validator=positive)
    num_trees = Param("random-forest tree count", 20, ptype=int,
                      validator=positive)
    max_iter = Param("gbt boosting rounds", 20, ptype=int, validator=positive)

    def _make_learner(self) -> Estimator:
        from mmlspark_tpu.stages.trees import (
            DecisionTreeRegressor,
            GBTRegressor,
            RandomForestRegressor,
        )

        tree_common = dict(
            features_col="features",
            label_col="__label_double__",
            max_depth=self.max_depth,
            seed=self.seed,
        )
        if self.model == DECISION_TREE:
            return DecisionTreeRegressor(**tree_common)
        if self.model == RANDOM_FOREST:
            return RandomForestRegressor(
                num_trees=self.num_trees, **tree_common
            )
        if self.model == GBT:
            return GBTRegressor(
                max_iter=self.max_iter,
                step_size=self.learning_rate
                if self.is_set("learning_rate")
                else 0.1,
                **tree_common,
            )
        if isinstance(self.model, Estimator):
            return self.model
        common = dict(
            loss="mse",
            epochs=self.epochs,
            batch_size=self.batch_size,
            learning_rate=self.learning_rate,
            optimizer=self.optimizer,
            seed=self.seed,
            steps_per_dispatch=self.steps_per_dispatch,
            features_col="features",
            label_col="__label_double__",
        )
        if self.model == LINEAR_REGRESSION:
            return DNNLearner(
                model_name="linear", model_config={"num_outputs": 1}, **common
            )
        if self.model == MLP_REGRESSOR:
            return DNNLearner(
                model_name="mlp",
                model_config={"num_outputs": 1, "hidden": tuple(self.hidden)},
                **common,
            )
        raise FriendlyError(
            f"unknown learner '{self.model}'; built-ins: "
            f"{LINEAR_REGRESSION!r}, {MLP_REGRESSOR!r}, {DECISION_TREE!r}, "
            f"{RANDOM_FOREST!r}, {GBT!r}",
            self.uid,
        )

    def _fit(self, dataset: Dataset) -> "TrainedRegressorModel":
        dataset.require(self.label_col)
        y = np.asarray(dataset[self.label_col], dtype=np.float64)
        ds = dataset.with_column("__label_double__", y)
        feature_inputs = [
            c
            for c in dataset.columns
            if c not in (self.label_col, "__label_double__")
        ]
        nf = self.number_of_features or (
            TREE_NN_NUM_FEATURES
            if self.model == MLP_REGRESSOR or self.model in _TREE_LEARNERS
            else DEFAULT_NUM_FEATURES
        )
        featurizer = Featurize(
            feature_columns={"features": feature_inputs},
            number_of_features=nf,
            one_hot_encode_categoricals=self.model not in _TREE_LEARNERS,
        ).fit(ds)
        featurized = featurizer.transform(ds)
        fitted = self._make_learner().fit(featurized)
        return TrainedRegressorModel(
            featurizer=featurizer,
            learner_model=fitted,
            label_col=self.label_col,
        )


class TrainedRegressorModel(Model):
    featurizer = Param("fitted FeaturizeModel")
    learner_model = Param("fitted scoring model")
    label_col = Param("original label column", "label", ptype=str)

    def _transform(self, dataset: Dataset) -> Dataset:
        ds = self.featurizer.transform(dataset)
        ds = self.learner_model.transform(ds)
        scores = np.asarray(ds["scores"], dtype=np.float64)
        pred = scores[:, 0] if scores.ndim > 1 else scores
        uid = self.uid
        ds = ds.with_column(
            "scores",
            pred,
            ColumnMeta(kind=SCORES_KIND, model=uid, value_kind=REGRESSION),
        )
        ds = ds.with_column(
            "scored_labels",
            pred,
            ColumnMeta(kind=SCORED_LABELS_KIND, model=uid, value_kind=REGRESSION),
        )
        if self.label_col in ds.columns:
            ds = ds.with_meta(
                self.label_col,
                ds.meta_of(self.label_col).evolve(
                    kind=LABEL_KIND, model=uid, value_kind=REGRESSION
                ),
            )
        return ds
