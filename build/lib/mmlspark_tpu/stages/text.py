"""TextFeaturizer — configurable text featurization pipeline.

Reference: text-featurizer/src/main/scala/TextFeaturizer.scala:180-405:
RegexTokenizer -> StopWordsRemover -> NGram -> HashingTF -> IDF, each stage
optional, tokenization auto-detected from the input type. Tokenization +
hashing live in :mod:`mmlspark_tpu.utils.text` (shared with Featurize so
fit/transform paths can never diverge).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param, positive
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.utils.text import DEFAULT_PATTERN, hash_token, tokenize

DEFAULT_NUM_FEATURES = 1 << 18


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    use_tokenizer = Param("split strings into tokens", True, ptype=bool)
    tokenizer_pattern = Param("regex split pattern", DEFAULT_PATTERN, ptype=str)
    to_lowercase = Param("lowercase before tokenizing", True, ptype=bool)
    remove_stop_words = Param("drop english stop words", False, ptype=bool)
    use_ngram = Param("emit n-grams instead of unigrams", False, ptype=bool)
    n_gram_length = Param("n-gram order", 2, ptype=int, validator=positive)
    num_features = Param(
        "hashing-TF space", DEFAULT_NUM_FEATURES, ptype=int, validator=positive
    )
    use_idf = Param("apply inverse-document-frequency weighting", True,
                    ptype=bool)
    min_doc_freq = Param("min docs a slot must appear in for IDF", 1,
                         ptype=int)

    def _tokenizer_config(self) -> dict:
        return {
            "use_tokenizer": self.use_tokenizer,
            "tokenizer_pattern": self.tokenizer_pattern,
            "to_lowercase": self.to_lowercase,
            "remove_stop_words": self.remove_stop_words,
            "use_ngram": self.use_ngram,
            "n_gram_length": self.n_gram_length,
        }

    def _fit(self, dataset: Dataset) -> "TextFeaturizerModel":
        dataset.require(self.input_col)
        nf = self.num_features
        cfg = self._tokenizer_config()
        # document frequency per used hash slot
        df_counts: dict[int, int] = {}
        for v in dataset[self.input_col]:
            slots = {hash_token(t, nf) for t in tokenize(v, cfg)}
            for s in slots:
                df_counts[s] = df_counts.get(s, 0) + 1
        slots = sorted(
            s for s, c in df_counts.items() if c >= self.min_doc_freq
        )
        n_docs = dataset.num_rows
        if self.use_idf:
            idf = np.array(
                [np.log((n_docs + 1.0) / (df_counts[s] + 1.0)) for s in slots]
            )
        else:
            idf = np.ones(len(slots))
        return TextFeaturizerModel(
            input_col=self.input_col,
            output_col=self.output_col,
            slots=list(slots),
            idf=idf,
            num_features=nf,
            tokenizer_config=cfg,
        )


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    slots = Param("selected hash slots", default=list)
    idf = Param("per-slot idf weights")
    num_features = Param("hash space", DEFAULT_NUM_FEATURES, ptype=int)
    tokenizer_config = Param("tokenizer settings", default=dict)

    def _transform(self, dataset: Dataset) -> Dataset:
        dataset.require(self.input_col)
        pos = {s: j for j, s in enumerate(self.slots)}
        nf = self.num_features
        cfg = self.tokenizer_config
        idf = np.asarray(self.idf, dtype=np.float64)
        out = np.zeros((dataset.num_rows, len(self.slots)))
        for i, v in enumerate(dataset[self.input_col]):
            for t in tokenize(v, cfg):
                j = pos.get(hash_token(t, nf))
                if j is not None:
                    out[i, j] += 1.0
        out *= idf
        return dataset.with_column(self.output_col, out)
