"""Classical learners: multinomial naive Bayes and one-vs-rest reduction.

Reference learner dispatch: train-classifier/src/main/scala/
TrainClassifier.scala:45-52 (NaiveBayesClassifier) and the OneVsRest wrap
applied to multiclass logistic regression (:110-122). The reference
delegates to Spark MLlib; here naive Bayes is a closed-form log-count
computation (one matmul at inference — MXU-friendly), and OneVsRest is a
generic estimator combinator usable around ANY binary learner stage.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.params import (
    HasFeaturesCol,
    HasLabelCol,
    HasOutputCol,
    Param,
)
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.data.feed import stack_column
from mmlspark_tpu.stages.trees import _prep_xy


class NaiveBayes(Estimator, HasFeaturesCol, HasLabelCol):
    """Multinomial naive Bayes over non-negative (count-like) features.

    The natural pairing with hashed text features (Featurize /
    TextFeaturizer output). Negative feature values are rejected, matching
    Spark MLlib's requirement.
    """

    smoothing = Param("Laplace/Lidstone smoothing", 1.0, ptype=float)

    def _fit(self, dataset: Dataset) -> "NaiveBayesModel":
        x, y, k = _prep_xy(self, dataset, classification=True)
        if np.any(x < 0):
            raise FriendlyError(
                "NaiveBayes requires non-negative feature values", self.uid
            )
        d = x.shape[1]
        counts = np.zeros((k, d))
        class_n = np.zeros(k)
        for c in range(k):
            rows = x[y == c]
            counts[c] = rows.sum(axis=0)
            class_n[c] = len(rows)
        a = self.smoothing
        log_prior = np.log(
            np.maximum(class_n, 1e-15) / max(len(y), 1)
        )
        log_like = np.log(counts + a) - np.log(
            counts.sum(axis=1, keepdims=True) + a * d
        )
        return NaiveBayesModel(
            log_prior=log_prior,
            log_likelihood=log_like,
            features_col=self.features_col,
        )


class NaiveBayesModel(Model, HasFeaturesCol, HasOutputCol):
    log_prior = Param("log class priors [K]")
    log_likelihood = Param("log feature likelihoods [K, d]")

    def __init__(self, **kwargs: Any):
        kwargs.setdefault("output_col", "scores")
        super().__init__(**kwargs)

    def _transform(self, dataset: Dataset) -> Dataset:
        x = np.asarray(stack_column(dataset, self.features_col), np.float64)
        # log joint: one [n,d]x[d,K] matmul — softmax downstream recovers
        # the posterior
        scores = x @ np.asarray(self.log_likelihood).T + np.asarray(
            self.log_prior
        )
        return dataset.with_column(self.output_col, scores)


class OneVsRest(Estimator, HasFeaturesCol, HasLabelCol):
    """K binary copies of any learner stage, one per class.

    Reference: the OneVsRest wrap TrainClassifier applies to multiclass
    logistic regression (TrainClassifier.scala:110-122). The wrapped
    learner must produce a 'scores' column; class k's score is the binary
    model's positive-class score.
    """

    learner = Param("binary learner Estimator to replicate", required=True)
    num_classes = Param("class count (None = infer from labels)")

    def _fit(self, dataset: Dataset) -> "OneVsRestModel":
        dataset.require(self.label_col)
        y = np.asarray(dataset[self.label_col])
        # same label hygiene as every sibling learner: missing labels drop
        # (CNTKLearner.scala:58), string labels index to [0, k)
        levels: list | None = None
        if y.dtype == object:
            keep = np.array([v is not None for v in y])
            dataset, y = dataset.filter(keep), y[keep]
            levels = sorted(set(y))
            lookup = {v: i for i, v in enumerate(levels)}
            y = np.asarray([lookup[v] for v in y], np.int64)
        else:
            if np.issubdtype(y.dtype, np.floating):
                keep = ~np.isnan(y)
                dataset, y = dataset.filter(keep), y[keep]
            y = y.astype(np.int64)
        k = (
            int(self.num_classes)
            if self.num_classes is not None
            else max(int(y.max()) + 1 if y.size else 2, 2)
        )
        models = []
        for c in range(k):
            binary = (y == c).astype(np.int32)
            ds_c = dataset.with_column("__ovr_label__", binary)
            learner = self.learner.copy(label_col="__ovr_label__")
            models.append(learner.fit(ds_c))
        return OneVsRestModel(
            models=models, features_col=self.features_col, levels=levels
        )


class OneVsRestModel(Model, HasFeaturesCol, HasOutputCol):
    models = Param("per-class fitted binary models", default=list)
    levels = Param("original label levels when labels were strings")

    def __init__(self, **kwargs: Any):
        kwargs.setdefault("output_col", "scores")
        super().__init__(**kwargs)

    def _transform(self, dataset: Dataset) -> Dataset:
        cols = []
        for m in self.models:
            scored = m.transform(dataset)
            s = np.asarray(scored["scores"], np.float64)
            if s.ndim == 2 and s.shape[1] >= 2:
                # binary softmax scores -> positive-class log-odds margin
                cols.append(s[:, 1] - s[:, 0])
            else:
                cols.append(s.reshape(len(s)))
        scores = np.stack(cols, axis=1)
        return dataset.with_column(self.output_col, scores)
