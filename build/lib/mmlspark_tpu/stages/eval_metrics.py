"""ComputeModelStatistics / ComputePerInstanceStatistics — zero-config
evaluation keyed off schema metadata.

Reference: compute-model-statistics/src/main/scala/
ComputeModelStatistics.scala:82-567 (discovers label/scored columns from
column metadata — ``getSchemaInfo`` :213-226 — so no column config is
needed; classification: confusion matrix, accuracy, Sokolova-Lapalme
micro/macro precision/recall (:383-437), AUC via 1000-bin ROC (:439-455);
regression: MSE/RMSE/R^2/MAE (:189-207)) and compute-per-instance-statistics/
.../ComputePerInstanceStatistics.scala:40-96 (per-row log_loss with
eps=1e-15, L1/L2 loss).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError, SchemaError
from mmlspark_tpu.core.metrics_contracts import MetricData
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.schema import (
    CLASSIFICATION,
    REGRESSION,
    find_label_column,
    find_scored_labels_column,
    find_scored_probabilities_column,
    get_score_value_kind,
)
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.data.dataset import Dataset

ROC_BINS = 1000  # ComputeModelStatistics.scala:78
LOG_LOSS_EPS = 1e-15  # ComputePerInstanceStatistics log_loss epsilon


def _schema_info(dataset: Dataset, model: str | None):
    """Discover evaluation inputs from metadata (getSchemaInfo analog)."""
    label = find_label_column(dataset, model)
    scored = find_scored_labels_column(dataset, model)
    kind = get_score_value_kind(dataset, model)
    if label is None or scored is None:
        raise SchemaError(
            "dataset carries no score-column metadata; run a Train* model "
            "first or set evaluation_metric + columns explicitly"
        )
    return label, scored, kind


def _encode_labels(y_true, y_pred, order=None):
    """Map arbitrary label values to shared integer codes. ``order`` (the
    producing model's level ordering, from categorical metadata) keeps codes
    aligned with the columns of scored_probabilities; unseen values are
    appended after."""
    seen = set(list(y_true)) | set(list(y_pred))
    if order is not None:
        levels = list(order) + sorted(seen - set(order), key=repr)
    else:
        levels = sorted(seen, key=repr)
    lookup = {v: i for i, v in enumerate(levels)}
    t = np.asarray([lookup[v] for v in y_true])
    p = np.asarray([lookup[v] for v in y_pred])
    return t, p, levels


def classification_metrics(y_true, y_pred, order=None) -> dict:
    """Accuracy + Sokolova-Lapalme micro/macro precision/recall."""
    t, p, levels = _encode_labels(y_true, y_pred, order)
    n = len(levels)
    cm = np.zeros((n, n), dtype=np.int64)
    np.add.at(cm, (t, p), 1)
    tp = np.diag(cm).astype(np.float64)
    support = cm.sum(axis=1).astype(np.float64)
    predicted = cm.sum(axis=0).astype(np.float64)
    total = cm.sum()
    accuracy = tp.sum() / max(total, 1)
    with np.errstate(divide="ignore", invalid="ignore"):
        per_prec = np.where(predicted > 0, tp / predicted, 0.0)
        per_rec = np.where(support > 0, tp / support, 0.0)
    macro_prec = float(per_prec.mean()) if n else 0.0
    macro_rec = float(per_rec.mean()) if n else 0.0
    # micro-averaged precision == recall == accuracy in single-label tasks
    micro = float(accuracy)
    return {
        "accuracy": float(accuracy),
        "precision_macro": macro_prec,
        "recall_macro": macro_rec,
        "precision_micro": micro,
        "recall_micro": micro,
        "confusion_matrix": cm,
        "levels": levels,
    }


def binary_auc(y_true01: np.ndarray, prob1: np.ndarray, bins: int = ROC_BINS):
    """AUC via binned ROC (reference binning=1000,
    ComputeModelStatistics.scala:439-455). One histogram pass + cumsum —
    O(n + bins), not O(n * bins). Returns (auc, roc_points)."""
    y = np.asarray(y_true01)
    p = np.clip(np.asarray(prob1, dtype=np.float64), 0.0, 1.0)
    edges = np.linspace(0.0, 1.0, bins + 1)
    pos_hist, _ = np.histogram(p[y == 1], bins=edges)
    neg_hist, _ = np.histogram(p[y == 0], bins=edges)
    pos = max(int(pos_hist.sum()), 1)
    neg = max(int(neg_hist.sum()), 1)
    # threshold sweep from 1.0 down to 0.0: cumulative counts from the top
    tpr = np.concatenate([[0.0], np.cumsum(pos_hist[::-1])]) / pos
    fpr = np.concatenate([[0.0], np.cumsum(neg_hist[::-1])]) / neg
    auc = float(np.trapezoid(tpr, fpr))
    return auc, np.stack([fpr, tpr], axis=1)


def regression_metrics(y_true: np.ndarray, y_pred: np.ndarray) -> dict:
    err = y_pred - y_true
    mse = float(np.mean(err**2))
    mae = float(np.mean(np.abs(err)))
    var = float(np.var(y_true))
    r2 = 1.0 - mse / var if var > 0 else 0.0
    return {
        "mean_squared_error": mse,
        "root_mean_squared_error": float(np.sqrt(mse)),
        "mean_absolute_error": mae,
        "R^2": float(r2),
    }


class ComputeModelStatistics(Transformer):
    """transform(scored dataset) -> one-row metrics Dataset. The confusion
    matrix and ROC curve are exposed as attributes after transform (the
    reference surfaces them as DataFrames/MetricData,
    ComputeModelStatistics.scala:494-529)."""

    evaluation_metric = Param(
        "task kind", "auto", domain=("auto", "classification", "regression")
    )
    model = Param("producing model uid (None = discover)")
    label_col = Param("explicit label column (overrides metadata)")
    scores_col = Param("explicit scored-labels column (overrides metadata)")

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.confusion_matrix: np.ndarray | None = None
        self.roc_curve: np.ndarray | None = None
        self.metrics: list[MetricData] = []

    def _transform(self, dataset: Dataset) -> Dataset:
        if self.label_col and self.scores_col:
            label, scored = self.label_col, self.scores_col
            kind = (
                None if self.evaluation_metric == "auto"
                else self.evaluation_metric
            )
        else:
            label, scored, kind = _schema_info(dataset, self.model)
        if self.evaluation_metric != "auto":
            kind = self.evaluation_metric
        if kind is None:
            raise FriendlyError("cannot infer task kind; set evaluation_metric",
                                self.uid)

        if kind == CLASSIFICATION:
            # class order from the producing model's categorical metadata —
            # keeps codes aligned with scored_probabilities columns
            cat = dataset.meta_of(scored).categorical
            if cat is None:
                cat = dataset.meta_of(label).categorical
            order = list(cat.levels) if cat is not None else None
            stats = classification_metrics(
                dataset[label], dataset[scored], order
            )
            self.confusion_matrix = stats.pop("confusion_matrix")
            levels = stats.pop("levels")
            prob_col = find_scored_probabilities_column(dataset, self.model)
            if prob_col is not None and len(levels) == 2:
                probs = np.asarray(dataset[prob_col], dtype=np.float64)
                t, _, _ = _encode_labels(
                    dataset[label], dataset[scored], order
                )
                auc, roc = binary_auc(t, probs[:, 1])
                stats["AUC"] = auc
                self.roc_curve = roc
            self.metrics = [
                MetricData.create(k, v, self.model) for k, v in stats.items()
            ]
            return Dataset({k: [v] for k, v in stats.items()})

        if kind == REGRESSION:
            y = np.asarray(dataset[label], dtype=np.float64)
            p = np.asarray(dataset[scored], dtype=np.float64)
            stats = regression_metrics(y, p)
            self.metrics = [
                MetricData.create(k, v, self.model) for k, v in stats.items()
            ]
            return Dataset({k: [v] for k, v in stats.items()})

        raise FriendlyError(f"unknown evaluation kind '{kind}'", self.uid)


class ComputePerInstanceStatistics(Transformer):
    """Per-row metrics: log_loss (classification), L1/L2 loss (regression)
    (reference ComputePerInstanceStatistics.scala:40-96)."""

    model = Param("producing model uid (None = discover)")

    def _transform(self, dataset: Dataset) -> Dataset:
        label, scored, kind = _schema_info(dataset, self.model)
        if kind == CLASSIFICATION:
            prob_col = find_scored_probabilities_column(dataset, self.model)
            if prob_col is None:
                raise FriendlyError(
                    "per-instance log_loss needs scored probabilities",
                    self.uid,
                )
            probs = np.asarray(dataset[prob_col], dtype=np.float64)
            cat = dataset.meta_of(scored).categorical
            order = list(cat.levels) if cat is not None else None
            t, _, levels = _encode_labels(dataset[label], dataset[scored], order)
            if len(t) and t.max() >= probs.shape[1]:
                bad = levels[int(t.max())]
                raise FriendlyError(
                    f"label value {bad!r} was never seen by the model "
                    f"({probs.shape[1]} classes); cannot score it",
                    self.uid,
                )
            # clip like the reference (eps=1e-15)
            p_true = np.clip(
                probs[np.arange(len(t)), t], LOG_LOSS_EPS, 1 - LOG_LOSS_EPS
            )
            return dataset.with_column("log_loss", -np.log(p_true))
        y = np.asarray(dataset[label], dtype=np.float64)
        p = np.asarray(dataset[scored], dtype=np.float64)
        ds = dataset.with_column("L1_loss", np.abs(p - y))
        return ds.with_column("L2_loss", (p - y) ** 2)
