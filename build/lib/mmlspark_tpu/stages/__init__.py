"""The pipeline-stage surface: importing this package registers every stage.

Mirrors the reference's per-capability sbt sub-projects (SURVEY.md §2.3-2.7);
each module here corresponds to one or more reference modules and the import
below is what populates :meth:`PipelineStage.registry` (the analog of
JarLoadingUtils loading every Transformer/Estimator from built jars).
"""

_STAGE_MODULES = [
    "dnn_model",
    "dnn_learner",
    "value_indexer",
    "featurize",
    "text",
    "word2vec",
    "trees",
    "classical",
    "train_classifier",
    "train_regressor",
    "eval_metrics",
    "find_best",
    "image",
    "prep",
    "ensemble",
]

import importlib

for _m in _STAGE_MODULES:
    importlib.import_module(f"mmlspark_tpu.stages.{_m}")
