"""ValueIndexer / IndexToValue — typed categorical indexing.

Reference: value-indexer/src/main/scala/ValueIndexer.scala (typed
StringIndexer generalization: distinct + null-aware sort of levels ->
categorical metadata; :37-47,63-82,140-149) and IndexToValue.scala:26-48
(inverse transform back to the original type via the metadata).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param
from mmlspark_tpu.core.schema import CategoricalMeta, ColumnMeta
from mmlspark_tpu.core.stage import Estimator, Model, Transformer
from mmlspark_tpu.data.dataset import Dataset


def _is_missing(v) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    return False


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Learn distinct levels of a column (any dtype) and map to indices.

    Null-aware: missing values get the trailing level index (reference
    null-ordering, ValueIndexer.scala:37-47)."""

    def _fit(self, dataset: Dataset) -> "ValueIndexerModel":
        dataset.require(self.input_col)
        arr = dataset[self.input_col]
        present = [v for v in arr if not _is_missing(v)]
        has_null = len(present) < len(arr)
        try:
            levels = sorted(set(present))
        except TypeError:
            raise FriendlyError(
                f"column '{self.input_col}' mixes unorderable types", self.uid
            )
        return ValueIndexerModel(
            input_col=self.input_col,
            output_col=self.output_col,
            levels=list(levels),
            has_null=has_null,
        )


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    levels = Param("ordered category levels", default=list)
    has_null = Param("whether a trailing null level exists", False, ptype=bool)

    def categorical_meta(self) -> CategoricalMeta:
        return CategoricalMeta(tuple(self.levels), has_null=self.has_null)

    def _transform(self, dataset: Dataset) -> Dataset:
        dataset.require(self.input_col)
        lookup = {lvl: i for i, lvl in enumerate(self.levels)}
        null_index = len(self.levels)
        out = np.empty(dataset.num_rows, dtype=np.int32)
        for i, v in enumerate(dataset[self.input_col]):
            if _is_missing(v):
                if not self.has_null:
                    raise FriendlyError(
                        f"unseen null in '{self.input_col}' (no null level)",
                        self.uid,
                    )
                out[i] = null_index
            elif v in lookup:
                out[i] = lookup[v]
            else:
                raise FriendlyError(
                    f"unseen level {v!r} in column '{self.input_col}'", self.uid
                )
        meta = ColumnMeta(categorical=self.categorical_meta())
        return dataset.with_column(self.output_col, out, meta)


class IndexToValue(Transformer, HasInputCol, HasOutputCol):
    """Inverse of ValueIndexerModel using the column's categorical metadata
    (zero-config — reference IndexToValue.scala:26-48)."""

    def _transform(self, dataset: Dataset) -> Dataset:
        dataset.require(self.input_col)
        cat = dataset.meta_of(self.input_col).categorical
        if cat is None:
            raise FriendlyError(
                f"column '{self.input_col}' has no categorical metadata",
                self.uid,
            )
        levels = list(cat.levels)
        null_index = len(levels)
        values = []
        for idx in dataset[self.input_col]:
            idx = int(idx)
            if idx == null_index and cat.has_null:
                values.append(None)
            elif 0 <= idx < len(levels):
                values.append(levels[idx])
            else:
                raise FriendlyError(
                    f"index {idx} out of range for {len(levels)} levels",
                    self.uid,
                )
        return dataset.with_column(self.output_col, values)
