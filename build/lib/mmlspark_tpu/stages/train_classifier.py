"""TrainClassifier — one-liner AutoML-style classification.

Reference: train-classifier/src/main/scala/TrainClassifier.scala:40-348.
Pipeline reproduced feature-for-feature:

- label reindex via ValueIndexer (+ explicit labels option) with levels kept
  for inverse mapping (convertLabel, :203-249)
- auto-Featurize of all non-label columns, learner-aware config (2^18
  features default, 2^12 for NN learners; no OHE for tree learners —
  :107,186-201)
- the learner is just another estimator; built-ins mirror the reference's
  full dispatch list (TrainClassifier.scala:45-52): logistic regression /
  MLP (SPMD-trained), decision tree / random forest / GBT (histogram
  trees built with XLA segment-sums, stages/trees.py), and naive Bayes;
  a custom Estimator plugs in the same way. Delta vs reference: our
  logistic regression and GBT are natively multiclass (softmax), so the
  OneVsRest wrap the reference needs at :110-122 is unnecessary — the
  OneVsRest combinator still exists (stages/classical.py) for wrapping
  binary-only custom learners.
- output model = featurizer + learner + score-column metadata tagging
  (TrainedClassifierModel.transform, :297-348)
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.params import HasLabelCol, Param, positive
from mmlspark_tpu.core.schema import (
    CLASSIFICATION,
    LABEL_KIND,
    SCORED_LABELS_KIND,
    SCORED_PROBABILITIES_KIND,
    SCORES_KIND,
    CategoricalMeta,
    ColumnMeta,
)
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.dnn_learner import DNNLearner
from mmlspark_tpu.stages.featurize import (
    DEFAULT_NUM_FEATURES,
    TREE_NN_NUM_FEATURES,
    Featurize,
)
from mmlspark_tpu.stages.value_indexer import ValueIndexer

#: built-in learners; mirrors the supported-learner dispatch at
#: TrainClassifier.scala:45-52
LOGISTIC_REGRESSION = "logistic_regression"
MLP_CLASSIFIER = "mlp"
DECISION_TREE = "decision_tree"
RANDOM_FOREST = "random_forest"
GBT = "gbt"
NAIVE_BAYES = "naive_bayes"

#: learners featurized tree-style: small hash space, no one-hot
#: (TrainClassifier.scala:107, Featurize.scala:13-19)
_TREE_LEARNERS = (DECISION_TREE, RANDOM_FOREST, GBT)


class TrainClassifier(Estimator, HasLabelCol):
    model = Param(
        "learner: built-in name or a custom Estimator producing a scores "
        "column on 'features'",
        LOGISTIC_REGRESSION,
    )
    number_of_features = Param(
        "hash space for text features (None = learner-aware default)"
    )
    reindex_label = Param("reindex label to [0, n)", True, ptype=bool)
    labels = Param("explicit label levels (overrides discovered ordering)")
    # pass-through training knobs for built-in learners
    epochs = Param("epochs", 10, ptype=int, validator=positive)
    batch_size = Param("global batch size", 256, ptype=int, validator=positive)
    learning_rate = Param("learning rate", 1e-2, ptype=float)
    hidden = Param("hidden layer sizes for the mlp learner", (128,))
    seed = Param("rng seed", 0, ptype=int)
    steps_per_dispatch = Param(
        "optimizer steps per compiled call (NN learners)", 1, ptype=int,
        validator=positive,
    )

    # tree knobs (pass-through to the histogram learners)
    max_depth = Param("tree depth", 5, ptype=int, validator=positive)
    num_trees = Param("random-forest tree count", 20, ptype=int,
                      validator=positive)
    max_iter = Param("gbt boosting rounds", 20, ptype=int, validator=positive)

    def _make_learner(self, num_classes: int) -> Estimator:
        from mmlspark_tpu.stages.classical import NaiveBayes
        from mmlspark_tpu.stages.trees import (
            DecisionTreeClassifier,
            GBTClassifier,
            RandomForestClassifier,
        )

        tree_common = dict(
            features_col="features",
            label_col="__label_idx__",
            max_depth=self.max_depth,
            seed=self.seed,
        )
        if self.model == DECISION_TREE:
            return DecisionTreeClassifier(**tree_common)
        if self.model == RANDOM_FOREST:
            return RandomForestClassifier(
                num_trees=self.num_trees, **tree_common
            )
        if self.model == GBT:
            return GBTClassifier(
                max_iter=self.max_iter,
                step_size=self.learning_rate
                if self.is_set("learning_rate")
                else 0.1,
                **tree_common,
            )
        if self.model == NAIVE_BAYES:
            return NaiveBayes(
                features_col="features", label_col="__label_idx__"
            )
        if isinstance(self.model, Estimator):
            return self.model
        if self.model == LOGISTIC_REGRESSION:
            return DNNLearner(
                model_name="linear",
                model_config={"num_outputs": num_classes},
                loss="softmax_xent",
                epochs=self.epochs,
                batch_size=self.batch_size,
                learning_rate=self.learning_rate,
                seed=self.seed,
                steps_per_dispatch=self.steps_per_dispatch,
                features_col="features",
                label_col="__label_idx__",
            )
        if self.model == MLP_CLASSIFIER:
            return DNNLearner(
                model_name="mlp",
                model_config={
                    "num_outputs": num_classes,
                    "hidden": tuple(self.hidden),
                },
                loss="softmax_xent",
                epochs=self.epochs,
                batch_size=self.batch_size,
                learning_rate=self.learning_rate,
                seed=self.seed,
                steps_per_dispatch=self.steps_per_dispatch,
                features_col="features",
                label_col="__label_idx__",
            )
        raise FriendlyError(
            f"unknown learner '{self.model}'; built-ins: "
            f"{LOGISTIC_REGRESSION!r}, {MLP_CLASSIFIER!r}, "
            f"{DECISION_TREE!r}, {RANDOM_FOREST!r}, {GBT!r}, "
            f"{NAIVE_BAYES!r}",
            self.uid,
        )

    def _num_features(self) -> int:
        if self.number_of_features is not None:
            return int(self.number_of_features)
        # tree/NN learners get the smaller hash space (Featurize.scala:13-19)
        return (
            TREE_NN_NUM_FEATURES
            if self.model == MLP_CLASSIFIER or self.model in _TREE_LEARNERS
            else DEFAULT_NUM_FEATURES
        )

    def _fit(self, dataset: Dataset) -> "TrainedClassifierModel":
        dataset.require(self.label_col)
        # -- label conversion (convertLabel, :203-249)
        if self.labels is not None:
            levels = list(self.labels)
            lookup = {lvl: i for i, lvl in enumerate(levels)}
            try:
                idx = np.asarray(
                    [lookup[v] for v in dataset[self.label_col]], np.int32
                )
            except KeyError as e:
                raise FriendlyError(
                    f"label value {e.args[0]!r} not in explicit labels",
                    self.uid,
                )
            indexed = dataset.with_column("__label_idx__", idx)
        elif self.reindex_label:
            indexer = ValueIndexer(
                input_col=self.label_col, output_col="__label_idx__"
            ).fit(dataset)
            indexed = indexer.transform(dataset)
            levels = list(indexer.levels)
        else:
            idx = np.asarray(dataset[self.label_col], np.int64)
            levels = list(range(int(idx.max()) + 1)) if len(idx) else []
            indexed = dataset.with_column("__label_idx__", idx.astype(np.int32))
        num_classes = max(len(levels), 2)

        # -- featurize all non-label columns
        feature_inputs = [
            c
            for c in dataset.columns
            if c not in (self.label_col, "__label_idx__")
        ]
        featurizer = Featurize(
            feature_columns={"features": feature_inputs},
            number_of_features=self._num_features(),
            # trees split categoricals on the raw index — no OHE
            # (TrainClassifier.scala:107)
            one_hot_encode_categoricals=self.model not in _TREE_LEARNERS,
            # naive Bayes needs raw non-negative counts (Spark MLlib
            # requirement); z-scoring would sign-flip them
            standardize=self.model != NAIVE_BAYES,
        ).fit(indexed)
        featurized = featurizer.transform(indexed)

        learner = self._make_learner(num_classes)
        fitted = learner.fit(featurized)

        return TrainedClassifierModel(
            featurizer=featurizer,
            learner_model=fitted,
            levels=levels,
            label_col=self.label_col,
        )


class TrainedClassifierModel(Model):
    featurizer = Param("fitted FeaturizeModel")
    learner_model = Param("fitted scoring model (scores on 'features')")
    levels = Param("label levels for inverse mapping", default=list)
    label_col = Param("original label column", "label", ptype=str)

    def _transform(self, dataset: Dataset) -> Dataset:
        ds = self.featurizer.transform(dataset)
        ds = self.learner_model.transform(ds)
        scores = np.asarray(ds["scores"], dtype=np.float64)
        # softmax probabilities + argmax labels (the reference's
        # probability/prediction columns, tagged via metadata :297-348)
        z = scores - scores.max(axis=1, keepdims=True)
        ez = np.exp(z)
        probs = ez / ez.sum(axis=1, keepdims=True)
        pred_idx = scores.argmax(axis=1)
        levels = list(self.levels)
        if levels:
            inv = np.array(levels + [None], dtype=object)
            pred = inv[np.minimum(pred_idx, len(levels) - 1)]
            pred = np.array([p for p in pred], dtype=object)
        else:
            pred = pred_idx
        uid = self.uid
        cat = CategoricalMeta(tuple(levels)) if levels else None
        ds = ds.with_column(
            "scores",
            scores,
            ColumnMeta(kind=SCORES_KIND, model=uid, value_kind=CLASSIFICATION),
        )
        ds = ds.with_column(
            "scored_probabilities",
            probs,
            ColumnMeta(
                kind=SCORED_PROBABILITIES_KIND,
                model=uid,
                value_kind=CLASSIFICATION,
            ),
        )
        ds = ds.with_column(
            "scored_labels",
            pred,
            ColumnMeta(
                kind=SCORED_LABELS_KIND,
                model=uid,
                value_kind=CLASSIFICATION,
                categorical=cat,
            ),
        )
        if self.label_col in ds.columns:
            ds = ds.with_meta(
                self.label_col,
                ds.meta_of(self.label_col).evolve(
                    kind=LABEL_KIND, model=uid, value_kind=CLASSIFICATION,
                    categorical=cat,
                ),
            )
        return ds
