"""Data-prep stages.

Reference modules (SURVEY.md §2.7): pipeline-stages (Cacher, CheckpointData,
DropColumns, SelectColumns, Repartition, ClassBalancer, Timer),
clean-missing-data, data-conversion, partition-sample, summarize-data,
multi-column-adapter.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.params import (
    HasInputCols,
    HasOutputCols,
    Param,
    in_unit_interval,
    positive,
)
from mmlspark_tpu.core.stage import Estimator, Model, Transformer
from mmlspark_tpu.data.dataset import Dataset

_log = get_logger("stages")


class Cacher(Transformer):
    """Materialization hint (reference Cacher persists the DataFrame;
    Datasets here are host-materialized already, so this is the identity
    with the same pipeline role)."""

    def _transform(self, dataset: Dataset) -> Dataset:
        return dataset


class CheckpointData(Transformer):
    """Persist the dataset to disk and reload (reference
    checkpoint-data/.../CheckpointData.scala:13-62; disk option maps to an
    on-disk column store, remove_checkpoint drops it after load)."""

    checkpoint_dir = Param("directory to persist into", required=True)
    remove_checkpoint = Param("delete files after reload", False, ptype=bool)

    def _transform(self, dataset: Dataset) -> Dataset:
        import shutil

        from mmlspark_tpu.core.serialize import load_dataset, save_dataset

        save_dataset(dataset, self.checkpoint_dir)
        out = load_dataset(self.checkpoint_dir)
        if self.remove_checkpoint:
            shutil.rmtree(self.checkpoint_dir, ignore_errors=True)
        return out


class DropColumns(Transformer):
    cols = Param("columns to drop", default=list)

    def _transform(self, dataset: Dataset) -> Dataset:
        dataset.require(*self.cols)
        return dataset.drop(*self.cols)


class SelectColumns(Transformer):
    cols = Param("columns to keep", default=list)

    def _transform(self, dataset: Dataset) -> Dataset:
        return dataset.select(*self.cols)


class Repartition(Transformer):
    """Set the dataset's partition count (reference Repartition stage; here
    partitioning advises the host feed pipeline, not cluster shuffles)."""

    n = Param("partition count", 1, ptype=int, validator=positive)

    def _transform(self, dataset: Dataset) -> Dataset:
        return dataset.with_partitions(self.n)


class ClassBalancer(Estimator):
    """Inverse-frequency observation weights (reference ClassBalancer:
    weight = max_count/count per label level)."""

    input_col = Param("label column", "label", ptype=str)
    output_col = Param("weight column", "weight", ptype=str)

    def _fit(self, dataset: Dataset) -> "ClassBalancerModel":
        dataset.require(self.input_col)
        values, counts = np.unique(
            np.asarray(dataset[self.input_col], dtype=object), return_counts=True
        )
        weights = counts.max() / counts
        return ClassBalancerModel(
            input_col=self.input_col,
            output_col=self.output_col,
            table={v: float(w) for v, w in zip(values.tolist(), weights)},
        )


class ClassBalancerModel(Model):
    input_col = Param("label column", "label", ptype=str)
    output_col = Param("weight column", "weight", ptype=str)
    table = Param("level -> weight", default=dict)

    def _transform(self, dataset: Dataset) -> Dataset:
        w = np.array(
            [self.table.get(v, 1.0) for v in dataset[self.input_col]],
            dtype=np.float64,
        )
        return dataset.with_column(self.output_col, w)


class Timer(Transformer):
    """Wrap a stage and log wall time of fit/transform (reference
    pipeline-stages/.../Timer.scala:14-123). The wrapped stage's output is
    returned unchanged; timings accumulate on ``records``."""

    stage = Param("wrapped stage", required=True)
    log_to_scala = Param("log timings (name kept for parity)", True, ptype=bool)
    profile_dir = Param(
        "when set, also capture a jax.profiler trace of each timed op "
        "under this directory (TensorBoard/Perfetto viewable)"
    )

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self.records: list[dict] = []

    def _time(self, what: str, fn, dataset: Dataset):
        import contextlib

        if self.profile_dir:
            from mmlspark_tpu.utils.profiling import trace_profile

            ctx: Any = trace_profile(self.profile_dir)
        else:
            ctx = contextlib.nullcontext()
        t0 = time.perf_counter()
        with ctx:
            result = fn(dataset)
        dt = time.perf_counter() - t0
        rec = {
            "stage": getattr(self.stage, "uid", str(self.stage)),
            "op": what,
            "seconds": dt,
            "rows": dataset.num_rows,
        }
        self.records.append(rec)
        if self.log_to_scala:
            _log.info("%(stage)s %(op)s took %(seconds).3fs on %(rows)d rows",
                      rec)
        return result

    def _transform(self, dataset: Dataset) -> Dataset:
        stage = self.stage
        if isinstance(stage, Estimator):
            model = self._time("fit", stage.fit, dataset)
            return self._time("transform", model.transform, dataset)
        return self._time("transform", stage.transform, dataset)


class CleanMissingData(Estimator):
    """Imputation: Mean / Median / Custom fill per column (reference
    clean-missing-data/src/main/scala/CleanMissingData.scala:14-45)."""

    MEAN, MEDIAN, CUSTOM = "Mean", "Median", "Custom"

    input_cols = Param("columns to clean", default=list)
    output_cols = Param("output columns (default: in place)")
    cleaning_mode = Param("imputation mode", "Mean",
                          domain=("Mean", "Median", "Custom"))
    custom_value = Param("fill value for Custom mode")

    def _fit(self, dataset: Dataset) -> "CleanMissingDataModel":
        explicit = bool(self.input_cols)
        cols = list(self.input_cols or dataset.columns)
        dataset.require(*cols)
        if not explicit:
            # zero-config mode imputes the numeric columns only
            cols = [
                c
                for c in cols
                if dataset[c].dtype != object and dataset[c].dtype.kind in "iuf"
            ]
        fills: dict[str, float] = {}
        for c in cols:
            try:
                arr = np.asarray(dataset[c], dtype=np.float64)
            except (ValueError, TypeError):
                raise FriendlyError(
                    f"column '{c}' is not numeric; CleanMissingData imputes "
                    "numeric columns",
                    self.uid,
                )
            if self.cleaning_mode == self.MEAN:
                fills[c] = float(np.nanmean(arr)) if not np.all(np.isnan(arr)) else 0.0
            elif self.cleaning_mode == self.MEDIAN:
                all_nan = np.all(np.isnan(arr))
                fills[c] = float(np.nanmedian(arr)) if not all_nan else 0.0
            else:
                if self.custom_value is None:
                    raise FriendlyError("Custom mode needs custom_value", self.uid)
                fills[c] = float(self.custom_value)
        out_cols = self.output_cols or cols
        return CleanMissingDataModel(
            input_cols=list(cols), output_cols=list(out_cols), fills=fills
        )


class CleanMissingDataModel(Model, HasInputCols, HasOutputCols):
    fills = Param("column -> fill value", default=dict)

    def _transform(self, dataset: Dataset) -> Dataset:
        out = dataset
        for c_in, c_out in zip(self.input_cols, self.output_cols):
            arr = np.asarray(out[c_in], dtype=np.float64).copy()
            arr[np.isnan(arr)] = self.fills[c_in]
            out = out.with_column(c_out, arr)
        return out


class DataConversion(Transformer):
    """Column type casting incl. date<->string (reference
    data-conversion/src/main/scala/DataConversion.scala:23-66)."""

    cols = Param("columns to convert", default=list)
    convert_to = Param(
        "target type",
        "double",
        domain=("boolean", "byte", "short", "integer", "long", "float",
                "double", "string", "toCategorical", "clearCategorical",
                "date"),
    )
    date_time_format = Param("strftime format for date<->string",
                             "%Y-%m-%d %H:%M:%S", ptype=str)

    _NUMPY = {
        "boolean": np.bool_, "byte": np.int8, "short": np.int16,
        "integer": np.int32, "long": np.int64, "float": np.float32,
        "double": np.float64,
    }

    def _transform(self, dataset: Dataset) -> Dataset:

        out = dataset
        for c in self.cols:
            out.require(c)
            arr = out[c]
            if self.convert_to in self._NUMPY:
                out = out.with_column(
                    c, np.asarray(arr).astype(self._NUMPY[self.convert_to])
                )
            elif self.convert_to == "string":
                if arr.dtype.kind == "M":
                    import pandas as pd

                    s = pd.Series(arr).dt.strftime(self.date_time_format)
                    out = out.with_column(c, list(s))
                else:
                    out = out.with_column(c, [str(v) for v in arr])
            elif self.convert_to == "date":
                import pandas as pd

                s = pd.to_datetime(
                    pd.Series(list(arr)), format=self.date_time_format
                )
                out = out.with_column(c, s.to_numpy())
            elif self.convert_to == "toCategorical":
                from mmlspark_tpu.stages.value_indexer import ValueIndexer

                model = ValueIndexer(input_col=c, output_col=c).fit(out)
                out = model.transform(out)
            elif self.convert_to == "clearCategorical":
                meta = out.meta_of(c)
                cat = meta.categorical
                if cat is not None:
                    levels = list(cat.levels) + ([None] if cat.has_null else [])
                    vals = [levels[int(i)] for i in arr]
                    out = out.with_column(c, vals, meta.evolve(categorical=None))
        return out


class PartitionSample(Transformer):
    """Head / RandomSample (absolute or percent) / AssignToPartition
    (reference partition-sample/.../PartitionSample.scala:13-135)."""

    mode = Param("sampling mode", "RandomSample",
                 domain=("Head", "RandomSample", "AssignToPartition"))
    count = Param("rows for Head or absolute RandomSample", 1000, ptype=int)
    percent = Param("fraction for percent RandomSample", 0.1, ptype=float,
                    validator=in_unit_interval)
    random_sample_mode = Param("Absolute | Percentage", "Percentage",
                               domain=("Absolute", "Percentage"))
    seed = Param("rng seed", 0, ptype=int)
    num_parts = Param("partitions for AssignToPartition", 10, ptype=int,
                      validator=positive)
    partition_col = Param("partition-id column name", "Partition", ptype=str)

    def _transform(self, dataset: Dataset) -> Dataset:
        if self.mode == "Head":
            return dataset.take(self.count)
        if self.mode == "RandomSample":
            if self.random_sample_mode == "Absolute":
                return dataset.sample(n=self.count, seed=self.seed)
            return dataset.sample(fraction=self.percent, seed=self.seed)
        rng = np.random.default_rng(self.seed)
        ids = rng.integers(0, self.num_parts, size=dataset.num_rows)
        return dataset.with_column(
            self.partition_col, ids.astype(np.int32)
        ).with_partitions(self.num_parts)


class SummarizeData(Transformer):
    """Per-column statistics table (reference
    summarize-data/.../SummarizeData.scala:22-98: counts / basic / sample /
    percentiles blocks, error threshold ignored — exact quantiles here)."""

    counts = Param("include count/unique/missing", True, ptype=bool)
    basic = Param("include min/max/mean/stddev", True, ptype=bool)
    sample = Param("include variance/skew/kurtosis", True, ptype=bool)
    percentiles = Param("include P0.5..P99.5", True, ptype=bool)
    error_threshold = Param("approx-quantile error (parity param)", 0.0,
                            ptype=float)

    _PCTS = (0.005, 0.25, 0.5, 0.75, 0.995)

    def _transform(self, dataset: Dataset) -> Dataset:
        rows: dict[str, list] = {"Feature": []}

        def put(name, value):
            rows.setdefault(name, []).append(value)

        for c in dataset.columns:
            arr = dataset[c]
            rows["Feature"].append(c)
            is_num = arr.dtype != object and arr.dtype.kind in "biuf"
            f = np.asarray(arr, dtype=np.float64) if is_num else None
            valid = f[~np.isnan(f)] if f is not None else None
            if self.counts:
                put("Count", dataset.num_rows)
                if arr.dtype == object:
                    vals = [v for v in arr if v is not None]
                    put("Unique Value Count", len(set(vals)))
                    put("Missing Value Count", dataset.num_rows - len(vals))
                else:
                    put("Unique Value Count", len(np.unique(arr)))
                    put("Missing Value Count",
                        int(np.isnan(f).sum()) if f is not None else 0)
            if self.basic:
                have = valid is not None and len(valid) > 0
                put("Min", float(valid.min()) if have else np.nan)
                put("Max", float(valid.max()) if have else np.nan)
                put("Mean", float(valid.mean()) if have else np.nan)
                put("Standard Deviation",
                    float(valid.std(ddof=1))
                    if have and len(valid) > 1 else np.nan)
            if self.sample:
                if valid is not None and len(valid) > 2:
                    m = valid.mean()
                    s = valid.std(ddof=0)
                    z = (valid - m) / s if s > 0 else valid * 0
                    put("Sample Variance", float(valid.var(ddof=1)))
                    put("Sample Skewness", float(np.mean(z**3)))
                    put("Sample Kurtosis", float(np.mean(z**4) - 3))
                else:
                    put("Sample Variance", np.nan)
                    put("Sample Skewness", np.nan)
                    put("Sample Kurtosis", np.nan)
            if self.percentiles:
                for p in self._PCTS:
                    put(
                        f"P{p * 100:g}",
                        float(np.quantile(valid, p))
                        if valid is not None and len(valid)
                        else np.nan,
                    )
        return Dataset(rows)


class MultiColumnAdapter(Transformer):
    """Apply a unary stage across paired input/output column lists
    (reference multi-column-adapter/.../MultiColumnAdapter.scala:17-53)."""

    base_stage = Param("unary stage with input_col/output_col params",
                       required=True)
    input_cols = Param("input columns", default=list)
    output_cols = Param("output columns", default=list)

    def _transform(self, dataset: Dataset) -> Dataset:
        if len(self.input_cols) != len(self.output_cols):
            raise FriendlyError(
                "input_cols and output_cols must pair up", self.uid
            )
        out = dataset
        for c_in, c_out in zip(self.input_cols, self.output_cols):
            stage = self.base_stage.copy(input_col=c_in, output_col=c_out)
            if isinstance(stage, Estimator):
                out = stage.fit(out).transform(out)
            else:
                out = stage.transform(out)
        return out
