"""EnsembleByKey — group-by-key aggregation of score/vector columns.

Reference: ensemble/src/main/scala/EnsembleByKey.scala:21-203 (group by key
columns, mean of scalar and vector columns — vector average via UDAF —
optional collapse to one row per key).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Transformer
from mmlspark_tpu.data.dataset import Dataset


class EnsembleByKey(Transformer):
    keys = Param("grouping key columns", default=list)
    cols = Param("columns to average", default=list)
    col_names = Param("output names (default '<col>_avg')")
    strategy = Param("aggregation strategy", "mean", domain=("mean",))
    collapse_group = Param("one row per key (vs broadcast back)", True,
                           ptype=bool)

    def _transform(self, dataset: Dataset) -> Dataset:
        if not self.keys or not self.cols:
            raise FriendlyError("keys and cols are required", self.uid)
        dataset.require(*self.keys, *self.cols)
        out_names = self.col_names or [f"{c}_avg" for c in self.cols]
        if len(out_names) != len(self.cols):
            raise FriendlyError("col_names must pair with cols", self.uid)

        key_tuples = list(
            zip(*[dataset[k] for k in self.keys])
        )
        groups: dict[tuple, list[int]] = {}
        for i, kt in enumerate(key_tuples):
            groups.setdefault(kt, []).append(i)

        # mean per group for each column (vectors via row-stack mean)
        means: dict[str, dict[tuple, np.ndarray]] = {}
        for c in self.cols:
            col = dataset[c]
            per = {}
            for kt, idxs in groups.items():
                vals = [np.asarray(col[i], dtype=np.float64) for i in idxs]
                per[kt] = np.mean(np.stack(vals), axis=0)
            means[c] = per

        if self.collapse_group:
            uniq = list(groups)
            cols: dict[str, list] = {
                k: [kt[j] for kt in uniq] for j, k in enumerate(self.keys)
            }
            for c, name in zip(self.cols, out_names):
                vals = [means[c][kt] for kt in uniq]
                arr = np.stack(vals)
                cols[name] = arr if arr.ndim > 1 else arr.ravel()
            return Dataset(cols)

        out = dataset
        for c, name in zip(self.cols, out_names):
            vals = [means[c][kt] for kt in key_tuples]
            arr = np.stack(vals)
            out = out.with_column(name, arr if arr.ndim > 1 else arr.ravel())
        return out
