"""FindBestModel — model selection over an evaluation dataset.

Reference: find-best-model/src/main/scala/FindBestModel.scala:24-230
(evaluate each trained model with ComputeModelStatistics on one metric,
higher/lower-is-better dispatch, keep best + all-model metrics table + ROC
of the best model).
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.params import Param
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics

#: metric -> higher is better?
_METRIC_DIRECTION = {
    "accuracy": True,
    "precision_macro": True,
    "recall_macro": True,
    "precision_micro": True,
    "recall_micro": True,
    "AUC": True,
    "R^2": True,
    "mean_squared_error": False,
    "root_mean_squared_error": False,
    "mean_absolute_error": False,
    "log_loss": False,
}


class FindBestModel(Estimator):
    models = Param("candidate fitted models", default=list)
    evaluation_metric = Param("metric to rank by", "accuracy", ptype=str)

    def _fit(self, dataset: Dataset) -> "BestModel":
        if not self.models:
            raise FriendlyError("no candidate models given", self.uid)
        metric = self.evaluation_metric
        if metric not in _METRIC_DIRECTION:
            raise FriendlyError(
                f"unknown metric '{metric}'; known: "
                f"{sorted(_METRIC_DIRECTION)}",
                self.uid,
            )
        higher_better = _METRIC_DIRECTION[metric]
        rows: list[dict] = []
        best_idx, best_val, best_roc = -1, None, None
        for i, model in enumerate(self.models):
            scored = model.transform(dataset)
            evaluator = ComputeModelStatistics(model=model.uid)
            stats = evaluator.transform(scored)
            row = {"model": model.uid, **{c: stats[c][0] for c in stats.columns}}
            rows.append(row)
            if metric not in row:
                raise FriendlyError(
                    f"metric '{metric}' not produced for model {model.uid} "
                    f"(got {sorted(row)})",
                    self.uid,
                )
            val = float(row[metric])
            if (
                best_val is None
                or (higher_better and val > best_val)
                or (not higher_better and val < best_val)
            ):
                best_idx, best_val, best_roc = i, val, evaluator.roc_curve
        all_cols = sorted({k for r in rows for k in r})
        table = Dataset(
            {c: [r.get(c, np.nan) for r in rows] for c in all_cols}
        )
        return BestModel(
            best_model=self.models[best_idx],
            best_metric_value=best_val,
            evaluation_metric=metric,
            all_model_metrics=table,
            roc_curve=best_roc,
        )


class BestModel(Model):
    best_model = Param("the winning fitted model")
    best_metric_value = Param("winning metric value")
    evaluation_metric = Param("metric ranked by", "accuracy", ptype=str)
    all_model_metrics = Param("metrics table over all candidates")
    roc_curve = Param("ROC points of the best model (binary cls only)")

    def _transform(self, dataset: Dataset) -> Dataset:
        return self.best_model.transform(dataset)
