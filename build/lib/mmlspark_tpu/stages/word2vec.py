"""Word2Vec — skip-gram embeddings trained SPMD, notebook-202 capability.

Reference: notebooks/samples/202 - Amazon Book Reviews - Word2Vec.ipynb
drives Spark MLlib's ``Word2Vec`` (vector size / window / min count) and
classifies over the per-document averaged embeddings. The TPU-first
re-design trains the skip-gram objective with full-softmax cross entropy
(two MXU matmuls per step: embed lookup + vocab projection) through the
same :class:`~mmlspark_tpu.train.trainer.SPMDTrainer` the DNN learners
use — gradient sync over the mesh's data axis, not Spark's driver-side
aggregation.

The fitted model mirrors Spark's ``Word2VecModel``: ``transform`` writes
per-document mean vectors, ``find_synonyms`` ranks by cosine similarity.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.params import (
    HasInputCol,
    HasOutputCol,
    Param,
    positive,
)
from mmlspark_tpu.core.stage import Estimator, Model
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models.graph import FINAL_NODE, NamedGraph
from mmlspark_tpu.models.registry import register_model
from mmlspark_tpu.utils.text import tokenize


@register_model("skipgram")
def skipgram(vocab_size: int = 1024, vector_size: int = 100) -> NamedGraph:
    """Embedding + tied-dim vocab projection; logits over context words."""
    import flax.linen as nn
    import jax.numpy as jnp

    class _Embed(nn.Module):
        @nn.compact
        def __call__(self, ids):
            return nn.Embed(
                vocab_size, vector_size, param_dtype=jnp.float32,
                name="embedding",
            )(ids.astype(jnp.int32))

    class _Project(nn.Module):
        @nn.compact
        def __call__(self, v):
            out = nn.Dense(
                vocab_size, use_bias=False, param_dtype=jnp.float32,
                name="context",
            )(v)
            return out.astype(jnp.float32)

    return NamedGraph(
        name="skipgram",
        blocks=[("embed", _Embed()), (FINAL_NODE, _Project())],
        extra={"vocab_size": vocab_size, "vector_size": vector_size},
    )


class Word2Vec(Estimator, HasInputCol, HasOutputCol):
    """Skip-gram word embeddings over a text (or pre-tokenized) column."""

    vector_size = Param("embedding dimension", 100, ptype=int,
                        validator=positive)
    window = Param("context window radius", 5, ptype=int, validator=positive)
    min_count = Param("minimum token frequency kept in the vocab", 5,
                      ptype=int, validator=positive)
    epochs = Param("training epochs over the pair set", 1, ptype=int,
                   validator=positive)
    batch_size = Param("global batch size", 512, ptype=int,
                       validator=positive)
    learning_rate = Param("learning rate", 0.025, ptype=float)
    max_vocab = Param("vocabulary cap (most frequent kept)", 1 << 16,
                      ptype=int)
    seed = Param("rng seed", 0, ptype=int)

    def __init__(self, **kwargs: Any):
        kwargs.setdefault("output_col", "features")
        super().__init__(**kwargs)

    def _docs(self, dataset: Dataset) -> list[list[str]]:
        dataset.require(self.input_col)
        docs = []
        for v in dataset[self.input_col]:
            if v is None:
                docs.append([])
            elif isinstance(v, str):
                docs.append(tokenize(v))
            else:
                docs.append([str(t) for t in v])
        return docs

    def _fit(self, dataset: Dataset) -> "Word2VecModel":
        from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

        docs = self._docs(dataset)
        counts: dict[str, int] = {}
        for doc in docs:
            for t in doc:
                counts[t] = counts.get(t, 0) + 1
        vocab = sorted(
            (t for t, c in counts.items() if c >= self.min_count),
            key=lambda t: (-counts[t], t),
        )[: self.max_vocab]
        if not vocab:
            raise FriendlyError(
                f"no token reaches min_count={self.min_count}", self.uid
            )
        index = {t: i for i, t in enumerate(vocab)}

        centers: list[int] = []
        contexts: list[int] = []
        w = self.window
        for doc in docs:
            ids = [index[t] for t in doc if t in index]
            for i, c in enumerate(ids):
                for j in range(max(0, i - w), min(len(ids), i + w + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            raise FriendlyError(
                "no skip-gram pairs (documents too short?)", self.uid
            )
        graph = skipgram(vocab_size=len(vocab),
                         vector_size=self.vector_size)
        trainer = SPMDTrainer(
            graph,
            TrainConfig(
                epochs=self.epochs,
                batch_size=min(self.batch_size, len(centers)),
                learning_rate=self.learning_rate,
                optimizer="adam",
                loss="softmax_xent",
                seed=self.seed,
            ),
        )
        variables = trainer.train(
            np.asarray(centers, np.int32), np.asarray(contexts, np.int32)
        )
        emb = np.asarray(
            variables["embed"]["params"]["embedding"]["embedding"],
            np.float32,
        )
        return Word2VecModel(
            vocabulary=list(vocab),
            vectors=emb,
            input_col=self.input_col,
            output_col=self.output_col,
        )


class Word2VecModel(Model, HasInputCol, HasOutputCol):
    vocabulary = Param("tokens, row-aligned with vectors", default=list)
    vectors = Param("embedding matrix [V, D]")

    def __init__(self, **kwargs: Any):
        kwargs.setdefault("output_col", "features")
        super().__init__(**kwargs)

    def _doc_tokens(self, v) -> list[str]:
        if v is None:
            return []
        if isinstance(v, str):
            return tokenize(v)
        return [str(t) for t in v]

    def _transform(self, dataset: Dataset) -> Dataset:
        dataset.require(self.input_col)
        vecs = np.asarray(self.vectors, np.float32)
        index = {t: i for i, t in enumerate(self.vocabulary)}
        out = np.zeros((dataset.num_rows, vecs.shape[1]), np.float64)
        for r, v in enumerate(dataset[self.input_col]):
            ids = [index[t] for t in self._doc_tokens(v) if t in index]
            if ids:
                # Spark Word2VecModel.transform: average of word vectors
                out[r] = vecs[ids].mean(axis=0)
        return dataset.with_column(self.output_col, out)

    def find_synonyms(self, word: str, num: int) -> list[tuple[str, float]]:
        """Cosine-ranked neighbors (Spark ``findSynonyms``)."""
        if word not in self.vocabulary:
            raise FriendlyError(f"'{word}' not in vocabulary", self.uid)
        vecs = np.asarray(self.vectors, np.float64)
        norms = np.linalg.norm(vecs, axis=1) + 1e-12
        q = vecs[self.vocabulary.index(word)]
        sims = vecs @ q / (norms * (np.linalg.norm(q) + 1e-12))
        order = np.argsort(-sims)
        out = []
        for i in order:
            if self.vocabulary[i] != word:
                out.append((self.vocabulary[i], float(sims[i])))
            if len(out) == num:
                break
        return out
