"""Host -> device batch feed.

The reference feeds minibatches from Spark row iterators through a lazy
buffered iterator into JNI (CNTKModel.scala:51-88 ``minibatchIterator``), and
for training materializes the whole dataset to a file the external trainer
re-reads (DataConversion.scala:107-174). The TPU-native replacement keeps data
in host RAM and ships fixed-shape batches straight to device HBM:

- **Fixed shapes**: every batch has exactly ``batch_size`` rows; the tail is
  padded and a validity mask returned, so a jitted step compiles once
  (SURVEY.md §7 "ragged/streaming host feed" hard part).
- **Sharded placement**: with a sharding, ``jax.device_put`` lays the batch
  out over the mesh's data axis — the replacement for Spark partition ->
  executor dispatch (CNTKModel.scala:248-256).
- **Bucketing** limits recompilation for genuinely ragged data (sequences) to
  one compile per bucket.
"""

from __future__ import annotations

from typing import Any, Iterator, Sequence

import numpy as np

from mmlspark_tpu.core.exceptions import SchemaError
from mmlspark_tpu.data.dataset import Dataset

MASK_COL = "__mask__"


def stack_column(dataset: Dataset, name: str) -> np.ndarray:
    """A column as one dense ndarray: typed columns pass through; object
    columns of equal-shape arrays are stacked."""
    arr = dataset.column(name)
    if arr.dtype != object:
        return arr
    if len(arr) == 0:
        return np.zeros((0,))
    first = np.asarray(arr[0])
    shapes = {np.asarray(v).shape for v in arr}
    if len(shapes) != 1:
        raise SchemaError(
            f"column '{name}' is ragged ({sorted(shapes)}); bucket or pad first"
        )
    return np.stack([np.asarray(v) for v in arr]).astype(first.dtype, copy=False)


def pad_to(arr: np.ndarray, n: int) -> np.ndarray:
    """Pad axis 0 to length n by repeating the last row (keeps values in
    distribution for BN-style stats; mask marks validity)."""
    if len(arr) == n:
        return arr
    if len(arr) == 0:
        raise SchemaError("cannot pad an empty batch")
    pad = np.repeat(arr[-1:], n - len(arr), axis=0)
    return np.concatenate([arr, pad], axis=0)


def batch_iterator(
    dataset: Dataset,
    columns: Sequence[str],
    batch_size: int,
    *,
    drop_remainder: bool = False,
    shuffle_seed: int | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield dicts of fixed-shape arrays plus a boolean MASK_COL.

    The analog of the reference's per-partition lazy minibatcher
    (CNTKModel.scala:51-88) — but shape-stable for XLA.
    """
    dataset.require(*columns)
    n = dataset.num_rows
    order = np.arange(n)
    if shuffle_seed is not None:
        order = np.random.default_rng(shuffle_seed).permutation(n)
    stacked = {c: stack_column(dataset, c) for c in columns}
    for start in range(0, n, batch_size):
        idx = order[start : start + batch_size]
        if len(idx) < batch_size and drop_remainder:
            return
        mask = np.zeros(batch_size, dtype=bool)
        mask[: len(idx)] = True
        yield {
            **{c: pad_to(stacked[c][idx], batch_size) for c in columns},
            MASK_COL: mask,
        }


def bucket_by_length(
    dataset: Dataset,
    column: str,
    buckets: Sequence[int],
) -> list[tuple[int, Dataset]]:
    """Split by ragged-sequence length into (bucket_len, subset) groups; each
    subset pads its column to bucket_len — one XLA compile per bucket."""
    arr = dataset.column(column)
    lengths = np.asarray([len(np.asarray(v)) for v in arr])
    buckets = sorted(buckets)
    if not buckets:
        raise SchemaError("bucket_by_length needs at least one bucket size")
    if lengths.size and lengths.max() > buckets[-1]:
        raise SchemaError(
            f"sequence length {int(lengths.max())} exceeds largest bucket "
            f"{buckets[-1]}"
        )
    out = []
    assigned = np.zeros(len(arr), dtype=bool)
    for b in buckets:
        mask = (~assigned) & (lengths <= b)
        if not mask.any():
            continue
        assigned |= mask
        subset = dataset.filter(mask)
        padded = []
        for v in subset.column(column):
            v = np.asarray(v)
            pad_width = [(0, b - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
            padded.append(np.pad(v, pad_width))
        out.append((b, subset.with_column(column, np.stack(padded))))
    return out


# -- device placement --------------------------------------------------------


def data_sharding(mesh, axis: str = "data"):
    """NamedSharding that splits batch dim over the mesh's data axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis))


def to_device(batch: dict[str, np.ndarray], sharding=None) -> dict[str, Any]:
    """Host batch -> device arrays (replicated, or batch-sharded over a mesh
    when a sharding is given). The replacement for the reference's
    JVM->native ``FloatVectorVector`` copies (CNTKModel.scala:66-74)."""
    import jax

    if sharding is None:
        return {k: jax.device_put(v) for k, v in batch.items()}
    return {k: jax.device_put(v, sharding) for k, v in batch.items()}
