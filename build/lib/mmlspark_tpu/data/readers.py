"""File readers: binary files, images, CSV/parquet tables.

Re-expression of the reference IO layer (SURVEY.md §2.2):

- :func:`read_binary_files` mirrors ``BinaryFileFormat``
  (readers/src/main/scala/BinaryFileFormat.scala): whole-file records,
  recursive directory traversal, transparent zip iteration, per-file seeded
  subsampling.
- :func:`read_images` mirrors ``ImageFileFormat`` + ``ImageReader``
  (ImageFileFormat.scala:22-90, ImageReader.scala:15-99): decode each file
  into image rows; non-decodable files silently dropped.
- :func:`stream_binary_files` / :func:`stream_images` mirror the structured-
  streaming entry points (Readers.scala:30-48) as chunked generators.

Determinism: the per-file sample decision is seeded by
``crc32(path) ^ seed`` — the analog of the reference's
``filename.hashCode ^ seed`` (BinaryFileFormat.scala:75) — so re-partitioning
or re-listing never changes which files are kept. (Python's builtin ``hash``
is salted per process and would break this.)
"""

from __future__ import annotations

import fnmatch
import os
import zipfile
import zlib
from typing import Iterable, Iterator

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.schema import ColumnMeta, ImageMeta, ImageRow
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.ops.decode import decode_image

_log = get_logger("readers")

IMAGE_COL = "image"
PATH_COL = "path"
BYTES_COL = "bytes"


def _keep_file(path: str, sample_ratio: float, seed: int) -> bool:
    if sample_ratio >= 1.0:
        return True
    file_seed = zlib.crc32(path.encode("utf-8")) ^ (seed & 0xFFFFFFFF)
    return np.random.default_rng(file_seed).random() <= sample_ratio


def list_files(
    path: str, recursive: bool = True, pattern: str | None = None
) -> list[str]:
    """Expand a file/dir/glob path into a sorted file list (reference
    ``BinaryFileReader.recursePath`` + glob handling, BinaryFileReader.scala:
    13-60). Sorted for cross-host determinism."""
    import glob as _glob

    if os.path.isfile(path):
        files = [path]
    elif os.path.isdir(path):
        if recursive:
            files = [
                os.path.join(root, f)
                for root, _dirs, fs in os.walk(path)
                for f in fs
            ]
        else:
            files = [
                os.path.join(path, f)
                for f in os.listdir(path)
                if os.path.isfile(os.path.join(path, f))
            ]
    else:
        files = [f for f in _glob.glob(path, recursive=True) if os.path.isfile(f)]
        if not files:
            raise FriendlyError(f"no files found at '{path}'")
    if pattern:
        files = [f for f in files if fnmatch.fnmatch(os.path.basename(f), pattern)]
    return sorted(files)


def _iter_file_records(
    files: Iterable[str],
    sample_ratio: float,
    seed: int,
    inspect_zip: bool,
) -> Iterator[tuple[str, bytes]]:
    """(path, whole-file bytes) records with zip traversal + seeded sampling
    (reference BinaryRecordReader, BinaryFileFormat.scala:36-115; ZipIterator,
    core/env/.../StreamUtilities.scala:44-83)."""
    for path in files:
        if inspect_zip and zipfile.is_zipfile(path):
            with zipfile.ZipFile(path) as zf:
                for info in zf.infolist():
                    if info.is_dir():
                        continue
                    entry_path = f"{path}/{info.filename}"
                    if not _keep_file(entry_path, sample_ratio, seed):
                        continue
                    yield entry_path, zf.read(info)
        else:
            if not _keep_file(path, sample_ratio, seed):
                continue
            with open(path, "rb") as f:
                yield path, f.read()


def read_binary_files(
    path: str,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    seed: int = 0,
    inspect_zip: bool = True,
    pattern: str | None = None,
) -> Dataset:
    """Whole files as rows ``(path, bytes)`` (reference
    ``spark.readBinaryFiles``, Readers.scala:14-48)."""
    records = list(
        _iter_file_records(
            list_files(path, recursive, pattern), sample_ratio, seed, inspect_zip
        )
    )
    return Dataset(
        {
            PATH_COL: [p for p, _ in records],
            BYTES_COL: [b for _, b in records],
        }
    )


def decode_image_rows(paths: Iterable[str], blobs: Iterable[bytes]):
    """Decode (path, bytes) pairs, dropping failures (reference
    ImageFileFormat.buildReader: non-decodable files silently dropped,
    ImageFileFormat.scala:43-82)."""
    rows = []
    dropped = 0
    for p, b in zip(paths, blobs):
        arr = decode_image(b)
        if arr is None:
            dropped += 1
            continue
        rows.append(ImageRow(path=p, data=arr))
    if dropped:
        _log.info("dropped %d non-decodable file(s)", dropped)
    return rows


def read_images(
    path: str,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    seed: int = 0,
    inspect_zip: bool = True,
    image_col: str = IMAGE_COL,
) -> Dataset:
    """Decode files under ``path`` into an image column (reference
    ``spark.readImages``, Readers.scala:14-29; ImageReader.scala:71-84)."""
    binary = read_binary_files(path, recursive, sample_ratio, seed, inspect_zip)
    rows = decode_image_rows(binary[PATH_COL], binary[BYTES_COL])
    return Dataset(
        {image_col: rows},
        {image_col: ColumnMeta(image=ImageMeta())},
    )


def stream_binary_files(
    path: str,
    chunk_rows: int = 256,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    seed: int = 0,
    inspect_zip: bool = True,
) -> Iterator[Dataset]:
    """Chunked streaming variant (reference ``streamBinaryFiles``)."""
    buf_p: list[str] = []
    buf_b: list[bytes] = []
    for p, b in _iter_file_records(
        list_files(path, recursive), sample_ratio, seed, inspect_zip
    ):
        buf_p.append(p)
        buf_b.append(b)
        if len(buf_p) >= chunk_rows:
            yield Dataset({PATH_COL: buf_p, BYTES_COL: buf_b})
            buf_p, buf_b = [], []
    if buf_p:
        yield Dataset({PATH_COL: buf_p, BYTES_COL: buf_b})


def stream_images(
    path: str,
    chunk_rows: int = 256,
    image_col: str = IMAGE_COL,
    **kwargs,
) -> Iterator[Dataset]:
    """Chunked streaming image decode (reference ``streamImages``)."""
    for chunk in stream_binary_files(path, chunk_rows, **kwargs):
        rows = decode_image_rows(chunk[PATH_COL], chunk[BYTES_COL])
        if rows:
            yield Dataset(
                {image_col: rows}, {image_col: ColumnMeta(image=ImageMeta())}
            )


# -- tabular ingestion -------------------------------------------------------


def read_csv(path: str, **pandas_kwargs) -> Dataset:
    import pandas as pd

    return Dataset.from_pandas(pd.read_csv(path, **pandas_kwargs))


def read_parquet(path: str, **pandas_kwargs) -> Dataset:
    import pandas as pd

    return Dataset.from_pandas(pd.read_parquet(path, **pandas_kwargs))
