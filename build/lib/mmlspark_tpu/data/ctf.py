"""CNTK-text-format (CTF) read/write.

The reference exports training data as CTF lines
``|<label_name> v ... |<features_name> i:v ...`` before launching the external
trainer (cntk-train/src/main/scala/DataConversion.scala:86-96
``convertDatasetToCNTKTextFormat``; dense ``toDense`` / sparse ``toSparse``
forms). The TPU framework trains in-process so no file round-trip is needed,
but the format is kept for data interchange with reference-era corpora.
"""

from __future__ import annotations

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.data.dataset import Dataset

DENSE = "dense"
SPARSE = "sparse"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def dataset_to_ctf_lines(
    dataset: Dataset,
    label_col: str = "label",
    features_col: str = "features",
    label_form: str = DENSE,
    features_form: str = SPARSE,
) -> list[str]:
    dataset.require(label_col, features_col)
    labels = dataset[label_col]
    feats = dataset[features_col]
    lines = []
    for i in range(dataset.num_rows):
        lab = np.atleast_1d(np.asarray(labels[i], dtype=float))
        if label_form == DENSE:
            lab_txt = " ".join(_fmt(v) for v in lab)
        else:
            lab_txt = " ".join(f"{j}:{_fmt(v)}" for j, v in enumerate(lab) if v != 0)
        fv = np.asarray(feats[i], dtype=float).ravel()
        if features_form == DENSE:
            feat_txt = " ".join(_fmt(v) for v in fv)
        else:
            nz = np.nonzero(fv)[0]
            feat_txt = " ".join(f"{j}:{_fmt(fv[j])}" for j in nz)
        lines.append(f"|{label_col} {lab_txt} |{features_col} {feat_txt}")
    return lines


def write_ctf(dataset: Dataset, path: str, **kwargs) -> None:
    with open(path, "w") as f:
        for line in dataset_to_ctf_lines(dataset, **kwargs):
            f.write(line + "\n")


def read_ctf(
    path: str,
    feature_dim: int | None = None,
    label_col: str = "label",
    features_col: str = "features",
) -> Dataset:
    """Parse CTF lines back into (label, features) columns. Sparse features
    require ``feature_dim`` to densify; dense streams infer their width.

    The production path is the native C++ parser (ops/native/ctf.cpp — the
    role the external ``cntk`` binary's reader block played for the
    reference); the Python loop below is the fallback and the error-message
    path for malformed input.
    """
    native = _read_ctf_native(path, feature_dim, label_col, features_col)
    if native is not None:
        return native
    labels: list[np.ndarray] = []
    feats: list[np.ndarray] = []
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            fields: dict[str, str] = {}
            for chunk in raw.split("|")[1:]:
                name, _, rest = chunk.partition(" ")
                fields[name] = rest.strip()
            if label_col not in fields or features_col not in fields:
                raise FriendlyError(
                    f"CTF line missing |{label_col} or |{features_col}: {raw[:80]}"
                )
            try:
                labels.append(_parse_values(fields[label_col], None))
                feats.append(_parse_values(fields[features_col], feature_dim))
            except FriendlyError:
                raise
            except (ValueError, IndexError) as e:
                raise FriendlyError(
                    f"malformed CTF line ({e}): {raw[:80]}"
                ) from e
    try:
        lab_arr = np.stack(labels) if labels else np.zeros((0, 1))
    except ValueError as e:
        raise FriendlyError(
            f"ragged CTF label rows (widths differ across lines): {e}"
        ) from e
    if lab_arr.shape[1] == 1:
        lab_arr = lab_arr[:, 0]
    try:
        feat_arr = (
            np.stack(feats) if feats else np.zeros((0, feature_dim or 0))
        )
    except ValueError as e:
        raise FriendlyError(
            f"ragged CTF feature rows (widths differ across lines): {e}"
        ) from e
    return Dataset({label_col: lab_arr, features_col: feat_arr})


def _read_ctf_native(
    path: str, feature_dim: int | None, label_col: str, features_col: str
) -> Dataset | None:
    """C++ fast path; None -> fall back to the Python parser (which also
    produces the precise FriendlyError for malformed files)."""
    import ctypes
    import os

    from mmlspark_tpu.ops.native_build import load_native

    lib = load_native("ctf")
    if lib is None or not os.path.exists(path):
        return None
    labels_p = ctypes.POINTER(ctypes.c_double)()
    feats_p = ctypes.POINTER(ctypes.c_double)()
    lw = ctypes.c_int()
    fw = ctypes.c_int()
    rows = ctypes.c_long()
    rc = lib.mml_parse_ctf(
        path.encode(), label_col.encode(), features_col.encode(),
        int(feature_dim or -1),
        ctypes.byref(labels_p), ctypes.byref(lw),
        ctypes.byref(feats_p), ctypes.byref(fw), ctypes.byref(rows),
    )
    if rc != 0:
        return None
    try:
        n = rows.value
        lab = np.ctypeslib.as_array(
            labels_p, shape=(n * lw.value,)
        ).copy().reshape(n, lw.value) if n else np.zeros((0, 1))
        ft = np.ctypeslib.as_array(
            feats_p, shape=(n * fw.value,)
        ).copy().reshape(n, fw.value) if n else np.zeros(
            (0, fw.value or 0)
        )
    finally:
        lib.mml_ctf_free(labels_p)
        lib.mml_ctf_free(feats_p)
    if lab.shape[1] == 1:
        lab = lab[:, 0]
    return Dataset({label_col: lab, features_col: ft})


def _parse_values(text: str, dim: int | None) -> np.ndarray:
    toks = text.split()
    if not toks:
        return np.zeros(dim or 0)
    if ":" in toks[0]:
        if dim is None:
            raise FriendlyError("sparse CTF needs feature_dim to densify")
        out = np.zeros(dim)
        for t in toks:
            j, _, v = t.partition(":")
            out[int(j)] = float(v)
        return out
    return np.asarray([float(t) for t in toks])
