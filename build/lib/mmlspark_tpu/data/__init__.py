"""Data plane: columnar Dataset, file readers, host->device feed.

Mirrors the reference IO layer (SURVEY.md §2.2) with the Spark DataFrame
replaced by a host-resident columnar dataset feeding sharded device batches.
"""

from mmlspark_tpu.data.dataset import Dataset  # noqa: F401
