"""Parameter sharding rules: param-path regex -> PartitionSpec.

The reference has exactly one distribution strategy — replicate the model,
shard the data (SURVEY.md §2.5: Spark partitions + CNTK's MPI ring; no
tensor/pipeline parallelism exists). The TPU build adds tensor parallelism
the idiomatic XLA way: params carry :class:`~jax.sharding.NamedSharding`
annotations derived from small declarative rules, and GSPMD inserts the
all-gathers/reduce-scatters over ICI — no hand-written collectives in the
model code (the scaling-book recipe).

A rule set is an ordered list of ``(regex, spec_tuple)``; the first regex
matching the '/'-joined param path wins. Spec axis names not present in the
target mesh degrade to replicated, so one rule set serves data-only meshes
and dp×tp meshes unchanged.
"""

from __future__ import annotations

import re
from typing import Sequence

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from mmlspark_tpu.parallel.mesh import MODEL_AXIS

#: Megatron-style rules for the transformer family
#: (models/transformer.py): column-parallel into attention/MLP, row-parallel
#: out of them — the matched pairs keep activations replicated at block
#: boundaries with one psum per block, which XLA derives automatically.
TRANSFORMER_TP_RULES: list[tuple[str, tuple]] = [
    (r"qkv/kernel$", (None, MODEL_AXIS)),
    (r"attn_out/kernel$", (MODEL_AXIS, None)),
    (r"mlp_in/kernel$", (None, MODEL_AXIS)),
    (r"mlp_out/kernel$", (MODEL_AXIS, None)),
    (r"qkv/bias$", (MODEL_AXIS,)),
    (r"mlp_in/bias$", (MODEL_AXIS,)),
]


def spec_for_path(path: str, rules: Sequence[tuple[str, tuple]],
                  mesh) -> P:
    """Resolve the PartitionSpec for one param path; unmatched or
    mesh-incompatible rules fall back to replication per-axis."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            axes = tuple(
                a if (a is None or (a in mesh.shape and mesh.shape[a] > 1))
                else None
                for a in spec
            )
            return P(*axes)
    return P()


def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return "/".join(parts)


def build_param_shardings(params, mesh,
                          rules: Sequence[tuple[str, tuple]] | None):
    """Pytree of NamedSharding matching ``params``; dims that a rule would
    shard unevenly degrade to replicated (XLA requires even tiling)."""
    rules = rules or []

    def one(key_path, leaf):
        spec = spec_for_path(_path_str(key_path), rules, mesh)
        axes = []
        for i, a in enumerate(spec):
            if a is not None and (
                i >= leaf.ndim or leaf.shape[i] % mesh.shape[a]
            ):
                a = None
            axes.append(a)
        return NamedSharding(mesh, P(*axes))

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params, mesh, rules=None):
    """device_put the param tree according to the rules."""
    return jax.device_put(params, build_param_shardings(params, mesh, rules))
