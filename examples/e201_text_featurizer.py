"""Notebook-201 parity: TextFeaturizer on review-like text + rating model.

Reference flow (notebooks/samples/201 - Amazon Book Reviews -
TextFeaturizer.ipynb): featurize review text (tokenize -> TF-IDF) ->
train a classifier on the text features -> evaluate. Synthetic reviews
with sentiment-bearing vocabulary stand in for the download.
"""

import numpy as np

from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.dnn_learner import DNNLearner
from mmlspark_tpu.stages.text import TextFeaturizer

GOOD = ["wonderful", "gripping", "brilliant", "loved", "masterpiece"]
BAD = ["boring", "dreadful", "awful", "hated", "tedious"]
FILLER = ["the", "book", "story", "chapter", "author", "plot", "read"]


def make_reviews(n=400, seed=11) -> Dataset:
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n):
        pos = rng.random() < 0.5
        vocab = GOOD if pos else BAD
        words = list(rng.choice(FILLER, 5)) + list(rng.choice(vocab, 3))
        rng.shuffle(words)
        texts.append(" ".join(words))
        labels.append(int(pos))
    return Dataset({"text": texts, "rating": np.array(labels)})


def main():
    train, test = make_reviews(seed=11), make_reviews(n=150, seed=12)
    featurizer = TextFeaturizer(
        input_col="text", output_col="features", num_features=1 << 12,
        remove_stop_words=True,
    ).fit(train)
    train_f, test_f = featurizer.transform(train), featurizer.transform(test)

    model = DNNLearner(
        features_col="features", label_col="rating", epochs=12,
        learning_rate=5e-2,
    ).fit(train_f)
    scored = model.transform(test_f)
    pred = np.asarray(scored["scores"]).argmax(axis=1)
    acc = float((pred == np.asarray(test_f["rating"])).mean())
    assert acc > 0.85, f"accuracy {acc} too low"
    print(f"OK {{'accuracy': {acc:.3f}, "
          f"'feature_dim': {len(featurizer.slots)}}}")


if __name__ == "__main__":
    main()
