"""Notebook-305 parity: basic vs DNN image featurization, real images.

Reference flow (notebooks/samples/305 - Flowers ImageFeaturizer.ipynb):
sample a SMALL training set from the flowers data (the notebook keeps 3%),
featurize it two ways — a "basic" pipeline (ImageTransformer resize ->
UnrollImage raw pixels) and the pretrained DNN cut one layer from the top
(ModelDownloader -> ImageFeaturizer) — train the same LogisticRegression
on both feature sets, and compare held-out accuracy. The pretrained
features win on small data; that comparison is the notebook's headline.

Same flow here on REAL images: the full 10-class sklearn handwritten-digit
scans, rendered unregistered (random placement), with the zoo's real-data
backbone ``ResNet20_Digits04`` (pretrained on classes 0-4 only) standing
in for ResNet50 — its features lift even the classes it never saw.
"""

import os
import tempfile
import time

import numpy as np

from mmlspark_tpu.core.schema import ImageRow
from mmlspark_tpu.core.stage import Pipeline, PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models.zoo import ModelDownloader
from mmlspark_tpu.stages.dnn_learner import DNNLearner
from mmlspark_tpu.stages.image import (
    ImageFeaturizer,
    ImageTransformer,
    UnrollImage,
)
from mmlspark_tpu.data.sample_data import load_digit_images
from mmlspark_tpu.stages.prep import SelectColumns

ZOO = os.path.join(os.path.dirname(__file__), "..", "models", "zoo_repo")


def make_splits(n_train, n_test, seed):
    # real handwritten-digit scans, unregistered placement: raw pixels
    # stop being linearly separable, so the pretrained conv features
    # genuinely win (the notebook's basic-vs-dnn point)
    imgs, y = load_digit_images(max_shift=4, seed=seed)
    ds = Dataset({
        "image": [
            ImageRow(path=f"img{i}", data=im) for i, im in enumerate(imgs)
        ],
        "labels": y.astype(np.int64),
    })
    order = np.random.default_rng(seed).permutation(len(y))
    return ds.gather(order[:n_train]), ds.gather(order[n_train:n_train + n_test])


def featurize(featurizer, train, test, name):
    """The notebook's featurize() helper: pipe + select, timed."""
    start = time.time()
    pipe = Pipeline(
        [featurizer, SelectColumns(cols=["features", "labels"])]
    ).fit(train)
    train_f, test_f = pipe.transform(train), pipe.transform(test)
    elapsed = time.time() - start
    n = len(train_f) + len(test_f)
    print(f"featurized {n} images with {name} featurizer "
          f"in {elapsed:.2f}s")
    return train_f, test_f


def predict(train_f, test_f) -> float:
    lr = DNNLearner(
        model_name="linear",
        model_config={"num_outputs": 10},
        loss="softmax_xent",
        epochs=150,
        learning_rate=1e-1,
        features_col="features",
        label_col="labels",
    ).fit(train_f)
    scored = lr.transform(test_f)
    pred = np.asarray(scored["scores"]).argmax(axis=1)
    return float((pred == np.asarray(test_f["labels"])).mean())


def main():
    # tiny train split, larger held-out test — the notebook's 3% sample
    # (120 of 1,797 scans ≈ 7%)
    train, test = make_splits(120, 500, seed=21)

    # basic featurizer: resize + raw-pixel unroll (notebook's it/ur cell)
    basic = Pipeline([
        ImageTransformer(output_col="scaled").resize(height=32, width=32),
        UnrollImage(input_col="scaled", output_col="features"),
    ])
    basic_train, basic_test = featurize(basic, train, test, "basic")
    basic_acc = predict(basic_train, basic_test)

    # DNN featurizer: pretrained backbone from the zoo, cut 1 layer
    with tempfile.TemporaryDirectory() as local_repo:
        downloader = ModelDownloader(local_repo, remote=ZOO)
        schema = downloader.download_by_name("ResNet20_Digits04")
        backbone = PipelineStage.load(downloader.local_path(schema))
    dnn = ImageFeaturizer(
        model=backbone, cut_output_layers=1, scale=1.0 / 255.0
    )
    dnn_train, dnn_test = featurize(dnn, train, test, "dnn")
    dnn_acc = predict(dnn_train, dnn_test)

    assert dnn_acc > 0.8, f"dnn-featurized accuracy {dnn_acc} too low"
    assert dnn_acc >= basic_acc + 0.15, (dnn_acc, basic_acc)
    print(
        f"OK {{'basic_accuracy': {basic_acc:.3f}, "
        f"'dnn_accuracy': {dnn_acc:.3f}, 'train_rows': {len(train)}}}"
    )


if __name__ == "__main__":
    main()
