"""Notebook-102 parity: TrainRegressor on a REAL table.

Reference flow (notebooks/samples/102 - Regression Example with Flight
Delay Dataset.ipynb): read flight table -> TrainRegressor -> score ->
ComputeModelStatistics + ComputePerInstanceStatistics. The reference
installs the real On-Time Performance CSV at build time
(tools/config.sh:62-117); with no egress here, the committed REAL table
is the UCI Relative CPU Performance set (tests/fixtures/machine_cpu.csv,
209 machines, extracted from the scikit-learn wheel by
tools/make_fixtures.py) — the same shape of problem: categorical column
(vendor ~ carrier) + numerics, continuous target. The flight-shaped
synthetic generator stays as the fallback when the fixture is absent.
"""

import os


from mmlspark_tpu.stages.eval_metrics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
)
from mmlspark_tpu.stages.train_regressor import TrainRegressor

FIXTURE = os.path.join(
    os.path.dirname(__file__), "..", "tests", "fixtures", "machine_cpu.csv"
)


def load_real_or_synthetic():
    """(train, test, label_col, r2_floor)."""
    if os.path.exists(FIXTURE):
        from mmlspark_tpu.data.readers import read_csv

        ds = read_csv(FIXTURE)
        test, train = ds.random_split(0.25, seed=0)
        return train, test, "performance", 0.5
    from mmlspark_tpu.testing.datagen import make_flights

    return make_flights(seed=3), make_flights(n=250, seed=4), "arr_delay", 0.5


def main():
    from mmlspark_tpu.stages.find_best import FindBestModel

    train, test, label, floor = load_real_or_synthetic()
    # the notebook trains linear + tree-family regressors (each with its
    # own knobs) and compares; rank with FindBestModel like its
    # evaluation cells
    configs = [
        dict(model="linear_regression", epochs=120, learning_rate=5e-2),
        dict(model="gbt", max_iter=60),
        dict(model="random_forest", num_trees=30),
    ]
    candidates = [
        TrainRegressor(label_col=label, **cfg).fit(train)
        for cfg in configs
    ]
    best = FindBestModel(models=candidates, evaluation_metric="R^2").fit(
        test
    )
    scored = best.best_model.transform(test)
    stats = ComputeModelStatistics().transform(scored)
    r2 = float(stats["R^2"][0])
    rmse = float(stats["root_mean_squared_error"][0])
    per = ComputePerInstanceStatistics().transform(scored)
    assert r2 > floor, f"R^2 {r2} too low (floor {floor})"
    assert per["L2_loss"].min() >= 0
    print(f"OK {{'R^2': {r2:.3f}, 'RMSE': {rmse:.2f}, "
          f"'rows': {len(train) + len(test)}, "
          f"'candidates': {len(best.all_model_metrics)}}}")


if __name__ == "__main__":
    main()
