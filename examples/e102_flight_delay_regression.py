"""Notebook-102 parity: TrainRegressor on flight-delay-shaped data.

Reference flow (notebooks/samples/102 - Regression Example with Flight
Delay Dataset.ipynb): read flight table -> TrainRegressor -> score ->
ComputeModelStatistics + ComputePerInstanceStatistics. Synthetic
flight-shaped data stands in for the download.
"""

from mmlspark_tpu.stages.eval_metrics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
)
from mmlspark_tpu.stages.train_regressor import TrainRegressor
from mmlspark_tpu.testing.datagen import make_flights


def main():
    from mmlspark_tpu.stages.find_best import FindBestModel

    train, test = make_flights(seed=3), make_flights(n=250, seed=4)
    # the notebook trains linear + tree-family regressors (each with its
    # own knobs) and compares; rank with FindBestModel like its
    # evaluation cells
    configs = [
        dict(model="linear_regression", epochs=120, learning_rate=5e-2),
        dict(model="gbt", max_iter=60),
        dict(model="random_forest", num_trees=30),
    ]
    candidates = [
        TrainRegressor(label_col="arr_delay", **cfg).fit(train)
        for cfg in configs
    ]
    best = FindBestModel(models=candidates, evaluation_metric="R^2").fit(
        test
    )
    scored = best.best_model.transform(test)
    stats = ComputeModelStatistics().transform(scored)
    r2 = float(stats["R^2"][0])
    rmse = float(stats["root_mean_squared_error"][0])
    per = ComputePerInstanceStatistics().transform(scored)
    assert r2 > 0.5, f"R^2 {r2} too low"
    assert per["L2_loss"].min() >= 0
    print(f"OK {{'R^2': {r2:.3f}, 'RMSE': {rmse:.2f}, "
          f"'candidates': {len(best.all_model_metrics)}}}")


if __name__ == "__main__":
    main()
