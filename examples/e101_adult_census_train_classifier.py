"""Notebook-101 parity: one-liner TrainClassifier on Adult-Census-like data.

Reference flow (notebooks/samples/101 - Adult Census Income Training.ipynb):
read census table -> TrainClassifier(LogisticRegression, labelCol="income")
-> save model -> score -> ComputeModelStatistics. Same flow here with
synthetic census-shaped data (no network egress in this environment).
"""

import tempfile

import numpy as np

from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics
from mmlspark_tpu.stages.train_classifier import TrainClassifier


def make_census(n=600, seed=7) -> Dataset:
    rng = np.random.default_rng(seed)
    age = rng.uniform(18, 80, n)
    hours = rng.uniform(10, 60, n)
    edu = rng.choice(["hs", "college", "phd"], n)
    occupation = rng.choice(["clerical", "exec", "tech", "service"], n)
    score = (age - 40) / 20 + (hours - 35) / 15 + (edu == "phd") * 1.5
    label = np.where(score + rng.normal(0, 0.4, n) > 0, ">50K", "<=50K")
    return Dataset({
        "age": age,
        "hours_per_week": hours,
        "education": list(edu),
        "occupation": list(occupation),
        "income": list(label),
    })


def main():
    train, test = make_census(seed=7), make_census(n=200, seed=8)

    model = TrainClassifier(
        label_col="income", epochs=25, learning_rate=5e-2
    ).fit(train)

    # save/load round trip (the notebook persists to wasb://)
    with tempfile.TemporaryDirectory() as d:
        model.save(d + "/census-model")
        model = PipelineStage.load(d + "/census-model")

    scored = model.transform(test)
    stats = ComputeModelStatistics().transform(scored)
    acc = float(stats["accuracy"][0])
    auc = float(stats["AUC"][0])
    assert acc > 0.75, f"accuracy {acc} too low"
    print(f"OK {{'accuracy': {acc:.3f}, 'AUC': {auc:.3f}}}")


if __name__ == "__main__":
    main()
