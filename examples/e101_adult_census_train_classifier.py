"""Notebook-101 parity: one-liner TrainClassifier on a REAL table.

Reference flow (notebooks/samples/101 - Adult Census Income Training.ipynb):
read census table -> TrainClassifier(LogisticRegression, labelCol="income")
-> save model -> score -> ComputeModelStatistics. The reference installs
the real Adult Census CSV at build time (tools/config.sh:62-117); this
environment has no egress, so the committed REAL table is the complete
1,309-passenger Titanic manifest (tests/fixtures/titanic.csv, extracted
from the scikit-learn wheel by tools/make_fixtures.py) — the same shape
of problem: mixed categorical/numeric columns, missing values, binary
label. The census-shaped synthetic generator stays as the fallback when
the fixture is absent.
"""

import os
import tempfile

from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics
from mmlspark_tpu.stages.prep import CleanMissingData
from mmlspark_tpu.stages.train_classifier import TrainClassifier

FIXTURE = os.path.join(
    os.path.dirname(__file__), "..", "tests", "fixtures", "titanic.csv"
)


def load_real_or_synthetic():
    """(train, test, label_col, accuracy_floor)."""
    if os.path.exists(FIXTURE):
        from mmlspark_tpu.data.readers import read_csv

        ds = read_csv(FIXTURE)
        test, train = ds.random_split(0.25, seed=0)
        # age/fare have real gaps; impute numerics like the notebook's
        # data-prep cell, with TRAIN-only statistics (no test leakage;
        # missing embarked strings stay their own level)
        imputer = CleanMissingData(
            input_cols=["age", "fare"], cleaning_mode="Mean"
        ).fit(train)
        return (
            imputer.transform(train),
            imputer.transform(test),
            "survived",
            0.73,  # real-data bar: standard Titanic tabular accuracy
        )
    from mmlspark_tpu.testing.datagen import make_census

    return make_census(seed=7), make_census(n=200, seed=8), "income", 0.75


def main():
    from mmlspark_tpu.stages.find_best import FindBestModel

    train, test, label, floor = load_real_or_synthetic()

    # three learner families, like the notebook's LR/GBT/RF sweep ranked
    # with FindBestModel (notebook 101 cells 4-6)
    candidates = [
        TrainClassifier(
            label_col=label, model=name, epochs=25, learning_rate=5e-2
        ).fit(train)
        for name in ("logistic_regression", "gbt", "random_forest")
    ]
    best = FindBestModel(models=candidates, evaluation_metric="AUC").fit(test)
    model = best.best_model

    # save/load round trip (the notebook persists to wasb://)
    with tempfile.TemporaryDirectory() as d:
        model.save(d + "/census-model")
        model = PipelineStage.load(d + "/census-model")

    scored = model.transform(test)
    stats = ComputeModelStatistics().transform(scored)
    acc = float(stats["accuracy"][0])
    auc = float(stats["AUC"][0])
    assert acc > floor, f"accuracy {acc} too low (floor {floor})"
    table = best.all_model_metrics
    print(
        f"OK {{'accuracy': {acc:.3f}, 'AUC': {auc:.3f}, "
        f"'rows': {len(train) + len(test)}, "
        f"'candidates': {len(table)}}}"
    )


if __name__ == "__main__":
    main()
