"""Notebook-101 parity: one-liner TrainClassifier on Adult-Census-like data.

Reference flow (notebooks/samples/101 - Adult Census Income Training.ipynb):
read census table -> TrainClassifier(LogisticRegression, labelCol="income")
-> save model -> score -> ComputeModelStatistics. Same flow here with
synthetic census-shaped data (no network egress in this environment).
"""

import tempfile

from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics
from mmlspark_tpu.stages.train_classifier import TrainClassifier
from mmlspark_tpu.testing.datagen import make_census


def main():
    from mmlspark_tpu.stages.find_best import FindBestModel

    train, test = make_census(seed=7), make_census(n=200, seed=8)

    # three learner families, like the notebook's LR/GBT/RF sweep ranked
    # with FindBestModel (notebook 101 cells 4-6)
    candidates = [
        TrainClassifier(
            label_col="income", model=name, epochs=25, learning_rate=5e-2
        ).fit(train)
        for name in ("logistic_regression", "gbt", "random_forest")
    ]
    best = FindBestModel(models=candidates, evaluation_metric="AUC").fit(test)
    model = best.best_model

    # save/load round trip (the notebook persists to wasb://)
    with tempfile.TemporaryDirectory() as d:
        model.save(d + "/census-model")
        model = PipelineStage.load(d + "/census-model")

    scored = model.transform(test)
    stats = ComputeModelStatistics().transform(scored)
    acc = float(stats["accuracy"][0])
    auc = float(stats["AUC"][0])
    assert acc > 0.75, f"accuracy {acc} too low"
    table = best.all_model_metrics
    print(
        f"OK {{'accuracy': {acc:.3f}, 'AUC': {auc:.3f}, "
        f"'candidates': {len(table)}}}"
    )


if __name__ == "__main__":
    main()
