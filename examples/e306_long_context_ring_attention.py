"""Long-context sequence parallelism — beyond the reference's ceiling.

The reference "scales sequence length" by not scaling it (SURVEY.md §5:
no attention, no sequence parallelism anywhere in its tree; the only
sequence model pads to max length in notebook UDFs). This example shows
the TPU-native long-context story end to end on the virtual mesh:

1. a `transformer_lm` built with RING attention (context parallelism:
   each device holds S/n_seq tokens of activations; K/V blocks rotate
   around the mesh via `ppermute`) trains over a data x seq mesh;
2. the same weights then serve a sequence FOUR TIMES the per-device
   activation budget, and the ring output is checked against the dense
   XLA attention path on identical weights — exactness, not vibes;
3. the BiLSTM chunked-recurrence chain (the recurrent long-context
   analog, parallel/sequence_rnn.py) trains with batch AND time sharded
   in one jitted SGD step.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
     python examples/e306_long_context_ring_attention.py
"""

import numpy as np

from mmlspark_tpu.models import build_model
from mmlspark_tpu.parallel import (
    TRANSFORMER_TP_RULES,
    bilstm_seq_parallel_train_step,
    make_mesh,
)
from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

VOCAB = 64
SEQ = 32  # 4 seq-shards x 8 tokens per device on the 8-way mesh


def main():
    import jax
    import jax.numpy as jnp

    n_dev = jax.device_count()
    seq_ax = 4 if n_dev % 4 == 0 else 1
    data_ax = max(n_dev // seq_ax, 1)
    mesh_axes = {"data": data_ax, "seq": seq_ax}
    mesh = make_mesh(mesh_axes)

    # -- 1. train a ring-attention LM over data x seq -----------------------
    graph = build_model(
        "transformer_lm", vocab_size=VOCAB, d_model=32, heads=4, depth=2,
        max_len=SEQ, attn_impl="ring", mesh=mesh,
    )
    rng = np.random.default_rng(0)
    ids = rng.integers(1, VOCAB, size=(8 * data_ax, SEQ)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1)
    trainer = SPMDTrainer(
        graph,
        TrainConfig(
            epochs=4, batch_size=4 * data_ax, learning_rate=5e-3,
            mesh_axes=mesh_axes, param_rules=TRANSFORMER_TP_RULES,
            log_every=5, shuffle=False,
        ),
    )
    variables = trainer.train(ids, labels)
    losses = [h["loss"] for h in trainer.history]
    assert losses[-1] < losses[0], losses

    # -- 2. ring == dense on the SAME weights ------------------------------
    dense_graph = build_model(
        "transformer_lm", vocab_size=VOCAB, d_model=32, heads=4, depth=2,
        max_len=SEQ, attn_impl="dense",
    )
    probe = ids[:2]
    ring_out = np.asarray(graph.apply(variables, jnp.asarray(probe)))
    dense_out = np.asarray(dense_graph.apply(variables, jnp.asarray(probe)))
    np.testing.assert_allclose(ring_out, dense_out, atol=2e-2, rtol=2e-2)
    max_err = float(np.max(np.abs(ring_out - dense_out)))

    # -- 2b. sliding window: O(window) ring communication ------------------
    # a windowed LM (Mistral-style local attention) over the same mesh:
    # the ring drops rotations whose kv chunks lie wholly outside the
    # window, so communication scales with the window, not the sequence
    from mmlspark_tpu.ops.attention import dense_attention
    from mmlspark_tpu.parallel import ring_attention
    from mmlspark_tpu.parallel.context_parallel import _ring_window_steps

    W = SEQ // 4
    qkv = rng.normal(size=(3, 2, SEQ, 4, 8)).astype(np.float32)
    qw, kw, vw = (jnp.asarray(t) for t in qkv)
    ring_w = np.asarray(
        ring_attention(qw, kw, vw, mesh, causal=True, window=W)
    )
    dense_w = np.asarray(
        dense_attention(qw, kw, vw, causal=True, window=W)
    )
    np.testing.assert_allclose(ring_w, dense_w, atol=1e-5, rtol=1e-5)
    live_rounds = _ring_window_steps(seq_ax, SEQ // seq_ax, W, True)

    # -- 3. recurrent long-context: mixed-axis BiLSTM training -------------
    bgraph = build_model(
        "bilstm_tagger", vocab_size=VOCAB, embed_dim=8, hidden=8, num_tags=4
    )
    bvars = bgraph.init(jax.random.PRNGKey(0), jnp.zeros((2, SEQ), jnp.int32))
    bids = rng.integers(0, VOCAB, size=(2 * data_ax, SEQ)).astype(np.int32)
    btags = (bids % 4).astype(np.int32)
    bmesh = make_mesh({"data": data_ax, "seq": 2 if n_dev % 2 == 0 else 1})
    blosses = []
    for _ in range(3):
        loss, bvars = bilstm_seq_parallel_train_step(
            bgraph, bvars, bids, btags, bmesh, learning_rate=5e-2
        )
        blosses.append(float(loss))
    assert blosses[-1] < blosses[0], blosses

    print(
        f"OK {{'lm_loss_drop': {losses[0] - losses[-1]:.3f}, "
        f"'ring_vs_dense_max_err': {max_err:.4f}, "
        f"'seq_shards': {seq_ax}, "
        f"'window_ring_rounds': '{live_rounds}/{seq_ax}', "
        f"'bilstm_loss_drop': {blosses[0] - blosses[-1]:.4f}}}"
    )


if __name__ == "__main__":
    main()
