"""Notebook-202 parity: Word2Vec embeddings -> classifier over documents.

Reference flow (notebooks/samples/202 - Amazon Book Reviews - Word2Vec
.ipynb): tokenize review text -> Spark Word2Vec (setVectorSize etc.) ->
per-document averaged vectors -> train classifiers over the embeddings ->
evaluate. Same flow with synthetic two-topic "reviews" (no egress), the
SPMD-trained skip-gram Word2Vec, and TrainClassifier + FindBestModel on
the embedding features.
"""

import numpy as np

from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics
from mmlspark_tpu.stages.find_best import FindBestModel
from mmlspark_tpu.stages.train_classifier import TrainClassifier
from mmlspark_tpu.stages.word2vec import Word2Vec

TOPICS = {
    "positive": ("great wonderful loved brilliant excellent beautiful "
                 "favorite classic enjoyed recommend").split(),
    "negative": ("boring awful terrible waste disappointing dull worst "
                 "refund skip bland").split(),
}
FILLER = "the a and book story plot it read pages author".split()


def make_reviews(n, seed):
    rng = np.random.default_rng(seed)
    docs, labels = [], []
    for _ in range(n):
        topic = rng.choice(list(TOPICS))
        words = list(rng.choice(TOPICS[topic], 10)) + list(
            rng.choice(FILLER, 6)
        )
        rng.shuffle(words)
        docs.append(" ".join(words))
        labels.append(topic)
    return Dataset({"text": docs, "rating": labels})


def main():
    train, test = make_reviews(400, seed=1), make_reviews(150, seed=2)

    w2v = Word2Vec(
        input_col="text", vector_size=24, window=5, min_count=2, epochs=3
    ).fit(train)
    # embeddings carry sentiment structure: nearest neighbors of a
    # positive word stay positive (the notebook's findSynonyms cell)
    syns = [w for w, _ in w2v.find_synonyms("great", 3)]
    train_e = w2v.transform(train).select("features", "rating")
    test_e = w2v.transform(test).select("features", "rating")

    candidates = [
        TrainClassifier(label_col="rating", model=m, epochs=25,
                        learning_rate=5e-2).fit(train_e)
        for m in ("logistic_regression", "gbt")
    ]
    best = FindBestModel(models=candidates, evaluation_metric="AUC").fit(
        test_e
    )
    stats = ComputeModelStatistics().transform(
        best.best_model.transform(test_e)
    )
    acc = float(stats["accuracy"][0])
    assert acc > 0.9, f"accuracy {acc} too low"
    print(f"OK {{'accuracy': {acc:.3f}, 'synonyms_of_great': {syns}}}")


if __name__ == "__main__":
    main()
