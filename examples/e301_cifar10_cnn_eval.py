"""Notebook-301 parity: batched CNN inference over an image dataset.

Reference flow (notebooks/samples/301 - CIFAR10 CNTK CNN Evaluation.ipynb):
load a trained CNTK ConvNet -> CNTKModel.transform over CIFAR-10 rows ->
argmax -> accuracy. Here the ResNet-20 graph is the flagship model, the
inference stage is TPUModel (partition-parallel batched evaluation,
CNTKModel.scala:51-88 analog), and the "trained" weights come from a few
quick fitting steps on the synthetic task so accuracy is meaningfully
above chance without a dataset download.
"""

import numpy as np

from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models import build_model
from mmlspark_tpu.stages.dnn_model import TPUModel
from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig


def make_cifar_like(n, seed=0, classes=10):
    """Class-conditional color-blob images (32x32x3 float in [0,1])."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, n)
    means = np.linspace(0.1, 0.9, classes)
    x = rng.normal(means[y][:, None, None, None], 0.15,
                   size=(n, 32, 32, 3))
    return x.astype(np.float32), y.astype(np.int32)


def main():
    import jax

    graph = build_model("resnet20_cifar10", width=8)
    x_train, y_train = make_cifar_like(512, seed=0)
    trainer = SPMDTrainer(
        graph,
        TrainConfig(epochs=20, batch_size=128, learning_rate=1e-2,
                    log_every=20),
    )
    variables = trainer.train(x_train, y_train)

    # the notebook's eval half: model as a pipeline stage over a dataset
    stage = TPUModel.from_graph(
        graph, variables, "resnet20_cifar10", model_config={"width": 8},
        input_col="image", output_col="scores", batch_size=64,
    )
    x_test, y_test = make_cifar_like(256, seed=1)
    ds = Dataset({"image": list(x_test), "label": y_test})
    scored = stage.transform(ds)
    pred = np.asarray(scored["scores"]).argmax(axis=1)
    acc = float((pred == y_test).mean())
    assert acc > 0.5, f"accuracy {acc} not above chance"
    n_params = graph.param_count(variables)
    print(f"OK {{'accuracy': {acc:.3f}, 'params': {n_params}, "
          f"'devices': {jax.device_count()}}}")


if __name__ == "__main__":
    main()
