"""Run every example headless with a per-script timeout — the analog of
the reference's notebook test runner (tools/notebook/tester/
NotebookTestSuite.py: nbconvert ExecutePreprocessor(timeout=600) per
notebook, PROC_SHARD=i/m sharding at TestNotebooksLocally.py:46-52).

Usage:
    python examples/harness.py                 # run all e*.py
    PROC_SHARD=0/2 python examples/harness.py  # run shard 0 of 2
    python examples/harness.py e301 e304       # run by prefix

Each script runs in its own process on the virtual 8-device CPU mesh so a
crash or hang in one cannot take down the runner, exactly like the
reference's per-notebook subprocesses.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

TIMEOUT_S = 600  # NotebookTestSuite.py:13


def discover(selectors: list[str], use_shard: bool = True) -> list[str]:
    root = os.path.dirname(os.path.abspath(__file__))
    names = sorted(
        f for f in os.listdir(root)
        if f.startswith("e") and f.endswith(".py")
    )
    if selectors:
        names = [
            n for n in names if any(n.startswith(s) for s in selectors)
        ]
    shard = os.environ.get("PROC_SHARD") if use_shard else None
    if shard:
        i, m = (int(p) for p in shard.split("/"))
        names = [n for k, n in enumerate(names) if k % m == i]
    return [os.path.join(root, n) for n in names]


def run_one(path: str) -> tuple[bool, float, str]:
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(path)))
    env["PYTHONPATH"] = (
        repo_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else repo_root
    )
    # force the virtual 8-device CPU mesh even when the environment
    # pre-selects a real backend (same override tests/conftest.py applies)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
    # env vars alone are not enough on hosts whose site customization
    # registers a real accelerator backend; force the platform through
    # jax.config before the script runs (same override tests/conftest.py
    # applies in-process)
    boot = (
        "import jax, runpy, sys; "
        "jax.config.update('jax_platforms', 'cpu'); "
        f"runpy.run_path({path!r}, run_name='__main__')"
    )
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", boot],
            capture_output=True, text=True, timeout=TIMEOUT_S, env=env,
        )
        ok = proc.returncode == 0 and "OK" in proc.stdout
        # on success surface the script's headline OK line; on failure the
        # last error line
        src = proc.stdout if ok else (proc.stdout + proc.stderr)
        tail = src.strip().splitlines()
        detail = tail[-1] if tail else ""
    except subprocess.TimeoutExpired:
        ok, detail = False, f"TIMEOUT after {TIMEOUT_S}s"
    return ok, time.time() - t0, detail


def main() -> int:
    paths = discover(sys.argv[1:])
    if not paths:
        print("no examples matched")
        return 2
    failures = 0
    for path in paths:
        name = os.path.basename(path)
        ok, dt, detail = run_one(path)
        status = "PASS" if ok else "FAIL"
        print(f"{status} {name} ({dt:.1f}s) {detail}")
        failures += 0 if ok else 1
    print(f"{len(paths) - failures}/{len(paths)} examples passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
