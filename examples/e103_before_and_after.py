"""Notebook-103 parity: the same model sweep "before and after" mmlspark.

Reference flow (notebooks/samples/103 - Before and After MMLSpark.ipynb):
book reviews with derived wordCount/wordLength columns; the BEFORE half
hand-builds tokenizer + HashingTF + assembler, hand-rolls the
regParam sweep and the evaluator; the AFTER half is the one-liner
``TrainClassifier`` sweep ranked by ``FindBestModel`` and scored by
``ComputeModelStatistics``. Same contrast here: the before half is raw
jax/optax with manual hashing and a hand-computed AUC; the after half is
the framework one-liner. Both halves see identical data and must agree.
"""

import numpy as np

from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.stages.dnn_learner import DNNLearner
from mmlspark_tpu.stages.eval_metrics import ComputeModelStatistics
from mmlspark_tpu.stages.find_best import FindBestModel
from mmlspark_tpu.stages.train_classifier import TrainClassifier

GOOD = ["wonderful", "gripping", "brilliant", "loved", "masterpiece"]
BAD = ["boring", "dreadful", "awful", "hated", "tedious"]
FILLER = ["the", "book", "story", "chapter", "author", "plot", "read"]

REG_PARAMS = [0.05, 0.1, 0.2, 0.4]  # the notebook's lrHyperParams cell


def make_reviews(n, seed) -> Dataset:
    """Review text + 1-5 star rating; label = rating > 3 (notebook cell 3)."""
    rng = np.random.default_rng(seed)
    texts, ratings = [], []
    for _ in range(n):
        pos = rng.random() < 0.5
        # mixed sentiment vocabulary keeps the task non-separable, like
        # real reviews: mostly on-sentiment words, some off-sentiment
        n_sent = int(rng.integers(1, 4))
        words = list(rng.choice(FILLER, rng.integers(4, 9)))
        for _w in range(n_sent):
            on_sentiment = rng.random() < 0.88
            vocab = (GOOD if pos else BAD) if on_sentiment else (
                BAD if pos else GOOD
            )
            words.append(str(rng.choice(vocab)))
        rng.shuffle(words)
        texts.append(" ".join(words))
        ratings.append(int(rng.integers(4, 6) if pos else rng.integers(1, 4)))
    ds = Dataset({"rating": np.array(ratings), "text": texts})
    # derived columns, as the notebook's word_count/word_length UDFs
    ds = ds.with_column(
        "wordCount", np.array([len(t.split()) for t in texts], np.int64)
    )
    ds = ds.with_column(
        "wordLength",
        np.array(
            [np.mean([len(w) for w in t.split()]) for t in texts], np.float64
        ),
    )
    ds = ds.with_column("label", (np.asarray(ds["rating"]) > 3).astype(np.int64))
    return ds.drop("rating")


def manual_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-statistic AUC, hand-rolled like the notebook's evaluator cell."""
    order = np.argsort(scores, kind="stable")
    ranks = np.empty(len(scores))
    ranks[order] = np.arange(1, len(scores) + 1)
    pos = labels == 1
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    return float(
        (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
    )


def before(train: Dataset, test: Dataset) -> float:
    """The pre-framework path: every step by hand (notebook cells 5-7)."""
    import jax
    import jax.numpy as jnp
    import optax

    dim = 1 << 12

    def featurize(ds: Dataset) -> np.ndarray:
        # manual Tokenizer + HashingTF + VectorAssembler (crc32, not the
        # per-process-salted builtin hash, keeps the example reproducible)
        import zlib

        mat = np.zeros((len(ds), dim + 2), np.float32)
        for i, text in enumerate(ds["text"]):
            for tok in text.lower().split():
                mat[i, zlib.crc32(tok.encode()) % dim] += 1.0
        mat[:, dim] = np.asarray(ds["wordCount"], np.float32)
        mat[:, dim + 1] = np.asarray(ds["wordLength"], np.float32)
        return mat

    x_train, x_test = featurize(train), featurize(test)
    y_train = np.asarray(train["label"], np.int32)
    y_test = np.asarray(test["label"], np.int32)

    def fit_lr(reg: float) -> np.ndarray:
        def loss_fn(w, b):
            logits = x_train @ w + b
            nll = optax.sigmoid_binary_cross_entropy(
                logits, y_train.astype(np.float32)
            ).mean()
            return nll + reg * jnp.sum(w * w)

        w, b = jnp.zeros((x_train.shape[1],)), jnp.zeros(())
        opt = optax.adam(1e-1)
        state = opt.init((w, b))

        @jax.jit
        def step(params, state):
            grads = jax.grad(lambda p: loss_fn(*p))(params)
            updates, state = opt.update(grads, state)
            return optax.apply_updates(params, updates), state

        params = (w, b)
        for _ in range(60):
            params, state = step(params, state)
        w, b = params
        return np.asarray(x_test @ w + b)

    # manual hyperparameter sweep + manual metric + manual best-model pick
    aucs = [manual_auc(y_test, fit_lr(reg)) for reg in REG_PARAMS]
    return max(aucs)


def after(train: Dataset, test: Dataset) -> float:
    """The framework path: sweep, rank, evaluate — three stages, no UDFs."""
    models = [
        TrainClassifier(
            label_col="label",
            model=DNNLearner(
                model_name="linear",
                model_config={"num_outputs": 2},
                loss="softmax_xent",
                weight_decay=reg,
                epochs=20,
                learning_rate=1e-1,
                features_col="features",
                label_col="__label_idx__",
            ),
            number_of_features=1 << 12,
        ).fit(train)
        for reg in REG_PARAMS
    ]
    best = FindBestModel(models=models, evaluation_metric="AUC").fit(test)
    stats = ComputeModelStatistics().transform(
        best.best_model.transform(test)
    )
    return float(stats["AUC"][0])


def main():
    train, test = make_reviews(400, seed=21), make_reviews(150, seed=22)
    auc_before = before(train, test)
    auc_after = after(train, test)
    assert auc_before > 0.85, f"manual-path AUC {auc_before} too low"
    assert auc_after > 0.85, f"framework-path AUC {auc_after} too low"
    assert abs(auc_before - auc_after) < 0.08, (auc_before, auc_after)
    print(
        f"OK {{'auc_before': {auc_before:.3f}, 'auc_after': {auc_after:.3f}, "
        f"'sweep': {len(REG_PARAMS)}}}"
    )


if __name__ == "__main__":
    main()
