"""Autoregressive generation with KV-cache decode — beyond the reference.

The reference has no generative model at all (its only sequence model is
an opaque downloaded BiLSTM tagger, notebook 304). This example shows
the full decode story on the causal LM family:

1. overfit a tiny `transformer_lm` on a periodic token stream;
2. greedy-generate with the default KV-cache decode (prefill + one-token
   `lax.scan` steps against preallocated buffers) and check the model
   CONTINUES the period — and that the O(T²) full-recompute oracle
   produces the identical tokens;
3. the same on a sliding-window + RoPE model generating far past BOTH
   its window and its trained max_len: the cache rolls (O(window)
   circular buffers, constant memory however long the generation runs)
   and RoPE extrapolates structurally;
4. nucleus/top-k sampling: temperature sampling with `top_p` truncation
   still follows the learned period on a peaked model (the nucleus
   collapses to the top token), while loose filters reproduce the
   unfiltered stream rng-for-rng;
5. beam search over the same cache: `beams=1` reproduces greedy
   exactly and wider beams return score-sorted alternatives (eos beam
   freezing is covered by the unit suite, tests/test_generate.py).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
     python examples/e307_generation_kv_cache.py
"""

import numpy as np

from mmlspark_tpu.models import beam_search, build_model, generate

VOCAB = 8
PERIOD = 4  # stream cycles 1,2,3,4,1,2,...


def _overfit(m, seq=16, steps=60):
    import jax
    import jax.numpy as jnp
    import optax

    ids = jnp.asarray((np.arange(seq)[None] % PERIOD) + 1, jnp.int32)
    v = m.init(jax.random.PRNGKey(0), ids)
    opt = optax.adam(5e-2)
    st = opt.init(v)

    def loss(p):
        lg = m.apply(p, ids).astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            lg[:, :-1], ids[:, 1:]
        ).mean()

    @jax.jit
    def step(p, st):
        g = jax.grad(loss)(p)
        up, st = opt.update(g, st, p)
        return optax.apply_updates(p, up), st

    for _ in range(steps):
        v, st = step(v, st)
    return v, ids


def main():
    import jax

    # -- 1+2. dense LM: kv-cache decode == recompute oracle ----------------
    m = build_model("transformer_lm", vocab_size=VOCAB, d_model=32,
                    heads=2, depth=2, max_len=48)
    v, ids = _overfit(m)
    prompt = ids[:, :8]
    kv = np.asarray(generate(m, v, prompt, max_new_tokens=16))
    oracle = np.asarray(
        generate(m, v, prompt, max_new_tokens=16, kv_cache=False)
    )
    assert (kv == oracle).all(), "cache decode diverged from the oracle"
    want = (np.arange(24) % PERIOD) + 1
    np.testing.assert_array_equal(kv[0], want)

    # -- 3. rolled window cache: constant memory past max_len --------------
    wm = build_model("transformer_lm", vocab_size=VOCAB, d_model=32,
                     heads=2, depth=2, max_len=16, window=8,
                     pos_embedding="rope")
    wv, wids = _overfit(wm)
    LONG = 40  # 56 total >> window 8, >> trained max_len 16
    wout = np.asarray(generate(wm, wv, wids, max_new_tokens=LONG))
    wwant = (np.arange(16 + LONG) % PERIOD) + 1
    np.testing.assert_array_equal(wout[0], wwant)

    # -- 4. nucleus sampling on a peaked model -----------------------------
    nucleus = np.asarray(
        generate(m, v, prompt, max_new_tokens=12, temperature=1.0,
                 top_p=0.5, rng=jax.random.PRNGKey(3))
    )
    np.testing.assert_array_equal(
        nucleus[0], (np.arange(20) % PERIOD) + 1
    )
    base = np.asarray(
        generate(m, v, prompt, max_new_tokens=12, temperature=1.5,
                 rng=jax.random.PRNGKey(4))
    )
    loose = np.asarray(
        generate(m, v, prompt, max_new_tokens=12, temperature=1.5,
                 top_k=VOCAB, top_p=1.0, rng=jax.random.PRNGKey(4))
    )
    assert (base == loose).all()

    # -- 5. beam search over the same cache --------------------------------
    beam1 = np.asarray(beam_search(m, v, prompt, max_new_tokens=16,
                                   beams=1))
    np.testing.assert_array_equal(beam1, kv)  # beams=1 == greedy
    seqs, scores = beam_search(m, v, prompt, max_new_tokens=8, beams=4,
                               return_all=True)
    s = np.asarray(scores)
    assert seqs.shape == (1, 4, 16) and np.all(s[:, :-1] >= s[:, 1:])

    print(
        f"OK {{'kv_matches_oracle': True, "
        f"'rolled_window_tokens': {LONG}, "
        f"'window': 8, 'nucleus_follows_period': True, "
        f"'beam1_equals_greedy': True}}"
    )


if __name__ == "__main__":
    main()
