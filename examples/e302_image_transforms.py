"""Notebook-302 parity: image ingestion + ImageTransformer pipeline.

Reference flow (notebooks/samples/302 - Pipeline Image
Transformations.ipynb): spark.readImages -> sample -> ImageTransformer
resize/crop/flip/gaussian-blur chain -> inspect shapes. Here images are
written as real files, ingested through the binary reader + decode path
(the readers/ImageFileFormat analog), and run through the same op DSL.
"""

import os
import tempfile

import numpy as np

from mmlspark_tpu.data.readers import read_images
from mmlspark_tpu.stages.image import ImageSetAugmenter, ImageTransformer


def write_pngs(root: str, n=6) -> None:
    from PIL import Image

    rng = np.random.default_rng(0)
    for i in range(n):
        img = rng.integers(0, 256, (48 + 4 * i, 64, 3), dtype=np.uint8)
        Image.fromarray(img).save(os.path.join(root, f"img{i}.png"))


def main():
    with tempfile.TemporaryDirectory() as root:
        write_pngs(root)
        ds = read_images(root)
        assert ds.num_rows == 6

        out = (
            ImageTransformer(input_col="image", output_col="small")
            .resize(32, 32)
            .crop(0, 0, 24, 24)
            .flip(1)
            .blur(3, 3)
            .transform(ds)
        )
        shapes = {row.data.shape for row in out["small"]}
        assert shapes == {(24, 24, 3)}, shapes

        aug = ImageSetAugmenter(flip_left_right=True).transform(ds)
        assert aug.num_rows == 12
        print(f"OK {{'images': {ds.num_rows}, "
              f"'transformed_shape': [24, 24, 3], "
              f"'augmented_rows': {aug.num_rows}}}")


if __name__ == "__main__":
    main()
