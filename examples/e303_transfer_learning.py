"""Notebook-303/305 parity: transfer learning by DNN featurization.

Reference flow (notebooks/samples/303 - Transfer Learning by DNN
Featurization.ipynb): ``ModelDownloader.downloadByName`` fetches a
pretrained CNN from the model repo, ``ImageFeaturizer`` cuts it one layer
from the top, and the headless activations feed ``TrainClassifier``
(ModelDownloader.scala:230-236, ImageFeaturizer.scala:116-140). Same flow
here: the backbone comes out of the committed model zoo
(``models/zoo_repo``, published by ``tools/publish_zoo.py``) through the
sha256-verified download path — not trained inline.
"""

import os
import tempfile

import numpy as np

from mmlspark_tpu.core.schema import ImageRow
from mmlspark_tpu.core.stage import PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models.zoo import ModelDownloader
from mmlspark_tpu.stages.image import ImageFeaturizer
from mmlspark_tpu.stages.prep import SelectColumns
from mmlspark_tpu.stages.train_classifier import TrainClassifier

from mmlspark_tpu.testing.datagen import blob_images

ZOO = os.path.join(os.path.dirname(__file__), "..", "models", "zoo_repo")


def main():
    # pretrained backbone via the zoo download path (downloadByName with
    # sha256 verify + local cache), like the notebook's
    # d.downloadByName("ConvNet") cell
    with tempfile.TemporaryDirectory() as local_repo:
        downloader = ModelDownloader(local_repo, remote=ZOO)
        schema = downloader.download_by_name("ResNet20_Blobs")
        backbone = PipelineStage.load(downloader.local_path(schema))
    assert schema.layer_names, "zoo schema must carry layer names for cuts"

    # featurize fresh train/test splits with the headless net (cut the
    # logits layer); scale matches the backbone's normalization (pix/255)
    def featurize(seed, n):
        imgs2, y2 = blob_images(n, seed=seed)
        ds = Dataset({
            "image": [ImageRow(path=f"img{i}", data=im)
                      for i, im in enumerate(imgs2)],
            "label": [["top", "bottom"][c] for c in y2],
        })
        out = ImageFeaturizer(
            model=backbone, cut_output_layers=1, scale=1.0 / 255.0
        ).transform(ds)
        # keep only (features, label) for the downstream learner, as the
        # notebook does with a select()
        return SelectColumns(cols=["features", "label"]).transform(out)

    train_f, test_f = featurize(seed=5, n=200), featurize(seed=6, n=100)
    feat_dim = train_f["features"].shape[1]

    model = TrainClassifier(label_col="label", epochs=20,
                            learning_rate=5e-2).fit(train_f)
    scored = model.transform(test_f)
    acc = float(
        (np.asarray(scored["scored_labels"])
         == np.asarray(test_f["label"])).mean()
    )
    assert acc > 0.85, f"held-out accuracy {acc} too low"
    print(f"OK {{'accuracy': {acc:.3f}, 'feature_dim': {feat_dim}, "
          f"'model': '{schema.name}'}}")


if __name__ == "__main__":
    main()
