"""Notebook-303/305 parity: transfer learning by DNN featurization.

Reference flow (notebooks/samples/303 - Transfer Learning by DNN
Featurization.ipynb): ImageFeaturizer with a pretrained CNN cut one layer
from the top -> headless activations as features -> TrainClassifier on the
features. Here the backbone is a ResNet-20 briefly pre-fitted on a related
synthetic task (standing in for the model-zoo download), then cut and
reused to featurize a new two-class image problem.
"""

import numpy as np

from mmlspark_tpu.core.schema import ImageRow
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models import build_model
from mmlspark_tpu.stages.dnn_model import TPUModel
from mmlspark_tpu.stages.image import ImageFeaturizer
from mmlspark_tpu.stages.prep import SelectColumns
from mmlspark_tpu.stages.train_classifier import TrainClassifier
from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig


def blob_images(n, seed, classes=2):
    """Two visual classes: bright-top vs bright-bottom uint8 images."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, classes, n)
    imgs = []
    for label in y:
        img = rng.integers(0, 80, (32, 32, 3))
        half = slice(0, 16) if label == 0 else slice(16, 32)
        img[half] += 150
        imgs.append(np.clip(img, 0, 255).astype(np.uint8))
    return imgs, y


def main():
    # "pretrained" backbone: quick fit so features carry signal
    graph = build_model("resnet20_cifar10", width=8)
    imgs, y = blob_images(256, seed=0)
    x = np.stack(imgs).astype(np.float32) / 255.0
    # enough steps for the BatchNorm running statistics to converge
    # (eval mode uses them; momentum 0.9 needs ~50 steps)
    trainer = SPMDTrainer(
        graph, TrainConfig(epochs=15, batch_size=64, learning_rate=1e-2,
                           log_every=20),
    )
    variables = trainer.train(x, y.astype(np.int32))
    backbone = TPUModel.from_graph(
        graph, variables, "resnet20_cifar10", model_config={"width": 8},
        input_col="image", output_col="scores",
    )

    # featurize fresh train/test splits with the headless net (cut the
    # logits layer); scale matches the backbone's normalization (pix/255)
    def featurize(seed, n):
        imgs2, y2 = blob_images(n, seed=seed)
        ds = Dataset({
            "image": [ImageRow(path=f"img{i}", data=im)
                      for i, im in enumerate(imgs2)],
            "label": [["top", "bottom"][c] for c in y2],
        })
        out = ImageFeaturizer(
            model=backbone, cut_output_layers=1, scale=1.0 / 255.0
        ).transform(ds)
        # keep only (features, label) for the downstream learner, as the
        # notebook does with a select()
        return SelectColumns(cols=["features", "label"]).transform(out)

    train_f, test_f = featurize(seed=5, n=200), featurize(seed=6, n=100)
    feat_dim = train_f["features"].shape[1]

    model = TrainClassifier(label_col="label", epochs=20,
                            learning_rate=5e-2).fit(train_f)
    scored = model.transform(test_f)
    acc = float(
        (np.asarray(scored["scored_labels"])
         == np.asarray(test_f["label"])).mean()
    )
    assert acc > 0.85, f"held-out accuracy {acc} too low"
    print(f"OK {{'accuracy': {acc:.3f}, 'feature_dim': {feat_dim}}}")


if __name__ == "__main__":
    main()
