"""Notebook-303 parity: transfer learning by DNN featurization, real data.

Reference flow (notebooks/samples/303 - Transfer Learning by DNN
Featurization.ipynb): ``ModelDownloader.downloadByName`` fetches a
pretrained CNN from the model repo, ``ImageFeaturizer`` cuts it one layer
from the top, and the headless activations feed ``TrainClassifier``
(ModelDownloader.scala:230-236, ImageFeaturizer.scala:116-140).

Same flow here on REAL images: the zoo backbone ``ResNet20_Digits04``
(models/zoo_repo, published by ``tools/publish_zoo.py``) is a full-width
ResNet-20 pretrained on the scikit-learn handwritten-digit scans,
classes 0-4, shift-augmented. The transfer task is digits 5-9 — classes
the backbone NEVER saw — rendered unregistered (random placement), with
only 100 labels. The pretrained conv features transfer; a raw-pixel
model on the same 100 labels does not — the reference notebook's
headline capability, demonstrated rather than assumed.
"""

import os
import tempfile

import numpy as np

from mmlspark_tpu.core.schema import ImageRow
from mmlspark_tpu.core.stage import Pipeline, PipelineStage
from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.data.sample_data import load_digit_images
from mmlspark_tpu.models.zoo import ModelDownloader
from mmlspark_tpu.stages.image import (
    ImageFeaturizer,
    ImageTransformer,
    UnrollImage,
)
from mmlspark_tpu.stages.prep import SelectColumns
from mmlspark_tpu.stages.train_classifier import TrainClassifier

ZOO = os.path.join(os.path.dirname(__file__), "..", "models", "zoo_repo")
FEW = 100  # labeled examples for the target task


def target_task():
    """Digits 5-9 (never seen by the backbone), unregistered placement;
    real scans from the sklearn digits set."""
    imgs, y = load_digit_images((5, 6, 7, 8, 9), max_shift=4, seed=9)
    ds = Dataset({
        "image": [
            ImageRow(path=f"d{i}", data=im) for i, im in enumerate(imgs)
        ],
        "label": [f"digit{c + 5}" for c in y],
    })
    order = np.random.default_rng(1).permutation(len(y))
    return ds.gather(order[:FEW]), ds.gather(order[FEW:])


def accuracy(featurizer, train, test, name) -> float:
    pipe = Pipeline(
        [featurizer, SelectColumns(cols=["features", "label"])]
    ).fit(train)
    train_f, test_f = pipe.transform(train), pipe.transform(test)
    model = TrainClassifier(
        label_col="label", epochs=200, learning_rate=1e-1
    ).fit(train_f)
    scored = model.transform(test_f)
    acc = float(
        (np.asarray(scored["scored_labels"])
         == np.asarray(test_f["label"])).mean()
    )
    print(f"{name}: {FEW}-shot accuracy {acc:.3f} on {len(test_f)} "
          "held-out images")
    return acc


def main():
    # pretrained real-data backbone via the zoo download path (sha256
    # verify + local cache), like the notebook's d.downloadByName cell
    with tempfile.TemporaryDirectory() as local_repo:
        downloader = ModelDownloader(local_repo, remote=ZOO)
        schema = downloader.download_by_name("ResNet20_Digits04")
        backbone = PipelineStage.load(downloader.local_path(schema))
    assert schema.layer_names, "zoo schema must carry layer names for cuts"
    assert schema.extra.get("test_accuracy", 0) > 0.9, (
        "zoo meta must record the backbone's real held-out accuracy"
    )

    train, test = target_task()

    # transfer: headless pretrained net (cut the logits layer)
    dnn = ImageFeaturizer(
        model=backbone, cut_output_layers=1, scale=1.0 / 255.0
    )
    dnn_acc = accuracy(dnn, train, test, "pretrained features")

    # baseline: same labels, raw pixels (resize + unroll)
    raw = Pipeline([
        ImageTransformer(output_col="scaled").resize(height=32, width=32),
        UnrollImage(input_col="scaled", output_col="features"),
    ])
    raw_acc = accuracy(raw, train, test, "raw pixels")

    assert dnn_acc > 0.8, f"transfer accuracy {dnn_acc} too low"
    assert dnn_acc >= raw_acc + 0.08, (
        f"no transfer lift: features {dnn_acc} vs raw {raw_acc}"
    )
    print(
        f"OK {{'transfer_accuracy': {dnn_acc:.3f}, "
        f"'raw_accuracy': {raw_acc:.3f}, 'backbone': '{schema.name}', "
        f"'backbone_test_accuracy': {schema.extra['test_accuracy']}}}"
    )


if __name__ == "__main__":
    main()
