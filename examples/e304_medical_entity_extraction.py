"""Notebook-304 parity: per-token entity tagging with a BiLSTM.

Reference flow (notebooks/samples/304 - Medical Entity Extraction.ipynb):
download an opaque serialized BiLSTM graph, pad sentences to max length
in notebook UDFs, run CNTKModel per token, map tag ids back to labels.
Here the BiLSTM is a first-class model (models/bilstm.py) trained
in-process on a synthetic entity task, then the notebook's
OPAQUE-SERIALIZED-GRAPH leg is reproduced for real: the trained tagger is
exported to ONNX bytes, re-imported as an opaque graph
(models/onnx_export.py -> load_onnx), and served through the TPUModel
inference stage — the CNTKModel-over-downloaded-graph flow, TPU-native.
Padding uses a fixed max length exactly like the notebook.
"""

import os
import tempfile

import numpy as np

from mmlspark_tpu.data.dataset import Dataset
from mmlspark_tpu.models import build_model
from mmlspark_tpu.models.onnx_export import save_onnx
from mmlspark_tpu.models.onnx_import import load_onnx
from mmlspark_tpu.stages.dnn_model import TPUModel
from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

# tiny "medical" vocabulary: ids 0=PAD, 1..9 filler, 10..14 drug names,
# 15..19 dose tokens
VOCAB = 20
TAGS = ["O", "DRUG", "DOSE"]
MAX_LEN = 16


def make_sentences(n, seed):
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, 10, size=(n, MAX_LEN))
    tags = np.zeros((n, MAX_LEN), np.int32)  # O
    for row in range(n):
        i = rng.integers(0, MAX_LEN - 1)
        ids[row, i] = rng.integers(10, 15)       # drug mention
        tags[row, i] = 1
        ids[row, i + 1] = rng.integers(15, 20)   # followed by a dose
        tags[row, i + 1] = 2
    return ids.astype(np.int32), tags


def main():
    graph = build_model(
        "bilstm_tagger", vocab_size=VOCAB, embed_dim=16, hidden=32,
        num_tags=len(TAGS),
    )
    ids, tags = make_sentences(512, seed=0)
    trainer = SPMDTrainer(
        graph,
        TrainConfig(epochs=12, batch_size=64, learning_rate=1e-2,
                    log_every=10),
    )
    variables = trainer.train(ids, tags)

    test_ids, test_tags = make_sentences(128, seed=1)

    # the notebook's opaque-graph leg: serialize -> reload as ONNX ->
    # run through the batched inference stage
    batch = 32
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "tagger.onnx")
        save_onnx(graph, variables, (batch, MAX_LEN), path)
        with open(path, "rb") as f:
            opaque = load_onnx(f.read())
    model = TPUModel.from_graph(
        opaque, opaque.init(), "onnx", input_col="tokens",
        batch_size=batch, data_parallel=False,
    )
    scored = model.transform(Dataset({"tokens": test_ids}))
    pred = np.asarray(scored["scores"].tolist()).argmax(-1)
    acc = float((pred == test_tags).mean())
    entity_mask = test_tags > 0
    entity_recall = float(
        (pred[entity_mask] == test_tags[entity_mask]).mean()
    )
    assert acc > 0.9, f"token accuracy {acc} too low"
    assert entity_recall > 0.9, f"entity recall {entity_recall} too low"
    extracted = [TAGS[t] for t in pred[0] if t > 0]
    print(f"OK {{'token_accuracy': {acc:.3f}, "
          f"'entity_recall': {entity_recall:.3f}, "
          f"'example_entities': {extracted!r}}}")


if __name__ == "__main__":
    main()
