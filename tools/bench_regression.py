#!/usr/bin/env python
"""Bench regression gate: compare a fresh bench JSON against the repo's
recorded ``BENCH_*.json`` history and fail CI on a throughput
regression, so "PR N made serving slower" is a red build, not a human
rereading the numbers by hand (docs/OBSERVABILITY.md "The bench
regression gate").

What is compared
----------------
Every NUMERIC leaf whose dotted key path contains ``per_sec``
(``tokens_per_sec``, ``serve_faults.chaos.tokens_per_sec``,
``resnet50_images_per_sec_per_chip``, ...) — the repo's throughput
figures, all higher-is-better. Latency figures are deliberately out of
scope: their distributions on shared CI hosts are too heavy-tailed for
a tolerance band to mean anything.

Besides the throughput band, the gate enforces EMBEDDED BUDGETS: any
dict in the fresh doc carrying a numeric ``<name>`` next to a numeric
``<name>_budget`` (the ``serve_int8`` group's ``token_flip_rate`` /
``token_flip_budget`` and ``max_abs_err`` / ``max_abs_err_budget``
pairs, docs/PERFORMANCE.md "Quantized decode") fails the gate when the
measured value exceeds its budget — lower-is-better by construction,
no history needed, so an accuracy breach is red even on the first run
of a new metric.

History entries come in two shapes, both handled:

- direct bench dicts (``BENCH_FULL.json``, ``BENCH_LOCAL_r4.json`` —
  what ``tools/record_local_bench.sh`` appends);
- driver wrappers ``{"n", "cmd", "rc", "tail", "parsed"}``
  (``BENCH_r0*.json``): ``parsed`` is used when it is a dict, and any
  full JSON line inside ``tail`` is recovered. Entries that yield no
  throughput leaf (the TPU-unavailable runs) are skipped with a note —
  they are history, not evidence.

The baseline per key is the MEDIAN across history entries carrying it
(robust to one lucky/unlucky run). The fresh value fails the gate when
``fresh < median * (1 - tolerance)``; the default tolerance 0.15 makes
the acceptance bar concrete: a >=20% slowdown always fails, run-to-run
noise (the recorded serve noise floor is ~1-2%) never does. An empty
key intersection exits 0 with a warning — a gate that cannot compare
must not block.

Usage::

    python tools/bench_regression.py FRESH.json [--history 'BENCH*.json']
                                     [--tolerance 0.15]
    python tools/bench_regression.py --selftest

``--selftest`` (the ``tools/ci.sh`` step) needs no fresh bench run: it
replays the newest usable history entry against the full history
(must pass) and a copy with every throughput leaf scaled by 0.75 — a
25% slowdown — against the same history (must fail). Exit 0 means the
gate provably catches regressions on the REAL recorded history.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_HISTORY = "BENCH*.json"
DEFAULT_TOLERANCE = 0.15
_WRAPPER_KEYS = {"n", "cmd", "rc", "tail"}


def throughput_leaves(doc, path: tuple = ()) -> dict[str, float]:
    """Dotted-path -> value for every numeric leaf whose path mentions
    ``per_sec``. Bools and non-positive values are skipped (a 0
    tokens/sec is a failed run, not a comparable figure)."""
    out: dict[str, float] = {}
    if isinstance(doc, dict):
        for key, value in doc.items():
            out.update(throughput_leaves(value, path + (str(key),)))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        dotted = ".".join(path)
        if "per_sec" in dotted and doc > 0:
            out[dotted] = float(doc)
    return out


def budget_violations(doc, path: tuple = ()) -> list[str]:
    """Breached ``<name>`` / ``<name>_budget`` pairs anywhere in the
    doc, as report lines. A measured value AT the budget passes — the
    budget is the allowed ceiling, not an open bound."""
    out: list[str] = []
    if not isinstance(doc, dict):
        return out
    for key, value in doc.items():
        if isinstance(value, dict):
            out.extend(budget_violations(value, path + (str(key),)))
            continue
        if not str(key).endswith("_budget"):
            continue
        stem = str(key)[: -len("_budget")]
        # "max_abs_err" pairs with "max_abs_err_budget";
        # "token_flip_rate" pairs with "token_flip_budget"
        name = next(
            (n for n in (stem, stem + "_rate") if n in doc), stem
        )
        measured = doc.get(name)
        if (
            isinstance(measured, (int, float))
            and isinstance(value, (int, float))
            and not isinstance(measured, bool)
            and not isinstance(value, bool)
            and measured > value
        ):
            dotted = ".".join(path + (name,))
            out.append(
                f"{dotted}: measured {measured} exceeds its embedded "
                f"budget {value}"
            )
    return out


def unwrap(doc) -> list[dict]:
    """A history file's comparable payload(s): the dict itself, or —
    for driver wrappers — its ``parsed`` dict plus any full JSON line
    recoverable from ``tail``."""
    if not isinstance(doc, dict):
        return []
    if not _WRAPPER_KEYS.issubset(doc):
        return [doc]
    payloads = []
    if isinstance(doc.get("parsed"), dict):
        payloads.append(doc["parsed"])
    for line in str(doc.get("tail") or "").splitlines():
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(parsed, dict):
                payloads.append(parsed)
    return payloads


def load_history(pattern: str) -> tuple[dict[str, list[float]], list[str]]:
    """key -> every historical value, plus the usable file names."""
    values: dict[str, list[float]] = {}
    used: list[str] = []
    for path in sorted(glob.glob(os.path.join(REPO, pattern))):
        try:
            doc = json.load(open(path, encoding="utf-8"))
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_regression: skipping {os.path.basename(path)}: "
                  f"{e}", file=sys.stderr)
            continue
        leaves: dict[str, float] = {}
        for payload in unwrap(doc):
            leaves.update(throughput_leaves(payload))
        if not leaves:
            print(
                f"bench_regression: {os.path.basename(path)} carries no "
                "throughput figures (unavailable-backend run), skipping",
                file=sys.stderr,
            )
            continue
        used.append(os.path.basename(path))
        for key, value in leaves.items():
            values.setdefault(key, []).append(value)
    return values, used


def compare(fresh: dict[str, float], history: dict[str, list[float]],
            tolerance: float) -> tuple[list[str], list[str]]:
    """-> (per-key report lines, regression lines). Keys only one side
    has are reported but never fail the gate — a NEW metric must not
    break CI the day it lands."""
    report: list[str] = []
    regressions: list[str] = []
    for key in sorted(set(fresh) & set(history)):
        baseline = statistics.median(history[key])
        floor = baseline * (1.0 - tolerance)
        value = fresh[key]
        delta_pct = 100.0 * (value - baseline) / baseline
        line = (
            f"{key}: fresh {value:.1f} vs baseline {baseline:.1f} "
            f"(median of {len(history[key])}) -> {delta_pct:+.1f}%"
        )
        if value < floor:
            regressions.append(f"{line}  [below -{tolerance:.0%} band]")
        else:
            report.append(line)
    for key in sorted(set(fresh) - set(history)):
        report.append(f"{key}: fresh {fresh[key]:.1f} (no history — "
                      "informational)")
    return report, regressions


def run_gate(fresh_path: str, pattern: str, tolerance: float) -> int:
    try:
        doc = json.load(open(fresh_path, encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_regression: FAIL — cannot read fresh bench "
              f"{fresh_path}: {e}", file=sys.stderr)
        return 1
    fresh: dict[str, float] = {}
    breaches: list[str] = []
    for payload in unwrap(doc):
        fresh.update(throughput_leaves(payload))
        breaches.extend(budget_violations(payload))
    # budget breaches are absolute — they fail BEFORE (and regardless
    # of) whether any throughput history exists to band against
    for line in breaches:
        print(f"bench_regression: FAIL {line}", file=sys.stderr)
    if breaches:
        print(
            f"bench_regression: FAIL — {len(breaches)} embedded accuracy "
            f"budget breach(es) in {os.path.basename(fresh_path)}",
            file=sys.stderr,
        )
        return 1
    history, used = load_history(pattern)
    if not fresh or not set(fresh) & set(history):
        print(
            "bench_regression: WARN — no comparable throughput keys "
            f"between {os.path.basename(fresh_path)} and history "
            f"({', '.join(used) or 'none usable'}); nothing to gate"
        )
        return 0
    report, regressions = compare(fresh, history, tolerance)
    for line in report:
        print(f"bench_regression: ok   {line}")
    for line in regressions:
        print(f"bench_regression: FAIL {line}", file=sys.stderr)
    if regressions:
        print(
            f"bench_regression: FAIL — {len(regressions)} throughput "
            f"regression(s) beyond the {tolerance:.0%} tolerance band "
            f"(history: {', '.join(used)})", file=sys.stderr,
        )
        return 1
    print(
        f"bench_regression: OK — {len(report)} throughput figure(s) "
        f"within the {tolerance:.0%} band of {', '.join(used)}"
    )
    return 0


def _scale_leaves(doc, factor: float, path: tuple = ()):
    """Copy with every throughput leaf multiplied by ``factor`` — the
    selftest's injected slowdown."""
    if isinstance(doc, dict):
        return {
            k: _scale_leaves(v, factor, path + (str(k),))
            for k, v in doc.items()
        }
    if (
        isinstance(doc, (int, float)) and not isinstance(doc, bool)
        and "per_sec" in ".".join(path)
    ):
        return doc * factor
    return doc


def run_selftest(pattern: str, tolerance: float) -> int:
    """Prove the gate on the real history: the newest usable entry must
    pass against the full history; the same entry with a 25% injected
    slowdown must fail; a synthesized doc with a breached embedded
    accuracy budget must fail even with no comparable history."""
    import tempfile

    history, used = load_history(pattern)
    if not history:
        print("bench_regression: WARN — selftest found no usable "
              "history; nothing to prove")
        return 0
    # newest usable file = last in sorted order that contributed
    newest = None
    for path in sorted(glob.glob(os.path.join(REPO, pattern))):
        if os.path.basename(path) in used:
            newest = path
    doc = json.load(open(newest, encoding="utf-8"))
    with tempfile.TemporaryDirectory() as tdir:
        clean = os.path.join(tdir, "fresh.json")
        slow = os.path.join(tdir, "slow.json")
        breach = os.path.join(tdir, "breach.json")
        json.dump(doc, open(clean, "w", encoding="utf-8"))
        json.dump(_scale_leaves(doc, 0.75), open(slow, "w",
                                                 encoding="utf-8"))
        # the serve_int8 shape with its flip budget breached — proves
        # the accuracy gate trips with zero throughput history in play
        json.dump(
            {"serve_int8": {"token_flip_rate": 0.5,
                            "token_flip_budget": 0.25,
                            "max_abs_err": 0.01,
                            "max_abs_err_budget": 0.0625}},
            open(breach, "w", encoding="utf-8"),
        )
        rc_clean = run_gate(clean, pattern, tolerance)
        rc_slow = run_gate(slow, pattern, tolerance)
        rc_breach = run_gate(breach, pattern, tolerance)
    if rc_breach == 0:
        print(
            "bench_regression: SELFTEST FAIL — a breached embedded "
            "accuracy budget was NOT caught", file=sys.stderr,
        )
        return 1
    if rc_clean != 0:
        print(
            "bench_regression: SELFTEST FAIL — the newest usable "
            f"history entry ({os.path.basename(newest)}) does not pass "
            "against its own history", file=sys.stderr,
        )
        return 1
    if rc_slow == 0:
        print(
            "bench_regression: SELFTEST FAIL — a 25% injected slowdown "
            "was NOT caught", file=sys.stderr,
        )
        return 1
    print(
        "bench_regression: SELFTEST OK — clean history passes, a 25% "
        "injected slowdown fails, a breached accuracy budget fails "
        f"(tolerance {tolerance:.0%}, history: {', '.join(used)})"
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="fail on throughput regression vs BENCH history"
    )
    ap.add_argument("fresh", nargs="?", metavar="FRESH.json",
                    help="fresh bench JSON (one `python bench.py` line)")
    ap.add_argument("--history", default=DEFAULT_HISTORY, metavar="GLOB",
                    help=f"history glob under the repo root "
                    f"(default: {DEFAULT_HISTORY})")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="allowed fractional slowdown before failing "
                    f"(default: {DEFAULT_TOLERANCE} -> a >=20%% "
                    "regression always fails)")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the gate catches an injected 25%% "
                    "slowdown on the real history (no fresh run needed)")
    args = ap.parse_args()
    if args.selftest:
        return run_selftest(args.history, args.tolerance)
    if not args.fresh:
        ap.error("FRESH.json required (or --selftest)")
    return run_gate(args.fresh, args.history, args.tolerance)


if __name__ == "__main__":
    sys.exit(main())
