"""Generate the committed real-format parity fixtures.

The examples otherwise run on in-process synthetic arrays; these fixtures
put actual serialized formats on disk — real PNG/JPEG bytes through the
native libjpeg/libpng decode op, a zip archive through the zip-traversal
reader, and a census-schema CSV through read_csv — so behavior parity is
asserted against files a reference user would actually have.

Deterministic (seeded); regenerate with ``python tools/make_fixtures.py``.
"""

from __future__ import annotations

import os
import sys
import zipfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")


def blob_image(rng, label: int) -> np.ndarray:
    img = rng.integers(0, 80, (32, 32, 3))
    half = slice(0, 16) if label == 0 else slice(16, 32)
    img[half] += 150
    return np.clip(img, 0, 255).astype(np.uint8)


def main() -> None:
    from PIL import Image

    sys.path.insert(0, REPO)
    from mmlspark_tpu.testing.datagen import make_census

    img_dir = os.path.join(FIXTURES, "images")
    os.makedirs(img_dir, exist_ok=True)
    rng = np.random.default_rng(42)
    # class in the filename (like the notebook datasets' dir layout)
    for i in range(24):
        label = i % 2
        arr = blob_image(rng, label)
        name = f"{['top', 'bottom'][label]}_{i:02d}"
        ext = "png" if i % 3 else "jpg"  # a third jpeg, rest png
        Image.fromarray(arr).save(
            os.path.join(img_dir, f"{name}.{ext}"), quality=95
        )
    # a zip archive for the transparent zip-traversal path
    # (BinaryFileFormat.scala:36-114 semantics)
    zpath = os.path.join(FIXTURES, "images_extra.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        for i in range(24, 30):
            label = i % 2
            arr = blob_image(rng, label)
            tmp = os.path.join(img_dir, "_tmp.png")
            Image.fromarray(arr).save(tmp)
            z.write(tmp, f"zipped/{['top', 'bottom'][label]}_{i:02d}.png")
            os.remove(tmp)

    census = make_census(400, seed=11)
    census.to_pandas().to_csv(
        os.path.join(FIXTURES, "census.csv"), index=False
    )
    print(f"fixtures written under {FIXTURES}")


if __name__ == "__main__":
    main()
