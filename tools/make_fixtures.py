"""Generate the committed real-format parity fixtures.

The examples otherwise run on in-process synthetic arrays; these fixtures
put actual serialized formats on disk — real PNG/JPEG bytes through the
native libjpeg/libpng decode op, a zip archive through the zip-traversal
reader, and a census-schema CSV through read_csv — so behavior parity is
asserted against files a reference user would actually have.

Deterministic (seeded); regenerate with ``python tools/make_fixtures.py``.
"""

from __future__ import annotations

import os
import sys
import zipfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")


def main() -> None:
    from PIL import Image

    sys.path.insert(0, REPO)
    from mmlspark_tpu.testing.datagen import blob_images, make_census

    img_dir = os.path.join(FIXTURES, "images")
    os.makedirs(img_dir, exist_ok=True)
    imgs, labels = blob_images(30, seed=42)
    # class in the filename (like the notebook datasets' dir layout)
    for i in range(24):
        name = f"{['top', 'bottom'][labels[i]]}_{i:02d}"
        ext = "png" if i % 3 else "jpg"  # a third jpeg, rest png
        Image.fromarray(imgs[i]).save(
            os.path.join(img_dir, f"{name}.{ext}"), quality=95
        )
    # a zip archive for the transparent zip-traversal path
    # (BinaryFileFormat.scala:36-114 semantics)
    zpath = os.path.join(FIXTURES, "images_extra.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        for i in range(24, 30):
            tmp = os.path.join(img_dir, "_tmp.png")
            Image.fromarray(imgs[i]).save(tmp)
            z.write(
                tmp, f"zipped/{['top', 'bottom'][labels[i]]}_{i:02d}.png"
            )
            os.remove(tmp)

    census = make_census(400, seed=11)
    census.to_pandas().to_csv(
        os.path.join(FIXTURES, "census.csv"), index=False
    )
    print(f"fixtures written under {FIXTURES}")


if __name__ == "__main__":
    main()
