"""Generate the committed real-format parity fixtures.

The examples otherwise run on in-process synthetic arrays; these fixtures
put actual serialized formats on disk — real PNG/JPEG bytes through the
native libjpeg/libpng decode op, a zip archive through the zip-traversal
reader, and a census-schema CSV through read_csv — so behavior parity is
asserted against files a reference user would actually have.

Deterministic (seeded); regenerate with ``python tools/make_fixtures.py``.
"""

from __future__ import annotations

import os
import sys
import zipfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")


def main() -> None:
    from PIL import Image

    sys.path.insert(0, REPO)
    from mmlspark_tpu.testing.datagen import blob_images, make_census

    img_dir = os.path.join(FIXTURES, "images")
    os.makedirs(img_dir, exist_ok=True)
    imgs, labels = blob_images(30, seed=42)
    # class in the filename (like the notebook datasets' dir layout)
    for i in range(24):
        name = f"{['top', 'bottom'][labels[i]]}_{i:02d}"
        ext = "png" if i % 3 else "jpg"  # a third jpeg, rest png
        Image.fromarray(imgs[i]).save(
            os.path.join(img_dir, f"{name}.{ext}"), quality=95
        )
    # a zip archive for the transparent zip-traversal path
    # (BinaryFileFormat.scala:36-114 semantics)
    zpath = os.path.join(FIXTURES, "images_extra.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        for i in range(24, 30):
            tmp = os.path.join(img_dir, "_tmp.png")
            Image.fromarray(imgs[i]).save(tmp)
            z.write(
                tmp, f"zipped/{['top', 'bottom'][labels[i]]}_{i:02d}.png"
            )
            os.remove(tmp)

    census = make_census(400, seed=11)
    census.to_pandas().to_csv(
        os.path.join(FIXTURES, "census.csv"), index=False
    )
    extract_real_tables()
    print(f"fixtures written under {FIXTURES}")


def _arff_to_rows(path: str) -> tuple[list[str], list[list[str]]]:
    """Minimal ARFF reader for the bundled samples: attribute names +
    data rows (comma-separated, optionally quoted, '?' = missing)."""
    import csv
    import gzip
    import io

    names: list[str] = []
    rows: list[list[str]] = []
    in_data = False
    with gzip.open(path, "rt") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("%"):
                continue
            low = line.lower()
            if low.startswith("@attribute"):
                name = line.split(None, 2)[1].strip("':\"")
                names.append(name)
            elif low.startswith("@data"):
                in_data = True
            elif in_data:
                (row,) = csv.reader(io.StringIO(line), quotechar='"')
                rows.append(["" if v == "?" else v for v in row])
    return names, rows


def extract_real_tables() -> None:
    """Extract the REAL datasets that ship inside the scikit-learn wheel
    (tests/data/openml bundled samples — full tables, not truncations)
    into committed CSVs, the offline analog of the reference's dataset
    install with sha256 pinning (tools/config.sh:62-117):

    - titanic.csv: the complete 1,309-passenger Titanic manifest
      (OpenML id 40945) — real mixed-type table with missing values,
      drives e101's TrainClassifier flow. Leakage columns (boat, body)
      and free-text ids (name, ticket, cabin, home.dest) are dropped.
    - machine_cpu.csv: Relative CPU Performance, 209 real machines
      (OpenML id 561; UCI "Computer Hardware") — vendor categorical +
      numeric specs, target published relative performance; drives
      e102's TrainRegressor flow.
    """
    import csv
    import glob

    import sklearn

    openml = os.path.join(
        os.path.dirname(sklearn.__file__),
        "datasets", "tests", "data", "openml",
    )

    names, rows = _arff_to_rows(
        glob.glob(os.path.join(openml, "id_40945", "data-*.arff.gz"))[0]
    )
    keep = ["pclass", "sex", "age", "sibsp", "parch", "fare", "embarked",
            "survived"]
    idx = [names.index(k) for k in keep]
    with open(os.path.join(FIXTURES, "titanic.csv"), "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(keep)
        for row in rows:
            w.writerow([row[i] for i in idx])

    names, rows = _arff_to_rows(
        glob.glob(os.path.join(openml, "id_561", "data-*.arff.gz"))[0]
    )
    names[-1] = "performance"  # ARFF calls the target 'class'
    with open(
        os.path.join(FIXTURES, "machine_cpu.csv"), "w", newline=""
    ) as f:
        w = csv.writer(f)
        w.writerow(names)
        w.writerows(rows)


if __name__ == "__main__":
    main()
