"""Prove KV-cache decode fast on REAL TPU (VERDICT r4 next #3).

Runs ``generate()`` both ways — KV-cache decode (default) and the O(T²)
full-recompute oracle — on the chip, jitted end-to-end (prefill + scan
in ONE program, so the axon relay's per-dispatch latency is paid once
per call, not per token):

1. numerics: the cached prefill's last-position logits must match the
   standard full forward scale-normalized (the real parity check), and
   the greedy token streams must agree at >= 95% — NOT bit-exact:
   weights here are random init, so vocab-sized logit gaps sit near
   bf16 noise and a single reduction-order tie-flip diverges every
   later token; the trained-model unit test is where exact equality is
   asserted;
2. timing: per-token cost from the DIFFERENCE of two generation lengths
   (N=64 vs N=256) for each path — fixed costs (prefill, dispatch,
   host sync) cancel, leaving the marginal cost of one decode step.
   The headline is tokens/sec for the cache path and the speedup ratio;
   VERDICT r4 expects >= 5x at N=256 on the dense model.

Both paths run ``attn_impl='dense'`` so the comparison isolates the
cache machinery, not flash-vs-dense kernel differences.

Writes ``DECODE_TPU_EVIDENCE.json`` at the repo root for committing —
but ONLY when the run satisfies the committed-artifact contract that
``tests/test_decode_evidence.py`` asserts (no ``noise_fallback`` on
either path, monotone N=64 -> N=256 timings, speedup >= 1.5); a
violating run prints its evidence and exits 3 without touching the
artifact. A wedged tunnel is detected with a killable subprocess probe
first, so the script fails fast with exit 2 instead of hanging.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "DECODE_TPU_EVIDENCE.json")
sys.path.insert(0, REPO)

# serving-ish model: big enough that a step is real matmul work, small
# enough that the recompute leg's 256 full forwards stay measurable
VOCAB, D_MODEL, HEADS, DEPTH = 8192, 512, 8, 8
B, P = 8, 64
N_SHORT, N_LONG = 64, 256


def _probe(timeout_s: float = 90.0) -> str:
    code = (
        "import jax; d = jax.devices()[0]; "
        "assert 'TPU' in d.device_kind, d.device_kind; "
        "print(d.device_kind)"
    )
    r = subprocess.run([sys.executable, "-c", code],
                       timeout=timeout_s, capture_output=True, text=True)
    if r.returncode != 0:
        print("probe failed:", (r.stdout + r.stderr)[-400:], file=sys.stderr)
        sys.exit(2)
    return r.stdout.strip()


def _timed_best(fn, trials: int = 3) -> float:
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        np.asarray(fn())  # host fetch forces completion through the relay
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    try:
        kind = _probe()
    except subprocess.TimeoutExpired:
        print("probe hung (tunnel wedged)", file=sys.stderr)
        sys.exit(2)
    print(f"tunnel healthy: {kind}")

    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models import build_model, generate

    graph = build_model(
        "transformer_lm", vocab_size=VOCAB, d_model=D_MODEL, heads=HEADS,
        depth=DEPTH, max_len=P + N_LONG, attn_impl="dense",
    )
    rng = jax.random.PRNGKey(0)
    variables = graph.init(rng, jnp.zeros((1, P), jnp.int32))
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, VOCAB, size=(B, P)), jnp.int32
    )

    # compile each (length, path) program ONCE and reuse it for both the
    # numerics check and the timing trials — relay compiles cost 20-40 s
    # each and the healthy tunnel window is ~20 min total. Weights are
    # a jit ARGUMENT (not a closure constant) so the four programs
    # don't each embed the full parameter set as XLA constants.
    jitted = {
        (n, kv): jax.jit(
            lambda v, pr, n=n, kv=kv: generate(
                graph, v, pr, n, kv_cache=kv
            )
        )
        for n in (N_SHORT, N_LONG)
        for kv in (True, False)
    }

    evidence: dict = {
        "device_kind": kind,
        "model": {"vocab": VOCAB, "d_model": D_MODEL, "heads": HEADS,
                  "depth": DEPTH, "batch": B, "prompt": P},
        "method": (
            "whole generate() jitted (prefill + lax.scan in one program); "
            "per-token seconds = (t(N=256) - t(N=64)) / 192, best of 3 "
            "host-fetch-synced trials per length — fixed dispatch/prefill "
            "costs cancel in the difference"
        ),
    }

    # -- numerics ----------------------------------------------------------
    # logits parity at the prefill boundary: cached prefill's last
    # position vs the standard full forward, scale-normalized (the same
    # gate the flash evidence uses — TPU precision is relative to
    # magnitude)
    from mmlspark_tpu.models.generate import _cached_apply, init_cache

    cache0 = init_cache(graph, variables, B, P + N_SHORT)
    cached_logits, _ = jax.jit(
        lambda v, c, pr: _cached_apply(graph, v, pr, c, 0)
    )(variables, cache0, prompt)
    full_logits = jax.jit(
        lambda v, pr: graph.apply(v, pr)
    )(variables, prompt)
    got = np.asarray(cached_logits[:, -1], np.float32)
    want = np.asarray(full_logits[:, -1], np.float32)
    scaled_err = float(
        np.abs(got - want).max() / max(1.0, np.abs(want).max())
    )
    # greedy streams: random-init logit gaps sit near bf16 noise, so a
    # reduction-order tie can flip one argmax and diverge the suffix —
    # gate on agreement rate, assert exactness only up to first flip
    kv_short = np.asarray(jitted[(N_SHORT, True)](variables, prompt))
    rc_short = np.asarray(jitted[(N_SHORT, False)](variables, prompt))
    agree = float((kv_short == rc_short).mean())
    evidence["numerics"] = {
        "prefill_logits_scaled_err": scaled_err,
        "greedy_token_agreement": round(agree, 4),
        "n_tokens_compared": int(kv_short.size),
        "note": "random-init weights; exact equality on trained models "
                "is asserted by tests/test_generate.py",
    }
    print(f"numerics: prefill scaled err {scaled_err:.2e}, "
          f"greedy agreement {agree:.3f}")
    assert scaled_err <= 1e-2, ("prefill logits diverge", scaled_err)
    assert agree >= 0.95, ("greedy token agreement too low", agree)

    # -- timing ------------------------------------------------------------
    timing: dict = {}
    per_tok_s = {}
    for name, kv in (("kv_cache", True), ("recompute", False)):
        f_short, f_long = jitted[(N_SHORT, kv)], jitted[(N_LONG, kv)]
        f_short(variables, prompt)  # warm
        f_long(variables, prompt)
        t_short = _timed_best(lambda: f_short(variables, prompt))
        t_long = _timed_best(lambda: f_long(variables, prompt))
        delta = t_long - t_short
        fallback = delta <= 0  # noise swallowed the length delta
        per_tok = t_long / N_LONG if fallback else delta / (N_LONG - N_SHORT)
        per_tok_s[name] = per_tok
        timing[name] = {
            "t_n64_s": round(t_short, 4),
            "t_n256_s": round(t_long, 4),
            "per_token_ms": round(per_tok * 1e3, 4),
            "tokens_per_sec_per_seq": round(1.0 / per_tok, 1),
            "tokens_per_sec_batch": round(B / per_tok, 1),
            "noise_fallback": fallback,
        }
        print(f"{name}: {per_tok*1e3:.3f} ms/token "
              f"({B/per_tok:.0f} tok/s at batch {B})")
    speedup = per_tok_s["recompute"] / per_tok_s["kv_cache"]
    timing["kv_vs_recompute_speedup"] = round(speedup, 2)
    evidence["timing"] = timing
    print(f"kv-cache speedup vs recompute at N={N_LONG}: {speedup:.1f}x")

    # -- contract gate -----------------------------------------------------
    # tests/test_decode_evidence.py asserts these on the COMMITTED
    # artifact, so an evidence file that would fail them must never be
    # written: a run that violates the contract prints its evidence for
    # debugging and exits 3, leaving any previously-committed good
    # artifact in place.
    violations = []
    for name in ("kv_cache", "recompute"):
        if timing[name]["noise_fallback"]:
            violations.append(
                f"{name}: t(N=256) - t(N=64) <= 0 (timing noise swallowed "
                "the length delta; rerun on a quieter tunnel)"
            )
        if timing[name]["t_n256_s"] < timing[name]["t_n64_s"]:
            violations.append(
                f"{name}: t_n256 ({timing[name]['t_n256_s']}s) < t_n64 "
                f"({timing[name]['t_n64_s']}s)"
            )
    if speedup < 1.5:
        violations.append(
            f"kv_vs_recompute_speedup {speedup:.2f} < 1.5 (the committed "
            "contract floor; VERDICT r4 expects >= 5x)"
        )
    if violations:
        print("evidence FAILED its own contract; NOT writing "
              f"{os.path.basename(OUT)}:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        print(json.dumps(evidence, indent=1), file=sys.stderr)
        sys.exit(3)

    with open(OUT, "w", encoding="utf-8") as f:
        json.dump(evidence, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
