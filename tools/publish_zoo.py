"""Publish pretrained payloads into the committed local model zoo.

The reference serves pretrained CNNs with ``layerNames`` for transfer
learning from an HTTP repo (downloader/src/main/scala/
ModelDownloader.scala:109-155 ``DefaultModelRepo``). This environment has
no egress, so the zoo ships IN the repository under ``models/zoo_repo/``:
this script trains the e303 backbone and publishes it (payload + .meta +
MANIFEST + .files sidecar) so examples exercise the real
``ModelDownloader.download_by_name`` path, sha256 verification included.

Run: ``python tools/publish_zoo.py <Name ...>`` (or ``all``) — retrains
and republishes the named payloads in place; all-models churn is opt-in.
"""

from __future__ import annotations

import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ZOO = os.path.join(REPO, "models", "zoo_repo")


def _train_and_publish(name, make_data, epochs, lr) -> None:
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.models.zoo import publish_model
    from mmlspark_tpu.stages.dnn_model import TPUModel
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

    graph = build_model("resnet20_cifar10", width=8)
    imgs, y = make_data(256, seed=0)
    x = np.stack(imgs).astype(np.float32) / 255.0
    trainer = SPMDTrainer(
        graph,
        TrainConfig(epochs=epochs, batch_size=64, learning_rate=lr,
                    log_every=20),
    )
    variables = trainer.train(x, y.astype(np.int32))
    # held-out gate: a degenerate backbone must not reach the committed zoo
    h_imgs, h_y = make_data(128, seed=999)
    hx = np.stack(h_imgs).astype(np.float32) / 255.0
    pred = np.asarray(graph.apply(variables, hx)).argmax(axis=1)
    acc = float((pred == h_y).mean())
    assert acc > 0.9, f"{name}: held-out accuracy {acc} too low to publish"
    stage = TPUModel.from_graph(
        graph, variables, "resnet20_cifar10", model_config={"width": 8},
        input_col="image", output_col="scores",
    )
    with tempfile.TemporaryDirectory() as tmp:
        payload = os.path.join(tmp, name.lower())
        stage.save(payload)
        schema = publish_model(
            ZOO,
            name,
            payload,
            input_node="image",
            layer_names=tuple(graph.layer_names),
            dataset=f"synthetic-{name.split('_')[-1].lower()}",
            model_type="image-classifier",
            extra={"width": 8, "input_scale": "1/255"},
        )
    print(f"published {schema.name} -> {ZOO} (sha256 {schema.hash[:12]}…, "
          f"{schema.size} bytes, held-out acc {acc:.3f})")


def _train_and_publish_digits(
    name: str,
    classes: tuple = (0, 1, 2, 3, 4),
    max_shift: int = 4,
    copies: int = 8,
    train_frac: float = 0.85,
    epochs: int = 6,
    min_acc: float = 0.9,
) -> None:
    """The REAL-capability backbones: full-width ResNet-20 trained on the
    scikit-learn handwritten-digit scans (real images), shift-augmented so
    the features survive unregistered inputs — the transfer-learning
    property the reference zoo's ImageNet CNNs provide
    (ModelDownloader.scala:109-155). Two published variants:

    - ``ResNet20_Digits04`` (classes 0-4, 85% label budget): the e303/e305
      transfer source — its features transfer to the UNSEEN digits 5-9.
    - ``ResNet20_Digits10`` (all 10 classes, 25% label budget): the
      EVIDENCE backbone. The 5-class/85%-label variant saturates its
      held-out set (test_accuracy 1.0 — a ceiling that cannot distinguish
      a good backbone from a memorized one); the 10-class low-label task
      is hard enough that the recorded accuracy can move, so regressions
      in the conv stack show up as a number, not a hidden ceiling."""
    from mmlspark_tpu.data.sample_data import load_digit_images
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.models.zoo import publish_model
    from mmlspark_tpu.stages.dnn_model import TPUModel
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig
    # split by UNDERLYING image before augmenting: augmented copies of a
    # held-out digit must never appear in training
    _, y = load_digit_images(classes)
    n = len(y)
    order = np.random.default_rng(0).permutation(n)
    cut = int(train_frac * n)
    tr_idx, te_idx = order[:cut], order[cut:]  # exact complements
    xs, ys = [], []
    for s in range(copies):
        imgs, _ = load_digit_images(classes, max_shift=max_shift, seed=s)
        xs.append(imgs[tr_idx])
        ys.append(y[tr_idx])
    x = np.concatenate(xs).astype(np.float32) / 255.0
    yy = np.concatenate(ys).astype(np.int32)

    graph = build_model("resnet20_cifar10", num_classes=len(classes))
    trainer = SPMDTrainer(
        graph,
        TrainConfig(
            epochs=epochs, batch_size=128, learning_rate=2e-3,
            optimizer="adam", lr_schedule="cosine", seed=0, log_every=50,
        ),
    )
    variables = trainer.train(x, yy)

    h_imgs, _ = load_digit_images(classes, max_shift=max_shift, seed=997)
    hx = h_imgs[te_idx].astype(np.float32) / 255.0
    pred = np.asarray(graph.apply(variables, hx)).argmax(axis=1)
    acc = float((pred == y[te_idx]).mean())
    assert acc > min_acc, (
        f"{name}: held-out accuracy {acc} too low to publish"
    )

    stage = TPUModel.from_graph(
        graph, variables, "resnet20_cifar10",
        model_config={"num_classes": len(classes)},
        input_col="image", output_col="scores",
    )
    with tempfile.TemporaryDirectory() as tmp:
        payload = os.path.join(tmp, name.lower())
        stage.save(payload)
        schema = publish_model(
            ZOO,
            name,
            payload,
            input_node="image",
            layer_names=tuple(graph.layer_names),
            dataset=f"sklearn-digits {min(classes)}-{max(classes)} (real "
                    f"handwritten scans), shift-augmented ±{max_shift}px",
            model_type="image-classifier",
            extra={
                "input_scale": "1/255",
                "classes": list(classes),
                "max_shift": max_shift,
                "train_label_budget": f"{train_frac:.0%} of scans, "
                                      f"x{copies} shift copies",
                "test_accuracy": round(acc, 4),
                "test_condition": f"held-out digits, random ±{max_shift}px "
                                  "placement (unregistered)",
            },
        )
    print(f"published {schema.name} -> {ZOO} (sha256 {schema.hash[:12]}…, "
          f"{schema.size} bytes, held-out acc {acc:.3f})")


def main() -> None:
    sys.path.insert(0, REPO)
    from mmlspark_tpu.testing.datagen import bar_images, blob_images

    specs = {
        "ResNet20_Blobs": lambda: _train_and_publish(
            "ResNet20_Blobs", blob_images, epochs=15, lr=1e-2
        ),
        # bars: position-invariant orientation — the conv-vs-raw-pixel
        # comparison backbone
        "ResNet20_Bars": lambda: _train_and_publish(
            "ResNet20_Bars", bar_images, epochs=40, lr=1e-2
        ),
        # real data: trained on sklearn digit scans (see function doc)
        "ResNet20_Digits04": lambda: _train_and_publish_digits(
            "ResNet20_Digits04"
        ),
        # evidence backbone: 10 classes at a 25% label budget — held-out
        # accuracy lands OFF the 1.0 ceiling so the number can move
        "ResNet20_Digits10": lambda: _train_and_publish_digits(
            "ResNet20_Digits10", classes=tuple(range(10)),
            train_frac=0.25, copies=6, epochs=8, min_acc=0.75,
        ),
    }
    # republish only the named models (training is not bit-reproducible,
    # so republishing everything churns every committed payload); the
    # all-models run is opt-in via an explicit "all"
    if not sys.argv[1:]:
        raise SystemExit(
            "name the model(s) to republish, or 'all' for every one of: "
            + ", ".join(specs)
        )
    selected = list(specs) if sys.argv[1:] == ["all"] else sys.argv[1:]
    for name in selected:
        if name not in specs:
            raise SystemExit(
                f"unknown model {name!r}; valid names: {', '.join(specs)}"
            )
        specs[name]()


if __name__ == "__main__":
    main()
