"""Publish pretrained payloads into the committed local model zoo.

The reference serves pretrained CNNs with ``layerNames`` for transfer
learning from an HTTP repo (downloader/src/main/scala/
ModelDownloader.scala:109-155 ``DefaultModelRepo``). This environment has
no egress, so the zoo ships IN the repository under ``models/zoo_repo/``:
this script trains the e303 backbone and publishes it (payload + .meta +
MANIFEST + .files sidecar) so examples exercise the real
``ModelDownloader.download_by_name`` path, sha256 verification included.

Run: ``python tools/publish_zoo.py`` (idempotent; regenerates in place).
"""

from __future__ import annotations

import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ZOO = os.path.join(REPO, "models", "zoo_repo")


def main() -> None:
    sys.path.insert(0, REPO)
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.testing.datagen import blob_images
    from mmlspark_tpu.models.zoo import publish_model
    from mmlspark_tpu.stages.dnn_model import TPUModel
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

    graph = build_model("resnet20_cifar10", width=8)
    imgs, y = blob_images(256, seed=0)
    x = np.stack(imgs).astype(np.float32) / 255.0
    trainer = SPMDTrainer(
        graph,
        TrainConfig(epochs=15, batch_size=64, learning_rate=1e-2,
                    log_every=20),
    )
    variables = trainer.train(x, y.astype(np.int32))
    stage = TPUModel.from_graph(
        graph, variables, "resnet20_cifar10", model_config={"width": 8},
        input_col="image", output_col="scores",
    )
    with tempfile.TemporaryDirectory() as tmp:
        payload = os.path.join(tmp, "resnet20_blobs")
        stage.save(payload)
        schema = publish_model(
            ZOO,
            "ResNet20_Blobs",
            payload,
            input_node="image",
            layer_names=tuple(graph.layer_names),
            dataset="synthetic-blobs",
            model_type="image-classifier",
            extra={"width": 8, "input_scale": "1/255"},
        )
    print(f"published {schema.name} -> {ZOO} (sha256 {schema.hash[:12]}…, "
          f"{schema.size} bytes)")


if __name__ == "__main__":
    main()
