"""Capture a jax.profiler trace of the ResNet-50 forward on the chip.

VERDICT r3 prescription #2: if `resnet50_mfu` lands below the 0.40
target, commit profiler evidence of the residual blocker. This script
produces that evidence: a device trace of the compiled forward (the same
program the bench times) written under ``profiles/resnet50/`` plus a
printed summary of where the step time goes. Run it on a healthy tunnel:

    python tools/profile_resnet50.py [--size 224 --batch 256]

A wedged tunnel is detected with a killable probe first (exit 2).
TensorBoard reads the trace directory; the raw .pb/.json.gz files are
small enough to commit alongside BENCH_LOCAL artifacts.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _probe(timeout_s: float = 90.0) -> None:
    code = (
        "import jax; "
        "print(jax.default_backend(), jax.devices()[0].device_kind)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        print("tunnel wedged (probe hung)")
        raise SystemExit(2)
    if r.returncode != 0 or "tpu" not in r.stdout.lower():
        print(f"no TPU backend: {r.stdout.strip()}")
        raise SystemExit(2)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument(
        "--out", default=os.path.join(REPO, "profiles", "resnet50")
    )
    args = ap.parse_args()
    _probe()

    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.models import build_model

    graph = build_model("resnet50", input_size=args.size)
    variables = graph.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, args.size, args.size, 3), jnp.float32),
    )
    x = jnp.asarray(
        np.random.default_rng(0).normal(
            size=(args.batch, args.size, args.size, 3)
        ),
        jnp.bfloat16,
    )
    fwd = jax.jit(lambda v, x: graph.apply(v, x).mean())
    np.asarray(fwd(variables, x))  # compile outside the trace

    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        for _ in range(args.iters):
            np.asarray(fwd(variables, x))  # host fetch = sync per step

    t0 = time.perf_counter()
    for _ in range(args.iters):
        np.asarray(fwd(variables, x))
    dt = (time.perf_counter() - t0) / args.iters
    print(
        f"traced {args.iters} steps -> {args.out}\n"
        f"untraced step: {dt * 1e3:.2f} ms "
        f"({args.batch / dt:.0f} img/s) at ({args.batch}, {args.size})\n"
        "inspect: tensorboard --logdir "
        + args.out
    )


if __name__ == "__main__":
    main()
