"""Prove the Pallas flash kernels on REAL TPU (VERDICT r3 missing #3).

The flash forward/backward kernels (ops/flash_attention.py) are exercised
by the unit suite only in interpreter mode on the CPU mesh — a kernel that
has only ever been interpreted is not yet a TPU kernel. This script runs
OUTSIDE interpreter mode on the chip:

1. compiles forward + backward at (B=4, S=2048, H=8, D=64) bfloat16,
2. asserts numerics against the XLA einsum-softmax reference — forward
   and all three input gradients, causal and non-causal, PLUS a
   sliding-window + grouped-query case (window=256, kv_heads=2, fwd and
   grads vs the dense reference: the window-edge dead-block skipping
   and dK/dV group reduction are compiled paths the plain legs never
   execute) — gated on SCALE-NORMALIZED error (max abs err /
   max(1, max|want|) <= 1e-2; see ``_scaled_err`` for why raw abs error
   is the wrong metric on a platform whose precision is relative to
   magnitude),
3. times a block-size sweep (128/256/512) of the compiled forward and
   forward+backward with bench.py's ``_chained_op_seconds`` harness —
   the DIFFERENCE of two ``lax.scan``-chained runs (n1=8, n2=40 data-
   dependent iterations, one jit each), which cancels the axon relay's
   fixed per-dispatch tunnel latency (~50 ms, vs a sub-ms kernel)
   exactly — plus an identically-harnessed XLA attention for an
   on-chip speedup ratio,
4. times a long-context leg at S=8192 (``timing.long_context_s8192``):
   the fused kernel stays O(S·d) in VMEM while the XLA path pushes a
   ~2.1 GB (S, S) f32 score tensor through HBM each step — the regime
   the kernel exists for; the flash number is recorded even if the XLA
   side OOMs (that failure being evidence itself). Includes a
   window=1024 sliding-window run, whose O(S·W) work should land well
   under the full O(S²) time,
5. writes ``FLASH_TPU_EVIDENCE.json`` at the repo root for committing.

A wedged tunnel is detected with a killable subprocess probe first, so
the script fails fast with exit 2 instead of hanging.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "FLASH_TPU_EVIDENCE.json")
sys.path.insert(0, REPO)  # for `from bench import _chained_op_seconds`

B, S, H, D = 4, 2048, 8, 64
BLOCKS = (128, 256, 512)
TOL = 1e-2


def _scaled_err(got: np.ndarray, want: np.ndarray) -> float:
    """Max abs error normalized by the tensor's scale, max(1, max|want|).

    Precision on TPU is RELATIVE to magnitude, and that is true of BOTH
    sides of the comparison: the kernel emits bfloat16 (quantization eps
    2^-8 of the value), and the XLA einsum reference itself runs its
    matmuls at the platform's default precision (bf16 mantissas on the
    MXU) — measured on TPU v5e, rerunning the comparison with float32
    inputs still leaves ~8e-3 abs differences, so the gap is two
    differently-ordered reduced-precision computations, not kernel math.
    Causal attention makes the magnitudes large: early query rows emit
    near-raw ``v`` values (|out| up to ~3.3) and the S=2048 gradients
    reach |dk| ~ 3-5, so a raw abs gate at 1e-2 fails on platform
    precision alone (5 * 2^-8 ~ 2e-2) while a real kernel bug (e.g. a
    mask off-by-one) would move outputs by O(max|want|) and still trip
    the normalized gate by orders of magnitude.
    """
    scale = max(1.0, float(np.max(np.abs(want))))
    return float(np.max(np.abs(got - want))) / scale


def _probe(timeout_s: float = 90.0) -> str:
    code = "import jax; print(jax.default_backend(), jax.devices()[0].device_kind)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        raise SystemExit(2)
    if r.returncode != 0 or "tpu" not in r.stdout.lower():
        print(f"no TPU backend: {r.stdout.strip()} {r.stderr.strip()[-200:]}")
        raise SystemExit(2)
    return r.stdout.strip()


def main() -> None:
    print("probe:", _probe())
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.core.env import is_tpu
    from mmlspark_tpu.ops.flash_attention import flash_attention

    assert is_tpu(), (jax.default_backend(), jax.devices()[0].device_kind)
    kind = jax.devices()[0].device_kind
    rng = np.random.default_rng(0)
    q, k, v, g = (
        jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.bfloat16)
        for _ in range(4)
    )

    def reference(q, k, v, causal):
        # einsum-softmax in f32 on the same bf16 inputs
        qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * (D ** -0.5)
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vf)

    evidence: dict = {
        "device_kind": kind,
        "shape": {"B": B, "S": S, "H": H, "D": D, "dtype": "bfloat16"},
        "tolerance": TOL,
        "numerics": {},
        "timing": {},
    }

    # -- numerics: compiled (interpret=False) vs XLA reference -------------
    for causal in (False, True):
        name = "causal" if causal else "full"
        flash = jax.jit(
            lambda q, k, v, c=causal: flash_attention(
                q, k, v, causal=c, interpret=False
            )
        )
        ref = jax.jit(lambda q, k, v, c=causal: reference(q, k, v, c))
        out = np.asarray(flash(q, k, v), np.float32)
        want = np.asarray(ref(q, k, v), np.float32)
        fwd_abs = float(np.max(np.abs(out - want)))
        fwd_err = _scaled_err(out, want)

        def loss_flash(q, k, v, c=causal):
            return jnp.sum(
                flash_attention(q, k, v, causal=c, interpret=False)
                .astype(jnp.float32) * g.astype(jnp.float32)
            )

        def loss_ref(q, k, v, c=causal):
            return jnp.sum(reference(q, k, v, c) * g.astype(jnp.float32))

        gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        grad_errs = {
            n: _scaled_err(np.asarray(a, np.float32),
                           np.asarray(b, np.float32))
            for n, a, b in zip(("dq", "dk", "dv"), gf, gr)
        }
        evidence["numerics"][name] = {
            "fwd_max_abs_err": fwd_abs,
            "fwd_scaled_err": fwd_err,
            **{f"{n}_scaled_err": e for n, e in grad_errs.items()},
        }
        assert fwd_err <= TOL, (name, fwd_err)
        assert all(e <= TOL for e in grad_errs.values()), (name, grad_errs)
        print(f"numerics[{name}]: fwd {fwd_err:.2e} (abs {fwd_abs:.2e}) "
              "grads "
              + " ".join(f"{n}={e:.2e}" for n, e in grad_errs.items()))

    # -- numerics: sliding window + GQA, compiled, vs dense reference ------
    # the dense reference handles the kv-head repeat and window mask
    # (tests pin its exactness on CPU); here it certifies the COMPILED
    # kernel's windowed/grouped paths on the chip
    from mmlspark_tpu.ops.attention import dense_attention

    W, HKV = 256, 2
    kg, vg = (
        jnp.asarray(rng.normal(size=(B, S, HKV, D)), jnp.bfloat16)
        for _ in range(2)
    )
    wout = np.asarray(jax.jit(
        lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=W, interpret=False)
    )(q, kg, vg), np.float32)
    wwant = np.asarray(jax.jit(
        lambda q, k, v: dense_attention(q, k, v, causal=True, window=W)
    )(q, kg, vg), np.float32)
    werr = _scaled_err(wout, wwant)

    # ...and the backward: the window-edge dead-block skipping and the
    # dK/dV group reduction are window/GQA-specific compiled paths that
    # the full/causal legs above never execute
    def wloss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, window=W,
                            interpret=False)
            .astype(jnp.float32) * g.astype(jnp.float32)
        )

    def wloss_ref(q, k, v):
        return jnp.sum(
            dense_attention(q, k, v, causal=True, window=W)
            .astype(jnp.float32) * g.astype(jnp.float32)
        )

    wgf = jax.jit(jax.grad(wloss_flash, argnums=(0, 1, 2)))(q, kg, vg)
    wgr = jax.jit(jax.grad(wloss_ref, argnums=(0, 1, 2)))(q, kg, vg)
    wgrad_errs = {
        n: _scaled_err(np.asarray(a, np.float32),
                       np.asarray(b, np.float32))
        for n, a, b in zip(("dq", "dk", "dv"), wgf, wgr)
    }
    evidence["numerics"]["window_gqa"] = {
        "window": W, "kv_heads": HKV,
        "fwd_scaled_err": werr,
        "fwd_max_abs_err": float(np.max(np.abs(wout - wwant))),
        **{f"{n}_scaled_err": e for n, e in wgrad_errs.items()},
    }
    assert werr <= TOL, ("window_gqa", werr)
    assert all(e <= TOL for e in wgrad_errs.values()), (
        "window_gqa", wgrad_errs)
    print(f"numerics[window_gqa]: fwd {werr:.2e} (W={W}, h_kv={HKV}) "
          "grads "
          + " ".join(f"{n}={e:.2e}" for n, e in wgrad_errs.items()))

    # -- timing: block sweep, forward and forward+backward -----------------
    # A single dispatch over the axon relay costs tens of ms of tunnel
    # latency, which at this shape (~34 GFLOP forward) swamps the on-chip
    # time entirely — a naive per-call wall clock reads ~50 ms where the
    # kernel itself is sub-ms, and even one long chain leaves latency/len
    # residue. bench.py's _chained_op_seconds (imported — ONE
    # implementation, two artifacts) times two scan-chained programs of
    # different lengths and differences them, cancelling every fixed
    # per-dispatch cost; it returns a flag when tunnel noise forced the
    # t(n2)/n2 fallback, which each measurement records.
    from bench import _chained_op_seconds

    attn_flops_fwd = 4 * B * H * S * S * D  # QK^T + PV matmuls

    def _per_iter_s(step) -> tuple:
        return _chained_op_seconds(jax, jnp, step, q, k, v)

    # XLA einsum-softmax attention, timed under the identical harness:
    # the honest on-chip comparison target for the Pallas kernel.
    def xla_step(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * (D ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p.astype(jnp.bfloat16), v)

    t_xla, fb_xla = _per_iter_s(xla_step)
    evidence["timing"]["xla_reference"] = {
        "fwd_ms": round(t_xla * 1e3, 3),
        "fwd_tflops_per_s": round(attn_flops_fwd / t_xla / 1e12, 2),
        "noise_fallback_t_over_n": fb_xla,
    }
    print(f"xla reference: fwd {t_xla*1e3:.2f} ms/iter "
          f"({attn_flops_fwd / t_xla / 1e12:.1f} TFLOP/s)")

    for blk in BLOCKS:
        t_f, fb_f = _per_iter_s(
            lambda qq, k, v, b=blk: flash_attention(
                qq, k, v, block=b, interpret=False)
        )

        def loss(q, k, v, b=blk):
            return jnp.sum(
                flash_attention(q, k, v, block=b, interpret=False)
                .astype(jnp.float32) * g.astype(jnp.float32)
            )

        # fwd+bwd chained. ALL THREE grads must feed the carry: the
        # backward is two independent pallas_calls (dK/dV and dQ), so
        # consuming only dq would let XLA dead-code-eliminate the dK/dV
        # kernel and report roughly half the real backward cost.
        grad_all = jax.grad(loss, argnums=(0, 1, 2))
        t_fb, fb_b = _per_iter_s(
            lambda qq, k, v, ga=grad_all: sum(
                ga(qq, k, v)).astype(jnp.bfloat16)
        )
        evidence["timing"][f"block_{blk}"] = {
            "fwd_ms": round(t_f * 1e3, 3),
            "fwd_bwd_ms": round(t_fb * 1e3, 3),
            "fwd_tflops_per_s": round(attn_flops_fwd / t_f / 1e12, 2),
            "vs_xla_fwd_speedup": round(t_xla / t_f, 3),
            "noise_fallback_t_over_n": fb_f or fb_b,
        }
        print(f"block {blk}: fwd {t_f*1e3:.2f} ms "
              f"({attn_flops_fwd / t_f / 1e12:.1f} TFLOP/s, "
              f"{t_xla / t_f:.2f}x XLA), fwd+bwd {t_fb*1e3:.2f} ms")

    # -- long-context leg: the regime the kernel exists for ---------------
    # at S=8192 the XLA path materializes an (S, S) f32 score tensor
    # (~2.1 GB at B=1, H=8) through HBM every step, while the fused
    # kernel stays O(S·d) in VMEM — this is where fusion must WIN, not
    # just match. Timed under the identical chained harness; guarded so
    # an OOM or compile failure cannot cost the rest of the artifact.
    try:
        SL = 8192
        blk_best = min(
            BLOCKS,
            key=lambda b: evidence["timing"][f"block_{b}"]["fwd_ms"],
        )
        ql, kl, vl = (
            jnp.asarray(rng.normal(size=(1, SL, H, D)), jnp.bfloat16)
            for _ in range(3)
        )
        flops_l = 4 * 1 * H * SL * SL * D

        def _long(step):
            return _chained_op_seconds(jax, jnp, step, ql, kl, vl)

        t_lf, fb_lf = _long(
            lambda qq, k, v: flash_attention(
                qq, k, v, block=blk_best, interpret=False)
        )
        # record flash IMMEDIATELY: if the window or XLA legs then fail
        # (OOM, compile, tunnel), those failures are themselves evidence
        # and must not erase this number
        long_ev = {
            "block": blk_best,
            "flash_fwd_ms": round(t_lf * 1e3, 3),
            "flash_tflops_per_s": round(flops_l / t_lf / 1e12, 2),
            "noise_fallback_t_over_n": fb_lf,
        }
        evidence["timing"]["long_context_s8192"] = long_ev
        print(f"long-context S={SL}: flash {t_lf*1e3:.2f} ms "
              f"({flops_l / t_lf / 1e12:.1f} TFLOP/s)")
        try:
            # sliding window at the same length: work is O(S·W) not
            # O(S²), so W=1024 runs ~8x less attention math than full
            t_lw, fb_lw = _long(
                lambda qq, k, v: flash_attention(
                    qq, k, v, causal=True, window=1024, block=blk_best,
                    interpret=False)
            )
            long_ev.update(
                window1024_fwd_ms=round(t_lw * 1e3, 3),
                window1024_vs_full_speedup=round(t_lf / t_lw, 3),
                noise_fallback_t_over_n=(
                    long_ev["noise_fallback_t_over_n"] or fb_lw
                ),
            )
            print(f"  window=1024 {t_lw*1e3:.2f} ms "
                  f"({t_lf / t_lw:.2f}x vs full)")
        except Exception as e:  # noqa: BLE001
            long_ev["window1024_error"] = (
                f"{type(e).__name__}: {str(e)[:200]}"
            )
            print("  window leg failed (flash number kept):",
                  type(e).__name__, str(e)[:120])
        try:
            t_lx, fb_lx = _long(lambda qq, k, v: xla_step(qq, k, v))
            long_ev.update(
                xla_fwd_ms=round(t_lx * 1e3, 3),
                vs_xla_fwd_speedup=round(t_lx / t_lf, 3),
                noise_fallback_t_over_n=(
                    long_ev["noise_fallback_t_over_n"] or fb_lx
                ),
            )
            print(f"  xla {t_lx*1e3:.2f} ms -> {t_lx/t_lf:.2f}x")
        except Exception as e:  # noqa: BLE001
            long_ev["xla_error"] = f"{type(e).__name__}: {str(e)[:200]}"
            print("  xla side failed (flash number kept):",
                  type(e).__name__, str(e)[:120])
    except Exception as e:  # noqa: BLE001 — leg is additive evidence
        evidence["timing"]["long_context_s8192"] = {
            "error": f"{type(e).__name__}: {str(e)[:200]}"
        }
        print("long-context leg failed:", type(e).__name__, str(e)[:120])

    evidence["timing"]["method"] = (
        "difference of two lax.scan-chained runs (n1=8, n2=40) inside "
        "one jit each (bench.py _chained_op_seconds), best-of-3 trials, "
        "host-fetch sync; per-iter = (t(n2)-t(n1))/(n2-n1), cancelling "
        "fixed per-dispatch relay latency — except where a measurement "
        "records noise_fallback_t_over_n=true, meaning tunnel noise "
        "forced t(n2)/n2, which retains ~latency/n2 relay residue"
    )

    evidence["compiled"] = True
    evidence["interpret_mode"] = False
    with open(OUT, "w", encoding="utf-8") as f:
        json.dump(evidence, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
