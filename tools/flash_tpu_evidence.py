"""Prove the Pallas flash kernels on REAL TPU (VERDICT r3 missing #3).

The flash forward/backward kernels (ops/flash_attention.py) are exercised
by the unit suite only in interpreter mode on the CPU mesh — a kernel that
has only ever been interpreted is not yet a TPU kernel. This script runs
OUTSIDE interpreter mode on the chip:

1. compiles forward + backward at (B=4, S=2048, H=8, D=64) bfloat16,
2. asserts numerics against the XLA einsum-softmax reference — forward
   and all three input gradients within bf16 tolerance (<= 1e-2),
   causal and non-causal,
3. times a block-size sweep (128/256/512) of the compiled forward and
   forward+backward around a forced host fetch (the axon relay makes
   ``block_until_ready`` unreliable — see .claude/skills/verify),
4. writes ``FLASH_TPU_EVIDENCE.json`` at the repo root for committing.

A wedged tunnel is detected with a killable subprocess probe first, so
the script fails fast with exit 2 instead of hanging.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "FLASH_TPU_EVIDENCE.json")

B, S, H, D = 4, 2048, 8, 64
BLOCKS = (128, 256, 512)
TOL = 1e-2


def _probe(timeout_s: float = 90.0) -> str:
    code = "import jax; print(jax.default_backend(), jax.devices()[0].device_kind)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        raise SystemExit(2)
    if r.returncode != 0 or "tpu" not in r.stdout.lower():
        print(f"no TPU backend: {r.stdout.strip()} {r.stderr.strip()[-200:]}")
        raise SystemExit(2)
    return r.stdout.strip()


def _timed_best(fn, trials: int = 3) -> float:
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        np.asarray(fn())  # forced host fetch = sync point
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    print("probe:", _probe())
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.core.env import is_tpu
    from mmlspark_tpu.ops.flash_attention import flash_attention

    assert is_tpu(), (jax.default_backend(), jax.devices()[0].device_kind)
    kind = jax.devices()[0].device_kind
    rng = np.random.default_rng(0)
    q, k, v, g = (
        jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.bfloat16)
        for _ in range(4)
    )

    def reference(q, k, v, causal):
        # einsum-softmax in f32 on the same bf16 inputs
        qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * (D ** -0.5)
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, vf)

    evidence: dict = {
        "device_kind": kind,
        "shape": {"B": B, "S": S, "H": H, "D": D, "dtype": "bfloat16"},
        "tolerance": TOL,
        "numerics": {},
        "timing": {},
    }

    # -- numerics: compiled (interpret=False) vs XLA reference -------------
    for causal in (False, True):
        name = "causal" if causal else "full"
        flash = jax.jit(
            lambda q, k, v, c=causal: flash_attention(
                q, k, v, causal=c, interpret=False
            )
        )
        ref = jax.jit(lambda q, k, v, c=causal: reference(q, k, v, c))
        out = np.asarray(flash(q, k, v), np.float32)
        want = np.asarray(ref(q, k, v), np.float32)
        fwd_err = float(np.max(np.abs(out - want)))

        def loss_flash(q, k, v, c=causal):
            return jnp.sum(
                flash_attention(q, k, v, causal=c, interpret=False)
                .astype(jnp.float32) * g.astype(jnp.float32)
            )

        def loss_ref(q, k, v, c=causal):
            return jnp.sum(reference(q, k, v, c) * g.astype(jnp.float32))

        gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        grad_errs = {
            n: float(np.max(np.abs(
                np.asarray(a, np.float32) - np.asarray(b, np.float32)
            )))
            for n, a, b in zip(("dq", "dk", "dv"), gf, gr)
        }
        evidence["numerics"][name] = {"fwd_max_abs_err": fwd_err,
                                      **grad_errs}
        assert fwd_err <= TOL, (name, fwd_err)
        assert all(e <= TOL for e in grad_errs.values()), (name, grad_errs)
        print(f"numerics[{name}]: fwd {fwd_err:.2e} grads "
              + " ".join(f"{n}={e:.2e}" for n, e in grad_errs.items()))

    # -- timing: block sweep, forward and forward+backward -----------------
    attn_flops_fwd = 4 * B * H * S * S * D  # QK^T + PV matmuls
    for blk in BLOCKS:
        fwd = jax.jit(
            lambda q, k, v, b=blk: flash_attention(
                q, k, v, block=b, interpret=False
            ).astype(jnp.float32).mean()
        )

        def loss(q, k, v, b=blk):
            return jnp.sum(
                flash_attention(q, k, v, block=b, interpret=False)
                .astype(jnp.float32) * g.astype(jnp.float32)
            )

        fwdbwd = jax.jit(
            lambda q, k, v, f=loss: sum(
                t.astype(jnp.float32).sum()
                for t in jax.grad(f, argnums=(0, 1, 2))(q, k, v)
            )
        )
        np.asarray(fwd(q, k, v)), np.asarray(fwdbwd(q, k, v))  # compile
        t_f = _timed_best(lambda: fwd(q, k, v))
        t_fb = _timed_best(lambda: fwdbwd(q, k, v))
        evidence["timing"][f"block_{blk}"] = {
            "fwd_ms": round(t_f * 1e3, 3),
            "fwd_bwd_ms": round(t_fb * 1e3, 3),
            "fwd_tflops_per_s": round(attn_flops_fwd / t_f / 1e12, 2),
        }
        print(f"block {blk}: fwd {t_f*1e3:.2f} ms "
              f"({attn_flops_fwd / t_f / 1e12:.1f} TFLOP/s), "
              f"fwd+bwd {t_fb*1e3:.2f} ms")

    evidence["compiled"] = True
    evidence["interpret_mode"] = False
    with open(OUT, "w", encoding="utf-8") as f:
        json.dump(evidence, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
