"""Regenerate the recorded learner-benchmark metrics fixture.

The reference commits ``benchmarkMetrics.csv`` next to its TrainClassifier
suite and asserts every (dataset, learner) retrain reproduces the recorded
accuracy line-by-line (VerifyTrainClassifier.scala:41-42,224-240). Same
artifact here: ``tests/fixtures/benchmark_metrics.csv`` holds
``dataset,learner,accuracy,auc`` rows produced by this script, and
``tests/test_benchmark_metrics.py`` re-runs the matrix against it.

Run: ``python tools/make_benchmark_metrics.py`` (CPU mesh; seeds fixed).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "tests", "fixtures", "benchmark_metrics.csv")
REG_OUT = os.path.join(
    REPO, "tests", "fixtures", "benchmark_metrics_regression.csv"
)


def main() -> None:
    sys.path.insert(0, REPO)
    from mmlspark_tpu.testing.benchmark_metrics import (
        run_matrix,
        run_regressor_matrix,
    )

    rows = run_matrix()
    with open(OUT, "w") as f:
        f.write("dataset,learner,accuracy,auc\n")
        for r in rows:
            f.write(f"{r.dataset},{r.learner},{r.accuracy:.4f},{r.auc}\n")
    print(f"wrote {len(rows)} rows -> {OUT}")

    reg_rows = run_regressor_matrix()
    with open(REG_OUT, "w") as f:
        f.write("dataset,learner,r2,rmse\n")
        for r in reg_rows:
            f.write(f"{r.dataset},{r.learner},{r.r2:.4f},{r.rmse:.4f}\n")
    print(f"wrote {len(reg_rows)} rows -> {REG_OUT}")


if __name__ == "__main__":
    main()
