"""API documentation generator.

Role of the reference's codegen doc pipeline (codegen/src/main/scala/
DocGen.scala + WrapperClassDoc.scala: per-class .rst emitted from
colocated doc text, assembled into a sphinx tree). The TPU framework's
Python API is the API (SURVEY.md §7: the codegen layer is an intentional
architectural delta), so docs generate straight from the live registries:
every registered pipeline stage's docstring + param table, and every
registered model builder.

Usage: python tools/docgen.py [output_dir]   (default docs/api)
"""

from __future__ import annotations

import os
import sys
from collections import defaultdict

# runnable from a checkout: tools/ sits next to the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _import_all() -> None:
    # importing the packages populates the registries
    import mmlspark_tpu.stages  # noqa: F401
    import mmlspark_tpu.models  # noqa: F401
    import mmlspark_tpu.data.readers  # noqa: F401


def _underline(text: str, ch: str) -> str:
    return f"{text}\n{ch * len(text)}\n"


def _param_table(cls) -> list[str]:
    rows = []
    for name, p in sorted(cls.params().items()):
        default = "required" if p.required else repr(p.get_default())
        domain = " | ".join(p.domain) if p.domain else ""
        doc = (p.doc or "").replace("\n", " ")
        rows.append((name, default, domain, doc))
    if not rows:
        return ["(no parameters)", ""]
    widths = [max(len(r[i]) for r in rows + [_HDR]) for i in range(4)]

    def fmt(r):
        return "  ".join(v.ljust(w) for v, w in zip(r, widths)).rstrip()

    sep = tuple("=" * w for w in widths)
    return [fmt(sep), fmt(_HDR), fmt(sep), *(fmt(r) for r in rows),
            fmt(sep), ""]


_HDR = ("param", "default", "domain", "doc")


def generate(out_dir: str) -> list[str]:
    """Write one .rst per stage module + models.rst + index.rst; returns
    the written paths."""
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.models.registry import registered_models

    _import_all()
    os.makedirs(out_dir, exist_ok=True)
    by_module: dict[str, list[type]] = defaultdict(list)
    for name, cls in sorted(PipelineStage.registry().items()):
        mod = cls.__module__.rsplit(".", 1)[-1]
        by_module[mod].append(cls)

    written = []
    for mod, classes in sorted(by_module.items()):
        lines = [_underline(mod, "="), ""]
        for cls in classes:
            lines.append(_underline(cls.__name__, "-"))
            # own docstring, else the module overview (many stage classes
            # document the family at module level, like the reference's
            # colocated .txt doc files)
            doc = cls.__dict__.get("__doc__")
            if not doc:
                module = sys.modules.get(cls.__module__)
                mod_doc = (module.__doc__ or "") if module else ""
                doc = mod_doc.split("\n\n")[0] or "(undocumented)"
            lines.append(doc.strip())
            lines.append("")
            lines.extend(_param_table(cls))
        path = os.path.join(out_dir, f"{mod}.rst")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        written.append(path)

    # model registry page
    lines = [_underline("models", "="), "",
             "Registered model architectures (``build_model`` names):", ""]
    for name in registered_models():
        from mmlspark_tpu.models.registry import _BUILDERS

        fn = _BUILDERS[name]
        doc = (fn.__doc__ or "(undocumented)").strip().replace("\n", " ")
        lines.append(f"``{name}``")
        lines.append(f"    {doc}")
        lines.append("")
    path = os.path.join(out_dir, "models.rst")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    written.append(path)

    # index
    entries = "\n".join(
        f"   {os.path.splitext(os.path.basename(p))[0]}" for p in written
    )
    index = os.path.join(out_dir, "index.rst")
    with open(index, "w") as f:
        f.write(
            _underline("API reference", "=")
            + "\n.. toctree::\n   :maxdepth: 1\n\n"
            + entries + "\n"
        )
    written.append(index)
    return written


# -- HTML assembly -----------------------------------------------------------
#
# The reference assembles its generated .rst with a sphinx build
# (tools/pydocs). This image has neither sphinx nor docutils and no
# egress, so render_html() converts the exact .rst subset generate()
# emits (titles, sections, paragraphs, literals, simple-format tables)
# into a static HTML site; docs/conf.py remains for sphinx-equipped
# environments.

_CSS = """body{font-family:sans-serif;max-width:60em;margin:2em auto;
padding:0 1em;color:#222}table{border-collapse:collapse;margin:1em 0}
td,th{border:1px solid #bbb;padding:.3em .6em;text-align:left;
font-size:.9em}th{background:#eee}code{background:#f4f4f4;
padding:0 .2em}h1{border-bottom:2px solid #444}h2{color:#334}"""


def _esc(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


def _inline(s: str) -> str:
    import re

    return re.sub(r"``([^`]*)``", r"<code>\1</code>", _esc(s))


def _rst_to_html(text: str, title: str, pages: set[str] = frozenset()) -> str:
    lines = text.splitlines()
    out = [f"<!doctype html><html><head><meta charset='utf-8'>"
           f"<title>{_esc(title)}</title><style>{_CSS}</style></head><body>"]
    i = 0
    while i < len(lines):
        line = lines[i]
        nxt = lines[i + 1] if i + 1 < len(lines) else ""
        if nxt and set(nxt.strip()) == {"="} and len(nxt) >= len(line) > 0:
            out.append(f"<h1>{_inline(line)}</h1>")
            i += 2
        elif nxt and set(nxt.strip()) == {"-"} and len(nxt) >= len(line) > 0:
            out.append(f"<h2>{_inline(line)}</h2>")
            i += 2
        elif (line.strip() and set(line.strip()) <= {"=", " "}
              and " " in line.strip()):
            # simple-format table: border, header, border, rows..., border
            cols, start = [], 0
            for seg in line.split():
                begin = line.index(seg, start)
                cols.append((begin, begin + len(seg)))
                start = begin + len(seg)
            cols[-1] = (cols[-1][0], 10 ** 6)

            def cells(row):
                return [row[a:b].strip() for a, b in cols]

            header = cells(lines[i + 1])
            out.append("<table><tr>" + "".join(
                f"<th>{_inline(c)}</th>" for c in header) + "</tr>")
            j = i + 3
            def _is_border(row):
                st = row.strip()
                return st and set(st) <= {"=", " "}

            while j < len(lines) and not _is_border(lines[j]):
                out.append("<tr>" + "".join(
                    f"<td>{_inline(c)}</td>" for c in cells(lines[j])
                ) + "</tr>")
                j += 1
            out.append("</table>")
            i = j + 1
        elif line.startswith(".. toctree::"):
            i += 1  # directive; options/entries handled as links below
        elif line.strip().startswith(":"):
            i += 1  # directive option
        elif line.startswith("   ") and line.strip() in pages:
            name = line.strip()
            out.append(f"<p><a href='{name}.html'>{_esc(name)}</a></p>")
            i += 1
        elif line.startswith("   ") and line.strip():
            out.append(f"<p style='margin-left:2em'>{_inline(line)}</p>")
            i += 1
        elif line.strip():
            out.append(f"<p>{_inline(line)}</p>")
            i += 1
        else:
            i += 1
    out.append("</body></html>")
    return "\n".join(out)


def render_html(rst_dir: str, out_dir: str) -> list[str]:
    """Static HTML site from the generated .rst tree."""
    os.makedirs(out_dir, exist_ok=True)
    written = []
    pages = {os.path.splitext(f)[0] for f in os.listdir(rst_dir)
             if f.endswith(".rst")}
    for fname in sorted(os.listdir(rst_dir)):
        if not fname.endswith(".rst"):
            continue
        base = os.path.splitext(fname)[0]
        with open(os.path.join(rst_dir, fname)) as f:
            html = _rst_to_html(f.read(), base, pages)
        path = os.path.join(out_dir, f"{base}.html")
        with open(path, "w") as f:
            f.write(html)
        written.append(path)
    return written


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "docs/api"
    paths = generate(out)
    html = render_html(out, os.path.join(os.path.dirname(out) or ".",
                                         "html"))
    print(f"wrote {len(paths)} rst + {len(html)} html files under "
          f"{os.path.dirname(out) or '.'}")
