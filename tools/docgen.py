"""API documentation generator.

Role of the reference's codegen doc pipeline (codegen/src/main/scala/
DocGen.scala + WrapperClassDoc.scala: per-class .rst emitted from
colocated doc text, assembled into a sphinx tree). The TPU framework's
Python API is the API (SURVEY.md §7: the codegen layer is an intentional
architectural delta), so docs generate straight from the live registries:
every registered pipeline stage's docstring + param table, and every
registered model builder.

Usage: python tools/docgen.py [output_dir]   (default docs/api)
"""

from __future__ import annotations

import os
import sys
from collections import defaultdict

# runnable from a checkout: tools/ sits next to the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _import_all() -> None:
    # importing the packages populates the registries
    import mmlspark_tpu.stages  # noqa: F401
    import mmlspark_tpu.models  # noqa: F401
    import mmlspark_tpu.data.readers  # noqa: F401


def _underline(text: str, ch: str) -> str:
    return f"{text}\n{ch * len(text)}\n"


def _param_table(cls) -> list[str]:
    rows = []
    for name, p in sorted(cls.params().items()):
        default = "required" if p.required else repr(p.get_default())
        domain = " | ".join(p.domain) if p.domain else ""
        doc = (p.doc or "").replace("\n", " ")
        rows.append((name, default, domain, doc))
    if not rows:
        return ["(no parameters)", ""]
    widths = [max(len(r[i]) for r in rows + [_HDR]) for i in range(4)]

    def fmt(r):
        return "  ".join(v.ljust(w) for v, w in zip(r, widths)).rstrip()

    sep = tuple("=" * w for w in widths)
    return [fmt(sep), fmt(_HDR), fmt(sep), *(fmt(r) for r in rows),
            fmt(sep), ""]


_HDR = ("param", "default", "domain", "doc")


def generate(out_dir: str) -> list[str]:
    """Write one .rst per stage module + models.rst + index.rst; returns
    the written paths."""
    from mmlspark_tpu.core.stage import PipelineStage
    from mmlspark_tpu.models.registry import registered_models

    _import_all()
    os.makedirs(out_dir, exist_ok=True)
    by_module: dict[str, list[type]] = defaultdict(list)
    for name, cls in sorted(PipelineStage.registry().items()):
        mod = cls.__module__.rsplit(".", 1)[-1]
        by_module[mod].append(cls)

    written = []
    for mod, classes in sorted(by_module.items()):
        lines = [_underline(mod, "="), ""]
        for cls in classes:
            lines.append(_underline(cls.__name__, "-"))
            # own docstring, else the module overview (many stage classes
            # document the family at module level, like the reference's
            # colocated .txt doc files)
            doc = cls.__dict__.get("__doc__")
            if not doc:
                module = sys.modules.get(cls.__module__)
                mod_doc = (module.__doc__ or "") if module else ""
                doc = mod_doc.split("\n\n")[0] or "(undocumented)"
            lines.append(doc.strip())
            lines.append("")
            lines.extend(_param_table(cls))
        path = os.path.join(out_dir, f"{mod}.rst")
        with open(path, "w") as f:
            f.write("\n".join(lines))
        written.append(path)

    # model registry page
    lines = [_underline("models", "="), "",
             "Registered model architectures (``build_model`` names):", ""]
    for name in registered_models():
        from mmlspark_tpu.models.registry import _BUILDERS

        fn = _BUILDERS[name]
        doc = (fn.__doc__ or "(undocumented)").strip().replace("\n", " ")
        lines.append(f"``{name}``")
        lines.append(f"    {doc}")
        lines.append("")
    path = os.path.join(out_dir, "models.rst")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    written.append(path)

    # index
    entries = "\n".join(
        f"   {os.path.splitext(os.path.basename(p))[0]}" for p in written
    )
    index = os.path.join(out_dir, "index.rst")
    with open(index, "w") as f:
        f.write(
            _underline("API reference", "=")
            + "\n.. toctree::\n   :maxdepth: 1\n\n"
            + entries + "\n"
        )
    written.append(index)
    return written


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "docs/api"
    paths = generate(out)
    print(f"wrote {len(paths)} files under {out}")
