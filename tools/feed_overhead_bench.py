"""Bound the TPUModel feed machinery's own overhead — no relay in the path.

VERDICT r4 weak #7: the 704 img/s stage number vs 552k img/s model-only
was *explained* as tunnel bandwidth, but nothing measured isolated the
async-feed machinery (threaded host->device queue, batch slicing, dtype
coercion, output gather) from the network. This script closes that: it
runs the WHOLE TPUModel stage on the CPU backend, where host->device is
a memcpy, so the stage-vs-model-only gap IS the machinery cost.

- model-only ceiling: batches pre-sliced and pre-device_put, timed loop
  of jitted forward + host fetch of each output (the stage fetches its
  outputs too, so the ceiling includes that);
- stage: ``TPUModel.transform`` end to end at feed depths 1/2/4/8 from
  the same host-RAM Dataset.

Prints one JSON line and writes ``FEED_OVERHEAD.json`` at the repo root.
Self-re-execs onto the CPU backend with the relay env neutralized
(PALLAS_AXON_POOL_IPS would force the axon backend over JAX_PLATFORMS).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "FEED_OVERHEAD.json")

#: env-overridable so bench.py's cpu-smoke mode can run a fast proof
#: pass while the committed artifact keeps the full-size measurement
BATCH = int(os.environ.get("MMLTPU_FEED_BATCH", "256"))
ROWS = int(os.environ.get("MMLTPU_FEED_ROWS", "4096"))
DEPTHS = (1, 2, 4, 8)
TRIALS = int(os.environ.get("MMLTPU_FEED_TRIALS", "3"))


def _ensure_cpu() -> None:
    if os.environ.get("JAX_PLATFORMS") == "cpu" and \
            "PALLAS_AXON_POOL_IPS" not in os.environ:
        return
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)],
              env)


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def main() -> None:
    _ensure_cpu()
    sys.path.insert(0, REPO)
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.data.dataset import Dataset
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.stages.dnn_model import TPUModel

    assert jax.default_backend() == "cpu", jax.default_backend()
    graph = build_model("resnet20_cifar10")
    variables = graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )
    x = np.random.default_rng(3).normal(size=(ROWS, 32, 32, 3)).astype(
        np.float32
    )

    # -- model-only ceiling ------------------------------------------------
    fwd = jax.jit(lambda v, b: graph.apply(v, b))
    batches = [
        jax.device_put(x[i:i + BATCH]) for i in range(0, ROWS, BATCH)
    ]
    np.asarray(fwd(variables, batches[0]))  # compile

    def model_only():
        for b in batches:
            np.asarray(fwd(variables, b))

    t_model = min(_timed(model_only) for _ in range(TRIALS))
    model_ips = ROWS / t_model

    # -- full stage at each feed depth ------------------------------------
    ds = Dataset({"image": x})
    per_depth = {}
    for depth in DEPTHS:
        stage = TPUModel.from_graph(
            graph, variables, "resnet20_cifar10",
            input_col="image", output_col="scores", batch_size=BATCH,
            feed_depth=depth,
        )
        stage.transform(ds)  # warmup: compile + weight put
        dt = min(_timed(lambda: stage.transform(ds)) for _ in range(TRIALS))
        per_depth[depth] = ROWS / dt

    best = max(per_depth, key=per_depth.get)
    line = {
        "metric": "feed_overhead_fraction_cpu_backend",
        # fraction of the model-only ceiling LOST to the feed machinery
        # at the best depth — the design-bound claim; <0.2 means the
        # r4 TPU stage number (704 vs 552k) is tunnel, not design
        "value": round(1.0 - per_depth[best] / model_ips, 4),
        "unit": "fraction_of_ceiling_lost",
        "model_only_images_per_sec": round(model_ips, 1),
        "stage_images_per_sec_per_depth": {
            str(d): round(v, 1) for d, v in per_depth.items()
        },
        "stage_over_model_ratio_best": round(per_depth[best] / model_ips, 4),
        "best_feed_depth": best,
        "batch": BATCH,
        "rows": ROWS,
        "trials": TRIALS,
        "backend": "cpu (relay neutralized: host->device is a memcpy, so "
                   "stage-vs-model-only isolates the machinery itself)",
    }
    if ROWS >= 4096:
        # only a full-size run may replace the committed artifact; the
        # cpu-smoke proof pass (env-shrunk) just prints its line
        with open(OUT, "w", encoding="utf-8") as f:
            json.dump(line, f, indent=1)
    print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
