#!/usr/bin/env bash
# Round-5 TPU evidence orchestrator. Fired by the detached pounce loop the
# moment a tunnel probe succeeds; safe to fire repeatedly — each step is
# guarded by a marker file in /tmp/r5m/ and re-runs only until its "done"
# condition (a TPU-backed artifact committed) holds.
#
# Runs every step from a throwaway worktree at current HEAD so an
# in-session half-edited working tree can never crash a tunnel window.
# Artifacts are copied back to /root/repo and committed under a git lock.
#
# Priorities (VERDICT r4 "Next round"): flash-vs-XLA on-chip timings (#2),
# trees on TPU (#5), int8 serving shapes (#4), feed-overhead bound (#7) —
# the latter two ride the bench groups added this round.
set -u
REPO=/root/repo
WT=/tmp/r5wt
M=/tmp/r5m
mkdir -p "$M"
export PYTHONPATH="$WT:/root/.axon_site"
log() { echo "[$(date -u +%H:%M:%S)] $*"; }

commit_file() { # commit_file <repo-relative-path> <message>
  # pathspec commit: anything the interactive session happens to have
  # staged in /root/repo must NOT ride along under this message
  (
    flock -w 120 9 || exit 1
    cd "$REPO" && git add "$1" && git commit -m "$2" -- "$1"
  ) 9>/tmp/r5_git.lock
}

fresh_worktree() {
  cd "$REPO" || exit 1
  git worktree remove --force "$WT" 2>/dev/null
  rm -rf "$WT"
  git worktree add --detach "$WT" HEAD >/dev/null || exit 1
}

probe_ok() {
  timeout 55 python -c \
    "import jax; assert any('TPU' in d.device_kind for d in jax.devices())" \
    2>/dev/null
}

fresh_worktree
log "evidence run starts from $(git -C "$WT" rev-parse --short HEAD)"

# -- step 1: flash-vs-XLA chained on-chip timings (VERDICT #2) -------------
if [ ! -f "$M/flash_ev.done" ]; then
  log "step flash_ev: tools/flash_tpu_evidence.py"
  if (cd "$WT" && timeout 1800 python tools/flash_tpu_evidence.py); then
    cp "$WT/FLASH_TPU_EVIDENCE.json" "$REPO/FLASH_TPU_EVIDENCE.json"
    commit_file FLASH_TPU_EVIDENCE.json \
      "Refresh FLASH_TPU_EVIDENCE.json: on-chip chained flash-vs-XLA timings" \
      && touch "$M/flash_ev.done" && log "flash_ev DONE"
  else
    log "flash_ev failed (rc=$?)"
  fi
fi

# -- step 2: full bench with resumable scratch (VERDICT #1/#4/#5/#7) -------
# One scratch file across windows: a wedge mid-sweep keeps what landed and
# the next window completes only the missing groups. Done only when the
# headline landed AND trees+flash ran on the chip (the two groups r4 never
# recorded on TPU).
if [ ! -f "$M/bench.done" ]; then
  probe_ok || { log "tunnel gone before bench; stop"; exit 0; }
  log "step bench: full sweep (resumable scratch)"
  # cross-window resume hygiene: groups a previous window's CPU-smoke
  # fallback landed read as "done" to the scratch skip logic — strip
  # them so this window re-runs them on the chip, keeping TPU-landed
  # groups
  if [ -f /tmp/bench_r5_scratch.json ]; then
    (cd "$WT" && python - <<'PY'
import json
from bench import _GROUPS
path = "/tmp/bench_r5_scratch.json"
s = json.load(open(path))
gb = s.get("group_backends", {})
for g, keys in _GROUPS.items():
    if gb.get(g) and gb[g] != "tpu":
        for k in keys:
            s.pop(k, None)
        gb.pop(g, None)
        s.get("group_seconds", {}).pop(g, None)
s["group_backends"] = gb
for transient in ("wall_skipped", "fallback_reason", "probe",
                  "group_errors"):
    s.pop(transient, None)
json.dump(s, open(path, "w"))
print("scratch resume: tpu-landed groups kept:", sorted(gb))
PY
    )
  fi
  # wall 900s per pass, NOT the full sweep: the healthy window is ~20
  # min total and decode evidence (step 3) must get its turn. The
  # pounce refires this script every healthy probe; the shared scratch
  # means each pass completes only the still-missing groups, so a long
  # window converges across passes.
  (cd "$WT" && \
    MMLTPU_BENCH_SCRATCH=/tmp/bench_r5_scratch.json \
    MMLTPU_BENCH_PROBE_WINDOW_S=90 \
    MMLTPU_BENCH_WALL_S=900 \
    timeout 1100 python bench.py | tail -n 1 > /tmp/bench_r5_line.json)
  python - <<'PY'
import json, sys
line = json.load(open("/tmp/bench_r5_line.json"))
gb = line.get("group_backends", {})
print("bench landed:", {k: line.get(k) for k in
      ("value", "scale", "device_kind", "resnet50_mfu", "gbt_fit_seconds",
       "flash_vs_xla_speedup", "error_class")})
print("group_backends:", gb)
if line.get("value") is None:
    sys.exit("no headline value - not recording")
ok = all(gb.get(g) == "tpu" for g in ("inference", "trees", "flash"))
sys.exit(0 if ok else 3)  # 3: recorded but incomplete TPU coverage
PY
  rc=$?
  if [ "$rc" -le 3 ] && [ "$rc" -ne 1 ]; then
    cp /tmp/bench_r5_line.json "$REPO/BENCH_LOCAL_r5.json"
    commit_file BENCH_LOCAL_r5.json \
      "Record in-session TPU bench artifact BENCH_LOCAL_r5.json"
    [ "$rc" -eq 0 ] && touch "$M/bench.done" && log "bench DONE (full TPU)"
    [ "$rc" -eq 3 ] && log "bench recorded but trees/flash not on TPU yet"
  else
    log "bench produced no headline (rc=$rc)"
  fi
fi

# -- step 3: decode tokens/sec evidence (KV cache, VERDICT #3) -------------
if [ ! -f "$M/decode_ev.done" ] && [ -f "$WT/tools/decode_tpu_evidence.py" ]; then
  probe_ok || { log "tunnel gone before decode_ev; stop"; exit 0; }
  log "step decode_ev: tools/decode_tpu_evidence.py"
  if (cd "$WT" && timeout 1200 python tools/decode_tpu_evidence.py); then
    cp "$WT/DECODE_TPU_EVIDENCE.json" "$REPO/DECODE_TPU_EVIDENCE.json"
    commit_file DECODE_TPU_EVIDENCE.json \
      "Record on-chip KV-cache decode tokens/sec evidence" \
      && touch "$M/decode_ev.done" && log "decode_ev DONE"
  else
    log "decode_ev failed (rc=$?)"
  fi
fi

log "evidence run ends"
