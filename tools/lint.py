"""Self-contained static-analysis gate (the scalastyle analog).

The reference enforces scalastyle + -Xfatal-warnings on every build
(src/project/scalastyle.scala, build.scala:56-66,86). This environment has
no third-party linter and no egress, so this is a stdlib-ast implementation
of the checks that matter most for this codebase; tools/ci.sh prefers ruff
(configured in pyproject.toml) when one is installed.

Checks:
  syntax        file parses (compile())
  star-import   `from x import *` outside __init__.py
  unused-import imported name never referenced (``# noqa: unused`` opts out)
  bare-except   `except:` with no exception class
  mutable-default mutable literal as a function default
  tabs          tab indentation
  trailing-ws   trailing whitespace
  long-line     > MAX_LINE chars (URLs exempt)

Exit code 0 = clean, 1 = findings.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LINE = 88
ROOTS = ("mmlspark_tpu", "tests", "examples", "tools")
TOP_FILES = ("bench.py", "__graft_entry__.py")


class ImportChecker(ast.NodeVisitor):
    def __init__(self) -> None:
        self.imported: dict[str, int] = {}  # name -> lineno
        self.used: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imported[name] = node.lineno

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name != "*":
                self.imported[a.asname or a.name] = node.lineno

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)

    def unused(self) -> dict[str, int]:
        return {n: ln for n, ln in self.imported.items() if n not in self.used}


def check_file(path: Path) -> list[str]:
    problems: list[str] = []
    text = path.read_text()
    lines = text.splitlines()

    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]

    is_init = path.name == "__init__.py"
    ic = ImportChecker()
    ic.visit(tree)
    # names referenced in __all__ / docstring-driven re-exports count as used
    for n, ln in ic.unused().items():
        line = lines[ln - 1] if ln <= len(lines) else ""
        if is_init or "noqa" in line or f'"{n}"' in text or f"'{n}'" in text:
            continue
        problems.append(f"{path}:{ln}: unused import '{n}'")

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and any(
            a.name == "*" for a in node.names
        ):
            if not is_init:
                problems.append(f"{path}:{node.lineno}: star import")
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append(f"{path}:{node.lineno}: bare except")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        f"{path}:{d.lineno}: mutable default argument"
                    )

    for i, line in enumerate(lines, 1):
        stripped = line.rstrip("\n")
        if stripped.startswith("\t"):
            problems.append(f"{path}:{i}: tab indentation")
        if stripped != stripped.rstrip():
            problems.append(f"{path}:{i}: trailing whitespace")
        if len(stripped) > MAX_LINE and "http" not in stripped:
            problems.append(
                f"{path}:{i}: line too long ({len(stripped)} > {MAX_LINE})"
            )
    return problems


def main() -> int:
    repo = Path(__file__).resolve().parent.parent
    files: list[Path] = []
    for root in ROOTS:
        files.extend(sorted((repo / root).rglob("*.py")))
    files.extend(repo / f for f in TOP_FILES)
    problems: list[str] = []
    for f in files:
        if f.exists():
            problems.extend(check_file(f))
    for p in problems:
        print(p)
    print(f"lint: {len(files)} files, {len(problems)} problems")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
