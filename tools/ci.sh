#!/usr/bin/env bash
# One-command CI gate: lint -> install check -> tests -> examples -> docgen.
# The `runme` analog (reference runme:1-50 / sbt full-build at
# src/project/build.scala:84-93: scalastyle -> compile -> test -> package
# -> codegen). Usage:
#   tools/ci.sh            # full run
#   tools/ci.sh fast       # lint + tests only
#   PROC_SHARD=1/3 tools/ci.sh   # shard the example suite (harness.py)
set -euo pipefail
cd "$(dirname "$0")/.."

step() { echo; echo "=== $1 ==="; }

step "lint (scalastyle analog)"
if command -v ruff >/dev/null 2>&1; then
  ruff check .
else
  python tools/lint.py
fi

step "package import check"
python -c "import mmlspark_tpu; print('mmlspark_tpu', 'stages:',
len(mmlspark_tpu.all_stages()))"

step "unit + integration tests (8-device CPU mesh via tests/conftest.py)"
python -m pytest tests/ -q

if [ "${1:-}" != "fast" ]; then
  step "example suite (notebook-parity flows)"
  python examples/harness.py

  step "docgen"
  python tools/docgen.py

  step "bench smoke (one JSON line; real backend if available)"
  python bench.py
fi

echo
echo "CI green."
