#!/usr/bin/env bash
# One-command CI gate: lint -> install check -> tests -> examples -> docgen.
# The `runme` analog (reference runme:1-50 / sbt full-build at
# src/project/build.scala:84-93: scalastyle -> compile -> test -> package
# -> codegen). Usage:
#   tools/ci.sh            # full run
#   tools/ci.sh fast       # lint + tests only
#   PROC_SHARD=1/3 tools/ci.sh   # shard the example suite (harness.py)
set -euo pipefail
cd "$(dirname "$0")/.."

step() { echo; echo "=== $1 ==="; }

step "lint (scalastyle analog)"
if command -v ruff >/dev/null 2>&1; then
  ruff check .
else
  python tools/lint.py
fi

step "package import check"
python -c "import mmlspark_tpu; print('mmlspark_tpu', 'stages:',
len(mmlspark_tpu.all_stages()))"

step "native ops: build from source (no committed binaries)"
# .so files are gitignored; delete any stale build products so the C++
# ops compile fresh from the shipped sources, then prove both load —
# the parity tests (test_ctf_native.py, decode tests) then run against
# exactly these binaries (NativeLoader.java packaging analog)
rm -f mmlspark_tpu/ops/native/*.so
python - <<'PY'
from mmlspark_tpu.ops import native_build
for name in ("decode", "ctf"):
    lib = native_build.load_native(name)
    assert lib is not None, f"source build failed for native lib {name!r}"
print("native libs built from source: decode, ctf")
PY

step "unit + integration tests (8-device CPU mesh via tests/conftest.py)"
if [ "${1:-}" = "fast" ]; then
  python -m pytest tests/ -q
else
  # the example tier runs ONCE: harness.py below covers it, so the
  # in-pytest copy is skipped here (it remains for bare `pytest tests/`)
  python -m pytest tests/ -q --ignore=tests/test_examples.py
fi

if [ "${1:-}" != "fast" ]; then
  step "example suite (notebook-parity flows)"
  python examples/harness.py

  step "docker image (build if a daemon exists; else execute the pip RUN
line in a clean venv)"
  docker_built=no
  if command -v docker >/dev/null 2>&1; then
    # a daemon without egress (or without the base image cached) cannot
    # pull the base layer — fall through to the venv proof instead of
    # failing the whole gate on an environment limitation
    if docker build -t mmlspark-tpu-ci -f tools/docker/Dockerfile .; then
      docker_built=yes
    else
      echo "WARNING: docker build failed (no egress / base image" \
           "unavailable?) — falling back to the venv RUN-line proof"
    fi
  fi
  if [ "$docker_built" = no ]; then
    # no daemon in this environment: prove the Dockerfile's pip RUN line
    # executes by running it against a clean venv. The baked environment's
    # site-packages are linked in via a .pth, playing the role of the
    # image layer's earlier `pip install jax` (this runner may itself be
    # a venv, so --system-site-packages would miss them); the package +
    # its [test] extra must then resolve offline and import from OUTSIDE
    # the repo.
    venv_dir=$(mktemp -d)/venv
    python -m venv "$venv_dir"
    baked=$(python -c "import sysconfig; print(sysconfig.get_paths()['purelib'])")
    vsite=$("$venv_dir/bin/python" -c "import sysconfig; print(sysconfig.get_paths()['purelib'])")
    echo "$baked" > "$vsite/_baked_deps.pth"
    "$venv_dir/bin/pip" install --no-cache-dir --no-index \
      --no-build-isolation --quiet ".[test]"
    (cd / && "$venv_dir/bin/python" -c \
      "import mmlspark_tpu; print('docker RUN-line venv check:',
len(mmlspark_tpu.all_stages()), 'stages')")
    rm -rf "$(dirname "$venv_dir")"
  fi

  step "decode-block parity gate (fused blocks == generate(), every T)"
  python -m pytest tests/test_decode_block.py -q

  step "sharded serving parity gate (mesh engine == generate(), 2x2)"
  python -m pytest tests/test_serve_sharded.py -q

  step "serving resilience gate (fault injection / quarantine / chaos soak)"
  python -m pytest tests/test_serve_faults.py -q

  step "paged KV-cache gate (allocator / prefix cache / paged-decode parity)"
  python -m pytest tests/test_paging.py -q

  step "supervisor gate (replica failover / hedging / drain chaos drills)"
  python -m pytest tests/test_serve_supervisor.py -q

  step "quantized decode gate (int8 KV + weight-only int8 vs the bf16 oracle)"
  python -m pytest tests/test_quantized_serve.py -q

  step "chunked prefill + async host gate (parity, compile pins, sync budget)"
  python -m pytest tests/test_chunked_async.py -q

  step "disagg gate (prefill/decode fleet: hand-off, prefix index, autoscaler)"
  python -m pytest tests/test_serve_fleet.py -q
  python tools/check_metrics_schema.py --disagg

  step "multi-model gate (LM + stateless zoo deployments, one engine)"
  python -m pytest tests/test_multimodel.py -q
  python tools/check_metrics_schema.py --multi-model

  step "training resilience gate (fault drills / atomic resume / quarantine)"
  python -m pytest tests/test_train_resilience.py -q
  python tools/check_metrics_schema.py --train

  step "integrity gate (SDC detection / checksummed hand-offs / verified restore)"
  python -m pytest tests/test_integrity.py tests/test_faults_coverage.py -q
  # corrupt drill through the real CLI: a seeded train.step bit-flip
  # must be caught, quarantined, and replay-adjudicated (the --train
  # schema gate above pins the full metric contract; this run pins the
  # plane end-to-end at a different audit cadence)
  integrity_tmp=$(mktemp -d)
  JAX_PLATFORMS=cpu python -m mmlspark_tpu --cpu-mesh 4 train \
    --epochs 2 --samples 96 --batch-size 32 --seed 0 \
    --checkpoint-every 2 --audit-every 3 \
    --faults 'seed=3,train.step:corrupt=0.2' \
    --telemetry-dir "$integrity_tmp" \
    --checkpoint-dir "$integrity_tmp/ck" \
    | python -c '
import json, sys
md = json.load(sys.stdin)
assert md["train.integrity.audits"] >= 1, md
assert md["train.integrity.sdc_suspected"] >= 1, md
print("integrity drill: OK —",
      md["train.integrity.sdc_suspected"], "bit-flip(s) caught across",
      md["train.integrity.audits"], "audit(s)")
'
  rm -rf "$integrity_tmp"

  step "telemetry schema gate (serve --demo artifacts)"
  python tools/check_metrics_schema.py

  step "distributed tracing gate (TelemetryHub merge / flow arrows / alerts)"
  python -m pytest tests/test_tracehub.py -q
  python tools/check_metrics_schema.py --tracing

  step "bench regression gate (selftest vs the recorded BENCH history)"
  # proves the tolerance-band logic on the REAL history: the newest
  # usable entry must pass, a 25% injected slowdown must fail — no
  # fresh bench run needed. Gating a fresh run:
  #   python bench.py > /tmp/fresh.json \
  #     && python tools/bench_regression.py /tmp/fresh.json
  python tools/bench_regression.py --selftest

  step "trace-export smoke (serve --trace-out -> Perfetto-loadable JSON)"
  trace_tmp=$(mktemp -d)
  JAX_PLATFORMS=cpu python -m mmlspark_tpu serve --demo --slots 2 \
    --requests 3 --max-new-tokens 4 --trace-out "$trace_tmp/trace.json" \
    > /dev/null
  python - "$trace_tmp/trace.json" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
evs = doc["traceEvents"]
assert evs and all("ph" in e and "ts" in e for e in evs), "malformed trace"
assert any(e["ph"] == "X" and e["name"].startswith("request ") for e in evs)
print("trace-export smoke:", len(evs), "events, Chrome trace-event JSON ok")
PY
  rm -rf "$trace_tmp"

  step "docgen"
  python tools/docgen.py

  step "bench smoke (one JSON line; real backend if available)"
  # smoke semantics: a wedged tunnel should fall through to the CPU
  # metric groups in ~minutes, not consume the driver-scale 20-min probe
  # window (bench.py's default when invoked standalone)
  MMLTPU_BENCH_PROBE_WINDOW_S=60 MMLTPU_BENCH_PROBE_TIMEOUT_S=45 \
    python bench.py || test $? -eq 5  # 5 = no TPU headline (labeled CPU smoke)
fi

echo
echo "CI green."
