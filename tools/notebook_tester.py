"""Execute the sample notebooks headless — the reference harness analog.

Reference contract (tools/notebook/tester/NotebookTestSuite.py:12-13,
40-72 + TestNotebooksLocally.py:46-52): every sample notebook runs
through nbconvert's ExecutePreprocessor with a 600 s timeout, shardable
across processes with ``PROC_SHARD=i/m``. Same contract here; the
kernel inherits the virtual 8-device CPU mesh environment so notebooks
exercise the same sharded paths as the test suite.

Usage:
    python tools/notebook_tester.py            # run all samples
    PROC_SHARD=0/2 python tools/notebook_tester.py
    python tools/notebook_tester.py 301 305    # run by number prefix
"""

from __future__ import annotations

import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SAMPLES = os.path.join(REPO, "notebooks", "samples")
TIMEOUT_S = 600  # NotebookTestSuite.py:13


def discover(selectors: list[str]) -> list[str]:
    names = sorted(
        n for n in os.listdir(SAMPLES) if n.endswith(".ipynb")
    )
    if selectors:
        names = [
            n for n in names
            if any(n.startswith(s) for s in selectors)
        ]
    shard = os.environ.get("PROC_SHARD")
    if shard:
        i, m = (int(p) for p in shard.split("/"))
        names = [n for k, n in enumerate(names) if k % m == i]
    return names


def run_one(name: str) -> tuple[bool, float, str]:
    import nbformat
    from nbconvert.preprocessors import ExecutePreprocessor

    # kernel env: CPU mesh before any jax import, repo on sys.path.
    # FORCE cpu (not setdefault): the ambient env may pin
    # JAX_PLATFORMS=axon, which is unregistered in offline kernels
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # kernels stay offline
    os.environ["PYTHONPATH"] = (
        REPO + os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else REPO
    )

    path = os.path.join(SAMPLES, name)
    nb = nbformat.read(path, as_version=4)
    ep = ExecutePreprocessor(timeout=TIMEOUT_S, kernel_name="python3")
    t0 = time.time()
    try:
        # notebooks resolve repo-relative paths (zoo, fixtures) from the
        # examples dir, matching the scripts they are generated from
        ep.preprocess(
            nb, {"metadata": {"path": os.path.join(REPO, "examples")}}
        )
        return True, time.time() - t0, ""
    except Exception as e:  # noqa: BLE001 — harness reports, not raises
        msg = re.sub(r"\x1b\[[0-9;]*m", "", str(e))  # strip ANSI
        return False, time.time() - t0, msg[-2000:]


def main() -> None:
    names = discover(sys.argv[1:])
    if not names:
        raise SystemExit("no notebooks matched")
    failures = []
    for name in names:
        ok, dt, err = run_one(name)
        status = "ok" if ok else "FAIL"
        print(f"[{status}] {name} ({dt:.1f}s)")
        if not ok:
            failures.append((name, err))
    if failures:
        for name, err in failures:
            print(f"\n--- {name} ---\n{err}")
        raise SystemExit(f"{len(failures)}/{len(names)} notebooks failed")
    print(f"all {len(names)} notebooks passed")


if __name__ == "__main__":
    main()
