#!/usr/bin/env bash
# Run bench.py against the real backend and commit the raw line as the
# auditable in-session artifact (VERDICT r3 missing #1c): the perf claim
# in docs/PERFORMANCE.md is only as good as a committed raw JSON.
#
# Usage: tools/record_local_bench.sh <round-number>
set -euo pipefail
cd "$(dirname "$0")/.."
round="${1:?usage: tools/record_local_bench.sh <round-number>}"
out="BENCH_LOCAL_r${round}.json"

python bench.py | tail -n 1 > "$out"
python - "$out" <<'PY'
import json, sys
line = json.load(open(sys.argv[1]))
serve = line.get("serve") or {}
print("recorded:", {k: line.get(k) for k in
      ("value", "backend", "scale", "device_kind", "resnet50_mfu",
       "stage_images_per_sec_per_chip", "error_class")})
# device-level serve analytics (docs/OBSERVABILITY.md): keep the BENCH
# history comparable as the analytics keys land in the serve group
print("serve analytics:", {k: serve.get(k) for k in
      ("tokens_per_sec", "mfu", "hbm_bw_util_pct", "device_time_pct",
       "slo_burning", "slo_violations_total")})
if line.get("value") is None:
    raise SystemExit(
        "no TPU headline value landed - artifact saved but NOT worth "
        "committing as a perf claim; see error fields")
PY

# gate the fresh artifact against the committed history BEFORE it is
# committed: a recorded regression should be a loud decision, not a
# silent append (tools/bench_regression.py; override with
# MMLTPU_BENCH_NO_GATE=1 when recording a known-slower configuration)
if [ "${MMLTPU_BENCH_NO_GATE:-}" != "1" ]; then
  python tools/bench_regression.py "$out"
fi
git add "$out"
git commit -m "Record in-session TPU bench artifact ${out}"
echo "committed ${out}"
