"""Generate the sample-notebook tier from the example scripts.

The reference ships 10 runnable sample notebooks under
``notebooks/samples/`` (reference ``notebooks/samples/*.ipynb``) and its
CI executes them headless (tools/notebook/tester/NotebookTestSuite.py).
Here the examples are maintained once, as ``examples/e*.py`` scripts
(testable, diffable, shardable), and this tool derives the committed
notebook artifacts from them: markdown cell from the module docstring,
one code cell per top-level block, a final ``main()`` cell.

Run: ``python tools/make_notebooks.py`` — writes
``notebooks/samples/*.ipynb``. Execute them with
``python tools/notebook_tester.py`` (nbconvert ExecutePreprocessor,
600 s timeout per notebook, PROC_SHARD sharding — the reference
harness's exact contract).
"""

from __future__ import annotations

import ast
import os

import nbformat as nbf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")
OUT = os.path.join(REPO, "notebooks", "samples")

#: examples -> notebook titles (reference numbering, this repo's data)
TITLES = {
    "e101": "101 - Classification on a Real Table (TrainClassifier)",
    "e102": "102 - Regression on a Real Table (TrainRegressor)",
    "e103": "103 - Before and After mmlspark_tpu",
    "e201": "201 - Text Analytics - TextFeaturizer",
    "e202": "202 - Text Analytics - Word2Vec",
    "e301": "301 - CIFAR10-style CNN Evaluation (TPUModel)",
    "e302": "302 - Pipeline Image Transformations",
    "e303": "303 - Transfer Learning by DNN Featurization",
    "e304": "304 - Medical Entity Extraction (BiLSTM)",
    "e305": "305 - ImageFeaturizer: basic vs DNN featurization",
    # beyond the reference's ten: TPU-native long-context story
    "e306": "306 - Long-Context Ring Attention (sequence parallelism)",
    "e307": "307 - Generation with KV-Cache Decode (rolled window, "
            "nucleus sampling)",
}


def script_to_cells(path: str) -> list:
    """Split a script into notebook cells at top-level statement groups:
    docstring -> markdown; imports+constants -> one cell; each def/class
    -> its own cell; trailing __main__ guard -> a bare main() call."""
    src = open(path, encoding="utf-8").read()
    tree = ast.parse(src)
    lines = src.splitlines()

    cells = []
    doc = ast.get_docstring(tree)
    body = list(tree.body)
    if doc is not None:
        body.pop(0)
        title = TITLES.get(os.path.basename(path)[:4], "")
        cells.append(nbf.v4.new_markdown_cell(f"# {title}\n\n{doc}"))
    # the scripts resolve repo paths via __file__, which kernels don't
    # define; the tester runs notebooks with cwd=examples/
    cells.append(nbf.v4.new_code_cell(
        "import os\n"
        f"__file__ = os.path.join(os.getcwd(), {os.path.basename(path)!r})"
    ))

    def segment(node) -> str:
        return "\n".join(lines[node.lineno - 1: node.end_lineno])

    # group consecutive non-def statements (imports, constants) into one
    # cell; each function/class gets its own
    group: list[str] = []
    for node in body:
        is_main_guard = (
            isinstance(node, ast.If)
            and isinstance(node.test, ast.Compare)
            and getattr(node.test.left, "id", "") == "__name__"
        )
        if is_main_guard:
            continue  # replaced by the explicit call cell below
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if group:
                cells.append(nbf.v4.new_code_cell("\n".join(group)))
                group = []
            cells.append(nbf.v4.new_code_cell(segment(node)))
        else:
            group.append(segment(node))
    if group:
        cells.append(nbf.v4.new_code_cell("\n".join(group)))
    cells.append(nbf.v4.new_code_cell("main()"))
    return cells


def main(out_dir: str = OUT) -> list:
    os.makedirs(out_dir, exist_ok=True)
    written = []
    for name in sorted(os.listdir(EXAMPLES)):
        if not (name.startswith("e") and name.endswith(".py")):
            continue
        key = name[:4]
        if key not in TITLES:
            continue
        nb = nbf.v4.new_notebook()
        nb.cells = script_to_cells(os.path.join(EXAMPLES, name))
        nb.metadata["kernelspec"] = {
            "name": "python3", "display_name": "Python 3",
            "language": "python",
        }
        out = os.path.join(out_dir, f"{TITLES[key]}.ipynb")
        with open(out, "w", encoding="utf-8") as f:
            nbf.write(nb, f)
        written.append(os.path.basename(out))
    print(f"wrote {len(written)} notebooks under {out_dir}")
    for w in written:
        print(" ", w)
    return written


if __name__ == "__main__":
    main()
