#!/usr/bin/env python
"""Telemetry schema gate: run the real ``serve --demo`` CLI with
``--telemetry-dir`` and assert every emitted artifact keeps its contract.

Three surfaces, all produced by ONE subprocess run at smoke scale:

- stdout: exactly one JSON line (the CLI's parseable-output contract),
  carrying every historical ``ServeMetrics.to_dict()`` key plus the
  telemetry plane's percentile keys with the right types;
- ``metrics.json``: the same dict persisted under ``--telemetry-dir``;
- ``events.jsonl``: the flight recorder's timeline — every submitted
  request must appear as one COMPLETE span (start -> queued -> admitted
  -> prefill -> terminal status).

Exits non-zero with a pointed message on the first violation, so
``tools/ci.sh`` catches schema drift before a dashboard does
(docs/OBSERVABILITY.md). Usage::

    python tools/check_metrics_schema.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

N_REQUESTS = 4

# key -> allowed types in the flat metrics dict. ``type(None)`` appears
# where an empty/degenerate run may legitimately report null; the demo
# run below always populates them, so None is rejected for those.
NUM = (int, float)
REQUIRED_METRIC_KEYS: dict[str, tuple] = {
    # the pre-telemetry ServeMetrics.to_dict() contract — every key
    # dashboards already consume must survive
    "model": (str,),
    "slots": (int,),
    "ticks": (int,),
    "submitted": (int,),
    "rejected": (int,),
    "completed": (int,),
    "expired": (int,),
    "tokens_generated": (int,),
    "queue_depth_mean": NUM,
    "queue_depth_max": NUM,
    "ttft_ticks_mean": NUM,
    "ttft_ms_mean": NUM,
    "per_token_ms": NUM,
    "slot_utilization_mean": NUM,
    "slot_utilization_peak": NUM,
    "tokens_per_sec": NUM,
    "wall_s": NUM,
    "decode_live_kv_tokens": (int,),
    "decode_dense_kv_tokens": (int,),
    "decode_flop_utilization": NUM,
    "prefill_buckets": (dict,),
    # the telemetry plane's additions
    "ttft_ms_p50": NUM,
    "ttft_ms_p95": NUM,
    "ttft_ms_p99": NUM,
    "per_token_ms_p50": NUM,
    "per_token_ms_p95": NUM,
    "per_token_ms_p99": NUM,
    "tick_ms_p50": NUM,
    "tick_ms_p95": NUM,
    "tick_ms_p99": NUM,
    # fused decode blocks (tests/test_decode_block.py)
    "decode_block": (int,),
    "tokens_per_tick": NUM,
    "decode_blocks": (dict,),
    # mesh-sharded serving (docs/SERVING.md "Sharded serving"): the
    # topology keys are ALWAYS present — {} / 1 / total-bytes on a
    # single-device engine, so dashboards need no existence checks
    "mesh_shape": (dict,),
    "mesh_devices": (int,),
    "cache_pool_bytes_per_device": (int,),
    # resilience plane (docs/SERVING.md "Failure semantics"): terminal
    # statuses beyond completed/expired plus the fault-handling
    # counters — always present (0 on a fault-free run) so dashboards
    # can alert on them without existence checks
    "failed": (int,),
    "stalled": (int,),
    "retries_total": (int,),
    "faults_injected_total": (int,),
    "quarantined_total": (int,),
    "preemptions_total": (int,),
    "degraded_mode": (int,),
    "faults_by_kind": (dict,),
    # demo envelope
    "n_requests": (int,),
    "decode_compiles": (int,),
    "prefill_compiles": (int,),
    "prefill_bucket_count": (int,),
}


def fail(msg: str) -> "None":
    print(f"check_metrics_schema: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def check_metrics_dict(d: dict, source: str) -> None:
    for key, types in REQUIRED_METRIC_KEYS.items():
        if key not in d:
            fail(f"{source}: missing key {key!r}")
        if not isinstance(d[key], types):
            fail(
                f"{source}: key {key!r} has type "
                f"{type(d[key]).__name__}, expected one of "
                f"{[t.__name__ for t in types]} (value: {d[key]!r})"
            )


def check_events(path: str, n_requests: int) -> int:
    try:
        lines = open(path, encoding="utf-8").read().splitlines()
    except OSError as e:
        fail(f"events.jsonl unreadable: {e}")
    spans: dict[int, list[str]] = {}
    for i, line in enumerate(lines, 1):
        try:
            ev = json.loads(line)
        except json.JSONDecodeError as e:
            fail(f"events.jsonl line {i} is not JSON: {e}")
        if "t" not in ev or "name" not in ev:
            fail(f"events.jsonl line {i} lacks 't'/'name': {ev}")
        if ev.get("span_name") == "request":
            spans.setdefault(ev["span"], []).append(ev["name"])
    if len(spans) != n_requests:
        fail(
            f"events.jsonl holds {len(spans)} request spans, expected "
            f"one per submitted request ({n_requests})"
        )
    for sid, names in spans.items():
        if names[0] != "start":
            fail(f"span {sid} does not open with 'start': {names}")
        missing = {"queued", "admitted", "prefill"} - set(names)
        if missing:
            fail(f"span {sid} lacks lifecycle events {missing}: {names}")
        if names[-1] not in ("completed", "expired", "failed", "stalled"):
            fail(f"span {sid} never reached a terminal status: {names}")
    return len(lines)


def main() -> None:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as tdir:
        # --mesh makes the run exercise the SHARDED engine, so the gate
        # also pins the mesh topology keys' populated form
        cmd = [
            sys.executable, "-m", "mmlspark_tpu", "--cpu-mesh", "4",
            "serve", "--demo", "--slots", "2",
            "--requests", str(N_REQUESTS), "--max-new-tokens", "4",
            "--mesh", "data=2,model=2",
            "--telemetry-dir", tdir,
        ]
        res = subprocess.run(
            cmd, capture_output=True, text=True, timeout=300,
            env=env, cwd=repo,
        )
        if res.returncode != 0:
            fail(f"serve --demo exited {res.returncode}:\n{res.stderr}")
        out_lines = [ln for ln in res.stdout.splitlines() if ln.strip()]
        if len(out_lines) != 1:
            fail(
                f"stdout must be exactly ONE JSON line, got "
                f"{len(out_lines)}:\n{res.stdout}"
            )
        try:
            stdout_metrics = json.loads(out_lines[0])
        except json.JSONDecodeError as e:
            fail(f"stdout line is not JSON: {e}")
        check_metrics_dict(stdout_metrics, "stdout")
        if stdout_metrics.get("mesh_shape") != {"data": 2, "model": 2}:
            fail(
                "stdout: a --mesh data=2,model=2 run must report "
                f"mesh_shape {{'data': 2, 'model': 2}}, got "
                f"{stdout_metrics.get('mesh_shape')!r}"
            )
        if stdout_metrics.get("mesh_devices") != 4:
            fail(
                "stdout: mesh_devices must be 4 on a 2x2 mesh, got "
                f"{stdout_metrics.get('mesh_devices')!r}"
            )
        if not stdout_metrics.get("cache_pool_bytes_per_device", 0) > 0:
            fail("stdout: cache_pool_bytes_per_device must be positive")

        mpath = os.path.join(tdir, "metrics.json")
        if not os.path.exists(mpath):
            fail("--telemetry-dir did not produce metrics.json")
        check_metrics_dict(
            json.load(open(mpath, encoding="utf-8")), "metrics.json"
        )
        n_events = check_events(
            os.path.join(tdir, "events.jsonl"), N_REQUESTS
        )
    print(
        f"check_metrics_schema: OK — {len(REQUIRED_METRIC_KEYS)} metric "
        f"keys on both surfaces, {N_REQUESTS} complete request spans "
        f"across {n_events} events"
    )


if __name__ == "__main__":
    main()
